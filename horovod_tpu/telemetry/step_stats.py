"""Step-level training statistics: step time, throughput, MFU, goodput.

The aggregate layer above per-collective instrumentation — the numbers
the TPU-pod scaling study says are binding at scale (goodput, MFU,
straggler ranks) rather than per-op traces.  A :class:`StepTimer` wraps
the training loop (bench.py, ``step_pipeline.donated_step`` consumers,
user loops) and publishes:

* ``hvdt_step_time_seconds``  — host-fenced step duration summary
* ``hvdt_examples_per_sec``   — windowed throughput gauge
* ``hvdt_mfu``                — model-flops utilization gauge, from the
  caller's flops-per-step (bench.py reuses its XLA cost-analysis flops)
  against the device generation's peak (:func:`peak_flops_for`)
* ``hvdt_steps_total``        — monotonic step counter

A :class:`GoodputLedger` charges wall-clock lost to recompiles, restores
and recovered faults against total elapsed time and publishes
``hvdt_goodput_fraction`` — the "fraction of wall time spent making
forward progress" scalar an operator pages on.
:func:`bind_resilience_gauges` bridges the PR-4 resilience counters
(fault injector fire counts, emergency preemption checkpoints) into the
registry as live probes, so one scrape tells the whole recovery story.
"""

from __future__ import annotations

import contextlib
import dataclasses
import threading
import time
from typing import Dict, Optional

from .metrics import Gauge, MetricsRegistry, default_registry

__all__ = ["StepTimer", "GoodputLedger", "peak_flops_for",
           "bind_resilience_gauges", "record_memory_accounting",
           "tree_bytes", "PEAK_BY_DEVICE_KIND", "RECOVERY_PHASES",
           "recovery_ledger", "reset_recovery_ledger",
           "PerfExpectation", "DeviationTracker", "get_deviation_tracker",
           "publish_expected_schedule_cost",
           "maybe_publish_expected_cost", "reset_expectation",
           "expected_vs_observed_doc"]

# bf16 peak FLOP/s and HBM byte/s by TPU generation (device_kind
# substring, lowercase) — promoted from bench.py so MFU math has one
# home (bench imports this table).
PEAK_BY_DEVICE_KIND = (
    ("v6", 918e12, 1640e9), ("trillium", 918e12, 1640e9),
    ("v5p", 459e12, 2765e9),
    ("v5 lite", 197e12, 819e9), ("v5e", 197e12, 819e9),
    ("v5litepod", 197e12, 819e9),
    ("v4", 275e12, 1228e9), ("v3", 123e12, 900e9), ("v2", 46e12, 700e9),
)


def _positive_or_none(value) -> Optional[float]:
    """Finite positive float, else None — the 'is MFU publishable' test
    (0, NaN, inf, and unparsable values all mean 'unknown')."""
    try:
        v = float(value)
    except (TypeError, ValueError):
        return None
    return v if (v > 0 and v != float("inf")) else None


def peak_flops_for(device_kind: str):
    """(peak_flops, peak_hbm_bw) for a device kind, or (None, None) when
    unknown (CPU, simulators) — MFU is then unpublishable, not faked."""
    dk = (device_kind or "").lower()
    for sub, flops, bw in PEAK_BY_DEVICE_KIND:
        if sub in dk:
            return flops, bw
    return None, None


class StepTimer:
    """Times training steps and publishes throughput/MFU metrics.

    Usage (bench.py / custom loops)::

        timer = StepTimer(examples_per_step=batch,
                          flops_per_step=cost["flops"],
                          device_kind=dev.device_kind)
        for batch in loader:
            with timer.step():
                run_one_step(batch)   # must end with a host fence

    or call :meth:`observe` with externally measured durations (bench
    times whole iters and divides).  ``straggler`` optionally chains a
    :class:`~horovod_tpu.telemetry.straggler.StragglerMonitor` so the
    cross-rank skew check rides the same observation stream.
    """

    def __init__(self, examples_per_step: int = 0,
                 flops_per_step: Optional[float] = None,
                 peak_flops: Optional[float] = None,
                 device_kind: Optional[str] = None,
                 registry: Optional[MetricsRegistry] = None,
                 straggler=None,
                 ewma_alpha: float = 0.2):
        reg = registry if registry is not None else default_registry()
        self.registry = reg
        self.examples_per_step = int(examples_per_step)
        # MFU inputs are *validated up front*: an unknown device-peak
        # table entry (peak_flops_for -> None), zero/absent caller
        # flops, or a non-finite value mean MFU is unpublishable — the
        # gauge is then never registered (rather than rendering a
        # misleading 0) and observe() can't divide by zero.
        self.flops_per_step = _positive_or_none(flops_per_step)
        if peak_flops is None and device_kind:
            peak_flops, _ = peak_flops_for(device_kind)
        self.peak_flops = _positive_or_none(peak_flops)
        self.straggler = straggler
        self._alpha = float(ewma_alpha)
        self._ewma: Optional[float] = None
        self._lock = threading.Lock()
        self._summary = reg.summary(
            "hvdt_step_time_seconds",
            "Host-observed training step duration")
        self._steps = reg.counter(
            "hvdt_steps_total", "Training steps observed by the StepTimer")
        self._examples = reg.gauge(
            "hvdt_examples_per_sec",
            "Windowed training throughput (examples/s, EWMA of step time)")
        self._mfu: Optional[Gauge] = None
        if self.flops_per_step is not None and self.peak_flops is not None:
            self._mfu = reg.gauge(
                "hvdt_mfu",
                "Model-flops utilization: flops_per_step / (step_time * "
                "peak_flops); only published when caller flops and the "
                "device peak are both known")

    def step(self):
        """Context manager timing one step."""
        return _StepScope(self)

    def observe(self, seconds: float) -> None:
        """Record one step's duration (externally timed)."""
        s = float(seconds)
        self._summary.observe(s)
        self._steps.inc()
        with self._lock:
            self._ewma = s if self._ewma is None else (
                self._alpha * s + (1.0 - self._alpha) * self._ewma)
            ewma = self._ewma
        if ewma > 0:
            if self.examples_per_step:
                self._examples.set(self.examples_per_step / ewma)
            if self._mfu is not None:
                self._mfu.set(
                    self.flops_per_step / (ewma * self.peak_flops))
        if self.straggler is not None:
            self.straggler.observe(s)
        # Live perf attribution: the deviation tracker keeps
        # hvdt_perf_deviation_ratio current against the cost-model
        # prediction, and the history layer records the time-series
        # sample (both are None-when-off — one module lookup each).
        tracker = get_deviation_tracker()
        if tracker is not None:
            tracker.observe(s)
        from . import history as _history

        h = _history.get_history()
        if h is not None:
            h.observe_step(self._summary.count, s)

    @property
    def count(self) -> int:
        return self._summary.count

    def mean_step_seconds(self) -> Optional[float]:
        return self._summary.mean()

    def mfu(self) -> Optional[float]:
        if self._mfu is None:
            return None
        v = self._mfu.value()
        return v if v > 0 else None

    def snapshot(self) -> Dict[str, Optional[float]]:
        """The compact dict harnesses (bench JSON) embed."""
        pct = self._summary.percentiles()
        return {
            "steps": self._summary.count,
            "step_time_p50_ms": (round(pct[0.5] * 1e3, 3)
                                 if pct[0.5] is not None else None),
            "step_time_p99_ms": (round(pct[0.99] * 1e3, 3)
                                 if pct[0.99] is not None else None),
            "examples_per_sec": (round(self._examples.value(), 2)
                                 if self._summary.count else None),
            "mfu": (round(self._mfu.value(), 4)
                    if self._mfu is not None and self._mfu.value() > 0
                    else None),
        }


class _StepScope:
    __slots__ = ("_timer", "_t0")

    def __init__(self, timer: StepTimer):
        self._timer = timer
        self._t0 = 0.0

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is None:
            self._timer.observe(time.perf_counter() - self._t0)
        return False


# The recovery-time budget's phase vocabulary: every non-training
# second of a detect→restore→resume cycle is attributed to exactly one
# of these (ROADMAP item 4 — "we recovered" becomes "we recovered fast
# enough", phase by phase).
RECOVERY_PHASES = ("checkpoint_snapshot", "checkpoint_write", "rendezvous",
                   "compile", "restore", "replay")


class GoodputLedger:
    """Wall-clock accounting: where did the non-training time go?

    ``charge(reason, seconds)`` books lost time under a reason label
    (``recompile``, ``restore``, ``fault_recovery``, ...); the published
    ``hvdt_goodput_fraction`` gauge is ``(elapsed - lost) / elapsed``
    live-probed at scrape time, and
    ``hvdt_goodput_lost_seconds_total{reason=...}`` itemizes the bill.

    The recovery-time budget rides on top: :meth:`charge_phase` books
    seconds against one of :data:`RECOVERY_PHASES` and publishes them as
    ``hvdt_recovery_seconds{phase=...}``, the per-phase decomposition a
    sub-30s recovery SLO is audited against.  A phase marked
    ``overlapped`` (the async checkpoint write, which runs UNDER
    training) is attributed but not charged against goodput.
    """

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 clock=time.monotonic, already_elapsed: float = 0.0):
        """``already_elapsed`` backdates the ledger start — a harness
        that constructs the ledger after a compile it intends to charge
        must include that time in the elapsed denominator too, or the
        fraction double-penalizes."""
        reg = registry if registry is not None else default_registry()
        self.registry = reg
        self._clock = clock
        self._start = clock() - max(0.0, float(already_elapsed))
        self._lock = threading.Lock()
        self._lost: Dict[str, float] = {}
        self._phases: Dict[str, float] = {}
        self._lost_counter = reg.counter(
            "hvdt_goodput_lost_seconds_total",
            "Wall-clock seconds lost to non-training work, by reason")
        self._phase_counter = reg.counter(
            "hvdt_recovery_seconds",
            "Non-training wall-clock attributed to the recovery-time "
            "budget, by phase (checkpoint_snapshot | checkpoint_write | "
            "rendezvous | compile | restore | replay)")
        reg.gauge(
            "hvdt_goodput_fraction",
            "(elapsed - lost) / elapsed since ledger start"
        ).set_function(self.fraction)

    def charge(self, reason: str, seconds: float) -> None:
        s = max(0.0, float(seconds))
        with self._lock:
            self._lost[reason] = self._lost.get(reason, 0.0) + s
        self._lost_counter.inc(s, reason=str(reason))

    def charge_phase(self, phase: str, seconds: float,
                     overlapped: bool = False) -> None:
        """Attribute ``seconds`` to a recovery phase.  Unknown phases
        raise — a typo'd phase would silently fall out of the budget
        audit.  ``overlapped`` phases (background checkpoint writes)
        appear in ``hvdt_recovery_seconds`` but do NOT reduce the
        goodput fraction: training kept running under them."""
        if phase not in RECOVERY_PHASES:
            raise ValueError(
                f"unknown recovery phase {phase!r}; valid: "
                f"{', '.join(RECOVERY_PHASES)}")
        s = max(0.0, float(seconds))
        with self._lock:
            self._phases[phase] = self._phases.get(phase, 0.0) + s
        self._phase_counter.inc(s, phase=phase)
        if not overlapped:
            self.charge(phase, s)

    @contextlib.contextmanager
    def phase(self, name: str, overlapped: bool = False):
        """Context manager timing one recovery phase::

            with ledger.phase("restore"):
                state.restore()
        """
        t0 = self._clock()
        try:
            yield
        finally:
            self.charge_phase(name, self._clock() - t0,
                              overlapped=overlapped)

    def recovery_seconds(self, phase: Optional[str] = None) -> float:
        with self._lock:
            if phase is not None:
                return self._phases.get(phase, 0.0)
            return sum(self._phases.values())

    def recovery_snapshot(self) -> Dict[str, float]:
        """Per-phase totals (the bench JSON / scenario-test handle)."""
        with self._lock:
            return dict(self._phases)

    def lost_seconds(self, reason: Optional[str] = None) -> float:
        with self._lock:
            if reason is not None:
                return self._lost.get(reason, 0.0)
            return sum(self._lost.values())

    def elapsed_seconds(self) -> float:
        return max(0.0, self._clock() - self._start)

    def fraction(self) -> float:
        elapsed = self.elapsed_seconds()
        if elapsed <= 0:
            return 1.0
        return max(0.0, (elapsed - self.lost_seconds()) / elapsed)


# ---------------------------------------------------------------------------
# Process-wide recovery ledger (the instance elastic.py / checkpoint.py
# charge into; None when telemetry is off — the zero-overhead contract)
# ---------------------------------------------------------------------------

_recovery_lock = threading.Lock()
_recovery: Optional[GoodputLedger] = None


def recovery_ledger() -> Optional[GoodputLedger]:
    """The process-wide ledger recovery phases are charged into, created
    on first use — or None when the telemetry subsystem is off, so the
    steady-state cost at every charge site is one None-check."""
    from . import instrument

    if not instrument.enabled():
        return None
    global _recovery
    with _recovery_lock:
        if _recovery is None:
            _recovery = GoodputLedger()
        return _recovery


def reset_recovery_ledger() -> None:
    """Drop the process-wide recovery ledger (tests; pairs with
    metrics.reset_default_registry, which orphans the old instance's
    metric objects)."""
    global _recovery
    with _recovery_lock:
        _recovery = None


def bind_resilience_gauges(registry: Optional[MetricsRegistry] = None
                           ) -> None:
    """Publish the resilience subsystem's ad-hoc counters as live gauges.

    Live probes (``set_function``) rather than shadow copies: the fault
    injector and preemption guard keep their own state; a scrape reads
    it at scrape time.  Safe to call repeatedly (gauges are
    get-or-create and rebinding the probe is idempotent)."""
    reg = registry if registry is not None else default_registry()

    def _injected() -> float:
        from ..resilience import faults

        inj = faults.get_injector()
        return float(inj.fired_total()) if inj is not None else 0.0

    def _emergency() -> float:
        from ..resilience.preempt import PreemptionGuard

        return float(PreemptionGuard.emergency_checkpoints)

    reg.gauge(
        "hvdt_injected_faults",
        "Faults the HVDT_FAULT_PLAN injector has fired in this process"
    ).set_function(_injected)
    reg.gauge(
        "hvdt_emergency_checkpoints",
        "Preemption-guard emergency checkpoints taken in this process"
    ).set_function(_emergency)


def tree_bytes(tree) -> int:
    """Total array bytes of a pytree (host-side shape math, no device
    access) — the feed for the memory-accounting gauges."""
    import numpy as np

    total = 0
    import jax

    for leaf in jax.tree.leaves(tree):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            continue
        total += int(np.prod(shape or (1,))) * np.dtype(dtype).itemsize
    return int(total)


_MEMORY_GAUGE_DOCS = {
    "hvdt_param_bytes":
        "Per-rank parameter bytes (post-sharding: the replicated full "
        "tree, or 1/n of it under HVDT_ZERO=params)",
    "hvdt_optimizer_state_bytes":
        "Per-rank optimizer-state bytes (post-sharding: ~1/n of the "
        "replicated moments under HVDT_ZERO=states/params — the "
        "ZeRO memory win, observable from one scrape)",
}


def record_memory_accounting(param_bytes: Optional[float] = None,
                             optimizer_state_bytes: Optional[float] = None,
                             *, params=None, opt_state=None,
                             num_shards: int = 1,
                             zero_stage: str = "off",
                             registry: Optional[MetricsRegistry] = None
                             ) -> None:
    """Feed the per-rank memory-accounting gauges (``hvdt_param_bytes``,
    ``hvdt_optimizer_state_bytes``).

    Callers pass either precomputed byte counts or the live pytrees
    (``params=`` / ``opt_state=``, measured with :func:`tree_bytes` and
    divided by ``num_shards`` for sharded layouts).  No-op when the
    telemetry subsystem is off — the gauges themselves are registered
    (NaN) by ``hvd.init()``'s :func:`..telemetry.exporter.
    bind_process_gauges` so they always appear on /metrics."""
    from . import instrument

    if instrument.get_recorder() is None and registry is None:
        return
    reg = registry if registry is not None else default_registry()
    n = max(1, int(num_shards))
    if param_bytes is None and params is not None:
        param_bytes = tree_bytes(params)
        if zero_stage == "params":
            param_bytes //= n
    if optimizer_state_bytes is None and opt_state is not None:
        optimizer_state_bytes = tree_bytes(opt_state)
        if zero_stage in ("states", "params"):
            optimizer_state_bytes //= n
    if param_bytes is not None:
        reg.gauge("hvdt_param_bytes",
                  _MEMORY_GAUGE_DOCS["hvdt_param_bytes"]).set(
                      float(param_bytes))
    if optimizer_state_bytes is not None:
        reg.gauge("hvdt_optimizer_state_bytes",
                  _MEMORY_GAUGE_DOCS["hvdt_optimizer_state_bytes"]).set(
                      float(optimizer_state_bytes))


# ---------------------------------------------------------------------------
# Predicted-vs-observed perf attribution (the runtime mirror of the CI
# --perf ratchet): price the expected schedule fingerprint with the
# analytical cost model at init, then track observed step time against
# the prediction live.
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class PerfExpectation:
    """The cost model's per-step prediction for this run.

    ``comm_exposed_s`` is the predicted NON-overlapped communication
    seconds (the number the CI perf baseline ratchets);
    ``wire_bytes_by_axis`` the predicted per-tier wire bytes per step;
    ``compute_s`` the device-peak compute seconds when the caller's
    flops and the device generation are both known (None on CPU sims —
    the deviation tracker then calibrates a compute anchor from the
    first observed steps instead)."""

    comm_exposed_s: float
    wire_bytes_by_axis: Dict[str, int] = dataclasses.field(
        default_factory=dict)
    compute_s: Optional[float] = None
    label: str = ""
    source: str = ""


class DeviationTracker:
    """Maintains ``hvdt_perf_deviation_ratio``: observed EWMA step
    seconds over predicted step seconds.

    Predicted step seconds = predicted exposed comm + a compute anchor.
    The anchor is the expectation's device-peak compute time when
    known; otherwise it is **calibrated** from the median of the first
    ``calibration_steps`` observed steps minus the predicted comm (so
    the ratio reads 1.0 at calibration and any later slowdown —
    a straggling link, a throttled host, a policy regression — moves it
    off 1.0 in proportion).  The ratio is NaN until calibrated."""

    def __init__(self, expectation: PerfExpectation,
                 registry: Optional[MetricsRegistry] = None,
                 calibration_steps: int = 4, ewma_alpha: float = 0.3):
        reg = registry if registry is not None else default_registry()
        self.expectation = expectation
        self.calibration_steps = max(1, int(calibration_steps))
        self._alpha = float(ewma_alpha)
        self._lock = threading.Lock()
        self._warmup: list = []
        self._anchor: Optional[float] = expectation.compute_s
        self._ewma: Optional[float] = None
        self._gauge = reg.gauge(
            "hvdt_perf_deviation_ratio",
            "Observed EWMA step seconds / predicted step seconds "
            "(predicted exposed comm + compute anchor); the "
            "perf_deviation anomaly fires past "
            "HVDT_PERF_DEVIATION_RATIO")
        self._gauge.set(float("nan"))

    def observe(self, step_seconds: float) -> Optional[float]:
        """Feed one observed step; returns the current ratio (None
        while calibrating)."""
        s = float(step_seconds)
        with self._lock:
            if self._anchor is None:
                self._warmup.append(s)
                if len(self._warmup) < self.calibration_steps:
                    return None
                ordered = sorted(self._warmup)
                median = ordered[(len(ordered) - 1) // 2]
                self._anchor = max(
                    0.0, median - self.expectation.comm_exposed_s)
            self._ewma = s if self._ewma is None else (
                self._alpha * s + (1.0 - self._alpha) * self._ewma)
            predicted = self._anchor + self.expectation.comm_exposed_s
            if predicted <= 0:
                return None
            ratio = self._ewma / predicted
        self._gauge.set(ratio)
        return ratio

    def ratio(self) -> Optional[float]:
        with self._lock:
            if self._ewma is None or self._anchor is None:
                return None
            predicted = self._anchor + self.expectation.comm_exposed_s
            return self._ewma / predicted if predicted > 0 else None

    def observed_comm_s(self) -> Optional[float]:
        """Observed comm-exposed seconds: EWMA step time minus the
        compute anchor (what the prediction says compute costs)."""
        with self._lock:
            if self._ewma is None or self._anchor is None:
                return None
            return max(0.0, self._ewma - self._anchor)


_expect_lock = threading.Lock()
_expectation: Optional[PerfExpectation] = None
_deviation: Optional[DeviationTracker] = None


def get_expectation() -> Optional[PerfExpectation]:
    return _expectation


def get_deviation_tracker() -> Optional[DeviationTracker]:
    """The process-wide deviation tracker, or None when no expectation
    was published (the zero-overhead off path is one global read)."""
    return _deviation


def reset_expectation() -> None:
    """Drop the published expectation + tracker (test isolation; pairs
    with metrics.reset_default_registry)."""
    global _expectation, _deviation
    with _expect_lock:
        _expectation = None
        _deviation = None


def publish_expected_schedule_cost(
        fingerprint_path: Optional[str] = None,
        registry: Optional[MetricsRegistry] = None,
        device_kind: Optional[str] = None,
        flops_per_step: Optional[float] = None
        ) -> Optional[PerfExpectation]:
    """Price the expected schedule fingerprint with the fitted cost
    model on the ambient topology and publish the prediction:

    * ``hvdt_expected_step_comm_seconds`` — predicted exposed comm s;
    * ``hvdt_expected_wire_bytes{axis}`` — predicted per-tier wire
      bytes per step;
    * arms the process-wide :class:`DeviationTracker` so the StepTimer
      stream keeps ``hvdt_perf_deviation_ratio`` live.

    The fingerprint comes from ``fingerprint_path`` or the
    ``HVDT_EXPECTED_SCHEDULE`` knob (an in-process
    ``ScheduleFingerprint`` instance is also accepted via
    ``fingerprint_path``).  Returns None (and publishes nothing) when
    no fingerprint is available.  Raises on an unreadable file — use
    :func:`maybe_publish_expected_cost` from init paths."""
    from ..analysis import costmodel as _cm
    from ..analysis import schedule as _sched
    from ..analysis.topology import TopologySpec
    from ..common import config as _config

    global _expectation, _deviation
    fp = None
    source = ""
    if fingerprint_path is not None and not isinstance(
            fingerprint_path, str):
        fp = fingerprint_path            # an in-process fingerprint
        source = "in-process"
    else:
        path = (fingerprint_path
                or _config.get_str("HVDT_EXPECTED_SCHEDULE")).strip()
        if not path:
            return None
        fp = _sched.load_fingerprint(path)
        source = path
    topo = TopologySpec.from_env()
    cost = _cm.CostModel().evaluate(fp, topo)
    compute_s = None
    if device_kind and flops_per_step:
        peak, _ = peak_flops_for(device_kind)
        if peak:
            compute_s = float(flops_per_step) / peak
    exp = PerfExpectation(
        comm_exposed_s=float(cost.exposed_comm_s),
        wire_bytes_by_axis={k: int(v) for k, v in
                            sorted(cost.wire_bytes_by_axis.items())},
        compute_s=compute_s, label=fp.label or "step", source=source)
    reg = registry if registry is not None else default_registry()
    reg.gauge(
        "hvdt_expected_step_comm_seconds",
        "Cost-model-predicted exposed (non-overlapped) communication "
        "seconds per step for the expected schedule fingerprint on "
        "the ambient topology").set(exp.comm_exposed_s)
    wire_gauge = reg.gauge(
        "hvdt_expected_wire_bytes",
        "Cost-model-predicted wire bytes per step per transport tier "
        "for the expected schedule fingerprint")
    for axis in sorted(exp.wire_bytes_by_axis):
        wire_gauge.set(exp.wire_bytes_by_axis[axis], axis=axis)
    with _expect_lock:
        _expectation = exp
        _deviation = DeviationTracker(exp, registry=reg)
    return exp


def maybe_publish_expected_cost(**kwargs) -> Optional[PerfExpectation]:
    """The ``hvd.init()`` hook: publish the predicted-vs-observed feed
    iff telemetry is on and an expected schedule is configured.  Never
    raises — a bad fingerprint path must not sink init."""
    from . import instrument
    from ..common.logging_util import get_logger

    if not instrument.enabled():
        return None
    try:
        exp = publish_expected_schedule_cost(**kwargs)
    except Exception as e:
        get_logger(__name__).warning(
            "expected-schedule pricing failed (HVDT_EXPECTED_SCHEDULE): "
            "%s", e)
        return None
    if exp is not None:
        get_logger(__name__).info(
            "expected schedule %s priced: exposed comm %.1fus, wire %s",
            exp.label, exp.comm_exposed_s * 1e6,
            exp.wire_bytes_by_axis)
    return exp


def expected_vs_observed_doc(registry: Optional[MetricsRegistry] = None
                             ) -> Optional[Dict[str, object]]:
    """The compact predicted-vs-observed roll-up bench.py embeds in its
    telemetry JSON: predicted comm seconds, observed comm-exposed
    seconds, the deviation ratio, and per-kind anomaly counts.  None
    when no expectation was published."""
    exp = get_expectation()
    if exp is None:
        return None
    tracker = get_deviation_tracker()
    reg = registry if registry is not None else default_registry()
    anomaly_counts: Dict[str, float] = {}
    c = reg.get("hvdt_anomaly_total")
    if c is not None:
        for labels, v in c.items():
            kind = labels.get("kind", "")
            if kind:
                anomaly_counts[kind] = anomaly_counts.get(kind, 0) + v
    ratio = tracker.ratio() if tracker is not None else None
    observed = tracker.observed_comm_s() if tracker is not None else None
    return {
        "predicted_comm_s": round(exp.comm_exposed_s, 9),
        "predicted_wire_bytes_by_axis": dict(exp.wire_bytes_by_axis),
        "observed_comm_s": (round(observed, 6)
                            if observed is not None else None),
        "deviation_ratio": (round(ratio, 4)
                            if ratio is not None else None),
        "anomaly_counts": {k: int(v) for k, v in
                           sorted(anomaly_counts.items())},
        "fingerprint": exp.label,
        "source": exp.source,
    }
