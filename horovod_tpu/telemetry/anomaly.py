"""Windowed anomaly detection over the telemetry time series.

The detection layer of the live perf attribution plane: pure windowed
detectors (median-vs-median level shift, fractional drop, threshold
crossing, counter-rate drift) run over the ``telemetry/history.py``
series at the recording cadence, and every firing becomes

* one line in a structured JSONL event log (``HVDT_EVENT_LOG``) —
  ``{"ts", "kind", "scope", "step", "rank", "pod", "value",
  "baseline", "ratio", "message", ...}`` — the artifact
  ``python -m horovod_tpu.analysis --report`` post-mortems, and
* an ``hvdt_anomaly_total{kind}`` counter increment.

Worker-side kinds (:class:`AnomalyMonitor`): ``step_time_shift`` (step
time level shift), ``goodput_drop``, ``mfu_regression``, ``wire_drift``
(per-axis wire-byte rate shift), ``straggler_onset`` (skew gauge crosses
the straggler threshold), ``perf_deviation`` (observed-vs-predicted
ratio past ``HVDT_PERF_DEVIATION_RATIO`` — the runtime mirror of the CI
``--perf`` ratchet).

Driver-side (:class:`ClusterAnomalyMonitor`, fed by
``ElasticDriver.telemetry_snapshots()``): the same signals correlated
across ranks — a step-time shift on EVERY rank of one pod collapses to
ONE pod-scoped event (the PR-10 exit-correlation idiom), a single slow
rank is named individually, and one cluster-level ``perf_deviation``
names the worst offending rank/pod.  Every detector is latched: it
fires once on entering the anomalous state and re-arms only after the
signal recovers, so a sustained regression is one event, not one per
sample.

Zero-overhead contract: with ``HVDT_EVENT_LOG`` unset,
:func:`get_event_log` returns ``None`` after one env read; detectors
only run at all when the history layer is on.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..common import config
from ..common.logging_util import get_logger
from .metrics import MetricsRegistry, default_registry

__all__ = [
    "ANOMALY_KINDS", "level_shift", "level_drop", "threshold_cross",
    "rate_shift", "EventLog", "get_event_log", "reset",
    "read_event_log", "AnomalyMonitor", "ClusterAnomalyMonitor",
]

log = get_logger(__name__)

ANOMALY_KINDS: Tuple[str, ...] = (
    "step_time_shift", "goodput_drop", "mfu_regression", "wire_drift",
    "straggler_onset", "perf_deviation")

EVENT_VERSION = 1

# Detector defaults: the window is in SAMPLES (the history cadence),
# the shift factor is deliberately below the straggler threshold — a
# level shift should page before the skew rung evicts.
DEFAULT_WINDOW = 8
DEFAULT_SHIFT_FACTOR = 1.5
DEFAULT_DROP_FRACTION = 0.25


def _median(values: Sequence[float]) -> float:
    ordered = sorted(values)
    return ordered[(len(ordered) - 1) // 2]   # lower median (detector bias)


def level_shift(values: Sequence[float], window: int = DEFAULT_WINDOW,
                factor: float = DEFAULT_SHIFT_FACTOR
                ) -> Optional[Dict[str, float]]:
    """Median-vs-median level shift: the most recent ``window`` samples
    against the ``window`` before them.  Robust to single-sample noise
    by construction (a lone spike moves the recent median by at most
    one rank); fires only when ``recent / baseline > factor``."""
    vals = list(values)
    if len(vals) < 2 * window:
        return None
    recent = _median(vals[-window:])
    baseline = _median(vals[-2 * window:-window])
    if baseline <= 0:
        return None
    ratio = recent / baseline
    if ratio <= factor:
        return None
    return {"value": recent, "baseline": baseline, "ratio": ratio}


def level_drop(values: Sequence[float], window: int = DEFAULT_WINDOW,
               fraction: float = DEFAULT_DROP_FRACTION
               ) -> Optional[Dict[str, float]]:
    """Fractional drop of the recent median below the preceding one
    (goodput, MFU — signals where DOWN is bad)."""
    vals = list(values)
    if len(vals) < 2 * window:
        return None
    recent = _median(vals[-window:])
    baseline = _median(vals[-2 * window:-window])
    if baseline <= 0 or recent >= baseline * (1.0 - fraction):
        return None
    return {"value": recent, "baseline": baseline,
            "ratio": recent / baseline}


def threshold_cross(values: Sequence[float], threshold: float
                    ) -> Optional[Dict[str, float]]:
    """Last value above a fixed threshold (skew / deviation gauges that
    are already ratios against their own baseline)."""
    vals = list(values)
    if not vals or threshold <= 0 or vals[-1] <= threshold:
        return None
    return {"value": vals[-1], "baseline": threshold,
            "ratio": vals[-1] / threshold}


def rate_shift(points: Sequence[Tuple[float, int, float]],
               window: int = DEFAULT_WINDOW,
               factor: float = DEFAULT_SHIFT_FACTOR
               ) -> Optional[Dict[str, float]]:
    """Level shift over the per-step RATE of a cumulative counter
    series (``(ts, step, cumulative_value)`` points -> bytes/step),
    in either direction: a schedule that suddenly moves 2x the wire
    bytes per step and one that silently stopped exchanging are both
    drift."""
    pts = list(points)
    rates: List[float] = []
    for prev, cur in zip(pts, pts[1:]):
        dstep = cur[1] - prev[1]
        if dstep <= 0:
            continue
        rates.append(max(0.0, (cur[2] - prev[2]) / dstep))
    if len(rates) < 2 * window:
        return None
    recent = _median(rates[-window:])
    baseline = _median(rates[-2 * window:-window])
    if baseline <= 0:
        return None
    ratio = recent / baseline
    if max(ratio, 1.0 / ratio if ratio > 0 else float("inf")) <= factor:
        return None
    return {"value": recent, "baseline": baseline, "ratio": ratio}


# ---------------------------------------------------------------------------
# JSONL event log
# ---------------------------------------------------------------------------


class EventLog:
    """Append-only JSONL anomaly event log (one JSON object per line,
    flushed per event so a crashed run keeps everything it saw).

    Bounded: when ``HVDT_EVENT_LOG_MAX_BYTES`` is set (> 0) and an
    append would push the file past it, the current file rotates to
    ``<path>.1`` (keep-1 — the previous ``.1`` is replaced) and the
    append starts a fresh file, so a long run with a chatty controller
    can't grow the log unboundedly while the newest window plus one
    rotation of history always survives."""

    def __init__(self, path: str, max_bytes: Optional[int] = None):
        self.path = str(path)
        self.max_bytes = int(
            config.get_int("HVDT_EVENT_LOG_MAX_BYTES")
            if max_bytes is None else max_bytes)
        self._lock = threading.Lock()

    def _maybe_rotate(self, incoming: int) -> None:
        """(lock held) keep-1 size rotation before an oversize append."""
        if self.max_bytes <= 0:
            return
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return
        if size and size + incoming > self.max_bytes:
            os.replace(self.path, self.path + ".1")

    def emit(self, event: Dict[str, Any]) -> Dict[str, Any]:
        doc = dict(event)
        doc.setdefault("v", EVENT_VERSION)
        doc.setdefault("ts", time.time())
        line = json.dumps(doc, sort_keys=True)
        with self._lock:
            try:
                self._maybe_rotate(len(line) + 1)
                with open(self.path, "a") as fh:
                    fh.write(line + "\n")
            except OSError as e:   # the log must never sink training
                log.warning("anomaly event log write failed: %s", e)
        return doc


def read_event_log(path: str) -> List[Dict[str, Any]]:
    """Parse a JSONL event log; unparseable lines are skipped (a crash
    mid-write leaves at most one torn tail line)."""
    out: List[Dict[str, Any]] = []
    try:
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except ValueError:
                    continue
    except OSError:
        return []
    return out


_lock = threading.Lock()
_cached_env: Optional[str] = "\0unset"
_cached_log: Optional[EventLog] = None


def get_event_log() -> Optional[EventLog]:
    """The process-wide event log, or ``None`` when ``HVDT_EVENT_LOG``
    is unset (one env read, the zero-overhead contract)."""
    global _cached_env, _cached_log
    raw = os.environ.get("HVDT_EVENT_LOG")
    if raw != _cached_env:
        with _lock:
            if raw != _cached_env:
                path = (raw or "").strip()
                _cached_log = EventLog(path) if path else None
                _cached_env = raw
    return _cached_log


def reset() -> None:
    """Drop the cached event log (test isolation)."""
    global _cached_env, _cached_log
    with _lock:
        _cached_env = "\0unset"
        _cached_log = None


# ---------------------------------------------------------------------------
# Worker-side monitor
# ---------------------------------------------------------------------------


class _Latched:
    """Fire-once latching shared by both monitors: a detector key fires
    when its condition turns true and re-arms only after it turns false
    — a sustained anomaly is one event."""

    def __init__(self):
        self._active: set = set()

    def step(self, key: str, firing: bool) -> bool:
        """True exactly when ``key`` newly enters the firing state."""
        if firing:
            if key in self._active:
                return False
            self._active.add(key)
            return True
        self._active.discard(key)
        return False


class AnomalyMonitor:
    """Per-worker detector battery over the metric history, run after
    each recorded sample (``MetricHistory.sample`` calls
    :meth:`check`)."""

    def __init__(self, window: int = DEFAULT_WINDOW,
                 shift_factor: float = DEFAULT_SHIFT_FACTOR,
                 drop_fraction: float = DEFAULT_DROP_FRACTION,
                 skew_threshold: Optional[float] = None,
                 deviation_threshold: Optional[float] = None,
                 registry: Optional[MetricsRegistry] = None,
                 event_log: Optional[EventLog] = None,
                 rank: Optional[int] = None, pod: Optional[str] = None):
        self.window = int(window)
        self.shift_factor = float(shift_factor)
        self.drop_fraction = float(drop_fraction)
        self.skew_threshold = float(
            skew_threshold if skew_threshold is not None
            else config.get_float("HVDT_STRAGGLER_THRESHOLD"))
        self.deviation_threshold = float(
            deviation_threshold if deviation_threshold is not None
            else config.get_float("HVDT_PERF_DEVIATION_RATIO"))
        reg = registry if registry is not None else default_registry()
        self._counter = reg.counter(
            "hvdt_anomaly_total",
            "Anomaly detector firings by kind (step_time_shift | "
            "goodput_drop | mfu_regression | wire_drift | "
            "straggler_onset | perf_deviation)")
        self._explicit_log = event_log
        self._latch = _Latched()
        self.rank = (int(rank) if rank is not None
                     else config.get_int("HVDT_RANK"))
        self.pod = pod if pod is not None else config.get_str("HVDT_POD")

    def _emit(self, kind: str, step: int, message: str,
              series: str = "", **fields: Any) -> Dict[str, Any]:
        doc: Dict[str, Any] = {
            "kind": kind, "scope": "rank", "step": int(step),
            "message": message,
        }
        if self.rank >= 0:
            doc["rank"] = self.rank
        if self.pod:
            doc["pod"] = self.pod
        if series:
            doc["series"] = series
        doc.update(fields)
        self._counter.inc(kind=kind)
        sink = (self._explicit_log if self._explicit_log is not None
                else get_event_log())
        if sink is not None:
            doc = sink.emit(doc)
        log.warning("anomaly: %s at step %d: %s", kind, step, message)
        return doc

    def check(self, history, step: int) -> List[Dict[str, Any]]:
        """Run every detector over the current window; returns the
        events that newly fired (latched)."""
        events: List[Dict[str, Any]] = []
        step = int(step)

        def run(series_name, kind, hit, message_fn, **extra):
            fired = self._latch.step(f"{kind}:{series_name}",
                                     hit is not None)
            if fired and hit is not None:
                events.append(self._emit(
                    kind, step, message_fn(hit), series=series_name,
                    value=round(hit["value"], 6),
                    baseline=round(hit["baseline"], 6),
                    ratio=round(hit["ratio"], 4), **extra))

        s = history.series("step_time")
        if s is not None:
            run("step_time", "step_time_shift",
                level_shift(s.values(), self.window, self.shift_factor),
                lambda h: (f"step time level shift: recent median "
                           f"{h['value']:.4f}s is {h['ratio']:.2f}x the "
                           f"preceding window's {h['baseline']:.4f}s"))
        s = history.series("goodput_fraction")
        if s is not None:
            run("goodput_fraction", "goodput_drop",
                level_drop(s.values(), self.window, self.drop_fraction),
                lambda h: (f"goodput fraction dropped to "
                           f"{h['value']:.3f} ({h['ratio']:.2f}x of "
                           f"{h['baseline']:.3f})"))
        s = history.series("mfu")
        if s is not None:
            run("mfu", "mfu_regression",
                level_drop(s.values(), self.window, self.drop_fraction),
                lambda h: (f"MFU regressed to {h['value']:.4f} "
                           f"({h['ratio']:.2f}x of {h['baseline']:.4f})"))
        s = history.series("step_time_skew")
        if s is not None:
            run("step_time_skew", "straggler_onset",
                threshold_cross(s.values(), self.skew_threshold),
                lambda h: (f"cross-rank step-time skew {h['value']:.2f} "
                           f"crossed the straggler threshold "
                           f"{h['baseline']:.2f}"))
        s = history.series("perf_deviation_ratio")
        if s is not None:
            run("perf_deviation_ratio", "perf_deviation",
                threshold_cross(s.values(), self.deviation_threshold),
                lambda h: (f"observed step time is {h['value']:.2f}x "
                           f"the cost-model prediction (threshold "
                           f"{h['baseline']:.2f}x)"))
        for name in history.names():
            if not name.startswith("wire_bytes."):
                continue
            ser = history.series(name)
            if ser is None:
                continue
            axis = name.split(".", 1)[1]
            run(name, "wire_drift",
                rate_shift(ser.points(), self.window, self.shift_factor),
                lambda h, _axis=axis: (
                    f"per-step wire bytes on axis {_axis!r} drifted "
                    f"{h['ratio']:.2f}x (recent {h['value']:.0f} B/step "
                    f"vs {h['baseline']:.0f})"),
                axis=axis)
        return events


# ---------------------------------------------------------------------------
# Driver-side cluster rules
# ---------------------------------------------------------------------------


class ClusterAnomalyMonitor:
    """Cross-rank anomaly correlation over the driver's aggregated KV
    snapshots: one pod-wide regression is ONE event, a lone slow rank
    is named, and the worst observed-vs-predicted deviation becomes one
    cluster-level ``perf_deviation`` event."""

    def __init__(self, window: int = DEFAULT_WINDOW,
                 shift_factor: Optional[float] = None,
                 deviation_threshold: Optional[float] = None,
                 registry: Optional[MetricsRegistry] = None,
                 event_log: Optional[EventLog] = None):
        self.window = int(window)
        self.shift_factor = float(
            shift_factor if shift_factor is not None
            else config.get_float("HVDT_STRAGGLER_THRESHOLD"))
        self.deviation_threshold = float(
            deviation_threshold if deviation_threshold is not None
            else config.get_float("HVDT_PERF_DEVIATION_RATIO"))
        reg = registry if registry is not None else default_registry()
        self._counter = reg.counter(
            "hvdt_anomaly_total",
            "Anomaly detector firings by kind")
        self._explicit_log = event_log
        self._latch = _Latched()

    def _emit(self, doc: Dict[str, Any]) -> Dict[str, Any]:
        self._counter.inc(kind=str(doc.get("kind", "")))
        sink = (self._explicit_log if self._explicit_log is not None
                else get_event_log())
        if sink is not None:
            doc = sink.emit(doc)
        log.warning("cluster anomaly: %s — %s", doc.get("kind"),
                    doc.get("message"))
        return doc

    def observe(self, snapshots: Dict[int, Dict[str, Any]]
                ) -> List[Dict[str, Any]]:
        """Correlate one round of per-rank snapshots; returns the
        cluster events that newly fired."""
        from . import aggregate

        events: List[Dict[str, Any]] = []
        means = aggregate.recent_step_means(snapshots, window=self.window)
        pods = {rank: (snapshots.get(rank) or {}).get("pod") or ""
                for rank in means}
        outliers: Dict[int, float] = {}
        if len(means) >= 2:
            median = _median(list(means.values()))
            if median > 0:
                outliers = {r: m / median for r, m in means.items()
                            if m / median > self.shift_factor}
        by_pod: Dict[str, List[int]] = {}
        for rank in sorted(means):
            by_pod.setdefault(pods[rank], []).append(rank)

        handled: set = set()
        for pod in sorted(by_pod):
            ranks = by_pod[pod]
            pod_wide = (bool(pod) and len(ranks) >= 2
                        and all(r in outliers for r in ranks))
            if self._latch.step(f"step_time_shift:pod:{pod}", pod_wide) \
                    and pod_wide:
                worst = max(ranks, key=lambda r: outliers[r])
                events.append(self._emit({
                    "kind": "step_time_shift", "scope": "pod",
                    "pod": pod, "rank": worst, "ranks": ranks,
                    "ratio": round(max(outliers[r] for r in ranks), 4),
                    "step": _latest_step(snapshots, ranks),
                    "message": (f"pod {pod} step time shifted "
                                f"{max(outliers[r] for r in ranks):.2f}x "
                                f"vs the cluster median (all of ranks "
                                f"{ranks})"),
                }))
            if pod_wide:
                handled.update(ranks)
        for rank in sorted(means):
            firing = rank in outliers and rank not in handled
            if self._latch.step(f"step_time_shift:rank:{rank}",
                                firing) and firing:
                events.append(self._emit({
                    "kind": "step_time_shift", "scope": "rank",
                    "rank": rank, "pod": pods.get(rank, ""),
                    "ratio": round(outliers[rank], 4),
                    "step": _latest_step(snapshots, [rank]),
                    "message": (f"rank {rank} (pod "
                                f"{pods.get(rank) or '?'}) step time is "
                                f"{outliers[rank]:.2f}x the cluster "
                                f"median"),
                }))

        deviants = {
            r: float(snap.get("perf_deviation_ratio") or 0.0)
            for r, snap in snapshots.items()
            if (snap.get("perf_deviation_ratio") or 0.0)
            > self.deviation_threshold}
        if self._latch.step("perf_deviation:cluster", bool(deviants)) \
                and deviants:
            worst = max(sorted(deviants), key=lambda r: deviants[r])
            events.append(self._emit({
                "kind": "perf_deviation", "scope": "cluster",
                "rank": worst,
                "pod": (snapshots.get(worst) or {}).get("pod") or "",
                "ranks": sorted(deviants),
                "ratio": round(deviants[worst], 4),
                "step": _latest_step(snapshots, [worst]),
                "message": (f"observed step time deviates from the "
                            f"cost-model prediction: worst rank "
                            f"{worst} (pod "
                            f"{(snapshots.get(worst) or {}).get('pod') or '?'}) "
                            f"at {deviants[worst]:.2f}x (threshold "
                            f"{self.deviation_threshold:.2f}x)"),
            }))
        return events


def _latest_step(snapshots: Dict[int, Dict[str, Any]],
                 ranks: Sequence[int]) -> int:
    return max((int((snapshots.get(r) or {}).get("step") or 0)
                for r in ranks), default=0)
