"""Collective flight recorder + cross-rank desync forensics.

The TPU-native analog of a NCCL flight recorder: an **always-cheap ring
buffer** of the last N collective events on each rank — monotonic
sequence number, op/name/dtype/shape, bytes and wire format, start/end
timestamps, in-flight vs done status.  When a run wedges, the question
at pod scale is never "what does rank 0's log say" but *which
collective, on which rank, diverged first* ("Exploring the limits of
Concurrency in ML Training on Google TPUs", PAPERS.md) — and the ring
holds exactly the evidence needed to answer it after the fact.

Feeds: the eager negotiated path records begin-at-enqueue /
end-at-completion (a hung rank's peers therefore show its collectives
stuck ``inflight``), and the jit paths (``ops/device.fused_allreduce``,
``quant/collectives``) record one ``traced`` event per compiled bucket.

Dump triggers:

* the resilience :class:`~horovod_tpu.resilience.escalation.Escalator`
  **abort rung** — the coordinator gathers every rank's recent sequence
  over the rendezvous KV and emits a structured *desync report* naming
  the first divergent seq, the ranks missing from it, and any
  shape/dtype mismatches (:func:`analyze_desync` /
  :func:`emit_desync_report`);
* :class:`~horovod_tpu.resilience.preempt.PreemptionGuard` firing
  (:func:`dump_on_preempt` — the ring is on disk before the host dies);
* on demand via the exporter's ``/flightrecorder`` endpoint.

Sequence numbers are per-process counters: they align across ranks
exactly when every rank issues the same collectives in the same order —
the same determinism contract the eager auto-naming scheme
(``allreduce.noname.N``) already relies on, so a misalignment IS the
divergence being hunted.

Zero-overhead contract: with ``HVDT_FLIGHT_RECORDER`` unset,
:func:`get_flight_recorder` returns ``None`` (one env read + compare)
and every feed site skips on ``is None``.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence

from ..common import config
from ..common.logging_util import get_logger

__all__ = ["FlightRecorder", "get_flight_recorder", "reset",
           "analyze_desync", "emit_desync_report", "dump_on_preempt",
           "collect_server_events", "FLIGHT_KV_PREFIX"]

log = get_logger(__name__)

FLIGHT_KV_PREFIX = "/flightrecorder/"

_TRUTHY = ("1", "true", "yes", "on")

INFLIGHT, DONE, ERROR, TRACED = "inflight", "done", "error", "traced"


def enabled() -> bool:
    return os.environ.get("HVDT_FLIGHT_RECORDER",
                          "").strip().lower() in _TRUTHY


def _env_rank() -> int:
    try:
        return max(0, int(os.environ.get("HVDT_RANK", 0)))
    except ValueError:
        return 0


class FlightRecorder:
    """Bounded ring of recent collective events (one per rank).

    ``record_begin`` → ``record_end`` brackets an eager collective's
    lifetime (enqueue → handle completion); ``record`` books a one-shot
    event (jit trace-time, or externally-driven sequences in tests and
    harnesses).  Everything is a dict append / field update under one
    lock — cheap enough to leave on for whole runs, which is the point
    of a flight recorder.
    """

    def __init__(self, capacity: Optional[int] = None,
                 rank: Optional[int] = None):
        cap = int(capacity if capacity is not None
                  else config.get_int("HVDT_FLIGHT_RECORDER_EVENTS"))
        self.capacity = max(8, cap)
        self.rank = _env_rank() if rank is None else int(rank)
        self._lock = threading.Lock()
        self._ring: "deque[Dict[str, Any]]" = deque(maxlen=self.capacity)
        self._by_seq: Dict[int, Dict[str, Any]] = {}
        self._next_seq = 1

    # -- recording ----------------------------------------------------------
    def _new_event(self, op: str, name: str, dtype: str, shape, nbytes: int,
                   wire: str, path: str, count: int,
                   status: str, axis: str = "") -> Dict[str, Any]:
        ev = {
            "seq": 0,                       # assigned under the lock
            "op": str(op).lower(),
            "name": str(name),
            "dtype": str(dtype),
            "shape": list(shape) if shape is not None else None,
            "nbytes": int(nbytes),
            "wire": str(wire) if wire else str(dtype),
            "path": str(path),
            "count": int(count),
            # Mesh axis / tier the collective reduces over (jit paths;
            # "" on the eager negotiated path, whose group is a process
            # set) — lets a desync report say WHICH interconnect tier
            # the divergent collective was crossing.
            "axis": str(axis),
            "start_ts": time.time(),
            "end_ts": None,
            "status": status,
        }
        return ev

    def _append(self, ev: Dict[str, Any]) -> int:
        with self._lock:
            ev["seq"] = self._next_seq
            self._next_seq += 1
            if len(self._ring) == self.capacity:
                evicted = self._ring[0]
                self._by_seq.pop(evicted["seq"], None)
            self._ring.append(ev)
            if ev["status"] == INFLIGHT:
                self._by_seq[ev["seq"]] = ev
            return ev["seq"]

    def record_begin(self, op: str, name: str, dtype: str = "",
                     shape: Optional[Sequence[int]] = None,
                     nbytes: int = 0, wire: str = "", path: str = "eager",
                     count: int = 1, axis: str = "") -> int:
        """Open an in-flight collective event; returns its seq."""
        return self._append(self._new_event(op, name, dtype, shape, nbytes,
                                            wire, path, count, INFLIGHT,
                                            axis))

    def record_end(self, seq: Optional[int], status: str = DONE) -> None:
        """Close an in-flight event (no-op for evicted/unknown seqs)."""
        if seq is None:
            return
        with self._lock:
            ev = self._by_seq.pop(int(seq), None)
            if ev is not None:
                ev["end_ts"] = time.time()
                ev["status"] = status

    def record(self, op: str, name: str, dtype: str = "",
               shape: Optional[Sequence[int]] = None, nbytes: int = 0,
               wire: str = "", path: str = "jit", count: int = 1,
               status: str = TRACED, axis: str = "") -> int:
        """One-shot event (jit trace-time buckets, external sequences)."""
        ev = self._new_event(op, name, dtype, shape, nbytes, wire, path,
                             count, status, axis)
        ev["end_ts"] = ev["start_ts"]
        return self._append(ev)

    # -- export -------------------------------------------------------------
    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(ev) for ev in self._ring]

    def last_seq(self) -> int:
        with self._lock:
            return self._next_seq - 1

    def dump(self) -> Dict[str, Any]:
        return {"rank": self.rank, "capacity": self.capacity,
                "events": self.events(), "ts": time.time()}

    def publish(self, kv, rank: Optional[int] = None) -> bool:
        """Best-effort dump publish to the rendezvous KV."""
        r = self.rank if rank is None else int(rank)
        try:
            kv.put(f"{FLIGHT_KV_PREFIX}{r}", json.dumps(self.dump()).encode())
            return True
        except Exception as e:
            log.debug("flight recorder KV publish failed: %s", e)
            return False

    def write(self, directory: str) -> str:
        os.makedirs(directory, exist_ok=True)
        path = os.path.join(directory,
                            f"flightrecorder_rank{self.rank}.json")
        with open(path, "w") as fh:
            json.dump(self.dump(), fh, indent=2)
        return path


# ---------------------------------------------------------------------------
# Process-wide recorder (env-gated, cached — instrument.get_recorder idiom)
# ---------------------------------------------------------------------------

_lock = threading.Lock()
_cached_env: Optional[str] = "\0unset"
_cached: Optional[FlightRecorder] = None


def get_flight_recorder() -> Optional[FlightRecorder]:
    """The process-wide flight recorder, or ``None`` when
    ``HVDT_FLIGHT_RECORDER`` is unset — feed sites branch on ``is None``
    and touch nothing else."""
    global _cached_env, _cached
    raw = os.environ.get("HVDT_FLIGHT_RECORDER")
    if raw != _cached_env:
        with _lock:
            if raw != _cached_env:
                _cached = FlightRecorder() if enabled() else None
                _cached_env = raw
    return _cached


def reset() -> None:
    """Drop the cached recorder (test isolation)."""
    global _cached_env, _cached
    with _lock:
        _cached_env = "\0unset"
        _cached = None


# ---------------------------------------------------------------------------
# Desync analysis
# ---------------------------------------------------------------------------

_MISMATCH_FIELDS = ("op", "name", "dtype", "shape")


def analyze_desync(events_by_rank: Dict[int, List[Dict[str, Any]]],
                   expected_ranks: Optional[Sequence[int]] = None
                   ) -> Dict[str, Any]:
    """Cross-rank event-sequence comparison → structured desync report.

    Scans the overlapping seq window (ring eviction means early seqs may
    be gone on long-running ranks) and reports:

    * ``first_divergent_seq`` — the first seq some-but-not-all ranks
      recorded (None when sequences agree);
    * ``missing_ranks`` — ranks with no event at that seq (the hung /
      diverged suspects; a rank with NO events at all is missing from
      the start);
    * ``mismatches`` — seqs where ranks recorded *different* op / name /
      dtype / shape (host-side control-flow divergence, the classic
      "mismatched collective" failure);
    * ``per_rank_last_seq`` and ``inflight_by_rank`` — how far each rank
      got, and what it still had in flight.
    """
    ranks = sorted(int(r) for r in (expected_ranks if expected_ranks
                                    else events_by_rank.keys()))
    by_seq: Dict[int, Dict[int, Dict[str, Any]]] = {
        r: {int(e["seq"]): e for e in events_by_rank.get(r, [])}
        for r in ranks}
    nonempty = {r: s for r, s in by_seq.items() if s}
    report: Dict[str, Any] = {
        "ranks": ranks,
        "per_rank_last_seq": {str(r): (max(by_seq[r]) if by_seq[r]
                                       else None) for r in ranks},
        "inflight_by_rank": {
            str(r): [e["seq"] for e in events_by_rank.get(r, [])
                     if e.get("status") == INFLIGHT] for r in ranks},
        "first_divergent_seq": None,
        "missing_ranks": [],
        "mismatches": [],
    }
    if not nonempty:
        report["missing_ranks"] = ranks
        return report
    # Overlap window: start where every *reporting* rank still has
    # history; a rank with zero events is divergent from the window
    # start by definition.
    start = max(min(s) for s in nonempty.values())
    end = max(max(s) for s in nonempty.values())
    mismatches: List[Dict[str, Any]] = []
    for seq in range(start, end + 1):
        have = [r for r in ranks if seq in by_seq[r]]
        absent = [r for r in ranks if seq not in by_seq[r]]
        if absent and report["first_divergent_seq"] is None:
            report["first_divergent_seq"] = seq
            report["missing_ranks"] = absent
            ref = by_seq[have[0]][seq] if have else None
            if ref is not None:
                report["divergent_event"] = {
                    k: ref.get(k) for k in
                    ("op", "name", "dtype", "shape", "nbytes", "status")}
        if len(have) > 1:
            vals = {f: {r: by_seq[r][seq].get(f) for r in have}
                    for f in _MISMATCH_FIELDS}
            for field, per_rank in vals.items():
                if len({json.dumps(v) for v in per_rank.values()}) > 1:
                    mismatches.append({
                        "seq": seq, "field": field,
                        "values": {str(r): per_rank[r] for r in have}})
    report["mismatches"] = mismatches
    if report["first_divergent_seq"] is None and mismatches:
        # Everyone recorded every seq but disagreed on what it was: the
        # first mismatching seq is the divergence point.
        report["first_divergent_seq"] = mismatches[0]["seq"]
    return report


def _gather_events(kv_client, size: int, self_rank: int,
                   local_events: List[Dict[str, Any]]
                   ) -> Dict[int, List[Dict[str, Any]]]:
    out: Dict[int, List[Dict[str, Any]]] = {self_rank: local_events}
    for r in range(size):
        if r == self_rank:
            continue
        try:
            raw = kv_client.get(f"{FLIGHT_KV_PREFIX}{r}")
        except Exception:
            raw = None
        if raw:
            try:
                out[r] = json.loads(raw.decode()).get("events", [])
            except (ValueError, UnicodeDecodeError):
                continue
    return out


def _load_expected_schedule() -> Optional[Dict[str, Any]]:
    """The static schedule fingerprint named by
    ``HVDT_EXPECTED_SCHEDULE`` (exported by ``python -m
    horovod_tpu.analysis --schedule``), or None when unset/unreadable."""
    path = config.get_str("HVDT_EXPECTED_SCHEDULE")
    if not path:
        return None
    try:
        with open(path) as fh:
            return json.load(fh)
    except (OSError, ValueError) as e:
        log.warning("expected schedule %s unreadable: %r", path, e)
        return None


def _expected_schedule_section(doc: Dict[str, Any],
                               by_rank: Dict[int, List[Dict[str, Any]]],
                               report: Dict[str, Any]) -> Dict[str, Any]:
    """Static-expected vs runtime-observed: compare every rank's
    recorded events against the exported fingerprint (cyclically — the
    fingerprint is one step's schedule) and name the first deviation.
    A rank whose events all match but which stopped short is reported
    against the static entry it should have issued next."""
    entries = doc.get("events", [])
    sec: Dict[str, Any] = {
        "path": config.get_str("HVDT_EXPECTED_SCHEDULE"),
        "digest": doc.get("digest"),
        "label": doc.get("label", ""),
        "collectives_per_step": len(entries),
        "first_deviation": None,
    }
    if not entries:
        return sec
    try:
        from ..analysis.schedule import first_schedule_deviation
    except Exception as e:       # analysis layer must never break forensics
        log.debug("expected-schedule check unavailable: %r", e)
        return sec
    dev: Optional[Dict[str, Any]] = None
    for r in sorted(by_rank):
        d = first_schedule_deviation(by_rank[r], entries)
        if d is not None:
            d["rank"] = r
            if dev is None or d["seq"] < dev["seq"]:
                dev = d
    if dev is None and report.get("first_divergent_seq") is not None:
        # Every recorded event matched the static schedule — the
        # deviation is the collective the missing rank(s) never issued.
        seq = int(report["first_divergent_seq"])
        dev = {
            "seq": seq,
            "rank": report.get("missing_ranks"),
            "reason": "missing: rank(s) never recorded this collective "
                      "(the static schedule expects it every step)",
            "expected": dict(entries[(seq - 1) % len(entries)]),
            "observed": None,
        }
    sec["first_deviation"] = dev
    return sec


def emit_desync_report(stalled: Optional[str] = None,
                       age_s: Optional[float] = None,
                       kv_client=None, size: Optional[int] = None,
                       out_dir: Optional[str] = None
                       ) -> Optional[Dict[str, Any]]:
    """Stall-abort forensics: gather every rank's recent event sequence
    over the rendezvous KV, analyze, and persist the report.

    Called by the resilience ``Escalator`` when its abort rung fires (the
    coordinator side of a hung negotiation) and usable on demand.  Writes
    ``desync_report_rank<N>.json`` into ``HVDT_TRACE_DIR`` (when set),
    publishes ``/desync/report`` to the KV, and logs the headline.  With
    ``HVDT_EXPECTED_SCHEDULE`` set (a fingerprint exported by the static
    analyzer) the report gains an ``expected_schedule`` section naming
    the first static-expected-vs-runtime-observed deviation.  Best
    effort end to end: returns None (recording nothing) when the flight
    recorder is off, and never raises."""
    fr = get_flight_recorder()
    if fr is None:
        return None
    rank = fr.rank
    try:
        if size is None:
            try:
                size = int(os.environ.get("HVDT_SIZE", 0) or 0)
            except ValueError:
                size = 0
        client = kv_client
        if client is None and os.environ.get("HVDT_RENDEZVOUS_ADDR"):
            try:
                from ..runner.http_kv import KVClient

                client = KVClient.from_env()
            except Exception as e:
                log.debug("desync KV client unavailable: %s", e)
        local = fr.events()
        if client is not None:
            fr.publish(client, rank)
            by_rank = _gather_events(client, max(size, rank + 1), rank,
                                     local)
        else:
            by_rank = {rank: local}
        expected = list(range(size)) if size > 0 else sorted(by_rank)
        report = analyze_desync(by_rank, expected_ranks=expected)
        report.update({
            "stalled_collective": stalled,
            "stall_age_s": (round(float(age_s), 3)
                            if age_s is not None else None),
            "reporting_rank": rank,
            "ts": time.time(),
        })
        expected_doc = _load_expected_schedule()
        if expected_doc is not None:
            report["expected_schedule"] = _expected_schedule_section(
                expected_doc, by_rank, report)
        d = out_dir or config.get_str("HVDT_TRACE_DIR")
        if d:
            try:
                os.makedirs(d, exist_ok=True)
                path = os.path.join(d, f"desync_report_rank{rank}.json")
                with open(path, "w") as fh:
                    json.dump(report, fh, indent=2)
                report["report_path"] = path
            except OSError as e:
                log.warning("desync report not written: %r", e)
        if client is not None:
            try:
                client.put("/desync/report", json.dumps(report).encode())
            except Exception as e:
                log.debug("desync report KV publish failed: %s", e)
        log.warning(
            "DESYNC REPORT: stalled=%s first_divergent_seq=%s "
            "missing_ranks=%s mismatches=%d (last seq by rank: %s)",
            stalled, report["first_divergent_seq"],
            report["missing_ranks"], len(report["mismatches"]),
            report["per_rank_last_seq"])
        fd = report.get("expected_schedule", {}).get("first_deviation")
        if fd:
            log.warning(
                "DESYNC static-expected vs observed: seq=%s rank=%s %s",
                fd.get("seq"), fd.get("rank"), fd.get("reason"))
        return report
    except Exception as e:   # forensics must never worsen the failure
        log.warning("desync report failed: %r", e)
        return None


def dump_on_preempt() -> Optional[str]:
    """Preemption-grace-window dump: persist the ring to
    ``HVDT_TRACE_DIR`` before the host disappears (called by
    ``PreemptionGuard.check``).  Never raises."""
    fr = get_flight_recorder()
    if fr is None:
        return None
    try:
        d = config.get_str("HVDT_TRACE_DIR")
        if not d:
            log.info("flight recorder holds %d events at preemption "
                     "(set HVDT_TRACE_DIR to persist them)",
                     len(fr.events()))
            return None
        path = fr.write(d)
        log.warning("flight recorder dumped to %s at preemption", path)
        return path
    except Exception as e:
        log.warning("flight recorder preemption dump failed: %r", e)
        return None


def collect_server_events(kv_server) -> Dict[int, List[Dict[str, Any]]]:
    """Driver-side: read every worker's published flight-recorder events
    out of the rendezvous KV store."""
    out: Dict[int, List[Dict[str, Any]]] = {}
    with kv_server.lock:
        items = {k: v for k, v in kv_server.store.items()
                 if k.startswith(FLIGHT_KV_PREFIX)}
    for key, raw in items.items():
        try:
            rank = int(key[len(FLIGHT_KV_PREFIX):])
            out[rank] = json.loads(raw.decode()).get("events", [])
        except (ValueError, UnicodeDecodeError):
            continue
    return out
