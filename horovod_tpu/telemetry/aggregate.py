"""Driver-side step-aligned aggregation of worker telemetry snapshots.

``ElasticDriver.telemetry_snapshots()`` returns each rank's latest KV
snapshot; with the history layer on (``HVDT_HISTORY``) every snapshot
also embeds ``wall_ts``, the current ``step`` id, and a recent
``timeseries`` slice.  This module joins those per-rank series **on
step id** (wall clocks skew across hosts; deterministic step ids — the
PR-6 trace-id convention — do not) and rolls them up:

* :func:`step_join` — ``{step: {rank: value}}`` for one series across
  the fleet;
* :func:`rollup` — the full driver-side view: aligned step range,
  per-pod median/p99 step time, cluster wire-bytes-by-axis, mean
  goodput fraction, and a per-step cluster step-time series;
* :func:`recent_step_means` — per-rank recent mean step seconds, the
  input of the cluster anomaly rules.

Schema tolerance: snapshots from workers running an older schema (no
``step``/``timeseries`` — history off, or a pre-upgrade binary) are
skipped from the step-aligned roll-up and counted in
``hvdt_snapshot_unaligned_total``; their scalar fields still aggregate.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

from .metrics import MetricsRegistry, default_registry

__all__ = ["aligned_snapshots", "step_join", "recent_step_means",
           "rollup"]


def _series_points(snap: Dict[str, Any], name: str
                   ) -> List[Tuple[float, int, float]]:
    series = ((snap.get("timeseries") or {}).get("series") or {})
    pts = series.get(name) or []
    out: List[Tuple[float, int, float]] = []
    for p in pts:
        try:
            ts, step, value = p
            out.append((float(ts), int(step), float(value)))
        except (TypeError, ValueError):
            continue
    return out


def aligned_snapshots(snapshots: Dict[int, Dict[str, Any]],
                      registry: Optional[MetricsRegistry] = None
                      ) -> Tuple[Dict[int, Dict[str, Any]], List[int]]:
    """Split snapshots into step-alignable ones (carry ``step`` +
    ``timeseries``) and the unaligned rest; unaligned ranks are counted
    in ``hvdt_snapshot_unaligned_total`` (and skipped by the join, not
    failed — old workers keep reporting their scalars)."""
    aligned: Dict[int, Dict[str, Any]] = {}
    unaligned: List[int] = []
    for rank in sorted(snapshots):
        snap = snapshots[rank] or {}
        if snap.get("step") is not None and _series_points(
                snap, "step_time"):
            aligned[rank] = snap
        else:
            unaligned.append(rank)
    if unaligned:
        reg = registry if registry is not None else default_registry()
        reg.counter(
            "hvdt_snapshot_unaligned_total",
            "Driver-side roll-ups that skipped a rank whose KV "
            "snapshot carried no step id / time series (old snapshot "
            "schema or history off on that worker)"
        ).inc(len(unaligned))
    return aligned, unaligned


def step_join(snapshots: Dict[int, Dict[str, Any]],
              series: str = "step_time") -> Dict[int, Dict[int, float]]:
    """Join one series across ranks on step id: ``{step: {rank:
    value}}`` (only alignable snapshots contribute; pass the
    ``aligned_snapshots`` output to also get the skip accounting)."""
    out: Dict[int, Dict[int, float]] = {}
    for rank in sorted(snapshots):
        for _, step, value in _series_points(snapshots[rank], series):
            out.setdefault(step, {})[rank] = value
    return out


def recent_step_means(snapshots: Dict[int, Dict[str, Any]],
                      window: int = 8) -> Dict[int, float]:
    """Per-rank mean step seconds over each rank's most recent
    ``window`` samples — the cluster anomaly rules' input.  Ranks
    without a step series fall back to their scalar
    ``step_time_p50_ms`` so an old-schema worker still participates in
    outlier detection."""
    out: Dict[int, float] = {}
    for rank in sorted(snapshots):
        snap = snapshots[rank] or {}
        pts = _series_points(snap, "step_time")
        if pts:
            vals = [v for _, _, v in pts[-window:]]
            out[rank] = sum(vals) / len(vals)
            continue
        p50 = snap.get("step_time_p50_ms")
        if p50:
            out[rank] = float(p50) / 1e3
    return out


def _median(values: Sequence[float]) -> float:
    ordered = sorted(values)
    return ordered[(len(ordered) - 1) // 2]


def _p99(values: Sequence[float]) -> float:
    ordered = sorted(values)
    idx = min(len(ordered) - 1, max(0, int(0.99 * len(ordered) + 0.5) - 1))
    return ordered[idx]


def rollup(snapshots: Dict[int, Dict[str, Any]],
           registry: Optional[MetricsRegistry] = None) -> Dict[str, Any]:
    """The driver-side fleet view over one round of snapshots.

    Returns::

        {"ranks": [...], "unaligned_ranks": [...],
         "aligned_steps": [first, last] | None,
         "per_pod": {pod: {"ranks", "step_time_p50_ms",
                           "step_time_p99_ms"}},
         "cluster": {"step_time_series": {step: {"median_ms",
                                                 "p99_ms", "ranks"}},
                     "wire_bytes_by_axis": {axis: bytes},
                     "goodput_fraction_mean": float | None,
                     "goodput_series": {step: mean_fraction},
                     "worst_pod": pod | None}}
    """
    aligned, unaligned = aligned_snapshots(snapshots, registry=registry)
    joined = step_join(aligned, "step_time")
    all_ranks = sorted(snapshots)

    # Steps every aligned rank reported — the strictly comparable range.
    full_steps = sorted(s for s, per_rank in joined.items()
                        if len(per_rank) == len(aligned)) if aligned else []

    step_series: Dict[int, Dict[str, Any]] = {}
    for step in sorted(joined):
        vals = sorted(joined[step].values())
        step_series[step] = {
            "median_ms": round(_median(vals) * 1e3, 3),
            "p99_ms": round(_p99(vals) * 1e3, 3),
            "ranks": len(vals),
        }

    # Per-pod roll-up over each rank's recent window.
    means = recent_step_means(snapshots)
    by_pod: Dict[str, List[int]] = {}
    for rank in sorted(snapshots):
        pod = (snapshots[rank] or {}).get("pod") or ""
        by_pod.setdefault(pod, []).append(rank)
    per_pod: Dict[str, Dict[str, Any]] = {}
    for pod in sorted(by_pod):
        if not pod:
            continue
        vals = [means[r] for r in by_pod[pod] if r in means]
        if not vals:
            continue
        per_pod[pod] = {
            "ranks": by_pod[pod],
            "step_time_p50_ms": round(_median(vals) * 1e3, 3),
            "step_time_p99_ms": round(_p99(vals) * 1e3, 3),
        }
    worst_pod = max(per_pod,
                    key=lambda p: per_pod[p]["step_time_p50_ms"],
                    default=None)

    # Cluster wire bytes by axis: sum each rank's latest cumulative
    # per-axis sample (series "wire_bytes.<axis>").
    wire_by_axis: Dict[str, float] = {}
    for rank in sorted(aligned):
        series = ((aligned[rank].get("timeseries") or {})
                  .get("series") or {})
        for name in sorted(series):
            if not name.startswith("wire_bytes."):
                continue
            pts = _series_points(aligned[rank], name)
            if pts:
                axis = name.split(".", 1)[1]
                wire_by_axis[axis] = wire_by_axis.get(axis, 0.0) \
                    + pts[-1][2]

    # Goodput: scalar mean + a step-joined series when present.
    goodputs = [float(s["goodput_fraction"]) for s in snapshots.values()
                if s and s.get("goodput_fraction") is not None]
    gp_joined = step_join(aligned, "goodput_fraction")
    goodput_series = {
        step: round(sum(per.values()) / len(per), 4)
        for step, per in sorted(gp_joined.items())}

    return {
        "ranks": all_ranks,
        "unaligned_ranks": unaligned,
        "aligned_steps": ([full_steps[0], full_steps[-1]]
                          if full_steps else None),
        "per_pod": per_pod,
        "cluster": {
            "step_time_series": step_series,
            "wire_bytes_by_axis": {a: int(v) for a, v in
                                   sorted(wire_by_axis.items())},
            "goodput_fraction_mean": (round(sum(goodputs)
                                            / len(goodputs), 4)
                                      if goodputs else None),
            "goodput_series": goodput_series,
            "worst_pod": worst_pod,
        },
    }
