"""Unified telemetry: metrics registry, instrumentation, straggler
detection, and the per-worker /metrics exporter.

The observability layer the training stack was missing (the serving
plane had its own Prometheus-text metrics; training had Chrome traces
and ad-hoc module-level ints).  Layering, bottom up:

* :mod:`~horovod_tpu.telemetry.metrics` — Counter / Gauge / Summary
  primitives + the process-wide :func:`default_registry` (promoted out
  of ``serve/metrics.py``, which re-exports for back-compat);
* :mod:`~horovod_tpu.telemetry.instrument` — per-collective hook points
  threaded through the eager and jit data planes; zero-overhead identity
  objects when ``HVDT_TELEMETRY`` is off;
* :mod:`~horovod_tpu.telemetry.step_stats` — :class:`StepTimer`
  (step time, examples/s, MFU) and :class:`GoodputLedger` (time lost to
  recompiles / restores / recovered faults);
* :mod:`~horovod_tpu.telemetry.straggler` — cross-rank step-duration
  skew detection publishing a ``straggler_rank`` gauge;
* :mod:`~horovod_tpu.telemetry.exporter` — per-worker ``/metrics`` +
  ``/healthz`` + ``/flightrecorder`` HTTP endpoint (started by
  ``hvd.init()`` when enabled) and driver-side snapshot aggregation
  over the rendezvous KV;
* :mod:`~horovod_tpu.telemetry.trace` — distributed span tracing:
  bounded per-rank Chrome-trace buffers with deterministic per-step
  trace ids, merged driver-side into one rank-as-pid trace
  (``hvdtrun --trace-dir``);
* :mod:`~horovod_tpu.telemetry.flight_recorder` — always-cheap ring of
  recent collective events (seq/op/dtype/bytes/wire, in-flight vs done)
  + the cross-rank desync analyzer that names the first divergent
  collective on stall-abort;
* :mod:`~horovod_tpu.telemetry.history` — bounded per-metric time
  series (``HVDT_HISTORY``), served as ``/timeseries`` and embedded in
  the KV snapshot for step-aligned driver roll-ups;
* :mod:`~horovod_tpu.telemetry.anomaly` — windowed detectors over the
  series + the JSONL anomaly event log (``HVDT_EVENT_LOG``) and the
  driver-side pod-correlated cluster rules;
* :mod:`~horovod_tpu.telemetry.aggregate` — step-id-joined cross-rank
  roll-ups (per-pod median/p99, cluster wire bytes, goodput series);
* :mod:`~horovod_tpu.telemetry.top` — the ``hvdtrun top`` live
  terminal view over ``/timeseries``.

Predicted-vs-observed attribution lives in :mod:`~horovod_tpu.
telemetry.step_stats`: ``hvd.init()`` prices the expected schedule
fingerprint (``HVDT_EXPECTED_SCHEDULE``) with the analytical cost model
and the StepTimer stream keeps ``hvdt_perf_deviation_ratio`` live.

Knobs: ``HVDT_TELEMETRY``, ``HVDT_METRICS_PORT``,
``HVDT_STRAGGLER_WINDOW``, ``HVDT_STRAGGLER_THRESHOLD``,
``HVDT_TELEMETRY_PUBLISH_S``, ``HVDT_HISTORY``/``HVDT_HISTORY_*``,
``HVDT_EVENT_LOG``, ``HVDT_PERF_DEVIATION_RATIO`` (common/config.py);
launcher flags ``hvdtrun --telemetry`` / ``--metrics-port``.  See
docs/observability.md for semantics and docs/metrics.md for the
generated metric catalog.
"""

from .metrics import (  # noqa: F401
    Counter,
    Gauge,
    MetricsRegistry,
    Summary,
    default_registry,
    reset_default_registry,
)
from .instrument import (  # noqa: F401
    CollectiveRecorder,
    enabled,
    get_recorder,
    wrap_step,
)
from .step_stats import (  # noqa: F401
    DeviationTracker,
    GoodputLedger,
    PerfExpectation,
    StepTimer,
    bind_resilience_gauges,
    expected_vs_observed_doc,
    get_deviation_tracker,
    maybe_publish_expected_cost,
    peak_flops_for,
    publish_expected_schedule_cost,
)
from .straggler import StragglerMonitor  # noqa: F401
from .history import (  # noqa: F401
    MetricHistory,
    Series,
    get_history,
)
from .anomaly import (  # noqa: F401
    AnomalyMonitor,
    ClusterAnomalyMonitor,
    EventLog,
    get_event_log,
    read_event_log,
)
from .aggregate import rollup  # noqa: F401
from .exporter import (  # noqa: F401
    MetricsExporter,
    bind_process_gauges,
    collect_driver_snapshots,
    get_exporter,
    maybe_start_exporter,
    snapshot_dict,
    start_exporter,
    stop_exporter,
)
from .trace import (  # noqa: F401
    Tracer,
    get_tracer,
    merge_dumps,
    step_trace_id,
)
from .flight_recorder import (  # noqa: F401
    FlightRecorder,
    analyze_desync,
    emit_desync_report,
    get_flight_recorder,
)

__all__ = [
    "Counter", "Gauge", "Summary", "MetricsRegistry",
    "default_registry", "reset_default_registry",
    "CollectiveRecorder", "enabled", "get_recorder", "wrap_step",
    "StepTimer", "GoodputLedger", "bind_resilience_gauges",
    "peak_flops_for", "StragglerMonitor",
    "PerfExpectation", "DeviationTracker", "get_deviation_tracker",
    "publish_expected_schedule_cost", "maybe_publish_expected_cost",
    "expected_vs_observed_doc",
    "MetricHistory", "Series", "get_history",
    "AnomalyMonitor", "ClusterAnomalyMonitor", "EventLog",
    "get_event_log", "read_event_log", "rollup",
    "MetricsExporter", "start_exporter", "stop_exporter", "get_exporter",
    "maybe_start_exporter", "snapshot_dict", "collect_driver_snapshots",
    "bind_process_gauges",
    "Tracer", "get_tracer", "merge_dumps", "step_trace_id",
    "FlightRecorder", "analyze_desync", "emit_desync_report",
    "get_flight_recorder",
]
