"""Unified telemetry: metrics registry, instrumentation, straggler
detection, and the per-worker /metrics exporter.

The observability layer the training stack was missing (the serving
plane had its own Prometheus-text metrics; training had Chrome traces
and ad-hoc module-level ints).  Layering, bottom up:

* :mod:`~horovod_tpu.telemetry.metrics` — Counter / Gauge / Summary
  primitives + the process-wide :func:`default_registry` (promoted out
  of ``serve/metrics.py``, which re-exports for back-compat);
* :mod:`~horovod_tpu.telemetry.instrument` — per-collective hook points
  threaded through the eager and jit data planes; zero-overhead identity
  objects when ``HVDT_TELEMETRY`` is off;
* :mod:`~horovod_tpu.telemetry.step_stats` — :class:`StepTimer`
  (step time, examples/s, MFU) and :class:`GoodputLedger` (time lost to
  recompiles / restores / recovered faults);
* :mod:`~horovod_tpu.telemetry.straggler` — cross-rank step-duration
  skew detection publishing a ``straggler_rank`` gauge;
* :mod:`~horovod_tpu.telemetry.exporter` — per-worker ``/metrics`` +
  ``/healthz`` + ``/flightrecorder`` HTTP endpoint (started by
  ``hvd.init()`` when enabled) and driver-side snapshot aggregation
  over the rendezvous KV;
* :mod:`~horovod_tpu.telemetry.trace` — distributed span tracing:
  bounded per-rank Chrome-trace buffers with deterministic per-step
  trace ids, merged driver-side into one rank-as-pid trace
  (``hvdtrun --trace-dir``);
* :mod:`~horovod_tpu.telemetry.flight_recorder` — always-cheap ring of
  recent collective events (seq/op/dtype/bytes/wire, in-flight vs done)
  + the cross-rank desync analyzer that names the first divergent
  collective on stall-abort.

Knobs: ``HVDT_TELEMETRY``, ``HVDT_METRICS_PORT``,
``HVDT_STRAGGLER_WINDOW``, ``HVDT_STRAGGLER_THRESHOLD``,
``HVDT_TELEMETRY_PUBLISH_S`` (common/config.py); launcher flags
``hvdtrun --telemetry`` / ``--metrics-port``.  See docs/observability.md
for the metric catalog and a scrape example.
"""

from .metrics import (  # noqa: F401
    Counter,
    Gauge,
    MetricsRegistry,
    Summary,
    default_registry,
    reset_default_registry,
)
from .instrument import (  # noqa: F401
    CollectiveRecorder,
    enabled,
    get_recorder,
    wrap_step,
)
from .step_stats import (  # noqa: F401
    GoodputLedger,
    StepTimer,
    bind_resilience_gauges,
    peak_flops_for,
)
from .straggler import StragglerMonitor  # noqa: F401
from .exporter import (  # noqa: F401
    MetricsExporter,
    bind_process_gauges,
    collect_driver_snapshots,
    get_exporter,
    maybe_start_exporter,
    snapshot_dict,
    start_exporter,
    stop_exporter,
)
from .trace import (  # noqa: F401
    Tracer,
    get_tracer,
    merge_dumps,
    step_trace_id,
)
from .flight_recorder import (  # noqa: F401
    FlightRecorder,
    analyze_desync,
    emit_desync_report,
    get_flight_recorder,
)

__all__ = [
    "Counter", "Gauge", "Summary", "MetricsRegistry",
    "default_registry", "reset_default_registry",
    "CollectiveRecorder", "enabled", "get_recorder", "wrap_step",
    "StepTimer", "GoodputLedger", "bind_resilience_gauges",
    "peak_flops_for", "StragglerMonitor",
    "MetricsExporter", "start_exporter", "stop_exporter", "get_exporter",
    "maybe_start_exporter", "snapshot_dict", "collect_driver_snapshots",
    "bind_process_gauges",
    "Tracer", "get_tracer", "merge_dumps", "step_trace_id",
    "FlightRecorder", "analyze_desync", "emit_desync_report",
    "get_flight_recorder",
]
