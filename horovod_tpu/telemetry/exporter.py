"""Per-worker /metrics HTTP exporter + driver-side snapshot aggregation.

Every training worker gets its own scrape endpoint (stdlib
``ThreadingHTTPServer``, same zero-dependency stance as the serving front
end): ``/metrics`` renders the process-wide default registry as
Prometheus text, ``/healthz`` answers liveness with rank/step.  The bind
port is ``HVDT_METRICS_PORT + local_rank`` (ranks on one host must not
collide; different hosts can share the base port), falling back to an
ephemeral port — with a logged warning — when the slot is taken, because
a scrape endpoint must never be the reason training didn't start.

``hvd.init()`` starts the exporter automatically when ``HVDT_TELEMETRY``
is on (:func:`maybe_start_exporter`); ``hvd.shutdown()`` stops it.

Driver-side aggregation: under the elastic launcher, each worker also
publishes a compact JSON snapshot to the rendezvous KV
(``/telemetry/<rank>``) at most every ``HVDT_TELEMETRY_PUBLISH_S``
seconds, and :func:`collect_driver_snapshots` (used by
``ElasticDriver.telemetry_snapshots``) reads them back — so the driver
can answer "what is the fleet's goodput / who is the straggler" without
scraping N worker endpoints itself.
"""

from __future__ import annotations

import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional

from ..common import config
from ..common.logging_util import get_logger
from .metrics import MetricsRegistry, default_registry

__all__ = ["MetricsExporter", "start_exporter", "stop_exporter",
           "get_exporter", "maybe_start_exporter", "snapshot_dict",
           "serve_snapshot_dict", "collect_driver_snapshots",
           "bind_process_gauges"]

log = get_logger(__name__)

KV_PREFIX = "/telemetry/"


def snapshot_dict(registry: Optional[MetricsRegistry] = None
                  ) -> Dict[str, Any]:
    """Compact, JSON-able roll-up of the headline training metrics — what
    workers publish to the driver and bench.py embeds in its output."""
    reg = registry if registry is not None else default_registry()
    out: Dict[str, Any] = {}
    # Snapshot schema v2 (tolerant): wall_ts + the current step id let
    # the driver step-align cross-rank roll-ups (telemetry/aggregate);
    # v1 consumers ignore the extra keys, v1 producers are skipped by
    # the aligned roll-up with a counted hvdt_snapshot_unaligned_total.
    out["wall_ts"] = round(time.time(), 3)
    bytes_total = reg.get("hvdt_collective_bytes_total")
    if bytes_total is not None:
        out["bytes_on_wire_total"] = bytes_total.total()
    coll = reg.get("hvdt_collectives_total")
    if coll is not None:
        out["collectives_total"] = coll.total()
    step_counter = reg.get("hvdt_steps_total")
    if step_counter is not None:
        out["step"] = int(step_counter.total())
    steps = reg.get("hvdt_step_time_seconds")
    if steps is not None and steps.count:
        pct = steps.percentiles()
        out["steps"] = steps.count
        out["step_time_p50_ms"] = (round(pct[0.5] * 1e3, 3)
                                   if pct[0.5] is not None else None)
        out["step_time_p99_ms"] = (round(pct[0.99] * 1e3, 3)
                                   if pct[0.99] is not None else None)
    for gname, key in (("hvdt_mfu", "mfu"),
                       ("hvdt_examples_per_sec", "examples_per_sec"),
                       ("hvdt_goodput_fraction", "goodput_fraction"),
                       ("hvdt_straggler_rank", "straggler_rank"),
                       ("hvdt_step_time_skew", "step_time_skew"),
                       ("hvdt_straggler_pod", "straggler_pod"),
                       ("hvdt_pod_step_time_skew", "pod_step_time_skew"),
                       ("hvdt_perf_deviation_ratio",
                        "perf_deviation_ratio"),
                       ("hvdt_expected_step_comm_seconds",
                        "expected_step_comm_seconds")):
        g = reg.get(gname)
        if g is not None:
            v = g.value()
            out[key] = round(v, 4) if v == v else None   # NaN-safe
    anomalies = reg.get("hvdt_anomaly_total")
    if anomalies is not None:
        out["anomaly_total"] = anomalies.total()
    # Time-series tail (HVDT_HISTORY): a short recent slice so the
    # driver can join ranks on step id without scraping /timeseries.
    from . import history as _history

    hist = _history.get_history()
    if hist is not None:
        out["timeseries"] = hist.to_dict(max_points=64)
    # Control-plane flakiness counters (runner/http_kv.py) — surfaced so
    # ElasticDriver.telemetry_snapshots() sees KV retries/errors per
    # worker without scraping N endpoints.
    for cname, key in (("hvdt_kv_retries_total", "kv_retries_total"),
                       ("hvdt_kv_errors_total", "kv_errors_total")):
        c = reg.get(cname)
        if c is not None:
            out[key] = c.total()
    # Pod membership (launcher contract): lets the driver aggregate
    # snapshots per pod for the straggler-eviction rung.
    pod = os.environ.get("HVDT_POD")
    if pod:
        out["pod"] = pod
    return out


def serve_snapshot_dict(registry: MetricsRegistry) -> Dict[str, Any]:
    """Replica-side roll-up of one serving registry — the load + latency
    story a replica heartbeats to the rendezvous KV
    (``/serve/replicas/<id>``, serve/replica.py) and the router and
    autoscaler route/scale on.  The serving analog of
    :func:`snapshot_dict`: queue depth is the leading load signal,
    predict p50/p99 the SLO signal, the counters the audit trail."""
    out: Dict[str, Any] = {}
    depth = registry.get("serve_queue_depth")
    if depth is not None:
        v = depth.value()
        out["queue_depth"] = v if v == v else 0.0   # NaN-safe
    lat = registry.get("serve_request_latency_ms_predict")
    if lat is not None and lat.count:
        pct = lat.percentiles()
        out["p50_ms"] = (round(pct[0.5], 3)
                         if pct[0.5] is not None else None)
        out["p99_ms"] = (round(pct[0.99], 3)
                         if pct[0.99] is not None else None)
    for cname, key in (("serve_requests_total", "requests_total"),
                       ("serve_rejected_total", "rejected_total"),
                       ("serve_batches_total", "batches_total"),
                       ("serve_deadline_expired_total",
                        "deadline_expired_total")):
        c = registry.get(cname)
        if c is not None:
            out[key] = c.total()
    draining = registry.get("serve_draining")
    if draining is not None:
        out["draining"] = bool(draining.value() == 1.0)
    # Continuous-engine extras (serve/llm): decode throughput and KV
    # occupancy ride the same heartbeat so the autoscaler and dashboards
    # see the LLM engine's load story without a second channel.
    tps = registry.get("hvdt_engine_tokens_per_sec")
    if tps is not None:
        out["engine"] = "continuous"
        v = tps.value()
        out["tokens_per_sec"] = round(v, 3) if v == v else 0.0
        for gname, key in (("hvdt_engine_kv_blocks_in_use",
                            "kv_blocks_in_use"),
                           ("hvdt_engine_active_seqs", "active_seqs")):
            g = registry.get(gname)
            if g is not None:
                gv = g.value()
                out[key] = gv if gv == gv else 0.0
    return out


class _Handler(BaseHTTPRequestHandler):
    exporter: "MetricsExporter"
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):
        log.debug("telemetry http: " + fmt, *args)

    def _reply(self, status: int, body: bytes, content_type: str) -> None:
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        exp = self.exporter
        route = self.path.split("?")[0]
        if route == "/metrics":
            self._reply(200, exp.registry.render().encode(),
                        "text/plain; version=0.0.4")
        elif route == "/healthz":
            steps = exp.registry.get("hvdt_steps_total")
            payload = {
                "status": "ok",
                "rank": exp.rank,
                "steps": (int(steps.total()) if steps is not None else 0),
            }
            self._reply(200, json.dumps(payload).encode(),
                        "application/json")
        elif route == "/timeseries":
            from . import history as _history

            hist = _history.get_history()
            if hist is None:
                self._reply(404, json.dumps({
                    "error": "metric history disabled "
                             "(set HVDT_HISTORY=1)"}).encode(),
                    "application/json")
            else:
                doc = hist.to_dict()
                doc["rank"] = exp.rank
                pod = os.environ.get("HVDT_POD")
                if pod:
                    doc["pod"] = pod
                steps = exp.registry.get("hvdt_steps_total")
                doc["step"] = (int(steps.total())
                               if steps is not None else 0)
                self._reply(200, json.dumps(doc).encode(),
                            "application/json")
        elif route == "/flightrecorder":
            from . import flight_recorder as _frm

            fr = _frm.get_flight_recorder()
            if fr is None:
                self._reply(404, json.dumps({
                    "error": "flight recorder disabled "
                             "(set HVDT_FLIGHT_RECORDER=1)"}).encode(),
                    "application/json")
            else:
                self._reply(200, json.dumps(fr.dump()).encode(),
                            "application/json")
        else:
            self._reply(404, json.dumps(
                {"error": f"no route {self.path!r}"}).encode(),
                "application/json")


class _HTTPServer(ThreadingHTTPServer):
    daemon_threads = True
    request_queue_size = 32


class MetricsExporter:
    """One worker's scrape endpoint (+ optional KV snapshot publisher)."""

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 host: str = "0.0.0.0", port: Optional[int] = None,
                 rank: int = 0, port_offset: Optional[int] = None,
                 kv_client: Optional[Any] = None,
                 publish_interval_s: Optional[float] = None):
        self.registry = (registry if registry is not None
                         else default_registry())
        self.host = host
        base = int(port if port is not None
                   else config.get_int("HVDT_METRICS_PORT"))
        self.rank = int(rank)
        offset = int(port_offset if port_offset is not None else 0)
        # port 0 = ephemeral on purpose (tests, many workers per host
        # without a port plan); otherwise base + per-host offset.
        self.port = base + offset if base > 0 else 0
        self._kv = kv_client
        self.publish_interval_s = float(
            publish_interval_s if publish_interval_s is not None
            else config.get_float("HVDT_TELEMETRY_PUBLISH_S"))
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._publisher: Optional[threading.Thread] = None
        self._stop = threading.Event()

    def start(self) -> int:
        """Bind and serve in a daemon thread; returns the bound port."""
        handler = type("Handler", (_Handler,), {"exporter": self})
        try:
            self._httpd = _HTTPServer((self.host, self.port), handler)
        except OSError as e:
            # The configured slot is taken (another worker, a stale
            # process) — an ephemeral port with a loud log beats dying.
            log.warning("metrics port %d unavailable (%s); "
                        "binding an ephemeral port", self.port, e)
            self._httpd = _HTTPServer((self.host, 0), handler)
        self.port = self._httpd.server_address[1]
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="hvdt-metrics-http",
            daemon=True)
        self._thread.start()
        if self._kv is not None and self.publish_interval_s > 0:
            self._publisher = threading.Thread(
                target=self._publish_loop, name="hvdt-metrics-publish",
                daemon=True)
            self._publisher.start()
        log.info("telemetry /metrics on http://%s:%d (rank %d)",
                 self.host, self.port, self.rank)
        return self.port

    def stop(self) -> None:
        self._stop.set()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if self._publisher is not None:
            self._publisher.join(timeout=5)
            self._publisher = None

    # -- KV snapshot publishing (driver-side aggregation feed) -------------
    def publish_snapshot(self) -> bool:
        """Push one compact snapshot to the rendezvous KV (best-effort);
        also refreshes this rank's trace and flight-recorder dumps so
        the driver-side merge / desync gather sees recent data even from
        a worker that later dies without flushing."""
        if self._kv is None:
            return False
        try:
            doc = snapshot_dict(self.registry)
            doc["ts"] = time.time()
            self._kv.put(f"{KV_PREFIX}{self.rank}",
                         json.dumps(doc).encode())
        except Exception as e:
            log.debug("telemetry KV publish failed: %s", e)
            return False
        try:
            from . import flight_recorder as _frm
            from . import trace as _trace

            tracer = _trace.get_tracer()
            if tracer is not None:
                tracer.publish(self._kv, self.rank)
            fr = _frm.get_flight_recorder()
            if fr is not None:
                fr.publish(self._kv, self.rank)
        except Exception as e:
            log.debug("trace/flight KV publish failed: %s", e)
        return True

    def _publish_loop(self) -> None:
        while not self._stop.wait(self.publish_interval_s):
            self.publish_snapshot()


def bind_process_gauges(registry: Optional[MetricsRegistry] = None) -> None:
    """Publish process resource usage as live-probe gauges: RSS, open
    file descriptors, and device HBM in use.

    Live probes (``set_function``), read at scrape time.  Every probe is
    guarded: ``/proc`` may be absent (non-Linux), and
    ``jax.Device.memory_stats()`` returns ``None`` on CPU backends and
    older jax (0.4.37 in the container) — an unavailable number renders
    as ``nan``, never an exception.  Idempotent (gauges are
    get-or-create; rebinding the probe is a no-op in effect)."""
    import os as _os

    reg = registry if registry is not None else default_registry()

    def _rss() -> float:
        try:
            with open("/proc/self/statm") as fh:
                pages = int(fh.read().split()[1])
            return float(pages * _os.sysconf("SC_PAGE_SIZE"))
        except (OSError, ValueError, IndexError):
            try:
                import resource

                # ru_maxrss is KiB on Linux (peak, not live — the
                # portable fallback when /proc is unavailable).
                return float(resource.getrusage(
                    resource.RUSAGE_SELF).ru_maxrss * 1024)
            except Exception:
                return float("nan")

    def _fds() -> float:
        try:
            return float(len(_os.listdir("/proc/self/fd")))
        except OSError:
            return float("nan")

    def _hbm() -> float:
        try:
            import jax

            stats = jax.local_devices()[0].memory_stats()
            if not stats:   # CPU backends / jax 0.4.37 return None
                return float("nan")
            return float(stats.get("bytes_in_use", float("nan")))
        except Exception:
            return float("nan")

    def _hbm_peak() -> float:
        try:
            import jax

            stats = jax.local_devices()[0].memory_stats()
            if not stats:
                return float("nan")
            return float(stats.get("peak_bytes_in_use", float("nan")))
        except Exception:
            return float("nan")

    reg.gauge(
        "hvdt_process_rss_bytes",
        "Resident set size of this worker process (live /proc probe; "
        "peak-RSS fallback where /proc is unavailable)"
    ).set_function(_rss)
    reg.gauge(
        "hvdt_process_open_fds",
        "Open file descriptors of this worker process (nan off-Linux)"
    ).set_function(_fds)
    reg.gauge(
        "hvdt_hbm_bytes_in_use",
        "Live device memory in use (jax.Device.memory_stats; nan on CPU "
        "backends and jax builds where memory_stats returns None)"
    ).set_function(_hbm)
    reg.gauge(
        "hvdt_hbm_peak_bytes",
        "Peak device memory in use since process start "
        "(jax.Device.memory_stats peak_bytes_in_use; nan where "
        "unavailable) — pair with hvdt_param_bytes / "
        "hvdt_optimizer_state_bytes to see the ZeRO/remat headroom"
    ).set_function(_hbm_peak)
    # Memory-accounting gauges (fed by step_stats.record_memory_
    # accounting — ops/zero.py and bench.py report per-rank
    # post-sharding bytes): registered here so they exist on /metrics
    # from init, NaN until the training loop reports.
    from .step_stats import _MEMORY_GAUGE_DOCS

    for name, doc in _MEMORY_GAUGE_DOCS.items():
        g = reg.gauge(name, doc)
        if g.value() == 0.0:
            g.set(float("nan"))


def collect_driver_snapshots(kv_server) -> Dict[int, Dict[str, Any]]:
    """Read every worker's published snapshot out of the rendezvous KV
    store (driver side).  ``kv_server`` is a RendezvousServer (has
    ``lock``/``store``)."""
    out: Dict[int, Dict[str, Any]] = {}
    with kv_server.lock:
        items = {k: v for k, v in kv_server.store.items()
                 if k.startswith(KV_PREFIX)}
    for key, raw in items.items():
        try:
            rank = int(key[len(KV_PREFIX):])
            out[rank] = json.loads(raw.decode())
        except (ValueError, UnicodeDecodeError):
            continue
    return out


# ---------------------------------------------------------------------------
# Process-wide exporter lifecycle (hvd.init() / hvd.shutdown() hooks)
# ---------------------------------------------------------------------------

_exp_lock = threading.Lock()
_exporter: Optional[MetricsExporter] = None


def get_exporter() -> Optional[MetricsExporter]:
    return _exporter


def start_exporter(**kwargs) -> MetricsExporter:
    """Start (or return) the process-wide exporter."""
    global _exporter
    with _exp_lock:
        if _exporter is None:
            _exporter = MetricsExporter(**kwargs)
            _exporter.start()
        return _exporter


def stop_exporter() -> None:
    global _exporter
    with _exp_lock:
        if _exporter is not None:
            _exporter.stop()
            _exporter = None


def maybe_start_exporter(topology=None) -> Optional[MetricsExporter]:
    """The ``hvd.init()`` hook: start the exporter iff telemetry is on.

    Never raises — observability must not sink init.  Uses local_rank as
    the port offset (ranks sharing a host need distinct ports; hosts can
    share the base), binds the KV publisher when the launcher's
    rendezvous env contract is present, and arms the resilience bridge
    gauges so one scrape carries the recovery story too."""
    from . import instrument

    if not instrument.enabled():
        return None
    try:
        rank = getattr(topology, "rank", 0) or 0
        local_rank = getattr(topology, "local_rank", 0) or 0
        kv = None
        if config.get_str("HVDT_RENDEZVOUS_ADDR"):
            try:
                from ..runner.http_kv import KVClient

                kv = KVClient.from_env()
            except Exception as e:
                log.debug("telemetry KV client unavailable: %s", e)
        from .step_stats import bind_resilience_gauges

        bind_resilience_gauges()
        bind_process_gauges()
        return start_exporter(rank=rank,
                              port_offset=max(0, int(local_rank)),
                              kv_client=kv)
    except Exception as e:   # pragma: no cover - defensive
        log.warning("telemetry exporter not started: %s", e)
        return None
