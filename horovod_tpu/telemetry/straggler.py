"""Cross-rank straggler detection from step-duration skew.

The TPU-pod scaling study's observation: at scale the binding question
is often *which rank* is slow — one throttled host drags every
synchronous collective.  The stall inspector (``stall.py``) only sees a
rank that stopped *submitting*; a straggler submits fine, just late, and
is invisible to it.  This monitor closes that gap with data: every
``HVDT_STRAGGLER_WINDOW`` locally-observed steps it allgathers each
rank's mean step duration over the eager negotiated path (itself
instrumented, so the probe's wire cost is visible in the same registry),
compares ranks against the median, and

* logs the outlier ranks with their slowdown ratios,
* publishes ``hvdt_straggler_rank`` (worst offender, -1 = none) and
  ``hvdt_step_time_skew`` (max/median ratio) gauges,
* invokes ``on_straggler(rank, ratio)`` — the hook that feeds the stall
  escalation ladder (or a scheduler's drain list) a real signal instead
  of a timeout guess.

Single-process runs (size 1, or hvd not initialized) skip the gather and
publish skew 1.0 — the monitor is safe to leave on everywhere.
"""

from __future__ import annotations

import threading
from typing import Callable, List, Optional

from ..common import config
from ..common.logging_util import get_logger
from .metrics import MetricsRegistry, default_registry

__all__ = ["StragglerMonitor"]

log = get_logger(__name__)


class StragglerMonitor:
    def __init__(self, window: Optional[int] = None,
                 threshold: Optional[float] = None,
                 registry: Optional[MetricsRegistry] = None,
                 allgather_fn: Optional[Callable[[float], Optional[List[float]]]] = None,
                 rank: Optional[int] = None,
                 on_straggler: Optional[Callable[[int, float], None]] = None):
        """``allgather_fn(local_mean) -> per-rank means or None`` is
        injectable for tests and custom transports; the default rides
        the eager negotiated allgather when hvd is initialized."""
        self.window = int(window if window is not None
                          else config.get_int("HVDT_STRAGGLER_WINDOW"))
        self.threshold = float(
            threshold if threshold is not None
            else config.get_float("HVDT_STRAGGLER_THRESHOLD"))
        reg = registry if registry is not None else default_registry()
        self.registry = reg
        self._allgather = allgather_fn or self._eager_allgather
        self._rank_override = rank
        self.on_straggler = on_straggler
        self._lock = threading.Lock()
        self._durations: List[float] = []
        self._round = 0
        self.straggler_rank_gauge = reg.gauge(
            "hvdt_straggler_rank",
            "Rank whose mean step time most exceeds threshold x median "
            "over the last window (-1 = no straggler)")
        self.skew_gauge = reg.gauge(
            "hvdt_step_time_skew",
            "max(rank mean step time) / median over the last window")
        self.checks_counter = reg.counter(
            "hvdt_straggler_checks_total",
            "Cross-rank straggler checks performed")
        self.flagged_counter = reg.counter(
            "hvdt_straggler_flags_total",
            "Straggler detections, labelled by offending rank")
        self.straggler_rank_gauge.set(-1)
        self.skew_gauge.set(1.0)

    # -- observation stream -------------------------------------------------
    def observe(self, step_seconds: float) -> None:
        """Feed one local step duration; triggers a cross-rank check every
        ``window`` observations (window <= 0 disables)."""
        if self.window <= 0:
            return
        with self._lock:
            self._durations.append(float(step_seconds))
            if len(self._durations) < self.window:
                return
            durations, self._durations = self._durations, []
        self.check(sum(durations) / len(durations))

    # -- the cross-rank check ----------------------------------------------
    def check(self, local_mean: float) -> Optional[int]:
        """Allgather per-rank means and flag outliers.  Returns the worst
        straggler rank, or None."""
        with self._lock:
            self._round += 1
        try:
            means = self._allgather(float(local_mean))
        except Exception as e:  # a flaky probe must not sink training
            log.debug("straggler allgather failed: %s", e)
            return None
        self.checks_counter.inc()
        if not means or len(means) < 2:
            self.skew_gauge.set(1.0)
            self.straggler_rank_gauge.set(-1)
            return None
        ordered = sorted(means)
        # Lower median: with few ranks (or half the fleet slow) the upper
        # median can BE the straggler, hiding it behind skew 1.0 — biasing
        # the baseline toward the fast half is the conservative choice
        # for a detector.
        median = ordered[(len(ordered) - 1) // 2]
        worst_rank = max(range(len(means)), key=lambda r: means[r])
        worst = means[worst_rank]
        skew = (worst / median) if median > 0 else 1.0
        self.skew_gauge.set(skew)
        if skew <= self.threshold:
            self.straggler_rank_gauge.set(-1)
            return None
        outliers = [(r, m / median) for r, m in enumerate(means)
                    if median > 0 and m / median > self.threshold]
        log.warning(
            "straggler detected: rank %d mean step %.4fs is %.2fx the "
            "median %.4fs (all outliers: %s)",
            worst_rank, worst, skew,
            median, [(r, round(x, 2)) for r, x in outliers])
        self.straggler_rank_gauge.set(worst_rank)
        for r, _ in outliers:
            self.flagged_counter.inc(rank=str(r))
        if self.on_straggler is not None:
            try:
                self.on_straggler(worst_rank, skew)
            except Exception as e:
                log.debug("on_straggler hook failed: %s", e)
        return worst_rank

    # -- default transport --------------------------------------------------
    def _eager_allgather(self, local_mean: float) -> Optional[List[float]]:
        """Gather per-rank means over the eager negotiated path.  The
        tensor name carries the round counter — every rank reaches round
        N after the same N windows, so names line up without extra
        coordination."""
        from ..common import basics

        state = basics._global_state()
        if not state.initialized or state.topology is None:
            return None
        # Size 1 still rides the controller (single-rank collectives are
        # the identity): the probe's own wire accounting stays visible
        # in the registry, and single-process harnesses (bench.py)
        # exercise the full instrumented path.
        import numpy as np

        from ..ops import eager

        arr = np.asarray([local_mean], np.float64)
        out = eager.allgather(
            arr, name=f"hvdt.telemetry.straggler.{self._round}")
        return [float(v) for v in np.asarray(out).reshape(-1)]
