"""Cross-rank straggler detection from step-duration skew.

The TPU-pod scaling study's observation: at scale the binding question
is often *which rank* is slow — one throttled host drags every
synchronous collective.  The stall inspector (``stall.py``) only sees a
rank that stopped *submitting*; a straggler submits fine, just late, and
is invisible to it.  This monitor closes that gap with data: every
``HVDT_STRAGGLER_WINDOW`` locally-observed steps it allgathers each
rank's mean step duration over the eager negotiated path (itself
instrumented, so the probe's wire cost is visible in the same registry),
compares ranks against the median, and

* logs the outlier ranks with their slowdown ratios,
* publishes ``hvdt_straggler_rank`` (worst offender, -1 = none) and
  ``hvdt_step_time_skew`` (max/median ratio) gauges,
* invokes ``on_straggler(rank, ratio)`` — the hook that feeds the stall
  escalation ladder (or a scheduler's drain list) a real signal instead
  of a timeout guess.

Single-process runs (size 1, or hvd not initialized) skip the gather and
publish skew 1.0 — the monitor is safe to leave on everywhere.
"""

from __future__ import annotations

import threading
from typing import Callable, List, Optional

from ..common import config
from ..common.logging_util import get_logger
from .metrics import MetricsRegistry, default_registry

__all__ = ["StragglerMonitor"]

log = get_logger(__name__)


class StragglerMonitor:
    def __init__(self, window: Optional[int] = None,
                 threshold: Optional[float] = None,
                 registry: Optional[MetricsRegistry] = None,
                 allgather_fn: Optional[Callable[[float], Optional[List[float]]]] = None,
                 rank: Optional[int] = None,
                 on_straggler: Optional[Callable[[int, float], None]] = None,
                 pod_size: Optional[int] = None,
                 on_pod_straggler: Optional[Callable[[int, float],
                                                     None]] = None):
        """``allgather_fn(local_mean) -> per-rank means or None`` is
        injectable for tests and custom transports; the default rides
        the eager negotiated allgather when hvd is initialized.

        ``pod_size`` (default: the launcher's ``HVDT_POD_SIZE`` env
        contract) adds the pod dimension: ranks are contiguous within a
        pod (runner/elastic/pods.py layout), so rank r belongs to pod
        index r // pod_size, and every check also compares per-pod mean
        step times — the signal the driver's pod-eviction rung consumes
        (``hvdt_straggler_pod`` / ``hvdt_pod_step_time_skew`` gauges,
        ``on_pod_straggler(pod_index, ratio)`` hook)."""
        self.window = int(window if window is not None
                          else config.get_int("HVDT_STRAGGLER_WINDOW"))
        self.threshold = float(
            threshold if threshold is not None
            else config.get_float("HVDT_STRAGGLER_THRESHOLD"))
        if pod_size is None:
            pod_size = config.get_int("HVDT_POD_SIZE")
        self.pod_size = int(pod_size or 0)
        reg = registry if registry is not None else default_registry()
        self.registry = reg
        self._allgather = allgather_fn or self._eager_allgather
        self._rank_override = rank
        self.on_straggler = on_straggler
        self.on_pod_straggler = on_pod_straggler
        self._lock = threading.Lock()
        self._durations: List[float] = []
        self._round = 0
        self.straggler_rank_gauge = reg.gauge(
            "hvdt_straggler_rank",
            "Rank whose mean step time most exceeds threshold x median "
            "over the last window (-1 = no straggler)")
        self.skew_gauge = reg.gauge(
            "hvdt_step_time_skew",
            "max(rank mean step time) / median over the last window")
        self.checks_counter = reg.counter(
            "hvdt_straggler_checks_total",
            "Cross-rank straggler checks performed")
        self.flagged_counter = reg.counter(
            "hvdt_straggler_flags_total",
            "Straggler detections, labelled by offending rank (and pod "
            "when the pod contract is present)")
        self.straggler_rank_gauge.set(-1)
        self.skew_gauge.set(1.0)
        self.straggler_pod_gauge = reg.gauge(
            "hvdt_straggler_pod",
            "Pod index whose mean step time most exceeds threshold x "
            "the cross-pod median over the last window (-1 = none; "
            "ranks are contiguous per pod, pod = rank // HVDT_POD_SIZE)")
        self.pod_skew_gauge = reg.gauge(
            "hvdt_pod_step_time_skew",
            "max(pod mean step time) / cross-pod median over the last "
            "window")
        self.straggler_pod_gauge.set(-1)
        self.pod_skew_gauge.set(1.0)

    # -- observation stream -------------------------------------------------
    def observe(self, step_seconds: float) -> None:
        """Feed one local step duration; triggers a cross-rank check every
        ``window`` observations (window <= 0 disables)."""
        if self.window <= 0:
            return
        with self._lock:
            self._durations.append(float(step_seconds))
            if len(self._durations) < self.window:
                return
            durations, self._durations = self._durations, []
        self.check(sum(durations) / len(durations))

    # -- the cross-rank check ----------------------------------------------
    def check(self, local_mean: float) -> Optional[int]:
        """Allgather per-rank means and flag outliers.  Returns the worst
        straggler rank, or None."""
        with self._lock:
            self._round += 1
        try:
            means = self._allgather(float(local_mean))
        except Exception as e:  # a flaky probe must not sink training
            log.debug("straggler allgather failed: %s", e)
            return None
        self.checks_counter.inc()
        if not means or len(means) < 2:
            self.skew_gauge.set(1.0)
            self.straggler_rank_gauge.set(-1)
            return None
        self._pod_check(means)
        ordered = sorted(means)
        # Lower median: with few ranks (or half the fleet slow) the upper
        # median can BE the straggler, hiding it behind skew 1.0 — biasing
        # the baseline toward the fast half is the conservative choice
        # for a detector.
        median = ordered[(len(ordered) - 1) // 2]
        worst_rank = max(range(len(means)), key=lambda r: means[r])
        worst = means[worst_rank]
        skew = (worst / median) if median > 0 else 1.0
        self.skew_gauge.set(skew)
        if skew <= self.threshold:
            self.straggler_rank_gauge.set(-1)
            return None
        outliers = [(r, m / median) for r, m in enumerate(means)
                    if median > 0 and m / median > self.threshold]
        log.warning(
            "straggler detected: rank %d mean step %.4fs is %.2fx the "
            "median %.4fs (all outliers: %s)",
            worst_rank, worst, skew,
            median, [(r, round(x, 2)) for r, x in outliers])
        self.straggler_rank_gauge.set(worst_rank)
        pod_of = (lambda r: str(r // self.pod_size)) \
            if self.pod_size > 1 else (lambda r: "")
        for r, _ in outliers:
            if self.pod_size > 1:
                self.flagged_counter.inc(rank=str(r), pod=pod_of(r))
            else:
                self.flagged_counter.inc(rank=str(r))
        if self.on_straggler is not None:
            try:
                self.on_straggler(worst_rank, skew)
            except Exception as e:
                log.debug("on_straggler hook failed: %s", e)
        return worst_rank

    def _pod_check(self, means: List[float]) -> Optional[int]:
        """The pod dimension of the cross-rank check: fold per-rank
        means into per-pod means (contiguous pod layout) and flag a pod
        whose mean exceeds threshold x the cross-pod (lower) median.
        Publishes the pod gauges; returns the worst pod index or None.
        Skipped (gauges stay -1 / 1.0) without a multi-pod world."""
        n_pods = len(means) // self.pod_size if self.pod_size > 1 else 0
        if n_pods < 2:
            return None
        pod_means = [
            sum(means[p * self.pod_size:(p + 1) * self.pod_size])
            / self.pod_size for p in range(n_pods)]
        ordered = sorted(pod_means)
        median = ordered[(len(ordered) - 1) // 2]
        worst_pod = max(range(n_pods), key=lambda p: pod_means[p])
        skew = (pod_means[worst_pod] / median) if median > 0 else 1.0
        self.pod_skew_gauge.set(skew)
        if skew <= self.threshold:
            self.straggler_pod_gauge.set(-1)
            return None
        log.warning(
            "straggler pod detected: pod %d mean step %.4fs is %.2fx "
            "the cross-pod median %.4fs",
            worst_pod, pod_means[worst_pod], skew, median)
        self.straggler_pod_gauge.set(worst_pod)
        if self.on_pod_straggler is not None:
            try:
                self.on_pod_straggler(worst_pod, skew)
            except Exception as e:
                log.debug("on_pod_straggler hook failed: %s", e)
        return worst_pod

    # -- default transport --------------------------------------------------
    def _eager_allgather(self, local_mean: float) -> Optional[List[float]]:
        """Gather per-rank means over the eager negotiated path.  The
        tensor name carries the round counter — every rank reaches round
        N after the same N windows, so names line up without extra
        coordination."""
        from ..common import basics

        state = basics._global_state()
        if not state.initialized or state.topology is None:
            return None
        # Size 1 still rides the controller (single-rank collectives are
        # the identity): the probe's own wire accounting stays visible
        # in the registry, and single-process harnesses (bench.py)
        # exercise the full instrumented path.
        import numpy as np

        from ..ops import eager

        arr = np.asarray([local_mean], np.float64)
        out = eager.allgather(
            arr, name=f"hvdt.telemetry.straggler.{self._round}")
        return [float(v) for v in np.asarray(out).reshape(-1)]
