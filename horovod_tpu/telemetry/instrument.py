"""Per-collective instrumentation hooks — zero-overhead when disabled.

The recording surface the data planes call into: the eager negotiated
path (``ops/eager.py``) records per-execution bytes/latency, the jit path
(``ops/device.fused_allreduce``, ``quant/collectives``) records at trace
time (one record per compiled bucket — under jit the program, not the
host, executes the collective), and the timeline writer double-records
its Chrome-trace spans into latency summaries so aggregate percentiles
exist without opening the trace in a viewer.

Zero-overhead contract (same pattern as ``resilience/faults.get_injector``):
with ``HVDT_TELEMETRY`` unset/0, :func:`get_recorder` returns ``None`` —
one env read and a string compare — and :func:`wrap_step` returns its
argument **unchanged** (``wrap_step(fn) is fn``), so hot paths carry no
wrapper objects and no metric lookups.  Tests identity-check both.

Metric catalog (docs/observability.md has the full table):

* ``hvdt_collective_bytes_total{op,dtype,wire,path[,axis]}`` — bytes on
  wire (jit paths label the mesh axis the collective reduces over;
  hierarchical transport records one series per tier hop)
* ``hvdt_collectives_total{op,dtype,wire,path[,axis]}`` — collective count
* ``hvdt_wire_bytes_total{axis,wire}`` — per-mesh-axis wire bytes (the
  hierarchical-savings view: compare the dcn-axis series against the
  ici-axis series on /metrics)
* ``hvdt_collective_negotiate_seconds`` — announce → response (eager)
* ``hvdt_collective_queue_seconds``     — enqueue → announce (eager)
* ``hvdt_collective_execute_seconds``   — dispatch duration (eager)
* ``hvdt_fusion_fill_ratio``            — fused-bucket bytes / threshold
* ``hvdt_phase_<PHASE>_seconds``        — timeline span durations
"""

from __future__ import annotations

import os
import re
import threading
from typing import Callable, Optional

from .metrics import MetricsRegistry, default_registry

__all__ = ["enabled", "get_recorder", "CollectiveRecorder", "wrap_step",
           "reset"]

_TRUTHY = ("1", "true", "yes", "on")


def enabled() -> bool:
    """Whether the telemetry subsystem is on (``HVDT_TELEMETRY``)."""
    return os.environ.get("HVDT_TELEMETRY", "").strip().lower() in _TRUTHY


_phase_re = re.compile(r"[^a-zA-Z0-9_]")


class CollectiveRecorder:
    """Bound metric handles for the instrumentation hot paths.

    Constructed once per (enable-cycle, registry); every method is a
    couple of dict-free attribute loads plus one locked float update —
    cheap enough for the eager controller's execution path, and the jit
    path only calls at trace time anyway.
    """

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        reg = registry if registry is not None else default_registry()
        self.registry = reg
        self._bytes = reg.counter(
            "hvdt_collective_bytes_total",
            "Bytes on the wire per collective, labelled op/dtype/wire/path "
            "(path=eager counts executions; path=jit counts traced "
            "programs — multiply by step count for wire volume)")
        self._count = reg.counter(
            "hvdt_collectives_total",
            "Collectives recorded, labelled op/dtype/wire/path")
        self._wire_bytes = reg.counter(
            "hvdt_wire_bytes_total",
            "Bytes on the wire per mesh axis (axis/wire labels) — the "
            "per-tier view of hierarchical transport policies: int8 on "
            "the slow dcn axis shows up as that axis's series shrinking "
            "relative to the ici series")
        self._negotiate = reg.summary(
            "hvdt_collective_negotiate_seconds",
            "Eager-path announce -> negotiated-response latency")
        self._queue = reg.summary(
            "hvdt_collective_queue_seconds",
            "Eager-path enqueue -> announce latency (time spent waiting "
            "for the background cycle)")
        self._execute = reg.summary(
            "hvdt_collective_execute_seconds",
            "Eager-path response dispatch duration")
        self._fusion_fill = reg.summary(
            "hvdt_fusion_fill_ratio",
            "Fused-allreduce bucket occupancy: bucket bytes / "
            "HVDT_FUSION_THRESHOLD")
        self._step_dispatch = reg.summary(
            "hvdt_step_dispatch_seconds",
            "donated_step call duration (async dispatch interval, not "
            "device step time — see hvdt_step_time_seconds for the "
            "host-fenced number)")
        self._overlap_hidden = reg.counter(
            "hvdt_overlap_hidden_bytes_total",
            "Collective bytes issued with compute still scheduled under "
            "their flight window by the overlap scheduler (ops/overlap)")
        self._overlap_total = reg.counter(
            "hvdt_overlap_bytes_total",
            "Total collective bytes scheduled by the overlap scheduler")
        self._overlap_fraction = reg.gauge(
            "hvdt_overlap_fraction",
            "Hidden ÷ total collective bytes across overlapped exchange "
            "schedules (byte-weighted proxy for collective-seconds "
            "hidden ÷ total; recorded at trace time, path=jit "
            "convention)")

    # -- collectives --------------------------------------------------------
    def record_collective(self, op: str, dtype: str, wire: str,
                          nbytes: float, count: int = 1,
                          path: str = "eager", axis: str = "") -> None:
        """``axis`` (when known — the jit paths pass the mesh axis/tier
        the collective reduces over) adds an axis label to the main
        counters AND books the per-axis ``hvdt_wire_bytes_total``
        series; empty (eager/negotiated paths, where the reduce group
        is a process set, not a mesh axis) keeps the legacy label set."""
        labels = dict(op=str(op).lower(), dtype=str(dtype),
                      wire=str(wire), path=path)
        if axis:
            labels["axis"] = str(axis)
            self._wire_bytes.inc(float(nbytes), axis=str(axis),
                                 wire=str(wire))
        self._bytes.inc(float(nbytes), **labels)
        self._count.inc(float(count), **labels)

    def observe_queue(self, seconds: float) -> None:
        self._queue.observe(seconds)

    def observe_negotiate(self, seconds: float) -> None:
        self._negotiate.observe(seconds)

    def observe_execute(self, seconds: float) -> None:
        self._execute.observe(seconds)

    def observe_fusion_fill(self, ratio: float) -> None:
        self._fusion_fill.observe(ratio)

    def observe_overlap(self, hidden_bytes: float,
                        total_bytes: float) -> None:
        """One overlapped exchange schedule's byte accounting; the gauge
        tracks the cumulative hidden/total ratio."""
        self._overlap_hidden.inc(float(hidden_bytes))
        self._overlap_total.inc(float(total_bytes))
        total = self._overlap_total.value()
        if total > 0:
            self._overlap_fraction.set(
                self._overlap_hidden.value() / total)

    def observe_step_dispatch(self, seconds: float) -> None:
        self._step_dispatch.observe(seconds)

    # -- timeline double-record --------------------------------------------
    def observe_phase(self, phase: str, seconds: float) -> None:
        """Record a timeline span (NEGOTIATE_ALLREDUCE, EXEC_ALLGATHER, ...)
        into a per-phase latency summary."""
        name = _phase_re.sub("_", str(phase)).strip("_") or "unnamed"
        self.registry.summary(
            f"hvdt_phase_{name}_seconds",
            f"Timeline span duration for phase {phase}").observe(seconds)


# ---------------------------------------------------------------------------
# Process-wide recorder (env-gated, cached on the raw env string so per-test
# monkeypatching rebuilds it — same idiom as resilience/faults.get_injector)
# ---------------------------------------------------------------------------

_lock = threading.Lock()
_cached_env: Optional[str] = "\0unset"   # sentinel != any real env value
_cached_recorder: Optional[CollectiveRecorder] = None


def get_recorder() -> Optional[CollectiveRecorder]:
    """The process-wide recorder, or ``None`` when telemetry is disabled.

    The disabled steady state costs one environ read and a string
    compare; instrumentation sites branch on ``is None`` and touch
    nothing else."""
    global _cached_env, _cached_recorder
    raw = os.environ.get("HVDT_TELEMETRY")
    if raw != _cached_env:
        with _lock:
            if raw != _cached_env:
                _cached_recorder = (CollectiveRecorder()
                                    if enabled() else None)
                _cached_env = raw
    return _cached_recorder


def reset() -> None:
    """Drop the cached recorder so the next :func:`get_recorder` rebinds
    against the (possibly reset) default registry — test isolation."""
    global _cached_env, _cached_recorder
    with _lock:
        _cached_env = "\0unset"
        _cached_recorder = None


def wrap_step(fn: Callable) -> Callable:
    """Wrap a jitted step so each call's dispatch duration is recorded
    (metric summary, and a span + step-counter advance when the
    distributed tracer is on — trace.py derives the deterministic
    per-step trace ids from that counter).

    Zero-overhead contract: with both ``HVDT_TELEMETRY`` and
    ``HVDT_TRACE_DIR`` unset this returns ``fn`` ITSELF (no wrapper
    object, identity-tested).  The wrapper forwards attribute access
    (``.lower()``, ``.trace()``, static-arg plumbing) to the jitted
    callable so it stays a drop-in."""
    from . import trace as _trace

    if get_recorder() is None and _trace.get_tracer() is None:
        return fn
    return _TimedStep(fn)


class _TimedStep:
    """Attribute-forwarding timing shim around a jitted callable."""

    __slots__ = ("_fn",)

    def __init__(self, fn: Callable):
        self._fn = fn

    def __call__(self, *args, **kwargs):
        from . import trace as _trace

        rec = get_recorder()
        tracer = _trace.get_tracer()
        if rec is None and tracer is None:
            return self._fn(*args, **kwargs)
        import time

        t0 = time.perf_counter()
        out = self._fn(*args, **kwargs)
        dur = time.perf_counter() - t0
        if rec is not None:
            rec.observe_step_dispatch(dur)
        if tracer is not None:
            tracer.step_span(dur)
        return out

    def __getattr__(self, name):
        return getattr(self._fn, name)
