"""``hvdtrun top`` — a live terminal view over worker ``/timeseries``.

The operator's "why is this job slow" glance without a Grafana stack:
polls one or more workers' ``/timeseries`` endpoints (the history layer,
``HVDT_HISTORY``) and renders, per refresh,

* a per-rank step-time sparkline with current/median step time,
* goodput fraction, MFU, and the perf-deviation ratio where published,
* the worst pod by recent step time,
* the tail of the anomaly event log (``--event-log``),
* the last few policy-controller decisions (``controller_decision`` /
  ``controller_outcome`` records in the same event log): event ->
  chosen action -> predicted delta -> outcome.

Example frame::

    hvdt top — 2 ranks, step 128
    rank  pod    step time                         last     p50    dev
       0  podA   ▂▂▂▁▂▂▂▂▂▂▂▂▂▂▂▂▂▂▂▂▂▂▂▂       50.1ms  50.0ms  1.00
       1  podB   ▂▂▂▂▂▂▂▂█▂▂▂▂▂▂▂▂▂▂▂▂▂▂▂       50.3ms  50.2ms  1.02
    goodput 0.98   worst pod: podB
    anomalies:
      [step 88] step_time_shift rank=1 pod=podB: ...

Pure stdlib (urllib); ``--once`` prints a single frame and exits — the
scriptable/testable mode.  The refresh loop waits on an Event, not a
sleep poll, so Ctrl-C lands immediately.
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import urllib.request
from typing import Any, Dict, List, Optional

__all__ = ["main", "sparkline", "render_frame", "fetch_timeseries",
           "controller_lines", "fleet_lines"]

_CONTROLLER_KINDS = ("controller_decision", "controller_outcome")
_FLEET_KINDS = ("fleet_decision", "fleet_outcome")

_BLOCKS = "▁▂▃▄▅▆▇█"


def sparkline(values: List[float], width: int = 24) -> str:
    """Unicode block sparkline of the most recent ``width`` values,
    scaled to the window's own min/max (a flat series renders flat)."""
    vals = [float(v) for v in values][-width:]
    if not vals:
        return ""
    lo, hi = min(vals), max(vals)
    if hi <= lo:
        return _BLOCKS[1] * len(vals)
    out = []
    for v in vals:
        idx = int((v - lo) / (hi - lo) * (len(_BLOCKS) - 1) + 0.5)
        out.append(_BLOCKS[max(0, min(len(_BLOCKS) - 1, idx))])
    return "".join(out)


def fetch_timeseries(endpoint: str, timeout: float = 3.0
                     ) -> Optional[Dict[str, Any]]:
    """One worker's ``/timeseries`` doc, or None when unreachable /
    disabled (a dead worker must not kill the view)."""
    url = endpoint.rstrip("/")
    if not url.startswith("http"):
        url = "http://" + url
    if not url.endswith("/timeseries"):
        url = url + "/timeseries"
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return json.loads(resp.read().decode())
    except Exception:
        return None


def _series_values(doc: Dict[str, Any], name: str) -> List[float]:
    pts = ((doc.get("series") or {}).get(name)) or []
    out = []
    for p in pts:
        try:
            out.append(float(p[2]))
        except (TypeError, ValueError, IndexError):
            continue
    return out


def _median(vals: List[float]) -> Optional[float]:
    if not vals:
        return None
    ordered = sorted(vals)
    return ordered[(len(ordered) - 1) // 2]


def _action_str(action: Optional[Dict[str, Any]]) -> str:
    if not action:
        return "?"
    params = action.get("params") or {}
    inner = ",".join(f"{k}={v}" for k, v in sorted(params.items()))
    return f"{action.get('kind', '?')}({inner})" if inner \
        else str(action.get("kind", "?"))


def controller_lines(events: List[Dict[str, Any]], last: int = 4
                     ) -> List[str]:
    """Render the last ``last`` controller records from the event log —
    one line each: what fired, what was chosen at what predicted delta,
    and how it ended (applied/suppressed/recovered/rolled back)."""
    recs = [e for e in events if e.get("kind") in _CONTROLLER_KINDS]
    out = []
    for r in recs[-last:]:
        step = r.get("step", "?")
        if r.get("kind") == "controller_decision":
            chosen = r.get("chosen") or {}
            delta = chosen.get("predicted_delta_s")
            deltas = (f" pred {delta * 1e3:+.1f}ms"
                      if isinstance(delta, (int, float)) else "")
            out.append(
                f"  [step {step}] {(r.get('event') or {}).get('kind', '?')}"
                f" -> {_action_str(chosen.get('action'))}{deltas}"
                f" [{r.get('outcome', '?')}]")
        else:
            before, after = r.get("deviation_before"), \
                r.get("deviation_after")
            dev = (f" dev {before:.2f}->{after:.2f}"
                   if isinstance(before, (int, float))
                   and isinstance(after, (int, float)) else "")
            out.append(
                f"  [step {step}] {_action_str(r.get('action'))}"
                f" -> {r.get('outcome', '?')}{dev}")
    return out


def _move_str(move: Optional[Dict[str, Any]]) -> str:
    if not move:
        return "?"
    return f"{move.get('kind', '?')}({move.get('pod', '?')})"


def fleet_lines(events: List[Dict[str, Any]], last: int = 4
                ) -> List[str]:
    """Render the last ``last`` fleet-scheduler records from the event
    log — one line each: the trigger, the chosen move at its predicted
    gain, and the outcome (applied/suppressed/recovered/rolled back)."""
    recs = [e for e in events if e.get("kind") in _FLEET_KINDS]
    out = []
    for r in recs[-last:]:
        step = r.get("step", "?")
        if r.get("kind") == "fleet_decision":
            chosen = r.get("chosen") or {}
            gain = chosen.get("predicted_gain")
            gains = (f" gain {gain:+.3f}"
                     if isinstance(gain, (int, float)) else "")
            out.append(
                f"  [step {step}] "
                f"{(r.get('trigger') or {}).get('kind', '?')}"
                f" -> {_move_str(chosen.get('move'))}{gains}"
                f" [{r.get('outcome', '?')}]")
        else:
            before, after = r.get("pressure_before"), \
                r.get("pressure_after")
            press = (f" pressure {before:.2f}->{after:.2f}"
                     if isinstance(before, (int, float))
                     and isinstance(after, (int, float)) else "")
            out.append(
                f"  [step {step}] {_move_str(r.get('move'))}"
                f" -> {r.get('outcome', '?')}{press}")
    return out


def render_frame(docs: Dict[str, Optional[Dict[str, Any]]],
                 events: Optional[List[Dict[str, Any]]] = None,
                 width: int = 24) -> str:
    """One frame of the top view from fetched ``/timeseries`` docs
    (keyed by endpoint) and the anomaly event tail."""
    live = {ep: d for ep, d in docs.items() if d is not None}
    max_step = max((int(d.get("step") or 0) for d in live.values()),
                   default=0)
    lines = [f"hvdt top — {len(live)}/{len(docs)} ranks, "
             f"step {max_step}"]
    lines.append(f"{'rank':>4}  {'pod':<6} {'step time':<{width}}  "
                 f"{'last':>8} {'p50':>8} {'dev':>5}")
    pod_means: Dict[str, List[float]] = {}
    goodputs: List[float] = []
    for ep in sorted(docs):
        doc = docs[ep]
        if doc is None:
            lines.append(f"{'?':>4}  {'-':<6} "
                         f"{'(unreachable: ' + ep + ')':<{width}}")
            continue
        rank = doc.get("rank", "?")
        pod = str(doc.get("pod") or "-")
        steps = _series_values(doc, "step_time")
        spark = sparkline(steps, width)
        last = f"{steps[-1] * 1e3:.1f}ms" if steps else "-"
        p50 = _median(steps[-width:])
        p50s = f"{p50 * 1e3:.1f}ms" if p50 is not None else "-"
        dev_vals = _series_values(doc, "perf_deviation_ratio")
        dev = f"{dev_vals[-1]:.2f}" if dev_vals else "-"
        lines.append(f"{rank:>4}  {pod:<6} {spark:<{width}}  "
                     f"{last:>8} {p50s:>8} {dev:>5}")
        if steps:
            # Worst-pod ranking uses the recent MEAN, not the median:
            # a single multi-second hiccup is exactly what the operator
            # wants surfaced, and a median hides it.
            recent = steps[-width:]
            pod_means.setdefault(pod, []).append(
                sum(recent) / len(recent))
        gp = _series_values(doc, "goodput_fraction")
        if gp:
            goodputs.append(gp[-1])
    footer = []
    if goodputs:
        footer.append(f"goodput {sum(goodputs) / len(goodputs):.2f}")
    if pod_means:
        worst = max(sorted(pod_means),
                    key=lambda p: _median(pod_means[p]) or 0.0)
        footer.append(f"worst pod: {worst} "
                      f"({(_median(pod_means[worst]) or 0) * 1e3:.1f}ms)")
    if footer:
        lines.append("   ".join(footer))
    if events:
        anomalies = [e for e in events
                     if e.get("kind") not in _CONTROLLER_KINDS
                     and e.get("kind") not in _FLEET_KINDS]
        if anomalies:
            lines.append("anomalies:")
            for ev in anomalies[-5:]:
                who = []
                if ev.get("rank") is not None:
                    who.append(f"rank={ev['rank']}")
                if ev.get("pod"):
                    who.append(f"pod={ev['pod']}")
                lines.append(f"  [step {ev.get('step', '?')}] "
                             f"{ev.get('kind', '?')} {' '.join(who)}: "
                             f"{ev.get('message', '')}")
        ctl = controller_lines(events)
        if ctl:
            lines.append("controller:")
            lines.extend(ctl)
        flt = fleet_lines(events)
        if flt:
            lines.append("fleet:")
            lines.extend(flt)
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="hvdtrun top",
        description="Live terminal view over worker /timeseries "
                    "endpoints (requires HVDT_TELEMETRY + HVDT_HISTORY "
                    "on the workers).")
    p.add_argument("--endpoints", default="127.0.0.1:9090",
                   help="Comma list of worker exporter endpoints "
                        "(host:port; the /timeseries path is implied). "
                        "Default: the local worker's default metrics "
                        "port.")
    p.add_argument("--interval", type=float, default=2.0,
                   help="Refresh period in seconds.")
    p.add_argument("--once", action="store_true",
                   help="Print a single frame and exit (scriptable).")
    p.add_argument("--event-log", default=None,
                   help="Anomaly event log (HVDT_EVENT_LOG JSONL) to "
                        "tail into the frame.")
    args = p.parse_args(argv)

    endpoints = [e.strip() for e in args.endpoints.split(",") if e.strip()]
    stop = threading.Event()
    while True:
        docs = {ep: fetch_timeseries(ep) for ep in endpoints}
        events = None
        if args.event_log:
            from .anomaly import read_event_log

            events = read_event_log(args.event_log)
        frame = render_frame(docs, events)
        if args.once:
            print(frame)
            return 0
        # Full-frame refresh: clear + home (ANSI), then the frame.
        sys.stdout.write("\x1b[2J\x1b[H" + frame + "\n")
        sys.stdout.flush()
        try:
            if stop.wait(max(0.2, args.interval)):
                return 0
        except KeyboardInterrupt:
            return 0


if __name__ == "__main__":
    sys.exit(main())
