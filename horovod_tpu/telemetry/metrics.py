"""Shared metric primitives: counters, gauges, summaries → Prometheus text.

Promoted out of ``serve/metrics.py`` (which re-exports for back-compat):
the serving plane needed RED-triple observability first, but the same
primitives are what training, the collectives, and the elastic driver
need — so they live here now, one layer below every subsystem, together
with a **process-wide default registry** (:func:`default_registry`) that
training-side instrumentation (``telemetry/instrument.py``,
``telemetry/step_stats.py``) and the per-worker ``/metrics`` exporter
share.  Serving keeps per-engine registries (an inference replica scrapes
its own engine, not the trainer's).

No prometheus_client dependency: the text exposition format is a stable,
trivially-rendered contract, and the container must not grow deps.  A
:class:`Summary` keeps a bounded reservoir of recent samples and renders
pre-computed p50/p95/p99 quantiles (the Prometheus *summary* type), which
scrapers and humans can read directly — bucketed histograms would push
the percentile math onto a query engine the test rig doesn't have.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["Counter", "Gauge", "Summary", "MetricsRegistry",
           "default_registry", "reset_default_registry"]


def _fmt_labels(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def _fmt_value(v: float) -> str:
    f = float(v)
    if f != f:
        return "NaN"    # Prometheus-canonical (live probes with no data)
    if f in (float("inf"), float("-inf")):
        return "+Inf" if f > 0 else "-Inf"
    return str(int(f)) if f == int(f) else repr(f)


class _Metric:
    """Base: name/help/type plus per-metric lock."""

    kind = "untyped"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()

    def _header(self) -> List[str]:
        lines = []
        if self.help:
            lines.append(f"# HELP {self.name} {self.help}")
        lines.append(f"# TYPE {self.name} {self.kind}")
        return lines

    def render(self) -> List[str]:  # pragma: no cover - abstract
        raise NotImplementedError


class Counter(_Metric):
    """Monotonic counter (optionally labelled)."""

    kind = "counter"

    def __init__(self, name: str, help: str = ""):
        super().__init__(name, help)
        self._values: Dict[Tuple[Tuple[str, str], ...], float] = {}

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels: str) -> float:
        key = tuple(sorted(labels.items()))
        with self._lock:
            return self._values.get(key, 0.0)

    def total(self) -> float:
        """Sum across every label combination — the scrape-independent
        aggregate harnesses (bench JSON, driver roll-ups) report."""
        with self._lock:
            return sum(self._values.values())

    def render(self) -> List[str]:
        lines = self._header()
        with self._lock:
            items = sorted(self._values.items()) or [((), 0.0)]
            for key, v in items:
                lines.append(
                    f"{self.name}{_fmt_labels(dict(key))} {_fmt_value(v)}")
        return lines


class Gauge(_Metric):
    """Point-in-time value; ``set_function`` makes it a live probe (queue
    depth is read from the batcher at scrape time, not shadowed)."""

    kind = "gauge"

    def __init__(self, name: str, help: str = ""):
        super().__init__(name, help)
        self._value = 0.0
        self._fn = None

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    def set_function(self, fn) -> None:
        with self._lock:
            self._fn = fn

    def value(self) -> float:
        with self._lock:
            fn = self._fn
            if fn is None:
                return self._value
        try:
            return float(fn())
        except Exception:
            return float("nan")

    def render(self) -> List[str]:
        return self._header() + [f"{self.name} {_fmt_value(self.value())}"]


class Summary(_Metric):
    """Latency summary: cumulative count/sum plus streaming quantiles over
    a bounded reservoir of the most recent ``window`` observations.

    The reservoir is a plain ring buffer — recent-window quantiles are
    what an operator wants from a scrape (a p99 diluted by yesterday's
    warmup spike is useless), and the bound keeps a long-lived server's
    memory flat.
    """

    kind = "summary"

    QUANTILES: Sequence[float] = (0.5, 0.95, 0.99)

    def __init__(self, name: str, help: str = "", window: int = 2048):
        super().__init__(name, help)
        self._window = max(1, int(window))
        self._ring: List[float] = []
        self._next = 0
        self._count = 0
        self._sum = 0.0

    def observe(self, value: float) -> None:
        v = float(value)
        with self._lock:
            self._count += 1
            self._sum += v
            if len(self._ring) < self._window:
                self._ring.append(v)
            else:
                self._ring[self._next] = v
                self._next = (self._next + 1) % self._window

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def mean(self) -> Optional[float]:
        """Mean over the retained window (None before any observation)."""
        with self._lock:
            if not self._ring:
                return None
            return float(sum(self._ring) / len(self._ring))

    def quantile(self, q: float) -> Optional[float]:
        """Nearest-rank quantile over the retained window (None if no
        observations yet)."""
        with self._lock:
            if not self._ring:
                return None
            data = sorted(self._ring)
        idx = min(len(data) - 1, max(0, int(q * len(data) + 0.5) - 1))
        return data[idx]

    def percentiles(self) -> Dict[float, Optional[float]]:
        return {q: self.quantile(q) for q in self.QUANTILES}

    def render(self) -> List[str]:
        lines = self._header()
        with self._lock:
            data = sorted(self._ring)
            count, total = self._count, self._sum
        for q in self.QUANTILES:
            if data:
                idx = min(len(data) - 1, max(0, int(q * len(data) + 0.5) - 1))
                lines.append(f'{self.name}{{quantile="{q}"}} '
                             f"{_fmt_value(data[idx])}")
            else:
                lines.append(f'{self.name}{{quantile="{q}"}} NaN')
        lines.append(f"{self.name}_sum {_fmt_value(total)}")
        lines.append(f"{self.name}_count {count}")
        return lines


class MetricsRegistry:
    """Named metric collection rendering the Prometheus text format.

    ``counter``/``gauge``/``summary`` are get-or-create (idempotent), so
    independent components can reference the same metric by name without
    plumbing object handles."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def _get_or_create(self, cls, name: str, help: str, **kwargs):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help, **kwargs)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, not {cls.__name__}")
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(Gauge, name, help)

    def summary(self, name: str, help: str = "",
                window: int = 2048) -> Summary:
        return self._get_or_create(Summary, name, help, window=window)

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._metrics)

    def render(self) -> str:
        with self._lock:
            metrics = [self._metrics[n] for n in sorted(self._metrics)]
        lines: List[str] = []
        for m in metrics:
            lines.extend(m.render())
        return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# Process-wide default registry — what /metrics on a training worker serves.
# ---------------------------------------------------------------------------

_default_lock = threading.Lock()
_default: Optional[MetricsRegistry] = None


def default_registry() -> MetricsRegistry:
    """The process-wide registry every training-side instrumentation site
    and the worker ``/metrics`` exporter share.  Created on first use."""
    global _default
    with _default_lock:
        if _default is None:
            _default = MetricsRegistry()
        return _default


def reset_default_registry() -> MetricsRegistry:
    """Swap in a fresh default registry (tests — counters are cumulative
    and process-wide, so isolation requires an explicit reset)."""
    global _default
    with _default_lock:
        _default = MetricsRegistry()
        return _default
