"""Shared metric primitives: counters, gauges, summaries → Prometheus text.

Promoted out of ``serve/metrics.py`` (which re-exports for back-compat):
the serving plane needed RED-triple observability first, but the same
primitives are what training, the collectives, and the elastic driver
need — so they live here now, one layer below every subsystem, together
with a **process-wide default registry** (:func:`default_registry`) that
training-side instrumentation (``telemetry/instrument.py``,
``telemetry/step_stats.py``) and the per-worker ``/metrics`` exporter
share.  Serving keeps per-engine registries (an inference replica scrapes
its own engine, not the trainer's).

No prometheus_client dependency: the text exposition format is a stable,
trivially-rendered contract, and the container must not grow deps.  A
:class:`Summary` keeps a bounded reservoir of recent samples and renders
pre-computed p50/p95/p99 quantiles (the Prometheus *summary* type), which
scrapers and humans can read directly — bucketed histograms would push
the percentile math onto a query engine the test rig doesn't have.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["Counter", "Gauge", "Summary", "MetricsRegistry",
           "default_registry", "reset_default_registry",
           "MetricSpec", "CATALOG", "declared_metric"]


def _fmt_labels(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def _fmt_value(v: float) -> str:
    f = float(v)
    if f != f:
        return "NaN"    # Prometheus-canonical (live probes with no data)
    if f in (float("inf"), float("-inf")):
        return "+Inf" if f > 0 else "-Inf"
    return str(int(f)) if f == int(f) else repr(f)


class _Metric:
    """Base: name/help/type plus per-metric lock."""

    kind = "untyped"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()

    def _header(self) -> List[str]:
        lines = []
        if self.help:
            lines.append(f"# HELP {self.name} {self.help}")
        lines.append(f"# TYPE {self.name} {self.kind}")
        return lines

    def render(self) -> List[str]:  # pragma: no cover - abstract
        raise NotImplementedError


class Counter(_Metric):
    """Monotonic counter (optionally labelled)."""

    kind = "counter"

    def __init__(self, name: str, help: str = ""):
        super().__init__(name, help)
        self._values: Dict[Tuple[Tuple[str, str], ...], float] = {}

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels: str) -> float:
        key = tuple(sorted(labels.items()))
        with self._lock:
            return self._values.get(key, 0.0)

    def items(self) -> List[Tuple[Dict[str, str], float]]:
        """Every (labels, value) series — the per-label view harnesses
        (time-series sampling, anomaly-count roll-ups) read without
        reparsing the rendered text."""
        with self._lock:
            return [(dict(k), v) for k, v in sorted(self._values.items())]

    def total(self) -> float:
        """Sum across every label combination — the scrape-independent
        aggregate harnesses (bench JSON, driver roll-ups) report."""
        with self._lock:
            return sum(self._values.values())

    def render(self) -> List[str]:
        lines = self._header()
        with self._lock:
            items = sorted(self._values.items()) or [((), 0.0)]
            for key, v in items:
                lines.append(
                    f"{self.name}{_fmt_labels(dict(key))} {_fmt_value(v)}")
        return lines


class Gauge(_Metric):
    """Point-in-time value; ``set_function`` makes it a live probe (queue
    depth is read from the batcher at scrape time, not shadowed).

    Optionally labelled: ``set(v, axis="dcn")`` keeps one value per
    label combination (``hvdt_expected_wire_bytes{axis=...}``); without
    labels the gauge stays the scalar it always was, and live probes
    are scalar-only."""

    kind = "gauge"

    def __init__(self, name: str, help: str = ""):
        super().__init__(name, help)
        self._values: Dict[Tuple[Tuple[str, str], ...], float] = {(): 0.0}
        self._fn = None

    def set(self, value: float, **labels: str) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._values[key] = float(value)

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def dec(self, amount: float = 1.0, **labels: str) -> None:
        self.inc(-amount, **labels)

    def set_function(self, fn) -> None:
        with self._lock:
            self._fn = fn

    def value(self, **labels: str) -> float:
        key = tuple(sorted(labels.items()))
        with self._lock:
            fn = self._fn
            if fn is None or key:
                return self._values.get(key, 0.0 if not key
                                        else float("nan"))
        try:
            return float(fn())
        except Exception:
            return float("nan")

    def items(self) -> List[Tuple[Dict[str, str], float]]:
        """Every labelled (labels, value) series (the scalar slot is
        omitted unless it is the only one or was explicitly set)."""
        with self._lock:
            labelled = [(dict(k), v) for k, v in sorted(
                self._values.items()) if k]
            if labelled:
                return labelled
            return [({}, self._values.get((), 0.0))]

    def render(self) -> List[str]:
        with self._lock:
            fn = self._fn
            labelled = sorted((k, v) for k, v in self._values.items() if k)
        if fn is not None or not labelled:
            return self._header() + [
                f"{self.name} {_fmt_value(self.value())}"]
        return self._header() + [
            f"{self.name}{_fmt_labels(dict(k))} {_fmt_value(v)}"
            for k, v in labelled]


class Summary(_Metric):
    """Latency summary: cumulative count/sum plus streaming quantiles over
    a bounded reservoir of the most recent ``window`` observations.

    The reservoir is a plain ring buffer — recent-window quantiles are
    what an operator wants from a scrape (a p99 diluted by yesterday's
    warmup spike is useless), and the bound keeps a long-lived server's
    memory flat.
    """

    kind = "summary"

    QUANTILES: Sequence[float] = (0.5, 0.95, 0.99)

    def __init__(self, name: str, help: str = "", window: int = 2048):
        super().__init__(name, help)
        self._window = max(1, int(window))
        self._ring: List[float] = []
        self._next = 0
        self._count = 0
        self._sum = 0.0

    def observe(self, value: float) -> None:
        v = float(value)
        with self._lock:
            self._count += 1
            self._sum += v
            if len(self._ring) < self._window:
                self._ring.append(v)
            else:
                self._ring[self._next] = v
                self._next = (self._next + 1) % self._window

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def mean(self) -> Optional[float]:
        """Mean over the retained window (None before any observation)."""
        with self._lock:
            if not self._ring:
                return None
            return float(sum(self._ring) / len(self._ring))

    def _sorted_window(self) -> List[float]:
        """The ONE sort per render/percentile pass.  Every quantile
        consumer goes through here so a 3-quantile scrape costs one
        O(n log n), not three (regression-tested via a sort-spy
        subclass in tests/test_attribution.py)."""
        with self._lock:
            return sorted(self._ring)

    @staticmethod
    def _nearest_rank(data: List[float], q: float) -> float:
        idx = min(len(data) - 1, max(0, int(q * len(data) + 0.5) - 1))
        return data[idx]

    def quantile(self, q: float) -> Optional[float]:
        """Nearest-rank quantile over the retained window (None if no
        observations yet).  For several quantiles at once use
        :meth:`percentiles`, which sorts the window once."""
        data = self._sorted_window()
        if not data:
            return None
        return self._nearest_rank(data, q)

    def percentiles(self) -> Dict[float, Optional[float]]:
        data = self._sorted_window()
        if not data:
            return {q: None for q in self.QUANTILES}
        return {q: self._nearest_rank(data, q) for q in self.QUANTILES}

    def percentile(self, q: float) -> float:
        """Like :meth:`quantile` but TOTAL: an empty window reads 0.0,
        never ``None``.  Dashboards and roll-ups over the per-tenant
        ``hvdt_engine_*`` summaries read p50/p95/p99 before the first
        observation lands (a fresh replica, an idle tenant) and must see
        a number — callers that need to distinguish "no data yet" keep
        :meth:`quantile`'s ``None`` contract (router ejection does)."""
        v = self.quantile(q)
        return 0.0 if v is None else float(v)

    def render(self) -> List[str]:
        lines = self._header()
        data = self._sorted_window()
        with self._lock:
            count, total = self._count, self._sum
        for q in self.QUANTILES:
            if data:
                lines.append(f'{self.name}{{quantile="{q}"}} '
                             f"{_fmt_value(self._nearest_rank(data, q))}")
            else:
                lines.append(f'{self.name}{{quantile="{q}"}} NaN')
        lines.append(f"{self.name}_sum {_fmt_value(total)}")
        lines.append(f"{self.name}_count {count}")
        return lines


class MetricsRegistry:
    """Named metric collection rendering the Prometheus text format.

    ``counter``/``gauge``/``summary`` are get-or-create (idempotent), so
    independent components can reference the same metric by name without
    plumbing object handles."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def _get_or_create(self, cls, name: str, help: str, **kwargs):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help, **kwargs)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, not {cls.__name__}")
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(Gauge, name, help)

    def summary(self, name: str, help: str = "",
                window: int = 2048) -> Summary:
        return self._get_or_create(Summary, name, help, window=window)

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._metrics)

    def render(self) -> str:
        with self._lock:
            metrics = [self._metrics[n] for n in sorted(self._metrics)]
        lines: List[str] = []
        for m in metrics:
            lines.extend(m.render())
        return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# Process-wide default registry — what /metrics on a training worker serves.
# ---------------------------------------------------------------------------

_default_lock = threading.Lock()
_default: Optional[MetricsRegistry] = None


def default_registry() -> MetricsRegistry:
    """The process-wide registry every training-side instrumentation site
    and the worker ``/metrics`` exporter share.  Created on first use."""
    global _default
    with _default_lock:
        if _default is None:
            _default = MetricsRegistry()
        return _default


def reset_default_registry() -> MetricsRegistry:
    """Swap in a fresh default registry (tests — counters are cumulative
    and process-wide, so isolation requires an explicit reset)."""
    global _default
    with _default_lock:
        _default = MetricsRegistry()
        return _default


# ---------------------------------------------------------------------------
# Metric catalog — the declared universe of metric names.
#
# Every Counter/Gauge/Summary the package constructs must be declared
# here (name, type, label set, one-line doc).  The `metric-drift` lint
# rule (analysis/lint.py) fails the CI gate on any construction whose
# literal name is missing, and `python -m horovod_tpu.analysis
# --metric-table --write docs/metrics.md` generates the docs table from
# this registry — the docs/knobs.md pattern applied to metrics, so the
# catalog, the code, and the docs can never drift apart.  Names ending
# in `*` are prefix wildcards for dynamically-formatted families
# (hvdt_phase_<PHASE>_seconds).
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class MetricSpec:
    """One declared metric: name (or `prefix*` wildcard), kind
    (counter|gauge|summary), label names, and a docs line."""

    name: str
    kind: str
    labels: Tuple[str, ...]
    doc: str


def _m(name: str, kind: str, labels: Sequence[str], doc: str) -> MetricSpec:
    return MetricSpec(name, kind, tuple(labels), doc)


CATALOG: Dict[str, MetricSpec] = {
    s.name: s
    for s in [
        # -- collectives (telemetry/instrument.py) --
        _m("hvdt_collective_bytes_total", "counter",
           ("op", "dtype", "wire", "path", "axis"),
           "Bytes on the wire per collective (path=eager counts "
           "executions; path=jit counts traced programs)"),
        _m("hvdt_collectives_total", "counter",
           ("op", "dtype", "wire", "path", "axis"),
           "Collectives recorded, labelled op/dtype/wire/path"),
        _m("hvdt_wire_bytes_total", "counter", ("axis", "wire"),
           "Bytes on the wire per mesh axis — the per-tier view of "
           "hierarchical transport policies"),
        _m("hvdt_collective_negotiate_seconds", "summary", (),
           "Eager-path announce -> negotiated-response latency"),
        _m("hvdt_collective_queue_seconds", "summary", (),
           "Eager-path enqueue -> announce latency"),
        _m("hvdt_collective_execute_seconds", "summary", (),
           "Eager-path response dispatch duration"),
        _m("hvdt_fusion_fill_ratio", "summary", (),
           "Fused-allreduce bucket occupancy: bucket bytes / "
           "HVDT_FUSION_THRESHOLD"),
        _m("hvdt_step_dispatch_seconds", "summary", (),
           "donated_step call duration (async dispatch interval)"),
        _m("hvdt_overlap_hidden_bytes_total", "counter", (),
           "Collective bytes issued with compute still scheduled under "
           "their flight window (ops/overlap)"),
        _m("hvdt_overlap_bytes_total", "counter", (),
           "Total collective bytes scheduled by the overlap scheduler"),
        _m("hvdt_overlap_fraction", "gauge", (),
           "Hidden / total collective bytes across overlapped exchange "
           "schedules"),
        _m("hvdt_phase_*", "summary", (),
           "Timeline span durations per phase (hvdt_phase_<PHASE>_"
           "seconds, from the timeline writer's B/E pairs)"),
        # -- step stats / goodput (telemetry/step_stats.py) --
        _m("hvdt_step_time_seconds", "summary", (),
           "Host-observed training step duration"),
        _m("hvdt_steps_total", "counter", (),
           "Training steps observed by the StepTimer"),
        _m("hvdt_examples_per_sec", "gauge", (),
           "Windowed training throughput (examples/s, EWMA)"),
        _m("hvdt_mfu", "gauge", (),
           "Model-flops utilization (published only when caller flops "
           "and the device peak are both known)"),
        _m("hvdt_goodput_fraction", "gauge", (),
           "(elapsed - lost) / elapsed since ledger start"),
        _m("hvdt_goodput_lost_seconds_total", "counter", ("reason",),
           "Wall-clock seconds lost to non-training work, by reason"),
        _m("hvdt_recovery_seconds", "counter", ("phase",),
           "Recovery-time-budget seconds by phase (checkpoint_snapshot "
           "| checkpoint_write | rendezvous | compile | restore | "
           "replay)"),
        _m("hvdt_injected_faults", "gauge", (),
           "Faults the HVDT_FAULT_PLAN injector has fired"),
        _m("hvdt_emergency_checkpoints", "gauge", (),
           "Preemption-guard emergency checkpoints taken"),
        _m("hvdt_param_bytes", "gauge", (),
           "Per-rank parameter bytes (post-sharding)"),
        _m("hvdt_optimizer_state_bytes", "gauge", (),
           "Per-rank optimizer-state bytes (post-sharding)"),
        # -- perf attribution (predicted vs observed) --
        _m("hvdt_expected_step_comm_seconds", "gauge", (),
           "Cost-model-predicted exposed (non-overlapped) communication "
           "seconds per step for the expected schedule fingerprint on "
           "the ambient topology (published by hvd.init when "
           "HVDT_EXPECTED_SCHEDULE is set)"),
        _m("hvdt_expected_wire_bytes", "gauge", ("axis",),
           "Cost-model-predicted wire bytes per step per transport "
           "tier for the expected schedule fingerprint"),
        _m("hvdt_perf_deviation_ratio", "gauge", (),
           "Observed EWMA step seconds / predicted step seconds "
           "(predicted exposed comm + compute anchor) — >1 means the "
           "live run is slower than the cost model says it should be; "
           "the perf_deviation anomaly fires past "
           "HVDT_PERF_DEVIATION_RATIO"),
        _m("hvdt_anomaly_total", "counter", ("kind",),
           "Anomaly detector firings by kind (step_time_shift | "
           "goodput_drop | mfu_regression | wire_drift | "
           "straggler_onset | perf_deviation)"),
        _m("hvdt_history_samples_total", "counter", (),
           "Time-series samples recorded by the metric history "
           "(HVDT_HISTORY)"),
        _m("hvdt_snapshot_unaligned_total", "counter", (),
           "Driver-side roll-ups that skipped a rank whose KV snapshot "
           "carried no step id / time series (old snapshot schema or "
           "history off on that worker)"),
        # -- online policy controller (horovod_tpu/control) --
        _m("hvdt_controller_decisions_total", "counter",
           ("action", "outcome"),
           "Controller decisions by action kind (flip_transport | "
           "retune_bucket | toggle_overlap | toggle_zero | evict_pod | "
           "resize | scale_replicas) and outcome (applied | observed | "
           "recovered | rolled_back)"),
        _m("hvdt_controller_suppressed_total", "counter", ("reason",),
           "Controller decisions suppressed by guardrail (budget | "
           "hysteresis | cooldown | no_gain | apply_failed)"),
        _m("hvdt_controller_rollbacks_total", "counter", (),
           "Never-worse rollbacks: applied actions whose deviation "
           "ratio failed to recover inside the window"),
        _m("hvdt_controller_pending", "gauge", (),
           "Applied actions currently awaiting deviation-recovery "
           "verification"),
        _m("hvdt_controller_predicted_delta_s", "gauge", (),
           "Cost-model-predicted step-seconds gain of the last applied "
           "action"),
        _m("hvdt_controller_observed_delta_s", "gauge", (),
           "Observed deviation-ratio improvement of the last verified "
           "action (predicted-vs-observed closes the audit loop)"),
        # -- fleet scheduler (horovod_tpu/fleet) --
        _m("hvdt_fleet_decisions_total", "counter",
           ("move", "outcome"),
           "Fleet-scheduler decisions by move kind (reclaim | "
           "backfill) and outcome (applied | observed | recovered | "
           "rolled_back)"),
        _m("hvdt_fleet_suppressed_total", "counter", ("reason",),
           "Fleet moves suppressed by guardrail (budget | hysteresis | "
           "cooldown | no_gain | hint_not_growth | apply_failed)"),
        _m("hvdt_fleet_rollbacks_total", "counter", (),
           "Never-worse rollbacks: fleet moves whose serving pressure "
           "got worse than at decision time inside the window"),
        _m("hvdt_fleet_pending", "gauge", (),
           "Applied fleet moves currently awaiting pressure-recovery "
           "verification"),
        _m("hvdt_fleet_pressure", "gauge", (),
           "Serving-pressure ratio the scheduler last acted on "
           "(max of queue-depth and p99 ratios; 1.0 = at SLO)"),
        _m("hvdt_fleet_train_pods", "gauge", (),
           "Pods currently leased to the training workload"),
        _m("hvdt_fleet_serve_units", "gauge", (),
           "Pods currently leased to the serving workload"),
        # -- straggler (telemetry/straggler.py) --
        _m("hvdt_straggler_rank", "gauge", (),
           "Worst straggler rank over the last window (-1 = none)"),
        _m("hvdt_step_time_skew", "gauge", (),
           "max(rank mean step time) / median over the last window"),
        _m("hvdt_straggler_checks_total", "counter", (),
           "Cross-rank straggler checks performed"),
        _m("hvdt_straggler_flags_total", "counter", ("rank", "pod"),
           "Straggler detections by offending rank (and pod)"),
        _m("hvdt_straggler_pod", "gauge", (),
           "Worst straggler pod over the last window (-1 = none)"),
        _m("hvdt_pod_step_time_skew", "gauge", (),
           "max(pod mean step time) / cross-pod median"),
        # -- process gauges (telemetry/exporter.py) --
        _m("hvdt_process_rss_bytes", "gauge", (),
           "Resident set size of this worker process"),
        _m("hvdt_process_open_fds", "gauge", (),
           "Open file descriptors of this worker process"),
        _m("hvdt_hbm_bytes_in_use", "gauge", (),
           "Live device memory in use (nan where unavailable)"),
        _m("hvdt_hbm_peak_bytes", "gauge", (),
           "Peak device memory in use since process start"),
        # -- checkpointing (checkpoint.py) --
        _m("hvdt_ckpt_snapshot_seconds", "summary", (),
           "Commit-point device->host checkpoint snapshot duration"),
        _m("hvdt_ckpt_write_seconds", "summary", (),
           "Background checkpoint write+fsync duration"),
        _m("hvdt_ckpt_snapshot_over_budget_total", "counter", (),
           "Snapshots exceeding HVDT_CKPT_SNAPSHOT_BUDGET_S"),
        _m("hvdt_ckpt_superseded_total", "counter", (),
           "Queued async snapshots superseded by a newer one"),
        _m("hvdt_ckpt_write_failures_total", "counter", (),
           "Async checkpoint writes that failed (logged, never raised)"),
        # -- peer snapshot tier (resilience/peer_store.py) --
        _m("hvdt_peer_restore_total", "counter", (),
           "Recoveries served from the peer-replicated RAM tier"),
        _m("hvdt_peer_commit_total", "counter", (),
           "Commit-point snapshot publications to the peer tier"),
        _m("hvdt_peer_miss_total", "counter", (),
           "Peer-tier restore attempts that fell back to disk"),
        _m("hvdt_peer_replica_bytes", "gauge", (),
           "Host-RAM bytes holding peer snapshot replicas"),
        # -- control plane (runner/http_kv.py, optimizer.py) --
        _m("hvdt_kv_retries_total", "counter", (),
           "Rendezvous-KV bootstrap-wait retries"),
        _m("hvdt_kv_errors_total", "counter", ("op",),
           "Rendezvous-KV client op failures by op"),
        _m("hvdt_distributed_optimizer_builds_total", "counter",
           ("op", "compression", "backward_passes", "pipeline", "expert"),
           "DistributedOptimizer/GradientTransformation constructions, "
           "labelled reduce op / wire compression / accumulation and "
           "the declared pipeline/expert sharded axes (off when pure "
           "data-parallel)"),
        # -- 4D parallel substrate (parallel/moe.py, parallel/pipeline.py) --
        _m("hvdt_moe_capacity_slots", "gauge", (),
           "Per-expert dispatch slots of the last traced MoE layer "
           "(ceil(T*k/E * capacity_factor) — the static-shape capacity "
           "every dispatch tensor is sized by)"),
        _m("hvdt_moe_expansion_ratio", "gauge", (),
           "Dispatch slots / routed assignments of the last traced MoE "
           "layer (capacity head-room; < 1 guarantees dropped tokens)"),
        _m("hvdt_moe_load_balance_loss", "gauge", (),
           "Switch-transformer load-balance aux loss of the last "
           "reported step (E * sum_e f_e * P_e; report_moe_aux)"),
        _m("hvdt_moe_dropped_fraction", "gauge", (),
           "Fraction of routed token assignments dropped over expert "
           "capacity in the last reported step (report_moe_aux)"),
        _m("hvdt_pipeline_mfu", "gauge", (),
           "Model FLOPs utilization of the last reported pipeline step "
           "(achieved model FLOP/s / peak; report_pipeline_mfu)"),
        # -- serving router (serve/router.py) --
        _m("hvdt_router_requests_total", "counter",
           ("route", "status", "tenant"),
           "Requests admitted by the serving router front tier, by "
           "route, upstream status and tenant class (interactive | "
           "batch | default)"),
        _m("hvdt_router_request_latency_ms", "summary", (),
           "Router end-to-end /predict latency (ms), all tenants"),
        _m("hvdt_router_request_latency_ms_*", "summary", (),
           "Per-tenant router /predict latency "
           "(hvdt_router_request_latency_ms_<tenant>; Summary carries "
           "no labels)"),
        _m("hvdt_router_upstream_latency_ms", "summary", (),
           "Router upstream (replica) dispatch latency (ms)"),
        _m("hvdt_router_retries_total", "counter", ("tenant",),
           "Wire-death retries dispatched to another replica, by "
           "tenant class"),
        _m("hvdt_router_hedges_total", "counter", ("tenant",),
           "Hedge requests issued past the hedge threshold"),
        _m("hvdt_router_hedge_wins_total", "counter", ("tenant",),
           "Hedge requests that answered before the primary"),
        _m("hvdt_router_ejections_total", "counter", ("reason", "tenant"),
           "Replica ejections by reason (probe | slo | dispatch) and "
           "the tenant whose traffic triggered them (control-loop "
           "ejections carry tenant=control)"),
        _m("hvdt_router_readmissions_total", "counter", (),
           "Ejected replicas re-admitted after a fresh heartbeat"),
        _m("hvdt_router_no_replica_total", "counter", (),
           "Requests that found no live replica"),
        _m("hvdt_router_inflight", "gauge", (),
           "Requests currently in flight through the router"),
        _m("hvdt_router_replicas_live", "gauge", (),
           "Live replicas the router currently sees"),
        # -- serving plane (serve/*) --
        _m("serve_queue_depth", "gauge", (),
           "Rows queued but not yet dispatched (live probe)"),
        _m("serve_requests_total", "counter", (),
           "Rows admitted to the dynamic batcher"),
        _m("serve_rejected_total", "counter", (),
           "Rows shed at the admission bound (HTTP 503)"),
        _m("serve_batches_total", "counter", (),
           "Batches dispatched by the batcher"),
        _m("serve_deadline_expired_total", "counter", (),
           "Requests failed by the per-request deadline watchdog"),
        _m("serve_queue_wait_seconds", "summary", (),
           "Row wait from admission to dispatch"),
        _m("serve_batch_fill", "summary", (),
           "Dispatched batch rows / max_batch_size"),
        _m("serve_compiles_total", "counter", (),
           "Engine jit compiles (flat in steady state)"),
        _m("serve_engine_batches_total", "counter", (),
           "Batches executed by the inference engine"),
        _m("serve_pad_rows_total", "counter", (),
           "Pad rows added to reach the shape bucket"),
        _m("serve_http_responses_total", "counter", ("route", "status"),
           "HTTP responses by route and status"),
        _m("serve_request_latency_ms_*", "summary", (),
           "End-to-end handler latency per route "
           "(serve_request_latency_ms_<route>)"),
        _m("serve_draining", "gauge", (),
           "1 while the server drains (admission closed)"),
        _m("serve_reloads_total", "counter", (),
           "Hot weight reloads applied"),
        _m("serve_reload_failures_total", "counter", (),
           "Failed reload attempts (kept serving)"),
        _m("serve_skipped_unverified_total", "counter", (),
           "Checkpoint steps skipped by manifest verification"),
        _m("serve_checkpoint_step", "gauge", (),
           "Checkpoint step currently served"),
        _m("serve_last_good_step", "gauge", (),
           "Newest verified checkpoint step seen by the watcher"),
        # --- continuous-batching LLM engine (serve/llm) ---
        _m("hvdt_engine_iterations_total", "counter", (),
           "Continuous-batching scheduler iterations executed"),
        _m("hvdt_engine_decode_tokens_total", "counter", (),
           "Tokens emitted by the paged decode step"),
        _m("hvdt_engine_prefill_tokens_total", "counter", (),
           "Prompt tokens written into the paged KV cache"),
        _m("hvdt_engine_preemptions_total", "counter", (),
           "Sequences evicted under block pressure (recompute on "
           "return)"),
        _m("hvdt_engine_prefix_hits_total", "counter", (),
           "Admissions served by forking a live prompt's block table "
           "(copy-on-write prefix sharing)"),
        _m("hvdt_engine_admissions_total", "counter", ("tenant",),
           "Sequences admitted to the block budget, by tenant"),
        _m("hvdt_engine_tokens_per_sec", "gauge", (),
           "Decode throughput (EMA over iterations)"),
        _m("hvdt_engine_kv_blocks_total", "gauge", (),
           "Allocatable KV blocks (sink block excluded)"),
        _m("hvdt_engine_kv_blocks_in_use", "gauge", (),
           "KV blocks held by live block tables (live probe)"),
        _m("hvdt_engine_active_seqs", "gauge", (),
           "Admitted (prefilling or decoding) sequences (live probe)"),
        _m("hvdt_engine_batch_quota_slots", "gauge", (),
           "Decode slots the batch tenant may hold (adapts off the "
           "interactive-wait time series)"),
        _m("hvdt_engine_queue_depth", "gauge", ("tenant",),
           "Waiting (not yet admitted) sequences, by tenant"),
        _m("hvdt_engine_decode_step_seconds", "summary", (),
           "Wall time of one paged decode iteration"),
        _m("hvdt_engine_prefill_chunk_seconds", "summary", (),
           "Wall time of one prefill chunk (or ring prefill shot)"),
        _m("hvdt_engine_wait_ms_*", "summary", (),
           "Submit-to-first-token latency by tenant "
           "(hvdt_engine_wait_ms_<tenant>; Summary carries no labels)"),
    ]
}


def declared_metric(name: str) -> bool:
    """Whether a metric name is declared in the CATALOG (exact match, or
    covered by a `prefix*` wildcard family)."""
    if name in CATALOG:
        return True
    for spec_name in CATALOG:
        if spec_name.endswith("*") and name.startswith(spec_name[:-1]):
            return True
    return False
