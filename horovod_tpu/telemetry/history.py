"""Bounded per-metric time series — the history layer of the live perf
attribution plane.

Point-in-time gauges answer "what is the MFU *now*"; they cannot answer
"when did it drop, and was the drop a level shift or noise" — the
question the anomaly detectors (``telemetry/anomaly.py``) and a
post-mortem both need.  This module keeps a bounded ring buffer of
``(wall_ts, step, value)`` samples per tracked metric, recorded from the
:class:`~.step_stats.StepTimer` observation stream at a
``HVDT_HISTORY_SAMPLE_S`` cadence (steps arriving faster are coalesced
into one sample carrying their mean step time), so memory stays flat no
matter how long the run is.

Tracked series (all read from the process-wide registry at sample time):

* ``step_time``            — mean step seconds since the last sample
* ``examples_per_sec`` / ``mfu`` / ``goodput_fraction`` /
  ``step_time_skew`` / ``perf_deviation_ratio`` — the headline gauges
* ``wire_bytes.<axis>``    — per-mesh-axis cumulative wire bytes
  (``hvdt_wire_bytes_total`` split by axis label; detectors difference
  them into per-step rates)

Surfaces: the per-worker exporter serves the full window as
``/timeseries`` (the ``hvdtrun top`` feed); the KV telemetry snapshot
embeds a recent slice so :func:`~horovod_tpu.telemetry.aggregate.rollup`
can join ranks on step id driver-side.

Zero-overhead contract (the ``get_recorder()`` idiom): with
``HVDT_HISTORY`` unset, :func:`get_history` returns ``None`` after one
env read, and the StepTimer's feed site is a single ``is None`` branch.
Each recorded sample also runs the process-wide
:class:`~.anomaly.AnomalyMonitor` over the updated window, so detection
rides the same cadence as recording.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ..common import config
from .metrics import MetricsRegistry, default_registry

__all__ = ["Series", "MetricHistory", "get_history", "reset",
           "TRACKED_GAUGES"]

# Gauge name -> series name.  Sampled when present in the registry.
TRACKED_GAUGES: Tuple[Tuple[str, str], ...] = (
    ("hvdt_examples_per_sec", "examples_per_sec"),
    ("hvdt_mfu", "mfu"),
    ("hvdt_goodput_fraction", "goodput_fraction"),
    ("hvdt_step_time_skew", "step_time_skew"),
    ("hvdt_perf_deviation_ratio", "perf_deviation_ratio"),
)


class Series:
    """One bounded time series: a ring of ``(wall_ts, step, value)``."""

    __slots__ = ("name", "window", "_ring", "_next")

    def __init__(self, name: str, window: int):
        self.name = name
        self.window = max(1, int(window))
        self._ring: List[Tuple[float, int, float]] = []
        self._next = 0

    def append(self, wall_ts: float, step: int, value: float) -> None:
        point = (float(wall_ts), int(step), float(value))
        if len(self._ring) < self.window:
            self._ring.append(point)
        else:
            self._ring[self._next] = point
            self._next = (self._next + 1) % self.window

    def points(self) -> List[Tuple[float, int, float]]:
        """Samples in chronological order."""
        return self._ring[self._next:] + self._ring[:self._next]

    def values(self) -> List[float]:
        return [p[2] for p in self.points()]

    def steps(self) -> List[int]:
        return [p[1] for p in self.points()]

    def last(self) -> Optional[Tuple[float, int, float]]:
        pts = self.points()
        return pts[-1] if pts else None

    def __len__(self) -> int:
        return len(self._ring)


class MetricHistory:
    """The process-wide set of tracked series plus the sampling logic."""

    def __init__(self, window: Optional[int] = None,
                 sample_s: Optional[float] = None,
                 registry: Optional[MetricsRegistry] = None,
                 monitor: Optional[Any] = None,
                 clock=time.time):
        self.window = int(window if window is not None
                          else config.get_int("HVDT_HISTORY_WINDOW"))
        self.sample_s = float(
            sample_s if sample_s is not None
            else config.get_float("HVDT_HISTORY_SAMPLE_S"))
        self.registry = (registry if registry is not None
                         else default_registry())
        #: the anomaly monitor run after each recorded sample (may be
        #: None in unit tests that exercise recording alone)
        self.monitor = monitor
        self._clock = clock
        self._lock = threading.Lock()
        self._series: Dict[str, Series] = {}
        self._last_sample_ts: Optional[float] = None
        self._pending_step_s: List[float] = []
        self._samples = self.registry.counter(
            "hvdt_history_samples_total",
            "Time-series samples recorded by the metric history "
            "(HVDT_HISTORY)")

    # -- series access ------------------------------------------------------

    def series(self, name: str) -> Optional[Series]:
        with self._lock:
            return self._series.get(name)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._series)

    def _get_or_create(self, name: str) -> Series:
        s = self._series.get(name)
        if s is None:
            s = Series(name, self.window)
            self._series[name] = s
        return s

    def record(self, name: str, step: int, value: float,
               wall_ts: Optional[float] = None) -> None:
        """Append one point to one series (detectors and tests; the
        training path goes through :meth:`observe_step`)."""
        ts = self._clock() if wall_ts is None else float(wall_ts)
        with self._lock:
            self._get_or_create(name).append(ts, step, value)

    # -- the StepTimer feed --------------------------------------------------

    def observe_step(self, step: int, step_seconds: float) -> bool:
        """Feed one observed step; records a sample when the cadence
        allows (``sample_s`` seconds since the last one; 0 = always).
        Returns True when a sample was recorded."""
        now = self._clock()
        with self._lock:
            self._pending_step_s.append(float(step_seconds))
            if (self._last_sample_ts is not None and self.sample_s > 0
                    and now - self._last_sample_ts < self.sample_s):
                return False
            self._last_sample_ts = now
            pending, self._pending_step_s = self._pending_step_s, []
        self.sample(step, wall_ts=now,
                    step_seconds=sum(pending) / len(pending))
        return True

    def sample(self, step: int, wall_ts: Optional[float] = None,
               step_seconds: Optional[float] = None) -> None:
        """Record one sample across every tracked series, then run the
        anomaly monitor over the updated window."""
        ts = self._clock() if wall_ts is None else float(wall_ts)
        step = int(step)
        with self._lock:
            if step_seconds is not None:
                self._get_or_create("step_time").append(
                    ts, step, float(step_seconds))
            for gname, sname in TRACKED_GAUGES:
                g = self.registry.get(gname)
                if g is None:
                    continue
                v = g.value()
                if v == v:   # NaN-safe: an unknown gauge is no sample
                    self._get_or_create(sname).append(ts, step, float(v))
            wire = self.registry.get("hvdt_wire_bytes_total")
            if wire is not None:
                by_axis: Dict[str, float] = {}
                for labels, v in wire.items():
                    axis = labels.get("axis", "")
                    if axis:
                        by_axis[axis] = by_axis.get(axis, 0.0) + v
                for axis in sorted(by_axis):
                    self._get_or_create(f"wire_bytes.{axis}").append(
                        ts, step, by_axis[axis])
        self._samples.inc()
        if self.monitor is not None:
            try:
                self.monitor.check(self, step)
            except Exception:   # detection must never sink training
                pass

    # -- serialization (/timeseries + KV snapshot) ---------------------------

    def to_dict(self, max_points: Optional[int] = None) -> Dict[str, Any]:
        """JSON-able view: ``{"window", "sample_s", "series": {name:
        [[wall_ts, step, value], ...]}}``.  ``max_points`` caps each
        series to its most recent slice (the KV snapshot embeds a short
        tail; ``/timeseries`` serves the full window)."""
        with self._lock:
            names = sorted(self._series)
            series = {n: self._series[n].points() for n in names}
        out: Dict[str, Any] = {
            "window": self.window,
            "sample_s": self.sample_s,
            "series": {},
        }
        for n, pts in series.items():
            if max_points is not None and len(pts) > max_points:
                pts = pts[-max_points:]
            out["series"][n] = [[round(ts, 3), step, value]
                                for ts, step, value in pts]
        return out

    @classmethod
    def from_dict(cls, doc: Dict[str, Any]) -> "MetricHistory":
        """Rebuild a history from its serialized form (driver-side
        aggregation and tests; the rebuilt instance records into a
        private registry so it never collides with the live one)."""
        h = cls(window=int(doc.get("window", 0) or 1),
                sample_s=float(doc.get("sample_s", 0.0)),
                registry=MetricsRegistry())
        for name, pts in (doc.get("series") or {}).items():
            for ts, step, value in pts:
                h.record(str(name), int(step), float(value),
                         wall_ts=float(ts))
        return h


# ---------------------------------------------------------------------------
# Process-wide history (env-gated, cached on the raw env string — the
# instrument.get_recorder idiom, so per-test monkeypatching rebuilds it)
# ---------------------------------------------------------------------------

_TRUTHY = ("1", "true", "yes", "on")

_lock = threading.Lock()
_cached_env: Optional[str] = "\0unset"
_cached_history: Optional[MetricHistory] = None


def enabled() -> bool:
    """Whether the history layer is on (``HVDT_HISTORY``)."""
    return os.environ.get("HVDT_HISTORY", "").strip().lower() in _TRUTHY


def get_history() -> Optional[MetricHistory]:
    """The process-wide metric history, or ``None`` when ``HVDT_HISTORY``
    is unset — the disabled steady state costs one environ read and a
    string compare, and feed sites branch on ``is None``."""
    global _cached_env, _cached_history
    raw = os.environ.get("HVDT_HISTORY")
    if raw != _cached_env:
        with _lock:
            if raw != _cached_env:
                if enabled():
                    from .anomaly import AnomalyMonitor

                    _cached_history = MetricHistory(
                        monitor=AnomalyMonitor())
                else:
                    _cached_history = None
                _cached_env = raw
    return _cached_history


def reset() -> None:
    """Drop the cached history so the next :func:`get_history` rebinds
    against the (possibly reset) default registry — test isolation."""
    global _cached_env, _cached_history
    with _lock:
        _cached_env = "\0unset"
        _cached_history = None
