"""Distributed span tracing: per-rank Chrome-trace buffers + driver merge.

The timeline (``timeline.py``) answers "what did *this* rank's
collectives do, per tensor"; at pod scale the question that matters is
cross-rank: *which rank entered step N late, and which collective
diverged first* (the Horovod paper's Timeline, grown to the
multi-controller setting the TPU-concurrency study debugs at).  This
module is the span layer of that story:

* a :class:`Tracer` is a bounded per-rank buffer of Chrome-trace events
  (``X`` complete spans + ``i`` instants) stamped with **wall-clock**
  microseconds — ranks share no clock but NTP-level skew is enough to
  line up multi-millisecond steps in one merged view;
* every event carries a **deterministic per-step trace id**
  (``step-%08d`` from a counter advanced once per
  ``step_pipeline.donated_step`` call), so the merged trace can be
  filtered to one step across all ranks without any cross-rank
  coordination at record time;
* spans are fed from the instrumentation sites that already exist: the
  eager controller's execute path, the timeline writer's B/E pairs, and
  the ``wrap_step`` dispatch shim (telemetry/instrument.py);
* per-rank dumps ride the rendezvous KV (``/trace/<rank>``, published by
  the exporter's snapshot loop and flushed at ``hvd.shutdown()``), and
  :func:`merge_dumps` / :func:`write_merged` assemble the driver-side
  single-file view with **rank as pid** — ``hvdtrun --trace-dir`` wires
  it up end to end.

Zero-overhead contract (same idiom as ``instrument.get_recorder``):
with ``HVDT_TRACE_DIR`` unset, :func:`get_tracer` returns ``None`` — one
env read and a compare — and no site allocates anything.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from ..common import config
from ..common.logging_util import get_logger

__all__ = ["Tracer", "get_tracer", "reset", "step_trace_id", "flush",
           "merge_dumps", "collect_server_dumps", "write_merged",
           "TRACE_KV_PREFIX"]

log = get_logger(__name__)

TRACE_KV_PREFIX = "/trace/"

_DISABLED = ("", "0", "off", "none", "false")


def trace_dir() -> str:
    """The configured trace directory, or '' when tracing is off."""
    raw = config.get_str("HVDT_TRACE_DIR")
    return "" if raw.strip().lower() in _DISABLED else raw


def enabled() -> bool:
    return bool(trace_dir())


def step_trace_id(step: int) -> str:
    """Deterministic per-step trace id — every rank derives the same id
    for the same step number, so the merged trace groups without any
    record-time coordination."""
    return f"step-{int(step):08d}"


def _env_rank() -> int:
    try:
        return max(0, int(os.environ.get("HVDT_RANK", 0)))
    except ValueError:
        return 0


class Tracer:
    """Bounded per-rank buffer of Chrome-trace events.

    Recording is a dict build + deque append under a lock — cheap enough
    for the eager controller's per-response path; the jit paths only
    record at trace time.  The deque bound (``HVDT_TRACE_BUFFER``)
    keeps a long run's memory flat: forensics wants the *recent* spans.
    """

    def __init__(self, rank: Optional[int] = None,
                 capacity: Optional[int] = None):
        self.rank = _env_rank() if rank is None else int(rank)
        cap = int(capacity if capacity is not None
                  else config.get_int("HVDT_TRACE_BUFFER"))
        self._events: "deque[Dict[str, Any]]" = deque(maxlen=max(16, cap))
        self._lock = threading.Lock()
        self._tids: Dict[int, int] = {}
        self._step = 0

    # -- step bookkeeping ---------------------------------------------------
    def next_step(self) -> int:
        with self._lock:
            self._step += 1
            return self._step

    @property
    def step(self) -> int:
        with self._lock:
            return self._step

    def current_trace_id(self) -> str:
        return step_trace_id(self.step)

    # -- recording ----------------------------------------------------------
    def _tid(self) -> int:
        ident = threading.get_ident()
        tid = self._tids.get(ident)
        if tid is None:
            tid = len(self._tids)
            self._tids[ident] = tid
        return tid

    def _push(self, ev: Dict[str, Any],
              args: Optional[Dict[str, Any]]) -> None:
        with self._lock:
            a = dict(args) if args else {}
            a.setdefault("step", self._step)
            a.setdefault("trace_id", step_trace_id(self._step))
            ev["args"] = a
            ev["pid"] = self.rank
            ev["tid"] = self._tid()
            self._events.append(ev)

    def complete(self, name: str, dur_s: float, cat: str = "collective",
                 args: Optional[Dict[str, Any]] = None,
                 end_ts_us: Optional[float] = None) -> None:
        """Record a completed span ending now (or at ``end_ts_us``)."""
        end = time.time() * 1e6 if end_ts_us is None else float(end_ts_us)
        dur = max(0.0, float(dur_s)) * 1e6
        self._push({"ph": "X", "name": str(name), "cat": cat,
                    "ts": round(end - dur, 3), "dur": round(dur, 3)}, args)

    def instant(self, name: str, cat: str = "mark",
                args: Optional[Dict[str, Any]] = None) -> None:
        self._push({"ph": "i", "name": str(name), "cat": cat,
                    "ts": round(time.time() * 1e6, 3), "s": "p"}, args)

    def step_span(self, dur_s: float,
                  args: Optional[Dict[str, Any]] = None) -> None:
        """One training-step dispatch span; advances the step counter so
        the NEXT step's events carry the next deterministic trace id
        (called by instrument._TimedStep)."""
        self.complete("train.step", dur_s, cat="step", args=args)
        self.next_step()

    # -- export -------------------------------------------------------------
    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._events)

    def dump(self) -> Dict[str, Any]:
        """Chrome-trace JSON object for this rank (loadable standalone in
        ``chrome://tracing`` / Perfetto)."""
        return {
            "traceEvents": self.events(),
            "displayTimeUnit": "ms",
            "metadata": {"rank": self.rank, "clock": "unix-epoch-us"},
        }

    def write(self, path: str) -> str:
        with open(path, "w") as fh:
            json.dump(self.dump(), fh)
        return path

    def publish(self, kv, rank: Optional[int] = None) -> bool:
        """Best-effort per-rank dump publish to the rendezvous KV."""
        r = self.rank if rank is None else int(rank)
        try:
            kv.put(f"{TRACE_KV_PREFIX}{r}", json.dumps(self.dump()).encode())
            return True
        except Exception as e:
            log.debug("trace KV publish failed: %s", e)
            return False


# ---------------------------------------------------------------------------
# Process-wide tracer (env-gated, cached on the raw env string — same idiom
# as instrument.get_recorder)
# ---------------------------------------------------------------------------

_lock = threading.Lock()
_cached_env: Optional[str] = "\0unset"
_cached_tracer: Optional[Tracer] = None


def get_tracer() -> Optional[Tracer]:
    """The process-wide tracer, or ``None`` when ``HVDT_TRACE_DIR`` is
    unset — instrumentation sites branch on ``is None`` and touch
    nothing else."""
    global _cached_env, _cached_tracer
    raw = os.environ.get("HVDT_TRACE_DIR")
    if raw != _cached_env:
        with _lock:
            if raw != _cached_env:
                _cached_tracer = Tracer() if enabled() else None
                _cached_env = raw
    return _cached_tracer


def reset() -> None:
    """Drop the cached tracer (test isolation)."""
    global _cached_env, _cached_tracer
    with _lock:
        _cached_env = "\0unset"
        _cached_tracer = None


def flush(write_file: bool = True, publish: bool = True) -> Optional[str]:
    """Flush the active tracer: write ``<dir>/trace_rank<N>.json`` and
    publish the dump to the rendezvous KV when the launcher env is
    present.  Called from ``hvd.shutdown()``; never raises.  Returns the
    written path (or None)."""
    tracer = get_tracer()
    if tracer is None:
        return None
    path: Optional[str] = None
    d = trace_dir()
    if write_file and d:
        try:
            os.makedirs(d, exist_ok=True)
            path = tracer.write(
                os.path.join(d, f"trace_rank{tracer.rank}.json"))
            log.info("trace dump written to %s (%d events)", path,
                     len(tracer.events()))
        except OSError as e:
            log.warning("trace dump not written: %r", e)
    if publish and os.environ.get("HVDT_RENDEZVOUS_ADDR"):
        try:
            from ..runner.http_kv import KVClient

            tracer.publish(KVClient.from_env())
        except Exception as e:
            log.debug("trace KV flush skipped: %s", e)
    return path


# ---------------------------------------------------------------------------
# Driver-side merge: rank-as-pid single-file view
# ---------------------------------------------------------------------------

def merge_dumps(dumps: Dict[int, Dict[str, Any]]) -> Dict[str, Any]:
    """Merge per-rank Chrome-trace dumps into one document.

    Each rank becomes a Chrome-trace *process* (pid = rank, named
    ``rank N``), preserving per-rank thread rows underneath — the
    Horovod Timeline's "tensors as pids" idea turned sideways for
    cross-rank forensics.  Timestamps are rebased to the earliest event
    so the viewer opens at t=0."""
    events: List[Dict[str, Any]] = []
    min_ts: Optional[float] = None
    for rank in sorted(dumps):
        for ev in dumps[rank].get("traceEvents", []):
            ev = dict(ev)
            ev["pid"] = int(rank)
            events.append(ev)
            ts = ev.get("ts")
            if ts is not None:
                min_ts = ts if min_ts is None else min(min_ts, ts)
    base = min_ts or 0.0
    for ev in events:
        if "ts" in ev:
            ev["ts"] = round(ev["ts"] - base, 3)
    meta: List[Dict[str, Any]] = []
    for rank in sorted(dumps):
        meta.append({"ph": "M", "name": "process_name", "pid": int(rank),
                     "args": {"name": f"rank {int(rank)}"}})
        meta.append({"ph": "M", "name": "process_sort_index",
                     "pid": int(rank), "args": {"sort_index": int(rank)}})
    return {
        "traceEvents": meta + events,
        "displayTimeUnit": "ms",
        "metadata": {"ranks": sorted(int(r) for r in dumps),
                     "merged": True},
    }


def collect_server_dumps(kv_server) -> Dict[int, Dict[str, Any]]:
    """Read every worker's published trace dump out of the rendezvous KV
    store (driver side; ``kv_server`` has ``lock``/``store``)."""
    out: Dict[int, Dict[str, Any]] = {}
    with kv_server.lock:
        items = {k: v for k, v in kv_server.store.items()
                 if k.startswith(TRACE_KV_PREFIX)}
    for key, raw in items.items():
        try:
            rank = int(key[len(TRACE_KV_PREFIX):])
            out[rank] = json.loads(raw.decode())
        except (ValueError, UnicodeDecodeError):
            continue
    return out


def write_merged(kv_server, out_dir: str) -> Optional[str]:
    """Driver-side merge entry point (``hvdtrun --trace-dir`` under the
    elastic launcher): pull per-rank dumps from the KV, write one
    ``trace_merged.json``.  Returns the path, or None when no rank
    published anything."""
    dumps = collect_server_dumps(kv_server)
    if not dumps:
        return None
    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(out_dir, "trace_merged.json")
    with open(path, "w") as fh:
        json.dump(merge_dumps(dumps), fh)
    return path
