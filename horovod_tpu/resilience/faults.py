"""Deterministic fault injection for chaos testing the elastic stack.

Horovod's fault-tolerance claims were validated by killing workers in
integration tests (ref: test/integration/elastic_common.py — hosts
appear/disappear on a scripted timeline).  This module generalizes that
into a declarative, deterministic harness: a *fault plan* names what to
break, where, and when, and injection points threaded through the
production code paths fire the plan without any test-only forks of the
code under test.

Plan grammar (``HVDT_FAULT_PLAN`` or programmatic)::

    crash@step=12:rank=1,hang@step=30:secs=20,corrupt_ckpt@step=40,kv_drop@p=0.1

i.e. comma-separated ``kind@key=value:key=value`` entries.  Kinds:

* ``crash``   — ``os._exit(code)`` (default 1): a hard worker death, the
  SIGKILL/preemption analog.  Match: ``step``/``rank``.
* ``hang``    — block the injection point for ``secs`` (default 30): a
  stuck worker, the stall-escalation trigger.
* ``exc``     — raise :class:`InjectedFault` (a ``HorovodInternalError``
  subclass, so the elastic retry loop takes its restore path).
* ``corrupt_ckpt`` — flip bytes in a just-written checkpoint (fires at
  the ``checkpoint.save`` point, which passes the step directory): the
  torn-write / disk-rot case the manifest verification must catch.
  ``mode=truncate_manifest`` instead truncates the step's integrity
  manifest mid-file — the torn-manifest case a host crash between the
  manifest write and its fsync leaves behind.
* ``kv_drop`` — raise ``ConnectionError`` from rendezvous-KV client ops
  with probability ``p``: a flaky control network.
* ``pod_crash``  — ``crash`` scoped to a pod: every rank whose
  ``HVDT_POD`` matches ``pod=`` dies, e.g.
  ``pod_crash@step=10:pod=podB`` — the correlated whole-slice loss that
  dominates multi-pod fleets (the elastic driver must collapse it into
  ONE pod-removal event).
* ``pod_partition`` — the pod drops off the network for ``secs``: its
  ranks block at the injection point, so peers see stalled heartbeats /
  collectives, e.g. ``pod_partition@step=10:pod=podB:secs=20``.
* ``slow_disk`` — sleep ``secs`` at the checkpoint writer's write/fsync
  seam (``checkpoint.write`` point), e.g. ``slow_disk@step=8:secs=5``:
  a degraded filesystem.  Under the synchronous save the step loop
  stalls for the full sleep; under ``HVDT_ASYNC_CKPT`` only the
  background writer does — the testable form of the non-blocking claim.
* ``serve_crash`` — ``crash`` fired from the serving data path: the
  replica's predict admission (``serve.predict`` point, ``step`` =
  the replica's served-request count) or, via ``point=serve.dispatch``,
  the router's dispatch loop.  ``serve_crash@step=40:rank=2`` kills
  replica 2 at its 40th request — the mid-request death the router's
  retry budget must absorb without a dropped request.
* ``slow_replica`` — sleep ``secs`` in the serving path with
  probability ``p`` (``slow_replica@p=0.1:secs=2``): a degraded
  replica.  The router's hedging and p99-SLO ejection are the
  production answer; this is how they are chaos-tested.
* ``traffic_spike`` — add ``rps`` requests/second of synthetic offered
  load for ``secs`` seconds, e.g.
  ``traffic_spike@step=20:rps=300:secs=120``: the flash crowd.  A
  data-only fault — firing (at the router's ``serve.traffic`` point,
  ``step`` = the router's dispatch count, or per-tick in the fleet
  simulator) opens a spike window that
  :meth:`FaultInjector.extra_rps` reports until it expires; the fleet
  scheduler's reclaim path is the production answer.

Match keys: ``step`` (fires once at the first point whose step >= it —
commits are periodic, so exact equality would silently never fire),
``rank`` (default: any; accepts sets and ranges — ``rank=1,3`` /
``rank=0-3`` / ``rank=1,4-6`` — so targeted multi-rank faults and pod
faults share one parser), ``pod`` (default: any; matched against the
firing rank's ``HVDT_POD``), ``point`` (override the kind's default
injection point), ``p`` (probability per hit, deterministic under
``HVDT_FAULT_SEED``), ``times`` (max fires; default 1 for step-matched
faults, unlimited for probabilistic ones), plus per-kind params
(``secs``, ``code``).

Injection points in production code::

    inj = faults.get_injector()
    if inj is not None:
        inj.fire("step", step=batch, rank=rank)

The unset-plan path is two dict-free loads and an ``is None`` branch —
and wrapping helpers return their argument **unchanged**
(``instrument(fn, ...) is fn``), so an idle harness adds zero wrappers
to hot paths (verified by test).
"""

from __future__ import annotations

import dataclasses
import os
import random
import re
import time
from typing import Any, Callable, Dict, List, Optional

from ..common.exceptions import HorovodInternalError
from ..common.logging_util import get_logger

__all__ = ["InjectedFault", "FaultSpec", "FaultInjector", "parse_plan",
           "parse_rank_set", "get_injector", "instrument", "configure"]

log = get_logger(__name__)

KINDS = ("crash", "hang", "exc", "corrupt_ckpt", "kv_drop",
         "pod_crash", "pod_partition", "slow_disk",
         "serve_crash", "slow_replica", "traffic_spike")

# Default injection point per kind (spec may override with point=).
_DEFAULT_POINT = {
    "crash": "step",
    "hang": "step",
    "exc": "step",
    "corrupt_ckpt": "checkpoint.save",
    "kv_drop": "kv",
    "pod_crash": "step",
    "pod_partition": "step",
    "slow_disk": "checkpoint.write",
    "serve_crash": "serve.predict",
    "slow_replica": "serve.predict",
    "traffic_spike": "serve.traffic",
}


class InjectedFault(HorovodInternalError):
    """Raised by ``exc`` faults.  Subclasses ``HorovodInternalError`` so
    the elastic run() loop treats it exactly like a real collective
    failure (restore-from-commit), while tests can still catch the
    injected case specifically."""


def parse_rank_set(val: Any) -> frozenset:
    """``1`` / ``"1,3"`` / ``"0-3"`` / ``"1,4-6"`` → frozenset of ranks
    (shared by targeted multi-rank faults and tests)."""
    if isinstance(val, int):
        return frozenset((val,))
    if isinstance(val, (set, frozenset, list, tuple)):
        return frozenset(int(v) for v in val)
    out = set()
    for part in str(val).split(","):
        part = part.strip()
        if not part:
            continue
        lo, sep, hi = part.partition("-")
        try:
            if sep:
                lo_i, hi_i = int(lo), int(hi)
                if hi_i < lo_i:
                    raise ValueError
                out.update(range(lo_i, hi_i + 1))
            else:
                out.add(int(part))
        except ValueError:
            raise ValueError(
                f"bad rank set {val!r}: expected ranks like '1', '1,3' "
                f"or '0-3', got {part!r}") from None
    if not out:
        raise ValueError(f"bad rank set {val!r}: empty")
    return frozenset(out)


@dataclasses.dataclass
class FaultSpec:
    kind: str
    point: str
    step: Optional[int] = None
    rank: Any = None        # int | "1,3" | "0-3" | iterable; see ranks
    pod: Optional[str] = None
    p: Optional[float] = None
    secs: float = 30.0
    code: int = 1
    rps: float = 0.0        # traffic_spike: synthetic offered load
    mode: str = "payload"   # corrupt_ckpt: payload | truncate_manifest
    times: Optional[int] = None   # None = resolved default (see __post_init__)
    fired: int = 0

    def __post_init__(self) -> None:
        if self.kind not in KINDS:
            raise ValueError(
                f"unknown fault kind {self.kind!r}; valid: {', '.join(KINDS)}")
        if self.mode not in ("payload", "truncate_manifest"):
            raise ValueError(
                f"unknown corrupt_ckpt mode {self.mode!r}; valid: "
                f"payload, truncate_manifest")
        self.ranks: Optional[frozenset] = (
            parse_rank_set(self.rank) if self.rank is not None else None)
        if self.ranks is not None and len(self.ranks) == 1:
            # Singleton sets stay a plain int on .rank — the pre-set-
            # grammar surface every existing caller reads.
            self.rank = next(iter(self.ranks))
        if self.times is None:
            self.times = 1 if self.p is None else None  # None = unlimited

    def matches(self, point: str, step: Optional[int],
                rank: Optional[int], rng: random.Random,
                pod: Optional[str] = None) -> bool:
        if self.times is not None and self.fired >= self.times:
            return False
        if point != self.point:
            return False
        if self.ranks is not None and rank not in self.ranks:
            return False
        if self.pod is not None and (pod is None
                                     or str(pod) != str(self.pod)):
            return False
        if self.step is not None and (step is None or step < self.step):
            return False
        if self.p is not None and rng.random() >= self.p:
            return False
        return True


def _split_entries(plan: str) -> List[str]:
    """Split the comma-separated plan into entries, keeping rank
    sets/ranges intact: a fragment that is purely digits/ranges (no
    ``@``, no ``=``) continues the previous entry's rank list —
    ``crash@step=12:rank=1,3-5,hang@step=30`` is two entries."""
    entries: List[str] = []
    for frag in plan.split(","):
        frag = frag.strip()
        if not frag:
            continue
        if entries and re.fullmatch(r"[\d]+(-[\d]+)?", frag):
            entries[-1] += f",{frag}"
        else:
            entries.append(frag)
    return entries


def parse_plan(plan: str) -> List[FaultSpec]:
    """Parse the comma-separated plan grammar into specs (see module
    docstring).  Raises ValueError on malformed entries — a silently
    dropped fault would void the chaos run's evidence."""
    specs: List[FaultSpec] = []
    for entry in _split_entries(plan):
        kind, _, rest = entry.partition("@")
        kind = kind.strip()
        kwargs: Dict[str, Any] = {}
        if rest:
            for pair in rest.split(":"):
                key, sep, val = pair.partition("=")
                if not sep:
                    raise ValueError(
                        f"fault plan entry {entry!r}: expected key=value, "
                        f"got {pair!r}")
                key = key.strip()
                val = val.strip()
                if key in ("step", "code", "times"):
                    kwargs[key] = int(val)
                elif key == "rank":
                    kwargs[key] = parse_rank_set(val)
                elif key in ("p", "secs", "rps"):
                    kwargs[key] = float(val)
                elif key in ("point", "pod", "mode"):
                    kwargs[key] = val
                else:
                    raise ValueError(
                        f"fault plan entry {entry!r}: unknown key {key!r}; "
                        f"valid: step, rank, pod, point, p, secs, code, "
                        f"mode, times, rps")
        point = kwargs.pop("point", None) or _DEFAULT_POINT.get(kind)
        if point is None:
            raise ValueError(f"fault plan entry {entry!r}: unknown fault "
                             f"kind {kind!r}; valid: {', '.join(KINDS)}")
        specs.append(FaultSpec(kind=kind, point=point, **kwargs))
    return specs


def _env_rank() -> Optional[int]:
    raw = os.environ.get("HVDT_RANK")
    try:
        return int(raw) if raw is not None else None
    except ValueError:
        return None


def _env_pod() -> Optional[str]:
    """The firing rank's pod id (launcher contract HVDT_POD; the
    discovery ``@pod`` column on the host side)."""
    return os.environ.get("HVDT_POD") or None


class FaultInjector:
    """Executes a fault plan at named injection points.

    Deterministic: probabilistic faults draw from a seeded RNG
    (``HVDT_FAULT_SEED``, default 0), and step-matched faults fire
    exactly ``times`` times.  ``counters`` records every fire by kind so
    harnesses (bench, chaos tests) can audit what actually happened.
    """

    def __init__(self, specs: List[FaultSpec], seed: int = 0,
                 journal_path: Optional[str] = None,
                 sleep_fn: Callable[[float], None] = time.sleep,
                 exit_fn: Callable[[int], None] = os._exit):
        self.specs = specs
        self._rng = random.Random(seed)
        self._sleep = sleep_fn
        self._exit = exit_fn
        self.counters: Dict[str, int] = {}
        # traffic_spike windows: (expires_at, rps).  Timestamps come
        # from the firing context (``now=``) when given — the fleet
        # simulator runs on a virtual clock — else time.monotonic().
        self._spikes: List[tuple] = []
        # Fired-fault journal: the elastic model is PROCESS RESTART, so a
        # respawned worker builds a fresh injector — without persisted
        # fire counts, a once-only crash@step=N would kill the worker
        # again at its first commit past N in every generation.  The
        # journal (one spec index per line, appended BEFORE the action so
        # a crash is recorded) reloads each spec's fired count, making
        # `times` a per-JOB bound.  Ranks must not share one file: the
        # launcher contract appends .rank<N>.
        self._journal_path = journal_path
        if journal_path:
            try:
                with open(journal_path) as f:
                    for line in f:
                        idx = int(line)
                        if 0 <= idx < len(specs):
                            specs[idx].fired += 1
            except (OSError, ValueError):
                pass

    @classmethod
    def from_env(cls) -> Optional["FaultInjector"]:
        plan = os.environ.get("HVDT_FAULT_PLAN", "")
        if not plan.strip():
            return None
        seed = int(os.environ.get("HVDT_FAULT_SEED", "0") or 0)
        journal = os.environ.get("HVDT_FAULT_JOURNAL", "") or None
        if journal:
            rank = _env_rank()
            if rank is not None:
                journal = f"{journal}.rank{rank}"
        return cls(parse_plan(plan), seed=seed, journal_path=journal)

    @property
    def active(self) -> bool:
        return bool(self.specs)

    def fired_total(self) -> int:
        return sum(self.counters.values())

    def fire(self, point: str, step: Optional[int] = None,
             rank: Optional[int] = None, pod: Optional[str] = None,
             **ctx: Any) -> None:
        """Run every armed spec matching this injection point.  ``ctx``
        carries point-specific payload (``path=`` for checkpoint
        corruption)."""
        if rank is None:
            rank = _env_rank()
        if pod is None:
            pod = _env_pod()
        for i, spec in enumerate(self.specs):
            if spec.matches(point, step, rank, self._rng, pod=pod):
                spec.fired += 1
                self.counters[spec.kind] = self.counters.get(spec.kind, 0) + 1
                self._journal(i)
                self._execute(spec, point, step, rank, ctx)

    def _journal(self, spec_index: int) -> None:
        if not self._journal_path:
            return
        try:
            with open(self._journal_path, "a") as f:
                f.write(f"{spec_index}\n")
                f.flush()
                os.fsync(f.fileno())
        except OSError:
            pass

    # -- fault actions -----------------------------------------------------

    def _execute(self, spec: FaultSpec, point: str, step: Optional[int],
                 rank: Optional[int], ctx: Dict[str, Any]) -> None:
        log.warning("FAULT INJECTION: %s at point=%s step=%s rank=%s",
                    spec.kind, point, step, rank)
        if spec.kind in ("crash", "pod_crash", "serve_crash"):
            # os._exit, not sys.exit: a real crash runs no finalizers, no
            # atexit checkpointing, no graceful shutdown — that is the
            # point.  pod_crash is the same hard death, pod-scoped: each
            # rank of the matched pod dies at its own injection point,
            # producing the correlated whole-slice loss.
            self._exit(spec.code)
        elif spec.kind in ("hang", "pod_partition", "slow_disk",
                           "slow_replica"):
            # pod_partition: the matched pod's ranks block here — peers
            # outside the pod observe stalled heartbeats/collectives,
            # exactly what a network partition of the slice looks like.
            # slow_disk: same sleep, fired at the checkpoint writer's
            # write/fsync seam — whoever performs the write (the step
            # loop under sync saves, the background writer thread under
            # HVDT_ASYNC_CKPT) eats the stall.
            self._sleep(spec.secs)
        elif spec.kind == "exc":
            raise InjectedFault(
                f"injected fault at point={point} step={step} rank={rank}")
        elif spec.kind == "corrupt_ckpt":
            if spec.mode == "truncate_manifest":
                manifest = ctx.get("manifest")
                if manifest:
                    truncate_file(manifest)
            else:
                path = ctx.get("path")
                if path:
                    corrupt_checkpoint_dir(path)
        elif spec.kind == "kv_drop":
            raise ConnectionError(
                f"injected kv drop at point={point} (p={spec.p})")
        elif spec.kind == "traffic_spike":
            # Data-only: open a spike window instead of breaking
            # anything — extra_rps() reports it until it expires.
            now = ctx.get("now")
            now = float(now) if now is not None else time.monotonic()
            self._spikes.append((now + spec.secs, spec.rps))

    def extra_rps(self, now: Optional[float] = None) -> float:
        """Synthetic offered load (requests/second) from currently open
        ``traffic_spike`` windows.  Expired windows are pruned; ``now``
        follows the same clock the windows were opened on."""
        if not self._spikes:
            return 0.0
        t = float(now) if now is not None else time.monotonic()
        self._spikes = [(until, rps) for until, rps in self._spikes
                        if t < until]
        return sum(rps for _, rps in self._spikes)


def truncate_file(path: str, keep_fraction: float = 0.5) -> bool:
    """Truncate ``path`` mid-file (the torn-write a crash between write
    and fsync leaves) — shared by the ``corrupt_ckpt`` truncate-manifest
    mode and tests.  Returns True when the file was actually cut."""
    try:
        size = os.path.getsize(path)
        if size <= 1:
            return False
        with open(path, "r+b") as f:
            f.truncate(max(1, int(size * keep_fraction)))
    except OSError:
        return False
    log.warning("FAULT INJECTION: truncated %s to %d%% of %d bytes",
                path, int(keep_fraction * 100), size)
    return True


def corrupt_checkpoint_dir(path: str) -> Optional[str]:
    """Flip bytes in the largest regular file under ``path`` (the tensor
    payload, not metadata stubs) — returns the corrupted file, or None
    when nothing was writable.  Shared by the injector and tests."""
    victim, size = None, -1
    for root, _dirs, files in os.walk(path):
        for name in files:
            p = os.path.join(root, name)
            try:
                s = os.path.getsize(p)
            except OSError:
                continue
            if s > size:
                victim, size = p, s
    if victim is None or size <= 0:
        return None
    with open(victim, "r+b") as f:
        f.seek(max(0, size // 2))
        chunk = f.read(64) or b"\x00"
        f.seek(max(0, size // 2))
        f.write(bytes(b ^ 0xFF for b in chunk))
    log.warning("FAULT INJECTION: corrupted %d bytes of %s",
                len(chunk), victim)
    return victim


# ---------------------------------------------------------------------------
# Process-wide injector (env-configured, cached on the raw plan string)
# ---------------------------------------------------------------------------

_cached_plan: Optional[str] = None
_cached_injector: Optional[FaultInjector] = None


def get_injector() -> Optional[FaultInjector]:
    """The env-configured injector, or None when ``HVDT_FAULT_PLAN`` is
    unset/empty.  Cached on the raw env string so per-test monkeypatching
    rebuilds it, while the steady-state cost is one dict lookup and a
    string compare."""
    global _cached_plan, _cached_injector
    plan = os.environ.get("HVDT_FAULT_PLAN")
    if plan != _cached_plan:
        _cached_plan = plan
        # Explicit None-when-unset path (zero-overhead identity
        # contract): an empty plan never even parses.
        _cached_injector = (FaultInjector.from_env()
                            if plan and plan.strip() else None)
    return _cached_injector


def configure(plan: Optional[str], seed: int = 0) -> Optional[FaultInjector]:
    """Programmatic plan installation (tests, harnesses).  ``None``/empty
    disarms.  Returns the installed injector."""
    global _cached_plan, _cached_injector
    _cached_plan = plan
    _cached_injector = (FaultInjector(parse_plan(plan), seed=seed)
                        if plan and plan.strip() else None)
    return _cached_injector


def instrument(fn: Callable, point: str, step_from: Optional[str] = None):
    """Wrap ``fn`` so the injector fires at ``point`` before each call.

    The zero-overhead contract: with no plan configured this returns
    ``fn`` ITSELF (identity — no wrapper object, no indirection on the
    hot path).  ``step_from`` optionally names a kwarg of ``fn`` to
    forward as the fault step.
    """
    inj = get_injector()
    if inj is None:
        return fn

    def wrapped(*args: Any, **kwargs: Any):
        step = kwargs.get(step_from) if step_from else None
        inj.fire(point, step=step)
        return fn(*args, **kwargs)

    wrapped.__name__ = getattr(fn, "__name__", "instrumented")
    wrapped.__wrapped__ = fn
    return wrapped
