"""Stall escalation ladder: warn → abort collective → request elastic reset.

The reference's StallInspector stops at logging (and an optional
whole-job shutdown, ref: common/stall_inspector.cc) — at pod scale that
means a single hung rank quietly wedges everyone until an operator
notices.  This module grows the inspector into a *policy ladder* the
controller consumes:

1. **warn** (``HVDT_STALL_CHECK_TIME_SECONDS``) — the existing log line.
2. **abort** (``HVDT_STALL_ABORT_TIME_SECONDS``) — the coordinator
   aborts the stalled negotiation: pending ranks get an error response,
   their ``synchronize()`` raises ``HorovodInternalError``, and the
   elastic retry loop restores from the last commit instead of hanging
   forever.
3. **reset** (``HVDT_STALL_RESET_TIME_SECONDS``) — under the elastic
   launcher, additionally publish READY to the driver's registry so the
   whole generation is re-rendezvoused (the hung worker's host gets
   re-spawned or dropped by discovery).

Each level fires at most once per stall episode per tensor
(``resolve()`` re-arms).  Levels set to 0 are disabled, preserving the
seed behavior when unconfigured.
"""

from __future__ import annotations

import os
import threading
from typing import Callable, Dict, List, Optional, Set

from ..common import config
from ..common.logging_util import get_logger

__all__ = ["WARN", "ABORT", "RESET", "EscalationPolicy", "Escalator",
           "request_elastic_reset"]

log = get_logger(__name__)

WARN, ABORT, RESET = 1, 2, 3
_LEVEL_NAMES = {WARN: "warn", ABORT: "abort", RESET: "reset"}


class EscalationPolicy:
    """Age thresholds (seconds) per ladder level; 0/None disables a
    level.  Monotonicity is enforced by clamping: an abort threshold
    below warn escalates straight through, never out of order."""

    def __init__(self, warn_s: float = 60.0, abort_s: float = 0.0,
                 reset_s: float = 0.0):
        self.warn_s = warn_s
        self.abort_s = max(abort_s, warn_s) if abort_s else 0.0
        self.reset_s = max(reset_s, self.abort_s or warn_s) if reset_s else 0.0

    @classmethod
    def from_env(cls) -> "EscalationPolicy":
        return cls(
            warn_s=config.get_int("HVDT_STALL_CHECK_TIME_SECONDS"),
            abort_s=config.get_int("HVDT_STALL_ABORT_TIME_SECONDS"),
            reset_s=config.get_int("HVDT_STALL_RESET_TIME_SECONDS"))

    def level_for(self, age_s: float) -> int:
        level = 0
        if age_s > self.warn_s:
            level = WARN
        if self.abort_s and age_s > self.abort_s:
            level = ABORT
        if self.reset_s and age_s > self.reset_s:
            level = RESET
        return level


class Escalator:
    """Tracks per-tensor stall level and fires each rung once.

    Thread-safe: ``observe`` runs on the controller's background thread,
    ``drain_aborts``/``reset_requested`` are read from the same cycle
    loop, but tests drive them from the foreground.  Callbacks are
    optional — by default aborts/resets are *queued* for the consumer
    (the controller drains them inside its cycle, where it can emit error
    responses safely).
    """

    def __init__(self, policy: Optional[EscalationPolicy] = None,
                 on_warn: Optional[Callable[[str, float], None]] = None,
                 on_abort: Optional[Callable[[str], None]] = None,
                 on_reset: Optional[Callable[[], None]] = None):
        self.policy = policy or EscalationPolicy.from_env()
        self._on_warn = on_warn
        self._on_abort = on_abort
        self._on_reset = on_reset
        self._lock = threading.Lock()
        self._level: Dict[str, int] = {}
        self._pending_aborts: Set[str] = set()
        self._reset_pending = False
        self.counters: Dict[str, int] = {"warn": 0, "abort": 0, "reset": 0}

    def observe(self, name: str, age_s: float) -> int:
        """Feed one stalled tensor's age; fires every newly crossed rung
        in order.  Returns the current level."""
        target = self.policy.level_for(age_s)
        fired: List[int] = []
        with self._lock:
            current = self._level.get(name, 0)
            if target > current:
                fired = list(range(current + 1, target + 1))
                self._level[name] = target
                for lv in fired:
                    self.counters[_LEVEL_NAMES[lv]] += 1
                    if lv == ABORT:
                        self._pending_aborts.add(name)
                    elif lv == RESET:
                        self._reset_pending = True
        for lv in fired:
            log.warning("stall escalation: %s -> %s (stalled %.0fs)",
                        name, _LEVEL_NAMES[lv], age_s)
            if lv == WARN and self._on_warn is not None:
                self._on_warn(name, age_s)
            elif lv == ABORT:
                if self._on_abort is not None:
                    self._on_abort(name)
                _abort_forensics(name, age_s)
            elif lv == RESET and self._on_reset is not None:
                self._on_reset()
        return target

    def resolve(self, name: str) -> None:
        """The tensor completed (or was aborted) — re-arm its ladder."""
        with self._lock:
            self._level.pop(name, None)
            self._pending_aborts.discard(name)

    def drain_aborts(self) -> Set[str]:
        """Tensors whose negotiation the consumer must abort (cleared on
        read)."""
        with self._lock:
            out, self._pending_aborts = self._pending_aborts, set()
            return out

    def reset_requested(self) -> bool:
        """One-shot: True once per requested elastic reset."""
        with self._lock:
            out, self._reset_pending = self._reset_pending, False
            return out


def _abort_forensics(name: str, age_s: float) -> None:
    """Abort-rung forensics: when the flight recorder is on, gather every
    rank's recent collective sequence over the rendezvous KV and emit the
    structured desync report (telemetry/flight_recorder.py).  A no-op
    when the recorder is off; never raises — forensics must not worsen
    the failure being diagnosed."""
    try:
        from ..telemetry.flight_recorder import emit_desync_report

        emit_desync_report(stalled=name, age_s=age_s)
    except Exception as e:   # pragma: no cover - defensive
        log.debug("stall-abort forensics failed: %r", e)


def request_elastic_reset(reason: str = "stall escalation") -> bool:
    """Ask the elastic driver for a re-rendezvous by publishing READY to
    its worker registry (the same KV contract commit-point reporting
    uses — runner/elastic/driver.py _poll_worker_registry).  Best-effort:
    returns False outside elastic mode or when the KV is unreachable (the
    abort rung already unwedged the job; reset is an optimization)."""
    if "HVDT_RENDEZVOUS_ADDR" not in os.environ:
        return False
    try:
        from ..runner.http_kv import KVClient

        client = KVClient.from_env()
        gen = int(os.environ.get("HVDT_GENERATION", 0))
        rank = int(os.environ.get("HVDT_RANK", 0))
        client.put(f"/registry/{gen}/{rank}", b"READY")
        log.warning("requested elastic reset (%s)", reason)
        return True
    except (ConnectionError, OSError, KeyError, ValueError) as e:
        log.warning("elastic reset request failed: %r", e)
        return False
