"""Resilience subsystem: deterministic fault injection, shared
retry/backoff, preemption-safe shutdown, and stall escalation.

The elastic scaffolding (``horovod_tpu/elastic.py``, ``runner/elastic/``)
gives the framework its fault-tolerance *shape*; this package supplies
the machinery that makes the shape survive real failures — and the chaos
harness that proves it (``tests/test_resilience.py``):

* :mod:`~horovod_tpu.resilience.faults` — declarative fault plans
  (``HVDT_FAULT_PLAN``) fired at injection points threaded through the
  elastic loop, rendezvous KV, checkpoint save, and serve reload; a
  strict no-op when unset.
* :mod:`~horovod_tpu.resilience.retry` — the one exponential-backoff-
  with-jitter primitive every transient-failure path shares.
* :mod:`~horovod_tpu.resilience.preempt` — SIGTERM/SIGINT →
  emergency checkpoint → distinct clean exit code the elastic driver
  treats as host removal, not failure.
* :mod:`~horovod_tpu.resilience.escalation` — the stall ladder
  (warn → abort collective → request elastic reset) the controller
  consumes.
* :mod:`~horovod_tpu.resilience.peer_store` — the in-memory redundancy
  tier (``HVDT_PEER_STORE``): commit-point snapshots replicated to peer
  RAM over the rendezvous KV, so a lost rank restores without touching
  the filesystem; a strict no-op when unset.
"""

from .escalation import (ABORT, RESET, WARN, EscalationPolicy, Escalator,
                         request_elastic_reset)
from .faults import (FaultInjector, FaultSpec, InjectedFault, configure,
                     get_injector, instrument, parse_plan)
from .peer_store import PeerStore, get_peer_store
from .preempt import PREEMPT_EXIT_CODE, Preempted, PreemptionGuard
from .retry import Backoff, RetriesExhausted, retry

__all__ = [
    "FaultInjector", "FaultSpec", "InjectedFault", "parse_plan",
    "get_injector", "configure", "instrument",
    "Backoff", "retry", "RetriesExhausted",
    "PreemptionGuard", "Preempted", "PREEMPT_EXIT_CODE",
    "Escalator", "EscalationPolicy", "WARN", "ABORT", "RESET",
    "request_elastic_reset",
    "PeerStore", "get_peer_store",
]
