"""Preemption-safe shutdown: SIGTERM → emergency checkpoint → clean exit.

At TPU-pod scale preemption is the norm, not the exception (the
TPU-concurrency study, PAPERS.md): preemptible VMs get a SIGTERM and a
short grace window before the host disappears.  Without a handler that
window is wasted — the default action kills the process mid-step and the
job pays a full rollback to the last periodic commit.

:class:`PreemptionGuard` converts the signal into a *flag* (handlers must
stay trivial — Python runs them between bytecodes on the main thread, and
heavy work inside one deadlocks on locks the interrupted code holds).
The training loop polls ``check()`` at step/commit boundaries; on a
pending preemption it runs the registered emergency-checkpoint callback
and exits with :data:`PREEMPT_EXIT_CODE` — distinct from both failure
(non-zero) and the elastic restart code, so the elastic driver treats it
as a *clean host removal*: no blacklist, no failure count, just a
re-rendezvous without the departing host (``runner/elastic/driver.py
record_exit``).
"""

from __future__ import annotations

import os
import signal
import sys
import threading
from typing import Callable, Optional, Sequence

from ..common.logging_util import get_logger

__all__ = ["PREEMPT_EXIT_CODE", "Preempted", "PreemptionGuard"]

log = get_logger(__name__)

# Worker exit code meaning "preempted, state saved, do not blacklist me".
# Distinct from runner/elastic/driver.py RESTART_EXIT_CODE (79): a restart
# means "respawn me here", preemption means "this host is going away".
PREEMPT_EXIT_CODE = 83


class Preempted(SystemExit):
    """Raised by ``check(exit=False)`` so callers that need unwinding
    (context managers, finally blocks) can run before the process ends.
    Subclasses SystemExit: an uncaught Preempted still exits with the
    preemption code instead of a traceback."""

    def __init__(self) -> None:
        super().__init__(PREEMPT_EXIT_CODE)


class PreemptionGuard:
    """SIGTERM/SIGINT → emergency checkpoint at the next safe point.

    ::

        guard = PreemptionGuard(
            on_preempt=lambda: mgr.save(step, tree, force=True))
        with guard:
            for step in ...:
                train_step(...)
                guard.check(step=step)   # exits 83 after saving if signaled

    ``on_preempt`` runs in the *main flow* (not the signal handler), so it
    may safely touch JAX, locks, and the filesystem.  The class-level
    ``emergency_checkpoints`` counter feeds bench/chaos audit output.
    """

    emergency_checkpoints = 0   # process-wide audit counter

    def __init__(self, on_preempt: Optional[Callable[[], None]] = None,
                 signals: Sequence[int] = (signal.SIGTERM, signal.SIGINT),
                 exit_code: int = PREEMPT_EXIT_CODE):
        self._on_preempt = on_preempt
        self._signals = tuple(signals)
        self._exit_code = exit_code
        self._triggered = threading.Event()
        self._prev_handlers: dict = {}
        self._installed = False
        self.signum: Optional[int] = None

    # -- lifecycle ---------------------------------------------------------

    def install(self) -> "PreemptionGuard":
        """Register handlers (main thread only — signal.signal enforces
        this).  Idempotent; previous handlers are restored by
        :meth:`uninstall`."""
        if self._installed:
            return self
        for sig in self._signals:
            self._prev_handlers[sig] = signal.signal(sig, self._handler)
        self._installed = True
        return self

    def uninstall(self) -> None:
        if not self._installed:
            return
        for sig, prev in self._prev_handlers.items():
            try:
                signal.signal(sig, prev)
            except (ValueError, OSError):   # non-main thread / teardown
                pass
        self._prev_handlers.clear()
        self._installed = False

    def __enter__(self) -> "PreemptionGuard":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()

    # -- signal side (keep trivial) ---------------------------------------

    def _handler(self, signum, frame) -> None:
        self.signum = signum
        self._triggered.set()

    # -- main-flow side ----------------------------------------------------

    @property
    def triggered(self) -> bool:
        return self._triggered.is_set()

    def check(self, step: Optional[int] = None, exit: bool = True) -> bool:
        """Poll at a safe point.  Returns False when no signal is pending.
        Otherwise: run the emergency checkpoint, then ``sys.exit`` with
        the preemption code (or raise :class:`Preempted` when
        ``exit=False`` so the caller unwinds first)."""
        if not self._triggered.is_set():
            return False
        sig_name = (signal.Signals(self.signum).name
                    if self.signum is not None else "?")
        log.warning("preemption signal %s received — emergency checkpoint"
                    "%s", sig_name, f" at step {step}" if step is not None
                    else "")
        try:
            # Flight-recorder dump inside the grace window: the last N
            # collective events are on disk before the host disappears
            # (no-op when HVDT_FLIGHT_RECORDER is off; never raises).
            from ..telemetry.flight_recorder import dump_on_preempt

            dump_on_preempt()
        except Exception:   # pragma: no cover - defensive
            pass
        if self._on_preempt is not None:
            try:
                self._on_preempt()
                PreemptionGuard.emergency_checkpoints += 1
            except Exception as e:
                # A failed emergency save must not turn a clean preemption
                # exit into a crash-with-traceback: the periodic commit is
                # still on disk; log and take the clean exit anyway.
                log.error("emergency checkpoint failed: %r — exiting on "
                          "the last periodic commit", e)
        else:
            PreemptionGuard.emergency_checkpoints += 1
        if exit:
            # os._exit, not sys.exit: interpreter teardown would run the
            # JAX distributed client's shutdown barrier, which can block
            # on dying peers for its full heartbeat timeout — longer than
            # a preemption grace window.  The emergency checkpoint is on
            # disk; leave immediately.
            sys.stdout.flush()
            sys.stderr.flush()
            os._exit(self._exit_code)
            return True   # unreachable; keeps stubbed _exit tests sane
        raise Preempted()
