"""Shared retry/backoff primitive for every transient-failure path.

One policy object replaces the ad-hoc fixed-interval sleeps that used to
live in the rendezvous KV client (``runner/http_kv.py``), the TCP
socket-mesh bootstrap (``ops/tcp_backend.py``), and the serve-side
checkpoint watcher (``serve/reload.py``).  The shape follows the
reference's retry helpers (ref: runner/util/network.py resource retries
and gloo's bounded connect loop) hardened with the two properties
production retries need:

* **exponential growth with a cap** — a flapping dependency is probed
  quickly at first, then at a bounded steady rate instead of hammering;
* **full jitter** — concurrent workers retrying the same dead endpoint
  decorrelate instead of synchronizing into retry storms (the classic
  AWS-architecture result; every rank backing off identically re-creates
  the thundering herd each period).

Determinism: tests pass ``rng=random.Random(seed)`` (or ``jitter=0``) so
schedules are reproducible under the fault injector.
"""

from __future__ import annotations

import random
import time
from typing import Any, Callable, Optional, Tuple, Type

__all__ = ["Backoff", "retry", "RetriesExhausted"]


class RetriesExhausted(Exception):
    """Raised by :func:`retry` when attempts/deadline run out; chains the
    last underlying error as ``__cause__``."""


class Backoff:
    """Exponential backoff schedule with full jitter and an optional
    deadline.

    ::

        b = Backoff(first=0.05, cap=2.0, deadline_s=30.0)
        while not ready():
            if not b.sleep():
                raise TimeoutError(...)

    ``next_delay()`` returns the next delay without sleeping (for callers
    that wait on a condition variable instead of ``time.sleep``).
    ``sleep()`` sleeps it and returns False once the deadline would be
    exceeded (never overshooting: the final sleep is truncated to the
    remaining budget).
    """

    def __init__(self, first: float = 0.05, factor: float = 2.0,
                 cap: float = 2.0, jitter: float = 0.5,
                 deadline_s: Optional[float] = None,
                 rng: Optional[random.Random] = None,
                 sleep_fn: Callable[[float], None] = time.sleep,
                 clock: Callable[[], float] = time.monotonic):
        if first <= 0 or factor < 1.0 or cap < first:
            raise ValueError("need first > 0, factor >= 1, cap >= first")
        self.first = first
        self.factor = factor
        self.cap = cap
        self.jitter = max(0.0, min(1.0, jitter))
        self._rng = rng or random
        self._sleep = sleep_fn
        self._clock = clock
        self._deadline = (clock() + deadline_s
                          if deadline_s is not None else None)
        self.attempts = 0

    def reset(self) -> None:
        """Back to the first-delay rung (the dependency answered — the
        next outage starts the probe ladder over)."""
        self.attempts = 0

    def remaining(self) -> Optional[float]:
        if self._deadline is None:
            return None
        return self._deadline - self._clock()

    def expired(self) -> bool:
        r = self.remaining()
        return r is not None and r <= 0

    def next_delay(self) -> float:
        base = min(self.cap, self.first * (self.factor ** self.attempts))
        self.attempts += 1
        if self.jitter:
            # Full jitter over [base*(1-jitter), base]: preserves the cap
            # while decorrelating concurrent retriers.
            base -= self._rng.uniform(0.0, self.jitter) * base
        return base

    def sleep(self) -> bool:
        """Sleep the next delay (truncated to the deadline).  Returns
        False — without sleeping — once the deadline has passed."""
        delay = self.next_delay()
        rem = self.remaining()
        if rem is not None:
            if rem <= 0:
                return False
            delay = min(delay, rem)
        self._sleep(delay)
        return True


def retry(fn: Callable[[], Any], *,
          attempts: Optional[int] = None,
          deadline_s: Optional[float] = None,
          retry_on: Tuple[Type[BaseException], ...] = (ConnectionError,
                                                       OSError),
          backoff: Optional[Backoff] = None,
          on_retry: Optional[Callable[[int, BaseException], None]] = None,
          describe: str = "") -> Any:
    """Call ``fn()`` until it succeeds, backing off between failures.

    Bounded by ``attempts`` (total calls) and/or ``deadline_s`` —
    unbounded retries are a production anti-pattern (they turn a dead
    dependency into a silent hang), so at least one bound is required.
    Exceptions not in ``retry_on`` propagate immediately (a 403 is not a
    flake).  Exhaustion raises :class:`RetriesExhausted` chaining the
    last error.
    """
    if attempts is None and deadline_s is None and (
            backoff is None or backoff.remaining() is None):
        raise ValueError("retry() needs attempts= and/or deadline_s=")
    b = backoff or Backoff(deadline_s=deadline_s)
    last: Optional[BaseException] = None
    call = 0
    while True:
        call += 1
        try:
            return fn()
        except retry_on as e:
            last = e
            if on_retry is not None:
                on_retry(call, e)
            if attempts is not None and call >= attempts:
                break
            if not b.sleep():
                break
    raise RetriesExhausted(
        f"{describe or getattr(fn, '__name__', 'operation')} failed after "
        f"{call} attempt(s): {last!r}") from last
