"""Peer-replicated in-memory snapshot tier (``HVDT_PEER_STORE``).

At pod scale the crash itself is cheap — the filesystem round trip to
restore is what eats the recovery budget.  This module adds the
in-memory redundancy tier named by ROADMAP item 4: at every commit
point each rank publishes its committed snapshot over the rendezvous
KV (the HMAC-authenticated control-plane path that already survives
worker death — it lives in the driver process) and mirrors peer
``(rank + 1) % n``'s newest snapshot in host RAM.  A single-rank or
single-pod loss then restores the lost state entirely over the KV/TCP
path — ``hvdt_peer_restore_total`` counts it — without touching the
filesystem; the manifest-verified ``CheckpointManager`` disk path
remains the fallback tier when the replica is gone or corrupt.

The ZeRO tie-in is what makes replication cheap: under
``HVDT_ZERO=states|params`` each rank's optimizer state is a 1/n row of
the ``[n, shard_len]`` flat stacks (ops/zero.py), so a peer copy is one
allgather slice, not a full-state clone —
:func:`~horovod_tpu.ops.zero.extract_shard_rows` /
``implant_shard_rows`` extract and re-implant exactly that row.

Wire format (KV value at ``/peer/<rank>``)::

    b"HVPS1" + len(header) as 4 big-endian bytes + header JSON + payload

where the header carries ``{step, sha256, rank}`` and the payload is a
pickle of the committed snapshot.  The SHA-256 is verified before
unpickling; a mismatch counts as a miss, never a crash.

Zero-overhead contract (faults/telemetry idiom): with ``HVDT_PEER_STORE``
unset, :func:`get_peer_store` returns ``None`` and every integration
point is a single None-check.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import threading
from typing import Any, Dict, Optional, Tuple

from ..common.logging_util import get_logger

__all__ = ["PeerStore", "get_peer_store", "reset"]

log = get_logger(__name__)

_MAGIC = b"HVPS1"


def _pack(rank: int, step: int, payload: bytes) -> bytes:
    header = json.dumps({
        "rank": int(rank), "step": int(step),
        "sha256": hashlib.sha256(payload).hexdigest(),
    }).encode()
    return _MAGIC + len(header).to_bytes(4, "big") + header + payload


def _unpack(blob: bytes) -> Optional[Tuple[Dict[str, Any], bytes]]:
    """(header, payload) of a packed replica, or None when the blob is
    torn or fails its SHA-256 — corruption is a miss, not a crash."""
    try:
        if not blob or not blob.startswith(_MAGIC):
            return None
        hlen = int.from_bytes(blob[5:9], "big")
        header = json.loads(blob[9:9 + hlen])
        payload = blob[9 + hlen:]
        if hashlib.sha256(payload).hexdigest() != header["sha256"]:
            return None
        return header, payload
    except (ValueError, KeyError, IndexError):
        return None


class PeerStore:
    """Commit-point snapshot replication over the rendezvous KV.

    ``kv`` is any object with the ``KVClient`` get/put surface.  Every
    :meth:`commit` pushes this rank's snapshot to ``/peer/<rank>`` and
    refreshes the RAM mirror of the watched peer ``(rank + 1) % size``;
    :meth:`restore` is the recovery side — a respawned rank pulls its
    own last published snapshot back before considering disk.
    """

    def __init__(self, kv, rank: int, size: int,
                 registry=None):
        from ..telemetry.metrics import default_registry

        self.kv = kv
        self.rank = int(rank)
        self.size = max(1, int(size))
        self._lock = threading.Lock()
        # rank -> raw packed blob, refreshed at each commit: the host-RAM
        # replica tier (served back to the KV by serve_replicas when the
        # control plane lost it).
        self._replicas: Dict[int, bytes] = {}
        reg = registry if registry is not None else default_registry()
        self._restores = reg.counter(
            "hvdt_peer_restore_total",
            "Recoveries served from the peer-replicated RAM tier "
            "(no filesystem touched)")
        self._commits = reg.counter(
            "hvdt_peer_commit_total",
            "Commit-point snapshot publications to the peer tier")
        self._misses = reg.counter(
            "hvdt_peer_miss_total",
            "Peer-tier restore attempts that fell back to disk "
            "(no replica, torn blob, or SHA-256 mismatch)")
        self._replica_bytes = reg.gauge(
            "hvdt_peer_replica_bytes",
            "Host-RAM bytes holding peer snapshot replicas")
        self._replica_bytes.set_function(self._ram_bytes)

    # -- topology ----------------------------------------------------------

    def watched_peer(self) -> int:
        """The peer whose snapshot THIS rank mirrors in RAM."""
        return (self.rank + 1) % self.size

    def _ram_bytes(self) -> float:
        with self._lock:
            return float(sum(len(b) for b in self._replicas.values()))

    @staticmethod
    def _key(rank: int) -> str:
        return f"/peer/{rank}"

    # -- commit side -------------------------------------------------------

    def commit(self, step: int, snapshot: Any) -> bool:
        """Publish this rank's committed ``snapshot`` (any picklable
        tree — a JaxState ``_saved`` dict, a ZeRO shard-row payload)
        and refresh the watched peer's RAM replica.  KV failures are
        logged and swallowed: the peer tier is redundancy, and a flaky
        control network must not fail a commit that already persisted."""
        payload = pickle.dumps(snapshot, protocol=pickle.HIGHEST_PROTOCOL)
        blob = _pack(self.rank, step, payload)
        ok = True
        try:
            self.kv.put(self._key(self.rank), blob)
            self._commits.inc()
        except (ConnectionError, OSError) as e:
            log.warning("peer store: publish of step %s failed: %r", step, e)
            ok = False
        self.refresh_replica()
        return ok

    def refresh_replica(self) -> Optional[int]:
        """Pull the watched peer's newest snapshot into host RAM.
        Returns the replicated step, or None when nothing was fetched
        (solo world, missing key, or control-plane error)."""
        peer = self.watched_peer()
        if peer == self.rank:
            return None
        try:
            blob = self.kv.get(self._key(peer))
        except (ConnectionError, OSError):
            return None
        if blob is None:
            return None
        parsed = _unpack(blob)
        if parsed is None:
            return None
        with self._lock:
            self._replicas[peer] = blob
        return int(parsed[0]["step"])

    def serve_replicas(self) -> int:
        """Re-publish every RAM replica whose KV entry went missing (a
        restarted control plane) — the serving half of "peer RAM over
        the KV/TCP path".  Returns how many replicas were re-offered."""
        served = 0
        with self._lock:
            replicas = dict(self._replicas)
        for rank, blob in replicas.items():
            try:
                if self.kv.get(self._key(rank)) is None:
                    self.kv.put(self._key(rank), blob)
                    served += 1
            except (ConnectionError, OSError):
                continue
        return served

    # -- restore side ------------------------------------------------------

    def peek_step(self, rank: Optional[int] = None) -> Optional[int]:
        """Step of the newest replica published for ``rank`` (default:
        this rank), without unpickling the payload."""
        r = self.rank if rank is None else int(rank)
        try:
            blob = self.kv.get(self._key(r))
        except (ConnectionError, OSError):
            return None
        parsed = _unpack(blob) if blob is not None else None
        return int(parsed[0]["step"]) if parsed is not None else None

    def restore(self, rank: Optional[int] = None
                ) -> Optional[Tuple[Any, int]]:
        """(snapshot, step) of the newest verified replica for ``rank``
        (default: this rank), or None — the caller then falls back to
        the manifest-verified disk tier.  A served restore increments
        ``hvdt_peer_restore_total``; misses increment
        ``hvdt_peer_miss_total``."""
        r = self.rank if rank is None else int(rank)
        try:
            blob = self.kv.get(self._key(r))
        except (ConnectionError, OSError) as e:
            log.warning("peer store: restore probe failed: %r", e)
            blob = None
        parsed = _unpack(blob) if blob is not None else None
        if parsed is None:
            self._misses.inc()
            return None
        header, payload = parsed
        snapshot = pickle.loads(payload)
        self._restores.inc()
        log.info("peer store: restored rank %d from the RAM tier at "
                 "step %s (no filesystem touched)", r, header["step"])
        return snapshot, int(header["step"])

    def restore_count(self) -> int:
        return int(self._restores.total())

    # -- ZeRO shard-row convenience ---------------------------------------

    def commit_zero_shard(self, state, step: int,
                          shard_index: Optional[int] = None) -> bool:
        """Publish only this rank's ``[n, shard_len]`` row of a ZeRO
        state (ops/zero.py flat layout) — the one-allgather-slice
        replica the ROADMAP names."""
        from ..ops import zero as zero_mod

        s = self.rank if shard_index is None else int(shard_index)
        rows = zero_mod.extract_shard_rows(state, s)
        return self.commit(step, {"zero_shard": s, "rows": rows})

    def restore_zero_shard(self, state, shard_index: Optional[int] = None):
        """Re-implant this rank's replicated ZeRO row into ``state``;
        returns ``(state, step)`` with the row restored, or None."""
        from ..ops import zero as zero_mod

        got = self.restore(shard_index if shard_index is not None
                           else self.rank)
        if got is None:
            return None
        snapshot, step = got
        if not isinstance(snapshot, dict) or "rows" not in snapshot:
            return None
        s = int(snapshot.get("zero_shard", self.rank))
        return zero_mod.implant_shard_rows(state, s, snapshot["rows"]), step


# ---------------------------------------------------------------------------
# Process-wide store (env-configured, cached on the env tuple)
# ---------------------------------------------------------------------------

_cached_env: Optional[tuple] = None
_cached_store: Optional[PeerStore] = None
_cache_lock = threading.Lock()


def _env_tuple() -> tuple:
    return (os.environ.get("HVDT_PEER_STORE"),
            os.environ.get("HVDT_RENDEZVOUS_ADDR"),
            os.environ.get("HVDT_RENDEZVOUS_PORT"),
            os.environ.get("HVDT_RANK"),
            os.environ.get("HVDT_SIZE"))


def get_peer_store() -> Optional[PeerStore]:
    """The env-configured peer store, or None when ``HVDT_PEER_STORE``
    is unset (or the rendezvous KV env is absent — there is no transport
    to replicate over).  Cached on the env tuple so elastic respawns and
    per-test monkeypatching rebuild it."""
    from ..common import config

    global _cached_env, _cached_store
    env = _env_tuple()
    with _cache_lock:
        if env == _cached_env:
            return _cached_store
        _cached_env = env
        _cached_store = None
        if config.get_bool("HVDT_PEER_STORE") and env[1]:
            try:
                from ..runner.http_kv import KVClient

                _cached_store = PeerStore(
                    KVClient.from_env(),
                    rank=int(os.environ.get("HVDT_RANK", "0") or 0),
                    size=int(os.environ.get("HVDT_SIZE", "1") or 1))
            except (KeyError, ValueError) as e:
                log.warning("peer store: HVDT_PEER_STORE set but the "
                            "rendezvous env is incomplete (%r); disabled", e)
        return _cached_store


def reset() -> None:
    """Drop the cached store (tests)."""
    global _cached_env, _cached_store
    with _cache_lock:
        _cached_env = None
        _cached_store = None
