"""Cross-replica synchronized batch normalization.

TPU-native analog of the reference's SyncBatchNorm
(ref: torch/sync_batch_norm.py:1-218 — manual allgather of per-rank
mean/var/count + custom autograd; tensorflow/sync_batch_norm.py).

On TPU the idiomatic implementation is batch statistics computed with a
named-axis reduction inside the jitted step — flax's BatchNorm already
supports this via ``axis_name``, so SyncBatchNorm is that module with the
data-parallel axis bound by default, plus a functional helper for custom
norm implementations.  The gradient flows through the psum automatically
(no hand-written backward as the reference needs).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["SyncBatchNorm", "sync_batch_stats"]


def sync_batch_stats(x, axis_name: str = "dp",
                     reduction_axes=(0,)) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Global (mean, var) of ``x`` over local reduction axes AND the mesh
    axis — the statistic SyncBatchNorm normalizes with
    (ref: torch/sync_batch_norm.py _sync_batch_norm forward: allgather of
    local mean/var/count then weighted combine; here a psum of first and
    second moments, which is equivalent and rides one fused collective)."""
    m1 = jnp.mean(x, axis=reduction_axes)
    m2 = jnp.mean(jnp.square(x), axis=reduction_axes)
    m1 = lax.pmean(m1, axis_name)
    m2 = lax.pmean(m2, axis_name)
    return m1, m2 - jnp.square(m1)


try:
    import flax.linen as nn

    class SyncBatchNorm(nn.BatchNorm):
        """flax BatchNorm synchronized across the data-parallel mesh axis.

        Drop-in replacement (ref: hvd.SyncBatchNorm over torch BatchNorm):
        set ``axis_name`` to the mesh axis of the surrounding shard_map/pjit;
        defaults to 'dp'.
        """

        axis_name: Optional[str] = "dp"

except ImportError:  # pragma: no cover - flax is expected in the image
    SyncBatchNorm = None  # type: ignore
