"""Checkpoint / resume: the rank-0-save + broadcast-on-restart pattern.

The reference ships no checkpoint format of its own — it provides the
*consistency* primitives (broadcast_parameters/broadcast_optimizer_state,
rank-0-only Keras BestModelCheckpoint, elastic in-memory commit) and its
examples do rank-0 torch.save + broadcast on restart
(ref: SURVEY.md §5.4; examples/pytorch/pytorch_imagenet_resnet50.py).

Here the same pattern becomes a first-class API over Orbax (the
TPU-native checkpoint store — async, sharding-aware, the thing a JAX
user expects):

* ``save_checkpoint`` — rank 0 writes the pytree (+ step metadata);
  everyone barriers so no rank races ahead of a half-written save.
* ``restore_checkpoint`` — rank 0 reads, then the tree is broadcast to
  all ranks (multi-host consistency without shared storage).
* ``CheckpointManager`` — keep-N/interval policy around the above
  (ref: keras BestModelCheckpoint's save-frequency role).
"""

from __future__ import annotations

import os
import shutil
from typing import Any, Optional

__all__ = ["save_checkpoint", "restore_checkpoint", "CheckpointManager"]


def _named_dtype(name: str):
    """np.dtype from a dtype *name*, covering ml_dtypes extended types
    (bfloat16/float8_*) that plain ``np.dtype(name)`` doesn't know."""
    import numpy as np

    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # ships with jax

        return np.dtype(getattr(ml_dtypes, name))


def _rank_size():
    from .common import basics

    if basics.is_initialized():
        return basics.rank(), basics.size()
    return 0, 1


def _barrier():
    from .common import basics

    if basics.is_initialized() and basics.size() > 1:
        from .ops import eager

        eager.barrier()


def _checkpointer():
    """StandardCheckpointer scoped to THIS process only.

    These are rank-0-only saves (the broadcast provides multi-host
    consistency), so Orbax's default all-process barrier sync must be
    disabled — with it, rank 0 would block forever waiting for ranks
    that never call into Orbax."""
    import orbax.checkpoint as ocp

    rank, _ = _rank_size()
    return ocp.StandardCheckpointer(
        multiprocessing_options=ocp.options.MultiprocessingOptions(
            primary_host=rank, active_processes={rank}))


def save_checkpoint(path: str, tree: Any, step: Optional[int] = None,
                    force: bool = True) -> None:
    """Rank-0 Orbax save of a pytree; collective barrier on completion.

    ``tree`` may contain jax arrays (pulled to host), numpy arrays, and
    plain scalars.  ``step`` is stored alongside for resume bookkeeping.
    """
    rank, size = _rank_size()
    if rank == 0:
        import jax
        import orbax.checkpoint as ocp

        path = os.path.abspath(path)
        if force and os.path.exists(path):
            shutil.rmtree(path)
        payload = {"tree": jax.device_get(tree),
                   "step": int(step) if step is not None else -1}
        with _checkpointer() as ckptr:
            ckptr.save(path, payload)
    _barrier()


def restore_checkpoint(path: str, template: Any,
                       broadcast: bool = True) -> Any:
    """Restore a pytree saved by :func:`save_checkpoint`.

    ``template`` supplies the tree structure/shapes/dtypes (abstract or
    concrete).  With ``broadcast=True`` rank 0 reads and the result is
    broadcast — the reference's broadcast-on-restart consistency pattern,
    so only rank 0 needs the file.  Returns ``(tree, step)`` where step
    is None when absent.
    """
    rank, size = _rank_size()
    tree, step = None, None
    if rank == 0 or not broadcast:
        import jax
        import orbax.checkpoint as ocp

        with _checkpointer() as ckptr:
            payload = ckptr.restore(
                os.path.abspath(path),
                {"tree": jax.device_get(template), "step": 0})
        tree = payload["tree"]
        step = None if payload["step"] < 0 else int(payload["step"])
    if broadcast and size > 1:
        import numpy as _np

        import jax

        from .functions import broadcast_object, broadcast_parameters

        # Non-root ranks need same-shaped placeholders for the leaf
        # broadcasts — ship (treedef, step, shapes/dtypes) first.  Dtypes
        # travel by NAME, not dtype.str: for ml_dtypes types (bfloat16 —
        # the standard TPU training dtype — fp8 variants, ...) dtype.str
        # is an opaque '<V2' that round-trips to a raw void dtype and
        # breaks the collective broadcast.
        if rank == 0:
            leaves, treedef = jax.tree.flatten(tree)
            meta = (treedef, step,
                    [(_np.asarray(l).shape, _np.asarray(l).dtype.name)
                     for l in leaves])
        else:
            meta = None
        treedef, step, leaf_meta = broadcast_object(meta, root_rank=0)
        if rank != 0:
            leaves = [_np.zeros(shape, dtype=_named_dtype(name))
                      for shape, name in leaf_meta]
        leaves = broadcast_parameters(leaves, root_rank=0)
        tree = jax.tree.unflatten(treedef, leaves)
    return tree, step


class CheckpointManager:
    """Interval + keep-N checkpointing over save/restore.

    ::

        mgr = CheckpointManager("/ckpts", save_interval_steps=100, max_to_keep=3)
        for step in ...:
            ...
            mgr.save(step, {"params": params, "opt": opt_state})
        tree, step = mgr.restore_latest({"params": params, "opt": opt_state})
    """

    def __init__(self, directory: str, save_interval_steps: int = 1,
                 max_to_keep: int = 3):
        self.directory = os.path.abspath(directory)
        self.save_interval_steps = max(1, save_interval_steps)
        self.max_to_keep = max_to_keep
        os.makedirs(self.directory, exist_ok=True)

    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory, f"step_{step:012d}")

    def step_path(self, step: int) -> str:
        """Directory a given step is (or would be) stored at — the
        discovery contract the serve-side reload watcher restores from
        (serve/reload.py)."""
        return self._step_dir(step)

    def all_steps(self):
        """Sorted steps present on disk.  Only ``step_N`` *directories*
        count: stray files, foreign names, and Orbax's in-progress tmp
        dirs (``step_N.orbax-checkpoint-tmp-*`` et al. — anything whose
        suffix isn't a bare int) are skipped, so a watcher polling during
        a save never discovers a half-written checkpoint."""
        try:
            names = os.listdir(self.directory)
        except FileNotFoundError:
            return []
        out = []
        for name in names:
            if name.startswith("step_"):
                try:
                    step = int(name[5:])
                except ValueError:
                    continue
                if os.path.isdir(os.path.join(self.directory, name)):
                    out.append(step)
        return sorted(out)

    def should_save(self, step: int) -> bool:
        return step % self.save_interval_steps == 0

    def save(self, step: int, tree: Any, force: bool = False) -> bool:
        """Save if the interval says so (or force); prunes old steps.
        Returns True when a checkpoint was written."""
        if not force and not self.should_save(step):
            return False
        save_checkpoint(self._step_dir(step), tree, step=step)
        rank, _ = _rank_size()
        if rank == 0:
            steps = self.all_steps()
            for old in steps[:-self.max_to_keep]:
                shutil.rmtree(self._step_dir(old), ignore_errors=True)
        return True

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def restore_latest(self, template: Any, broadcast: bool = True):
        """(tree, step) of the newest checkpoint, or (None, None)."""
        step = self.latest_step()
        if step is None:
            return None, None
        return restore_checkpoint(self._step_dir(step), template,
                                  broadcast=broadcast)
