"""Checkpoint / resume: the rank-0-save + broadcast-on-restart pattern.

The reference ships no checkpoint format of its own — it provides the
*consistency* primitives (broadcast_parameters/broadcast_optimizer_state,
rank-0-only Keras BestModelCheckpoint, elastic in-memory commit) and its
examples do rank-0 torch.save + broadcast on restart
(ref: SURVEY.md §5.4; examples/pytorch/pytorch_imagenet_resnet50.py).

Here the same pattern becomes a first-class API over Orbax (the
TPU-native checkpoint store — async, sharding-aware, the thing a JAX
user expects):

* ``save_checkpoint`` — rank 0 writes the pytree (+ step metadata);
  everyone barriers so no rank races ahead of a half-written save.
* ``restore_checkpoint`` — rank 0 reads, then the tree is broadcast to
  all ranks (multi-host consistency without shared storage).
* ``CheckpointManager`` — keep-N/interval policy around the above
  (ref: keras BestModelCheckpoint's save-frequency role), hardened for
  production failure modes: every save writes a per-step SHA-256
  manifest (fsynced, directory-fsynced) and atomically advances a
  ``LAST_GOOD`` pointer; ``restore_latest`` verifies the manifest and
  falls back step-by-step to the newest intact checkpoint on corruption
  (counted, logged, never a crash).
* ``CheckpointManager.save_async`` — the continuous-goodput path
  (``HVDT_ASYNC_CKPT``): the step loop pays only the device→host
  snapshot (timed against ``HVDT_CKPT_SNAPSHOT_BUDGET_S``); a single
  background writer thread (queue depth 1 — a newer snapshot supersedes
  a queued older one) serializes, fsyncs, and only then advances
  ``LAST_GOOD``.  With the knob unset ``save_async`` IS the synchronous
  ``save`` (the faults/telemetry/overlap identity contract).
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import threading
import time
from typing import Any, Optional

from .common.logging_util import get_logger

__all__ = ["save_checkpoint", "restore_checkpoint", "CheckpointManager",
           "save_zero_state", "restore_zero_state",
           "save_zero_state_4d", "restore_zero_state_4d"]

log = get_logger(__name__)

_LAST_GOOD = "LAST_GOOD"


def _fsync_dir(path: str) -> None:
    """fsync a directory so a rename inside it is durable (a crash after
    ``os.replace`` but before the directory entry hits disk can otherwise
    resurrect the old pointer — or point at a file that never made it).
    Filesystems that refuse directory fsync are tolerated."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _named_dtype(name: str):
    """np.dtype from a dtype *name*, covering ml_dtypes extended types
    (bfloat16/float8_*) that plain ``np.dtype(name)`` doesn't know."""
    import numpy as np

    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # ships with jax

        return np.dtype(getattr(ml_dtypes, name))


def _rank_size():
    from .common import basics

    if basics.is_initialized():
        return basics.rank(), basics.size()
    return 0, 1


def _barrier():
    from .common import basics

    if basics.is_initialized() and basics.size() > 1:
        from .ops import eager

        eager.barrier()


def _checkpointer():
    """StandardCheckpointer scoped to THIS process only.

    These are rank-0-only saves (the broadcast provides multi-host
    consistency), so Orbax's default all-process barrier sync must be
    disabled — with it, rank 0 would block forever waiting for ranks
    that never call into Orbax."""
    import orbax.checkpoint as ocp

    rank, _ = _rank_size()
    return ocp.StandardCheckpointer(
        multiprocessing_options=ocp.options.MultiprocessingOptions(
            primary_host=rank, active_processes={rank}))


def save_checkpoint(path: str, tree: Any, step: Optional[int] = None,
                    force: bool = True) -> None:
    """Rank-0 Orbax save of a pytree; collective barrier on completion.

    ``tree`` may contain jax arrays (pulled to host), numpy arrays, and
    plain scalars.  ``step`` is stored alongside for resume bookkeeping.
    """
    rank, size = _rank_size()
    if rank == 0:
        import jax
        import orbax.checkpoint as ocp

        path = os.path.abspath(path)
        if force and os.path.exists(path):
            shutil.rmtree(path)
        payload = {"tree": jax.device_get(tree),
                   "step": int(step) if step is not None else -1}
        with _checkpointer() as ckptr:
            ckptr.save(path, payload)
    _barrier()


def restore_checkpoint(path: str, template: Any,
                       broadcast: bool = True) -> Any:
    """Restore a pytree saved by :func:`save_checkpoint`.

    ``template`` supplies the tree structure/shapes/dtypes (abstract or
    concrete).  With ``broadcast=True`` rank 0 reads and the result is
    broadcast — the reference's broadcast-on-restart consistency pattern,
    so only rank 0 needs the file.  Returns ``(tree, step)`` where step
    is None when absent.
    """
    rank, size = _rank_size()
    tree, step = None, None
    if rank == 0 or not broadcast:
        import jax
        import orbax.checkpoint as ocp

        with _checkpointer() as ckptr:
            payload = ckptr.restore(
                os.path.abspath(path),
                {"tree": jax.device_get(template), "step": 0})
        tree = payload["tree"]
        step = None if payload["step"] < 0 else int(payload["step"])
    if broadcast and size > 1:
        import numpy as _np

        import jax

        from .functions import broadcast_object, broadcast_parameters

        # Non-root ranks need same-shaped placeholders for the leaf
        # broadcasts — ship (treedef, step, shapes/dtypes) first.  Dtypes
        # travel by NAME, not dtype.str: for ml_dtypes types (bfloat16 —
        # the standard TPU training dtype — fp8 variants, ...) dtype.str
        # is an opaque '<V2' that round-trips to a raw void dtype and
        # breaks the collective broadcast.
        if rank == 0:
            leaves, treedef = jax.tree.flatten(tree)
            meta = (treedef, step,
                    [(_np.asarray(l).shape, _np.asarray(l).dtype.name)
                     for l in leaves])
        else:
            meta = None
        treedef, step, leaf_meta = broadcast_object(meta, root_rank=0)
        if rank != 0:
            leaves = [_np.zeros(shape, dtype=_named_dtype(name))
                      for shape, name in leaf_meta]
        leaves = broadcast_parameters(leaves, root_rank=0)
        tree = jax.tree.unflatten(treedef, leaves)
    return tree, step


_ZERO_MANIFEST = "zero_manifest.json"


def _sha256(data: bytes) -> str:
    h = hashlib.sha256()
    h.update(data)
    return h.hexdigest()


def save_zero_state(path: str, state, meta: dict,
                    step: Optional[int] = None) -> None:
    """Persist a ZeRO-sharded optimizer state (ops/zero.py) with
    **per-shard files and a per-shard manifest**.

    Each shard row s of every bucket stack lands in its own
    ``shard_NNNN.npz`` (on a real deployment each rank writes only its
    row; here rank 0 owns the save, matching the established
    rank-0-save + broadcast pattern), and ``zero_manifest.json`` records
    the layout metadata (``ops.zero.state_metadata``) plus a SHA-256
    per shard file, so restore can verify shard-by-shard and re-shard
    across a changed mesh size without the original transform.
    """
    import numpy as np

    rank, _ = _rank_size()
    if rank == 0:
        os.makedirs(path, exist_ok=True)
        n = int(meta["num_shards"])
        stacks = []
        if hasattr(state, "mu"):
            stacks.append(("mu", state.mu))
            stacks.append(("nu", state.nu))
        else:
            stacks.append(("trace", state.trace))
        digests = {}
        for s in range(n):
            arrays = {}
            for name, bufs in stacks:
                for bi, stack in enumerate(bufs):
                    arrays[f"{name}_{bi}"] = np.asarray(stack[s])
            fname = f"shard_{s:04d}.npz"
            fpath = os.path.join(path, fname)
            tmp = f"{fpath}.tmp.{os.getpid()}"
            with open(tmp, "wb") as f:
                np.savez(f, **arrays)
            os.replace(tmp, fpath)
            with open(fpath, "rb") as f:
                digests[fname] = _sha256(f.read())
        doc = {"meta": dict(meta),
               "step": int(step) if step is not None else None,
               # NB: hasattr(state, "count") is useless here — every
               # NamedTuple exposes tuple.count; key on the Adam-only
               # "mu" field instead.
               "count": (int(np.asarray(state.count))
                         if hasattr(state, "mu") else None),
               "buffers": [name for name, _ in stacks],
               "shards": digests}
        tmp = os.path.join(path, f".{_ZERO_MANIFEST}.tmp.{os.getpid()}")
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, os.path.join(path, _ZERO_MANIFEST))
    _barrier()


def restore_zero_state(path: str, num_shards: Optional[int] = None):
    """Restore a ZeRO-sharded optimizer state saved by
    :func:`save_zero_state`, **re-sharding across a changed mesh size**
    when ``num_shards`` differs from the saved layout (the
    shard/gather-fn pattern: shards are reassembled into the logical
    flat vectors, then re-split for the new shard count).

    Every shard file is verified against its manifest SHA-256 before
    unpickling-free ``np.load``; a mismatch raises ``ValueError`` (the
    caller's manager-level fallback decides what to do next).  Returns
    ``(state, meta, step)`` with ``meta`` describing the *restored*
    layout.
    """
    import numpy as np

    import jax.numpy as jnp

    from .ops import zero as _zero

    with open(os.path.join(path, _ZERO_MANIFEST)) as f:
        doc = json.load(f)
    meta = doc["meta"]
    n_saved = int(meta["num_shards"])
    per_buffer: dict = {name: {} for name in doc["buffers"]}
    for fname, digest in doc["shards"].items():
        fpath = os.path.join(path, fname)
        with open(fpath, "rb") as f:
            data = f.read()
        if _sha256(data) != digest:
            raise ValueError(
                f"zero checkpoint shard {fname} failed SHA-256 "
                f"verification")
        s = int(fname[len("shard_"):-len(".npz")])
        with np.load(fpath) as z:
            for key in z.files:
                name, bi = key.rsplit("_", 1)
                per_buffer[name].setdefault(int(bi), {})[s] = z[key]
    nbuckets = len(meta["buckets"])

    def stack_buffer(name):
        out = []
        for bi in range(nbuckets):
            rows = per_buffer[name][bi]
            out.append(jnp.asarray(np.stack(
                [rows[s] for s in range(n_saved)])))
        return tuple(out)

    if "mu" in per_buffer:
        state = _zero.ZeroAdamState(
            count=jnp.asarray(doc.get("count") or 0, jnp.int32),
            mu=stack_buffer("mu"), nu=stack_buffer("nu"))
    else:
        state = _zero.ZeroSgdState(trace=stack_buffer("trace"))
    if num_shards is not None and int(num_shards) != n_saved:
        state, meta = _zero.reshard_state(state, meta, int(num_shards))
    return state, meta, doc.get("step")


_ZERO_LAYOUT = "zero_layout.json"


def save_zero_state_4d(path: str, stage_states, stage_metas,
                       step: Optional[int] = None) -> None:
    """Persist a pipeline-sharded ZeRO state: one standard per-shard
    checkpoint per pipeline stage (``stage_0000/`` …, each with its own
    SHA-256 manifest via :func:`save_zero_state`) plus a top-level
    ``zero_layout.json`` naming the saved parallelism layout — the save
    half of the 4D layout-change contract.  A single-stage call is
    exactly a flat save plus the layout doc, so ``(dp=n)`` checkpoints
    round-trip through the same path."""
    stage_states = list(stage_states)
    stage_metas = list(stage_metas)
    if len(stage_states) != len(stage_metas):
        raise ValueError("one meta per stage state required")
    rank, _ = _rank_size()
    for si, (st, me) in enumerate(zip(stage_states, stage_metas)):
        save_zero_state(os.path.join(path, f"stage_{si:04d}"), st, me,
                        step)
    if rank == 0:
        doc = {"layout": {"pp": len(stage_states),
                          "dp": int(stage_metas[0]["num_shards"])},
               "stages": len(stage_states),
               "step": int(step) if step is not None else None}
        tmp = os.path.join(path, f".{_ZERO_LAYOUT}.tmp.{os.getpid()}")
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, os.path.join(path, _ZERO_LAYOUT))
    _barrier()


def restore_zero_state_4d(path: str, target_metas):
    """Restore a (possibly pipeline-sharded) ZeRO checkpoint into a
    **changed parallelism layout**.

    ``target_metas`` is one ``ops.zero.state_metadata`` per pipeline
    stage of the NEW layout (a one-element list for a flat ``(dp=n)``
    restore).  Handles every direction through the global logical
    vector (``ops.zero.concat_states`` + ``rebucket_state`` — the
    shard/gather-fn pattern): ``(pp=2, dp=4) → (dp=8)`` merges stage
    checkpoints, ``(dp=8) → (pp=2, dp=4)`` splits a flat one, and
    dp-only resharding falls out of the same path.  Every shard file of
    every stage is SHA-256-verified against its manifest before load.
    The one layout contract: the target's global LOGICAL vector must be
    stage-major (stage 0's logical elements first).  Logical order
    within a state is bucket-plan order — the reverse-topological
    overlap schedule, i.e. REVERSED flatten order — so a combined
    single-tree target matches only if stage 0's leaves sort after
    stage 1's; when in doubt, check alignment through
    ``ops.zero.flatten_state_buffers``, which reads the logical vector
    directly.

    Returns ``(states, metas, step)`` — lists with one entry per NEW
    stage.
    """
    import numpy as np

    import jax.numpy as jnp

    from .ops import zero as _zero

    layout_doc = os.path.join(path, _ZERO_LAYOUT)
    if os.path.exists(layout_doc):
        with open(layout_doc) as f:
            doc = json.load(f)
        n_stages = int(doc.get("stages", 1))
        saved = [restore_zero_state(os.path.join(path, f"stage_{s:04d}"))
                 for s in range(n_stages)]
        states = [s for s, _, _ in saved]
        metas = [m for _, m, _ in saved]
        step = saved[0][2]
    else:
        state, meta, step = restore_zero_state(path)
        states, metas = [state], [meta]
    combined, combined_meta = _zero.concat_states(states, metas)
    flats = _zero.flatten_state_buffers(combined, combined_meta)
    total = next(iter(flats.values())).size
    want = sum(int(b["size"]) for tm in target_metas
               for b in tm["buckets"])
    if want != total:
        raise ValueError(
            f"target layout covers {want} logical elements but the "
            f"checkpoint holds {total} — different parameter sets")
    out_states, out_metas = [], []
    off = 0
    for tm in target_metas:
        span = sum(int(b["size"]) for b in tm["buckets"])
        piece = {name: flat[off:off + span]
                 for name, flat in flats.items()}
        off += span
        n = int(tm["num_shards"])
        if "mu" in piece:
            st = _zero.ZeroAdamState(
                count=jnp.asarray(int(np.asarray(combined.count))
                                  if hasattr(combined, "mu") else 0,
                                  jnp.int32),
                mu=_zero._split_logical(piece["mu"], tm["buckets"], n),
                nu=_zero._split_logical(piece["nu"], tm["buckets"], n))
        else:
            st = _zero.ZeroSgdState(
                trace=_zero._split_logical(piece["trace"],
                                           tm["buckets"], n))
        out_states.append(st)
        out_metas.append(dict(tm))
    return out_states, out_metas, step


class CheckpointManager:
    """Interval + keep-N checkpointing over save/restore.

    ::

        mgr = CheckpointManager("/ckpts", save_interval_steps=100, max_to_keep=3)
        for step in ...:
            ...
            mgr.save(step, {"params": params, "opt": opt_state})
        tree, step = mgr.restore_latest({"params": params, "opt": opt_state})
    """

    def __init__(self, directory: str, save_interval_steps: int = 1,
                 max_to_keep: int = 3):
        self.directory = os.path.abspath(directory)
        self.save_interval_steps = max(1, save_interval_steps)
        self.max_to_keep = max_to_keep
        # Audit counters for the resilience story: corrupt checkpoints
        # detected-and-skipped during restore fallback (never a crash).
        self.corrupt_detected = 0
        os.makedirs(self.directory, exist_ok=True)
        from .common import config

        self._async = config.get_bool("HVDT_ASYNC_CKPT")
        self._snapshot_budget_s = config.get_float(
            "HVDT_CKPT_SNAPSHOT_BUDGET_S")
        self._writer: Optional[_AsyncCheckpointWriter] = None
        if not self._async:
            # Identity contract (faults/telemetry/overlap idiom): with
            # the knob unset, save_async IS the synchronous save — same
            # code object, no wrapper, no thread.
            self.save_async = self.save

    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory, f"step_{step:012d}")

    def step_path(self, step: int) -> str:
        """Directory a given step is (or would be) stored at — the
        discovery contract the serve-side reload watcher restores from
        (serve/reload.py)."""
        return self._step_dir(step)

    def all_steps(self):
        """Sorted steps present on disk.  Only ``step_N`` *directories*
        count: stray files, foreign names, and Orbax's in-progress tmp
        dirs (``step_N.orbax-checkpoint-tmp-*`` et al. — anything whose
        suffix isn't a bare int) are skipped, so a watcher polling during
        a save never discovers a half-written checkpoint."""
        try:
            names = os.listdir(self.directory)
        except FileNotFoundError:
            return []
        out = []
        for name in names:
            if name.startswith("step_"):
                try:
                    step = int(name[5:])
                except ValueError:
                    continue
                if os.path.isdir(os.path.join(self.directory, name)):
                    out.append(step)
        return sorted(out)

    def should_save(self, step: int) -> bool:
        return step % self.save_interval_steps == 0

    # -- integrity manifest / last-good pointer ---------------------------

    def _manifest_path(self, step: int) -> str:
        # Sibling of the step dir, not inside it: Orbax owns the dir's
        # contents, and ``all_steps`` already skips non-integer suffixes
        # so manifests are invisible to discovery.
        return self._step_dir(step) + ".manifest.json"

    @staticmethod
    def _hash_file(path: str) -> str:
        h = hashlib.sha256()
        with open(path, "rb") as f:
            for chunk in iter(lambda: f.read(1 << 20), b""):
                h.update(chunk)
        return h.hexdigest()

    def _write_manifest(self, step: int) -> None:
        """Checksum every file of a just-written step (atomic rename, so
        a crash mid-write leaves no half manifest).  The manifest is
        fsynced BEFORE the rename and the containing directory after it:
        ``LAST_GOOD`` advances only past this call, so a host crash at
        any moment can't leave the pointer naming a torn manifest."""
        from .resilience import faults

        root = self._step_dir(step)
        files = {}
        for dirpath, _dirs, names in os.walk(root):
            for name in names:
                p = os.path.join(dirpath, name)
                rel = os.path.relpath(p, root)
                files[rel] = [os.path.getsize(p), self._hash_file(p)]
        tmp = f"{self._manifest_path(step)}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({"step": step, "files": files}, f)
            f.flush()
            # The write/fsync seam: slow_disk@step=N:secs=S sleeps here,
            # in whichever thread performs the durable write.
            inj = faults.get_injector()
            if inj is not None:
                inj.fire("checkpoint.write", step=step)
            os.fsync(f.fileno())
        os.replace(tmp, self._manifest_path(step))
        _fsync_dir(self.directory)

    def verify_step(self, step: int) -> bool:
        """True when the step's files match its manifest.  A step without
        a manifest (pre-hardening checkpoint) passes — integrity checking
        must not strand old checkpoints."""
        root = self._step_dir(step)
        if not os.path.isdir(root):
            return False
        try:
            with open(self._manifest_path(step)) as f:
                manifest = json.load(f)
        except FileNotFoundError:
            log.debug("checkpoint step %d has no manifest; accepting", step)
            return True
        except (OSError, ValueError) as e:
            log.warning("checkpoint step %d manifest unreadable: %r", step, e)
            return False
        for rel, (size, digest) in manifest.get("files", {}).items():
            p = os.path.join(root, rel)
            try:
                if os.path.getsize(p) != size or self._hash_file(p) != digest:
                    return False
            except OSError:
                return False
        return True

    def _advance_last_good(self, step: int) -> None:
        tmp = os.path.join(self.directory, f".{_LAST_GOOD}.tmp.{os.getpid()}")
        with open(tmp, "w") as f:
            f.write(str(step))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(self.directory, _LAST_GOOD))
        _fsync_dir(self.directory)

    def last_good_step(self) -> Optional[int]:
        """Newest step whose save fully completed (manifest written and
        pointer atomically advanced).  Falls back to the newest on-disk
        step still present when the pointed-at one was pruned."""
        try:
            with open(os.path.join(self.directory, _LAST_GOOD)) as f:
                step = int(f.read().strip())
        except (OSError, ValueError):
            return self.latest_step()
        if os.path.isdir(self._step_dir(step)):
            return step
        steps = [s for s in self.all_steps() if s < step]
        return (steps[-1] if steps else self.latest_step())

    def _finalize_step(self, step: int) -> None:
        """Durability tail shared by the sync save and the async writer:
        manifest (fsync + dir fsync), the ``checkpoint.save`` fault
        point, the ``LAST_GOOD`` advance, and keep-N pruning."""
        self._write_manifest(step)
        from .resilience import faults

        inj = faults.get_injector()
        if inj is not None:
            inj.fire("checkpoint.save", step=step,
                     path=self._step_dir(step),
                     manifest=self._manifest_path(step))
        self._advance_last_good(step)
        steps = self.all_steps()
        for old in steps[:-self.max_to_keep]:
            shutil.rmtree(self._step_dir(old), ignore_errors=True)
            try:
                os.remove(self._manifest_path(old))
            except OSError:
                pass

    def save(self, step: int, tree: Any, force: bool = False) -> bool:
        """Save if the interval says so (or force); prunes old steps.
        Returns True when a checkpoint was written.  On rank 0 the save
        additionally writes the integrity manifest and — only after both
        are durable — advances the ``LAST_GOOD`` pointer, so a crash at
        any moment leaves the pointer on a fully verified step."""
        if not force and not self.should_save(step):
            return False
        save_checkpoint(self._step_dir(step), tree, step=step)
        rank, _ = _rank_size()
        if rank == 0:
            self._finalize_step(step)
        return True

    # -- async (non-blocking) saves ---------------------------------------

    def save_async(self, step: int, tree: Any, force: bool = False) -> bool:
        """Non-blocking save (``HVDT_ASYNC_CKPT``; otherwise this very
        attribute is rebound to :meth:`save` in ``__init__``).

        The calling thread pays only the device→host snapshot
        (``jax.device_get`` of the committed tree), timed into
        ``hvdt_ckpt_snapshot_seconds`` and checked against the
        ``HVDT_CKPT_SNAPSHOT_BUDGET_S`` stall budget.  The snapshot is
        handed to the single background writer (queue depth 1 — a newer
        snapshot supersedes a queued older one, counted in
        ``hvdt_ckpt_superseded_total``); the writer serializes, writes
        the manifest, fsyncs, and only then advances ``LAST_GOOD``.

        Rank-0-only, with **no collective barrier** — blocking peers on
        a filesystem write is exactly what this path removes.  Returns
        True when a snapshot was scheduled (on-interval or forced)."""
        if not force and not self.should_save(step):
            return False
        rank, _ = _rank_size()
        if rank != 0:
            return True
        import jax

        t0 = time.perf_counter()
        payload = {"tree": jax.device_get(tree), "step": int(step)}
        snap_s = time.perf_counter() - t0
        self._observe_snapshot(snap_s)
        self._writer_handle().submit(step, payload)
        return True

    def _writer_handle(self) -> "_AsyncCheckpointWriter":
        if self._writer is None:
            self._writer = _AsyncCheckpointWriter(self)
        return self._writer

    def _observe_snapshot(self, seconds: float) -> None:
        m = self._async_metrics()
        m["snapshot"].observe(seconds)
        if seconds > self._snapshot_budget_s:
            m["over_budget"].inc()
            log.warning(
                "checkpoint snapshot took %.3fs, over the %.1fs "
                "HVDT_CKPT_SNAPSHOT_BUDGET_S stall budget", seconds,
                self._snapshot_budget_s)
        ledger = _recovery_ledger()
        if ledger is not None:
            ledger.charge_phase("checkpoint_snapshot", seconds)

    def _async_metrics(self):
        metrics = getattr(self, "_async_metrics_cache", None)
        if metrics is None:
            from .telemetry.metrics import default_registry

            reg = default_registry()
            metrics = {
                "snapshot": reg.summary(
                    "hvdt_ckpt_snapshot_seconds",
                    "Commit-point device->host checkpoint snapshot "
                    "duration — the only stall the step loop pays under "
                    "HVDT_ASYNC_CKPT"),
                "write": reg.summary(
                    "hvdt_ckpt_write_seconds",
                    "Background checkpoint write duration (serialize + "
                    "manifest + fsync + LAST_GOOD advance)"),
                "over_budget": reg.counter(
                    "hvdt_ckpt_snapshot_over_budget_total",
                    "Snapshots exceeding HVDT_CKPT_SNAPSHOT_BUDGET_S"),
                "superseded": reg.counter(
                    "hvdt_ckpt_superseded_total",
                    "Queued async snapshots replaced by a newer one "
                    "before the writer got to them"),
                "failures": reg.counter(
                    "hvdt_ckpt_write_failures_total",
                    "Background checkpoint writes that raised (logged; "
                    "LAST_GOOD not advanced)"),
            }
            self._async_metrics_cache = metrics
        return metrics

    def _write_step_payload(self, step: int, payload: dict) -> None:
        """Writer-thread body: Orbax write of an already-host-resident
        payload (NO collective barrier — this runs off the step loop),
        then the shared durability tail."""
        path = self._step_dir(step)
        if os.path.exists(path):
            shutil.rmtree(path)
        with _checkpointer() as ckptr:
            ckptr.save(path, payload)
        self._finalize_step(step)

    def wait_for_async(self, timeout: Optional[float] = None) -> bool:
        """Block until the background writer has drained (tests,
        end-of-run flushes).  True when idle within ``timeout``;
        trivially True when async mode is off or never used."""
        if self._writer is None:
            return True
        return self._writer.wait_idle(timeout)

    def close(self) -> None:
        """Stop the background writer after draining pending work."""
        if self._writer is not None:
            self._writer.close()
            self._writer = None

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def restore_latest(self, template: Any, broadcast: bool = True):
        """(tree, step) of the newest *intact* checkpoint, or
        (None, None).

        Corruption policy: a checkpoint failing manifest verification (or
        whose restore raises) is counted (``corrupt_detected``), logged,
        and skipped — the manager falls back step-by-step to the newest
        checkpoint that restores cleanly instead of crashing the job.  In
        multi-rank broadcast mode rank 0 picks the step and the choice is
        broadcast, so ranks with skewed filesystem views cannot diverge.
        """
        rank, size = _rank_size()
        collective = broadcast and size > 1
        if collective:
            # Rank 0 verifies and chooses; everyone restores that step
            # through the usual broadcast path.
            step = None
            if rank == 0:
                for cand in reversed(self.all_steps()):
                    if self.verify_step(cand):
                        step = cand
                        break
                    self.corrupt_detected += 1
                    log.warning("checkpoint step %d failed verification; "
                                "falling back", cand)
            from .functions import broadcast_object

            step = broadcast_object(step, root_rank=0, name="ckpt_step_pick")
            if step is None:
                return None, None
            return restore_checkpoint(self._step_dir(step), template,
                                      broadcast=True)
        for cand in reversed(self.all_steps()):
            if not self.verify_step(cand):
                self.corrupt_detected += 1
                log.warning("checkpoint step %d failed verification; "
                            "falling back", cand)
                continue
            try:
                return restore_checkpoint(self._step_dir(cand), template,
                                          broadcast=broadcast)
            except Exception as e:
                # Manifest passed but the restore still failed (legacy
                # checkpoint without a manifest, or reader-level rot):
                # same policy — count, log, keep walking back.
                self.corrupt_detected += 1
                log.warning("checkpoint step %d restore failed (%r); "
                            "falling back", cand, e)
        return None, None


def _recovery_ledger():
    """The process-wide recovery ledger, or None when telemetry is off
    (zero-overhead contract — see telemetry/step_stats.recovery_ledger)."""
    from .telemetry import step_stats

    return step_stats.recovery_ledger()


class _AsyncCheckpointWriter:
    """Single background checkpoint writer with a depth-1 slot.

    ``submit`` never blocks the caller: if an older snapshot is still
    waiting for the writer, the newer one REPLACES it (at pod scale the
    only checkpoint worth finishing is the newest — writing a stale one
    first doubles the window where LAST_GOOD lags).  The write in flight
    is never abandoned mid-file; superseding only touches the queued
    slot.  Write errors are logged and counted, never raised into the
    training loop, and LAST_GOOD stays on the previous good step.
    """

    def __init__(self, manager: CheckpointManager):
        self._manager = manager
        self._cond = threading.Condition()
        self._pending: Optional[tuple] = None
        self._busy = False
        self._stopping = False
        self.last_written_step: Optional[int] = None
        self._thread = threading.Thread(
            target=self._run, name="hvdt-ckpt-writer", daemon=True)
        self._thread.start()

    def submit(self, step: int, payload: dict) -> None:
        with self._cond:
            if self._stopping:
                raise RuntimeError("async checkpoint writer is closed")
            if self._pending is not None:
                self._manager._async_metrics()["superseded"].inc()
                log.info("async checkpoint: step %s superseded by step %s "
                         "before write started", self._pending[0], step)
            self._pending = (step, payload)
            self._cond.notify_all()

    def _run(self) -> None:
        while True:
            with self._cond:
                while self._pending is None and not self._stopping:
                    self._cond.wait()
                if self._pending is None:
                    return
                step, payload = self._pending
                self._pending = None
                self._busy = True
            t0 = time.perf_counter()
            try:
                self._manager._write_step_payload(step, payload)
                self.last_written_step = step
            except Exception as e:  # noqa: BLE001 - must not kill training
                self._manager._async_metrics()["failures"].inc()
                log.warning("async checkpoint write of step %d failed "
                            "(LAST_GOOD unchanged): %r", step, e)
            finally:
                elapsed = time.perf_counter() - t0
                self._manager._async_metrics()["write"].observe(elapsed)
                ledger = _recovery_ledger()
                if ledger is not None:
                    ledger.charge_phase("checkpoint_write", elapsed,
                                        overlapped=True)
                with self._cond:
                    self._busy = False
                    self._cond.notify_all()

    def wait_idle(self, timeout: Optional[float] = None) -> bool:
        deadline = (time.monotonic() + timeout) if timeout is not None \
            else None
        with self._cond:
            while self._pending is not None or self._busy:
                remaining = None
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return False
                self._cond.wait(remaining)
            return True

    def close(self, timeout: float = 30.0) -> None:
        with self._cond:
            self._stopping = True
            self._cond.notify_all()
        self._thread.join(timeout)
        if self._thread.is_alive():
            log.warning("async checkpoint writer did not drain within "
                        "%.1fs of close()", timeout)
