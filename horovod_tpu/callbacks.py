"""Training-loop callbacks and schedules.

TPU-native analog of the reference's Keras callback set
(ref: horovod/_keras/callbacks.py — BroadcastGlobalVariablesCallback :20,
MetricAverageCallback :49, LearningRateScheduleCallback,
LearningRateWarmupCallback; keras/callbacks.py:151 BestModelCheckpoint).

JAX training loops are explicit, so these are functions/schedules rather
than Keras callback objects — same capabilities, idiomatic shape:

* ``broadcast_global_state``    — sync params+opt state from rank 0 at start
* ``average_metrics``           — allreduce epoch metrics across ranks
* ``warmup_schedule``           — LR warmup to lr*size over N steps (the
  "facebook paper" ramp the reference implements)
* ``rank_zero_only``            — checkpoint-on-rank-0 guard
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Mapping, Optional

import numpy as np

from .common import basics
from .common.process_sets import ProcessSet, global_process_set
from .functions import broadcast_optimizer_state, broadcast_parameters

__all__ = ["broadcast_global_state", "average_metrics", "warmup_schedule",
           "rank_zero_only", "BestModelCheckpoint"]


def broadcast_global_state(params, opt_state=None, root_rank: int = 0,
                           process_set: Optional[ProcessSet] = None):
    """Make rank 0's params (and optionally optimizer state) authoritative
    (ref: BroadcastGlobalVariablesCallback on_batch_end-once semantics)."""
    params = broadcast_parameters(params, root_rank, process_set)
    if opt_state is not None:
        opt_state = broadcast_optimizer_state(opt_state, root_rank,
                                              process_set)
        return params, opt_state
    return params


def average_metrics(metrics: Mapping[str, Any],
                    process_set: Optional[ProcessSet] = None) -> Dict[str, Any]:
    """Average scalar metrics across ranks at epoch end
    (ref: MetricAverageCallback _keras/callbacks.py:49)."""
    from .ops import eager

    ps = process_set or global_process_set()
    out = {}
    for key in sorted(metrics):
        val = np.asarray(metrics[key], dtype=np.float64)
        out[key] = float(eager.allreduce(val, name=f"metric.{key}",
                                         process_set=ps))
    return out


def warmup_schedule(base_lr: float, warmup_steps: int,
                    scale: Optional[float] = None,
                    after: Optional[Callable[[int], float]] = None):
    """LR schedule ramping from base_lr to base_lr*scale over warmup_steps
    (ref: LearningRateWarmupCallback — gradual warmup to the size-scaled
    rate per Goyal et al.), then following ``after`` (step→multiplier-free
    absolute schedule) or holding the scaled rate.

    ``scale`` defaults to world size (the linear-scaling rule)."""
    if scale is None:
        scale = float(max(1, basics.size())) if basics.is_initialized() else 1.0

    def schedule(step):
        import jax.numpy as jnp

        step = jnp.asarray(step, jnp.float32)
        target = base_lr * scale
        frac = jnp.minimum(step / max(1, warmup_steps), 1.0)
        warm = base_lr + (target - base_lr) * frac
        if after is None:
            return warm
        return jnp.where(step < warmup_steps, warm, after(step))

    return schedule


def rank_zero_only(fn: Callable) -> Callable:
    """Decorator: run only on (global) rank 0 — the checkpoint guard
    (ref: rank-0-only save pattern, keras/callbacks.py:151)."""

    def wrapper(*args, **kwargs):
        if basics.rank() == 0:
            return fn(*args, **kwargs)
        return None

    return wrapper


class BestModelCheckpoint:
    """Keep the best params by a monitored metric, saving on rank 0 only
    (ref: keras/callbacks.py:151 BestModelCheckpoint)."""

    def __init__(self, path: str, monitor: str = "val_loss",
                 mode: str = "min"):
        self.path = path
        self.monitor = monitor
        self.mode = mode
        self.best: Optional[float] = None

    def __call__(self, metrics: Mapping[str, Any], params) -> bool:
        value = float(np.asarray(metrics[self.monitor]))
        better = (self.best is None or
                  (value < self.best if self.mode == "min" else
                   value > self.best))
        if better:
            self.best = value
            if basics.rank() == 0:
                import pickle

                import jax

                with open(self.path, "wb") as f:
                    pickle.dump(jax.device_get(params), f)
        return better
