"""PyTorch interop: the reference's torch API over the eager core.

Re-conception of ref: horovod/torch/mpi_ops.py + functions.py — the same
user-facing calls (allreduce/allgather/broadcast/alltoall, async
variants, broadcast_parameters, broadcast_optimizer_state) accepting
``torch.Tensor``s.  Tensors cross into the framework as host arrays and
ride the eager controller's negotiation/fusion and whichever host data
plane is selected (XLA mesh or the native TCP backend) — there is no
second C++ binding to maintain (ref needed mpi_ops_v2.cc + adapters;
here the boundary is numpy's zero-copy view of CPU torch tensors).

Grad hooks for a DistributedOptimizer-style wrapper are torch-side sugar
over these calls; see examples in the docs.  GPU torch tensors are not
supported (this is a TPU framework — torch is CPU-only in its world).
"""

from __future__ import annotations

from typing import Any, Iterable, Mapping, Optional, Tuple

import numpy as np

from ..common.types import ReduceOp

__all__ = ["allreduce", "allreduce_async", "allgather", "allgather_async",
           "broadcast", "broadcast_async", "alltoall", "synchronize",
           "broadcast_parameters", "broadcast_optimizer_state",
           "DistributedOptimizer", "SyncBatchNorm"]


def __getattr__(name):
    if name == "DistributedOptimizer":
        from .torch_optimizer import DistributedOptimizer

        return DistributedOptimizer
    if name == "SyncBatchNorm":
        from .torch_sync_batch_norm import SyncBatchNorm

        return SyncBatchNorm
    raise AttributeError(name)


def _torch():
    import torch

    return torch


def _to_np(t) -> np.ndarray:
    torch = _torch()
    if isinstance(t, torch.Tensor):
        if t.device.type != "cpu":
            raise ValueError("interop.torch supports CPU tensors only")
        return t.detach().numpy()
    return np.asarray(t)


def _from_np(a: np.ndarray, like) -> "Any":
    torch = _torch()
    return torch.from_numpy(np.ascontiguousarray(a)).to(like.dtype)


def allreduce_async(tensor, average: Optional[bool] = None,
                    name: Optional[str] = None, op=None,
                    process_set=None) -> int:
    from ..ops import eager

    return eager.allreduce_async(_to_np(tensor), average=average, name=name,
                                 op=op, process_set=process_set)


def allreduce(tensor, average: Optional[bool] = None,
              name: Optional[str] = None, op=None, process_set=None):
    from ..ops import eager

    out = eager.allreduce(_to_np(tensor), average=average, name=name, op=op,
                          process_set=process_set)
    return _from_np(np.asarray(out), tensor)


def allgather_async(tensor, name: Optional[str] = None,
                    process_set=None) -> int:
    from ..ops import eager

    return eager.allgather_async(_to_np(tensor), name=name,
                                 process_set=process_set)


def allgather(tensor, name: Optional[str] = None, process_set=None):
    from ..ops import eager

    out = eager.allgather(_to_np(tensor), name=name, process_set=process_set)
    return _from_np(np.asarray(out), tensor)


def broadcast_async(tensor, root_rank: int = 0,
                    name: Optional[str] = None, process_set=None) -> int:
    from ..ops import eager

    return eager.broadcast_async(_to_np(tensor), root_rank=root_rank,
                                 name=name, process_set=process_set)


def broadcast(tensor, root_rank: int = 0, name: Optional[str] = None,
              process_set=None):
    from ..ops import eager

    out = eager.broadcast(_to_np(tensor), root_rank=root_rank, name=name,
                          process_set=process_set)
    return _from_np(np.asarray(out), tensor)


def alltoall(tensor, splits=None, name: Optional[str] = None,
             process_set=None):
    from ..ops import eager

    out, recv_splits = eager.alltoall(
        _to_np(tensor),
        splits=None if splits is None else _to_np(splits),
        name=name, process_set=process_set)
    return _from_np(np.asarray(out), tensor), recv_splits


def synchronize(handle: int):
    """Resolve an async handle to a numpy array (callers re-wrap as torch
    if needed; ref: mpi_ops.py synchronize)."""
    from ..ops import eager

    return eager.synchronize(handle)


def broadcast_parameters(params, root_rank: int = 0,
                         process_set=None) -> None:
    """In-place broadcast of a ``model.state_dict()`` or named_parameters
    iterable (ref: torch/functions.py:30 broadcast_parameters)."""
    torch = _torch()
    if isinstance(params, Mapping):
        items: Iterable[Tuple[str, Any]] = params.items()
    else:
        items = params
    for name, p in items:
        if not isinstance(p, torch.Tensor):
            continue
        new = broadcast(p, root_rank=root_rank, name=f"param.{name}",
                        process_set=process_set)
        with torch.no_grad():
            p.copy_(new)


def broadcast_optimizer_state(optimizer, root_rank: int = 0,
                              process_set=None) -> None:
    """Broadcast a torch optimizer's state tensors in place
    (ref: torch/functions.py broadcast_optimizer_state)."""
    torch = _torch()
    # Names must be rank-stable: key on (group index, param index, state
    # key) — id(p) differs per process and would never negotiate
    # (same convention as functions.py broadcast_parameters.{i}).
    for gi, group in enumerate(optimizer.param_groups):
        for pi, p in enumerate(group["params"]):
            state = optimizer.state.get(p, {})
            for key, value in sorted(state.items()):
                if isinstance(value, torch.Tensor):
                    new = broadcast(value, root_rank=root_rank,
                                    name=f"opt.{gi}.{pi}.{key}",
                                    process_set=process_set)
                    with torch.no_grad():
                        value.copy_(new)
