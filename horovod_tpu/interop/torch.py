"""PyTorch interop: the reference's torch API over the eager core.

Re-conception of ref: horovod/torch/mpi_ops.py + functions.py — the same
user-facing calls (allreduce/allgather/broadcast/alltoall, async
variants, broadcast_parameters, broadcast_optimizer_state) accepting
``torch.Tensor``s.  Tensors cross into the framework as host arrays and
ride the eager controller's negotiation/fusion and whichever host data
plane is selected (XLA mesh or the native TCP backend) — there is no
second C++ binding to maintain (ref needed mpi_ops_v2.cc + adapters;
here the boundary is numpy's zero-copy view of CPU torch tensors).

Grad hooks for a DistributedOptimizer-style wrapper are torch-side sugar
over these calls; see examples in the docs.  GPU torch tensors are not
supported (this is a TPU framework — torch is CPU-only in its world).
"""

from __future__ import annotations

from typing import Any, Iterable, Mapping, Optional, Tuple

import numpy as np

from ..common.types import ReduceOp

__all__ = ["allreduce", "allreduce_async", "allreduce_", "allreduce_async_",
           "grouped_allreduce", "grouped_allreduce_async",
           "grouped_allreduce_", "grouped_allreduce_async_",
           "sparse_allreduce_async",
           "allgather", "allgather_async",
           "broadcast", "broadcast_async", "broadcast_", "broadcast_async_",
           "alltoall", "alltoall_async", "join", "barrier", "poll",
           "synchronize",
           "broadcast_parameters", "broadcast_optimizer_state",
           "broadcast_object", "allgather_object", "Compression",
           "DistributedOptimizer", "SyncBatchNorm"]


def __getattr__(name):
    if name == "DistributedOptimizer":
        from .torch_optimizer import DistributedOptimizer

        return DistributedOptimizer
    if name == "SyncBatchNorm":
        from .torch_sync_batch_norm import SyncBatchNorm

        return SyncBatchNorm
    if name == "Compression":
        from ..ops.compression import Compression

        return Compression
    if name in ("broadcast_object", "allgather_object"):
        from .. import functions

        return getattr(functions, name)
    if name == "elastic":
        # ref: horovod.torch.elastic submodule (TorchState, run)
        from . import torch_elastic

        return torch_elastic
    if name == "TorchState":
        from .torch_elastic import TorchState

        return TorchState
    from . import core_attr

    found = core_attr(name)
    if found is not None:
        return found
    raise AttributeError(name)


def _torch():
    import torch

    return torch


def _to_np(t) -> np.ndarray:
    torch = _torch()
    if isinstance(t, torch.Tensor):
        if t.device.type != "cpu":
            raise ValueError("interop.torch supports CPU tensors only")
        t = t.detach()
        if t.dtype == torch.bfloat16:
            # torch has no direct numpy conversion for bf16; reinterpret
            # the bits so the wire dtype stays bfloat16 (ml_dtypes).
            import ml_dtypes

            return t.contiguous().view(torch.int16).numpy().view(
                ml_dtypes.bfloat16)
        return t.numpy()
    return np.asarray(t)


def _np_to_torch(a: np.ndarray):
    torch = _torch()
    a = np.ascontiguousarray(a)
    try:
        import ml_dtypes

        if a.dtype == ml_dtypes.bfloat16:
            return torch.from_numpy(a.view(np.int16)).view(torch.bfloat16)
    except ImportError:
        pass
    return torch.from_numpy(a)


def _from_np(a: np.ndarray, like) -> "Any":
    return _np_to_torch(a).to(like.dtype)


def _register(handle: int, like, inplace=None) -> int:
    """Attach (result torch dtype, weakref to in-place target) to the
    handle so this module's ``synchronize`` resolves it to a torch
    tensor (the reference contract: mpi_ops.py synchronize returns the
    output tensor, the in-place variants mutate their argument).  The
    metadata lives INSIDE the handle entry (HandleManager.set_meta), so
    it shares the handle's lifetime exactly — no side table to leak for
    abandoned or foreign-resolved handles.  The in-place target is a
    weak reference: a dropped tensor is never pinned by a pending op."""
    import weakref

    from ..ops import eager

    eager._controller().handles.set_meta(
        handle, (like.dtype,
                 None if inplace is None else weakref.ref(inplace)))
    return handle


def allreduce_async(tensor, average: Optional[bool] = None,
                    name: Optional[str] = None, op=None,
                    process_set=None) -> int:
    from ..ops import eager

    h = eager.allreduce_async(_to_np(tensor), average=average, name=name,
                              op=op, process_set=process_set)
    return _register(h, tensor)


def allreduce_async_(tensor, average: Optional[bool] = None,
                     name: Optional[str] = None, op=None,
                     process_set=None) -> int:
    """In-place async allreduce (ref: mpi_ops.py allreduce_async_):
    ``synchronize`` copies the result back into ``tensor``."""
    from ..ops import eager

    h = eager.allreduce_async(_to_np(tensor), average=average, name=name,
                              op=op, process_set=process_set)
    return _register(h, tensor, inplace=tensor)


def allreduce_(tensor, average: Optional[bool] = None,
               name: Optional[str] = None, op=None, process_set=None):
    return synchronize(allreduce_async_(tensor, average=average, name=name,
                                        op=op, process_set=process_set))


def grouped_allreduce_async(tensors, average: Optional[bool] = None,
                            name: Optional[str] = None, op=None,
                            process_set=None):
    from ..ops import eager

    handles = eager.grouped_allreduce_async(
        [_to_np(t) for t in tensors], average=average, name=name, op=op,
        process_set=process_set)
    return [_register(h, t) for h, t in zip(handles, tensors)]


def grouped_allreduce_async_(tensors, average: Optional[bool] = None,
                             name: Optional[str] = None, op=None,
                             process_set=None):
    from ..ops import eager

    handles = eager.grouped_allreduce_async(
        [_to_np(t) for t in tensors], average=average, name=name, op=op,
        process_set=process_set)
    return [_register(h, t, inplace=t) for h, t in zip(handles, tensors)]


def grouped_allreduce(tensors, **kwargs):
    return [synchronize(h)
            for h in grouped_allreduce_async(tensors, **kwargs)]


def grouped_allreduce_(tensors, **kwargs):
    return [synchronize(h)
            for h in grouped_allreduce_async_(tensors, **kwargs)]


def sparse_allreduce_async(tensor, name: str, op=None, process_set=None):
    """Sparse (COO) allreduce via double allgather
    (ref: torch/mpi_ops.py:556-578 sparse_allreduce_async).

    Returns a zero-arg callable that, when invoked, synchronizes both
    allgathers and builds the combined sparse tensor — the reference's
    handle contract for the torch optimizer's sparse path."""
    torch = _torch()
    from ..common.types import ReduceOp
    from ..common.process_sets import global_process_set

    ps = process_set or global_process_set()
    t = tensor.coalesce() if tensor.layout == torch.sparse_coo else tensor
    indices_h = allgather_async(
        t._indices().transpose(0, 1).contiguous(),
        name=f"{name}.indices", process_set=ps)
    values_h = allgather_async(t._values(), name=f"{name}.values",
                               process_set=ps)
    average = op is None or op == ReduceOp.AVERAGE

    def handle():
        values = synchronize(values_h)
        indices = synchronize(indices_h)
        if average:
            values = values / ps.size()
        if indices.dim() == 0 or values.dim() == 0:
            return torch.sparse_coo_tensor(
                torch.zeros((t._indices().shape[0], 0), dtype=torch.long),
                torch.zeros((0,), dtype=t._values().dtype), t.size())
        return torch.sparse_coo_tensor(indices.transpose(0, 1), values,
                                       t.size())

    return handle


def allreduce(tensor, average: Optional[bool] = None,
              name: Optional[str] = None, op=None, process_set=None):
    from ..ops import eager

    out = eager.allreduce(_to_np(tensor), average=average, name=name, op=op,
                          process_set=process_set)
    return _from_np(np.asarray(out), tensor)


def allgather_async(tensor, name: Optional[str] = None,
                    process_set=None) -> int:
    from ..ops import eager

    h = eager.allgather_async(_to_np(tensor), name=name,
                              process_set=process_set)
    return _register(h, tensor)


def allgather(tensor, name: Optional[str] = None, process_set=None):
    from ..ops import eager

    out = eager.allgather(_to_np(tensor), name=name, process_set=process_set)
    return _from_np(np.asarray(out), tensor)


def broadcast_async(tensor, root_rank: int = 0,
                    name: Optional[str] = None, process_set=None) -> int:
    from ..ops import eager

    h = eager.broadcast_async(_to_np(tensor), root_rank=root_rank,
                              name=name, process_set=process_set)
    return _register(h, tensor)


def broadcast_async_(tensor, root_rank: int = 0,
                     name: Optional[str] = None, process_set=None) -> int:
    """In-place async broadcast (ref: mpi_ops.py broadcast_async_)."""
    from ..ops import eager

    h = eager.broadcast_async(_to_np(tensor), root_rank=root_rank,
                              name=name, process_set=process_set)
    return _register(h, tensor, inplace=tensor)


def broadcast_(tensor, root_rank: int = 0, name: Optional[str] = None,
               process_set=None):
    return synchronize(broadcast_async_(tensor, root_rank=root_rank,
                                        name=name, process_set=process_set))


def broadcast(tensor, root_rank: int = 0, name: Optional[str] = None,
              process_set=None):
    from ..ops import eager

    out = eager.broadcast(_to_np(tensor), root_rank=root_rank, name=name,
                          process_set=process_set)
    return _from_np(np.asarray(out), tensor)


def alltoall_async(tensor, splits=None, name: Optional[str] = None,
                   process_set=None) -> int:
    from ..ops import eager

    h = eager.alltoall_async(
        _to_np(tensor),
        splits=None if splits is None else _to_np(splits),
        name=name, process_set=process_set)
    return _register(h, tensor)


def alltoall(tensor, splits=None, name: Optional[str] = None,
             process_set=None):
    from ..ops import eager

    out, recv_splits = eager.alltoall(
        _to_np(tensor),
        splits=None if splits is None else _to_np(splits),
        name=name, process_set=process_set)
    return _from_np(np.asarray(out), tensor), recv_splits


def join(process_set=None) -> int:
    """Signal no more work on this rank (ref: torch/mpi_ops.py:954)."""
    from ..ops import eager

    return eager.join(process_set)


def barrier(process_set=None) -> None:
    from ..ops import eager

    eager.barrier(process_set)


def poll(handle: int) -> bool:
    from ..ops import eager

    return eager.poll(handle)


def synchronize(handle: int):
    """Resolve an async handle (ref: mpi_ops.py synchronize).  Handles
    issued through this module come back as torch tensors (alltoall: a
    ``(tensor, recv_splits)`` pair); in-place handles additionally copy
    the result into the original tensor and return it.  Foreign handles
    resolve to the eager layer's numpy result."""
    from ..ops import eager

    meta = eager._controller().handles.take_meta(handle)
    out = eager.synchronize(handle)
    dtype, inplace_ref = meta if meta is not None else (None, None)
    inplace = inplace_ref() if inplace_ref is not None else None
    if dtype is None:
        return out
    torch = _torch()
    recv_splits = None
    if isinstance(out, tuple):          # alltoall: (output, recv_splits)
        out, recv_splits = out
    result = _np_to_torch(np.asarray(out)).to(dtype)
    if inplace is not None:
        # Mutate through .data so leaf tensors with requires_grad=True
        # (model parameters — the broadcast_parameters use case) accept
        # the copy; shapes never change for allreduce/broadcast.
        with torch.no_grad():
            if inplace.shape != result.shape:
                inplace.data = result
            else:
                inplace.data.copy_(result)
        result = inplace
    return result if recv_splits is None else (result, recv_splits)


def broadcast_parameters(params, root_rank: int = 0,
                         process_set=None) -> None:
    """In-place broadcast of a ``model.state_dict()`` or named_parameters
    iterable (ref: torch/functions.py:30 broadcast_parameters)."""
    torch = _torch()
    if isinstance(params, Mapping):
        items: Iterable[Tuple[str, Any]] = params.items()
    else:
        items = params
    for name, p in items:
        if not isinstance(p, torch.Tensor):
            continue
        new = broadcast(p, root_rank=root_rank, name=f"param.{name}",
                        process_set=process_set)
        with torch.no_grad():
            p.copy_(new)


def broadcast_optimizer_state(optimizer, root_rank: int = 0,
                              process_set=None) -> None:
    """Broadcast a torch optimizer's state tensors in place
    (ref: torch/functions.py broadcast_optimizer_state)."""
    torch = _torch()
    # Names must be rank-stable: key on (group index, param index, state
    # key) — id(p) differs per process and would never negotiate
    # (same convention as functions.py broadcast_parameters.{i}).
    for gi, group in enumerate(optimizer.param_groups):
        for pi, p in enumerate(group["params"]):
            state = optimizer.state.get(p, {})
            for key, value in sorted(state.items()):
                if isinstance(value, torch.Tensor):
                    new = broadcast(value, root_rank=root_rank,
                                    name=f"opt.{gi}.{pi}.{key}",
                                    process_set=process_set)
                    with torch.no_grad():
                        value.copy_(new)
