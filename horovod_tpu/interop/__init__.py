"""Framework interop: collective APIs over non-JAX tensors.

The reference binds TF/PyTorch/MXNet natively (SURVEY.md §2.4); here JAX
is the first-class citizen and other frameworks interoperate through the
eager named-collective path (host arrays ride the same negotiation,
fusion, and data plane).  Available adapters: ``interop.torch`` (incl.
the grad-hook ``DistributedOptimizer``), ``interop.tf``
(``DistributedGradientTape``, ``broadcast_variables``, Keras callbacks),
``interop.mxnet`` (``DistributedOptimizer``/``DistributedTrainer``).
All import their framework lazily.
"""

import importlib


def __getattr__(name):
    # `hvd.interop.tf` / `hvd.interop.torch` resolve without an explicit
    # submodule import (the docstring usage pattern).
    if name in ("tf", "torch", "mxnet"):
        return importlib.import_module(f".{name}", __name__)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


#: Core names every reference framework module re-exports (ref: e.g.
#: horovod/torch/__init__.py imports init/rank/size/... from mpi_ops) —
#: the interop bindings resolve them from the top-level package so
#: ``import horovod_tpu.interop.torch as hvd`` is drop-in for
#: ``import horovod.torch as hvd``.
CORE_NAMES = (
    "init", "shutdown", "is_initialized",
    "rank", "size", "local_rank", "local_size", "cross_rank",
    "cross_size", "is_homogeneous",
    "Average", "Sum", "Adasum", "Min", "Max", "Product", "ReduceOp",
    "ProcessSet", "global_process_set", "add_process_set",
    "remove_process_set", "process_set_by_id",
    "mpi_built", "mpi_enabled", "mpi_threads_supported",
    "gloo_built", "gloo_enabled", "nccl_built", "ddl_built", "ccl_built",
    "cuda_built", "rocm_built", "xla_built", "tpu_available",
    "start_timeline", "stop_timeline",
    "HorovodInternalError", "HostsUpdatedInterrupt",
)


def core_attr(name):
    """Resolve a core-API name against the top-level package, or None."""
    if name in CORE_NAMES:
        import horovod_tpu

        return getattr(horovod_tpu, name)
    return None
