"""Framework interop: collective APIs over non-JAX tensors.

The reference binds TF/PyTorch/MXNet natively (SURVEY.md §2.4); here JAX
is the first-class citizen and other frameworks interoperate through the
eager named-collective path (host arrays ride the same negotiation,
fusion, and data plane).  Available adapters: ``interop.torch`` (incl.
the grad-hook ``DistributedOptimizer``), ``interop.tf``
(``DistributedGradientTape``, ``broadcast_variables``, Keras callbacks),
``interop.mxnet`` (``DistributedOptimizer``/``DistributedTrainer``).
All import their framework lazily.
"""

import importlib


def __getattr__(name):
    # `hvd.interop.tf` / `hvd.interop.torch` resolve without an explicit
    # submodule import (the docstring usage pattern).
    if name in ("tf", "torch", "mxnet"):
        return importlib.import_module(f".{name}", __name__)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
