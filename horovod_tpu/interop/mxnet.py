"""MXNet interop: the reference's mxnet API over the eager core.

Re-conception of ref: horovod/mxnet/__init__.py + mpi_ops.py — the same
user-facing surface (allreduce/allreduce_/grouped variants, allgather,
broadcast/broadcast_, alltoall, ``DistributedOptimizer`` wrapping an
``mx.optimizer.Optimizer``, ``DistributedTrainer`` subclassing
``mx.gluon.Trainer``, ``broadcast_parameters``) accepting NDArrays.

Like the torch interop, tensors cross into the framework as host arrays
(``NDArray.asnumpy()`` / slice-assignment back) and ride the eager
controller's negotiation/fusion + host data plane — no C++ binding to
maintain (the reference needs ~1.2k LoC of mxnet/mpi_ops.cc + adapters).
``mxnet`` itself is imported lazily on first use, so the module is
importable (and the pure-protocol pieces testable) without mxnet
installed.

The reference's ``priority=`` argument is accepted and ignored: it maps
to MXNet's dependency-engine priority queues, which have no analog in
this host data plane (ops complete in negotiation order).
"""

from __future__ import annotations

import warnings
from collections import OrderedDict, defaultdict
from typing import Any, Optional

import numpy as np

__all__ = ["allreduce", "allreduce_", "grouped_allreduce",
           "grouped_allreduce_", "allgather", "broadcast", "broadcast_",
           "alltoall", "broadcast_parameters",
           "broadcast_object", "allgather_object", "Compression",
           "DistributedOptimizer", "DistributedTrainer"]


def __getattr__(name):
    if name == "DistributedOptimizer":
        return _optimizer_cls()
    if name == "DistributedTrainer":
        return _trainer_cls()
    if name == "Compression":
        from ..ops.compression import Compression

        return Compression
    if name in ("broadcast_object", "allgather_object"):
        from .. import functions

        return getattr(functions, name)
    from . import core_attr

    found = core_attr(name)
    if found is not None:
        return found
    raise AttributeError(name)


def _mx():
    import mxnet

    return mxnet


def _to_np(t) -> np.ndarray:
    if hasattr(t, "asnumpy"):
        return t.asnumpy()
    return np.asarray(t)


def _from_np(a: np.ndarray, like):
    if hasattr(like, "asnumpy"):
        mx = _mx()
        # Preserve the input's device: without ctx= the result lands on
        # the default CPU context even for a GPU NDArray input (the torch
        # binding raises for non-CPU instead; here mxnet can round-trip).
        ctx = getattr(like, "context", None)
        if ctx is not None:
            return mx.nd.array(a, dtype=a.dtype, ctx=ctx)
        return mx.nd.array(a, dtype=a.dtype)
    return a


def allreduce(tensor, average=None, name: Optional[str] = None, op=None,
              prescale_factor: float = 1.0, postscale_factor: float = 1.0,
              priority: int = 0, process_set=None):
    from ..ops import eager

    out = eager.allreduce(_to_np(tensor), average=average, name=name, op=op,
                          prescale_factor=prescale_factor,
                          postscale_factor=postscale_factor,
                          process_set=process_set)
    return _from_np(np.asarray(out), tensor)


def allreduce_(tensor, average=None, name: Optional[str] = None, op=None,
               prescale_factor: float = 1.0, postscale_factor: float = 1.0,
               priority: int = 0, process_set=None):
    """In-place allreduce (ref: mxnet/mpi_ops.py allreduce_)."""
    from ..ops import eager

    out = eager.allreduce(_to_np(tensor), average=average, name=name, op=op,
                          prescale_factor=prescale_factor,
                          postscale_factor=postscale_factor,
                          process_set=process_set)
    tensor[:] = np.asarray(out)
    return tensor


def grouped_allreduce(tensors, average=None, name: Optional[str] = None,
                      op=None, prescale_factor: float = 1.0,
                      postscale_factor: float = 1.0, priority: int = 0,
                      process_set=None):
    from ..ops import eager

    outs = eager.grouped_allreduce(
        [_to_np(t) for t in tensors], average=average, name=name, op=op,
        prescale_factor=prescale_factor, postscale_factor=postscale_factor,
        process_set=process_set)
    return [_from_np(np.asarray(o), t) for o, t in zip(outs, tensors)]


def grouped_allreduce_(tensors, average=None, name: Optional[str] = None,
                       op=None, prescale_factor: float = 1.0,
                       postscale_factor: float = 1.0, priority: int = 0,
                       process_set=None):
    from ..ops import eager

    outs = eager.grouped_allreduce(
        [_to_np(t) for t in tensors], average=average, name=name, op=op,
        prescale_factor=prescale_factor, postscale_factor=postscale_factor,
        process_set=process_set)
    for t, o in zip(tensors, outs):
        t[:] = np.asarray(o)
    return list(tensors)


def allgather(tensor, name: Optional[str] = None, priority: int = 0,
              process_set=None):
    from ..ops import eager

    out = eager.allgather(_to_np(tensor), name=name, process_set=process_set)
    return _from_np(np.asarray(out), tensor)


def broadcast(tensor, root_rank: int = 0, name: Optional[str] = None,
              priority: int = 0, process_set=None):
    from ..ops import eager

    out = eager.broadcast(_to_np(tensor), root_rank=root_rank, name=name,
                          process_set=process_set)
    return _from_np(np.asarray(out), tensor)


def broadcast_(tensor, root_rank: int = 0, name: Optional[str] = None,
               priority: int = 0, process_set=None):
    from ..ops import eager

    out = eager.broadcast(_to_np(tensor), root_rank=root_rank, name=name,
                          process_set=process_set)
    tensor[:] = np.asarray(out)
    return tensor


def alltoall(tensor, splits=None, name: Optional[str] = None,
             priority: int = 0, process_set=None):
    from ..ops import eager

    out, recv_splits = eager.alltoall(
        _to_np(tensor), splits=None if splits is None else _to_np(splits),
        name=name, process_set=process_set)
    return _from_np(np.asarray(out), tensor), recv_splits


def broadcast_parameters(params, root_rank: int = 0,
                         prefix: Optional[str] = None) -> None:
    """Broadcast ``Block.collect_params()`` / ``Module.get_params()`` /
    a plain dict of NDArrays from root (ref: mxnet/__init__.py
    broadcast_parameters — same three accepted shapes; name-keyed so the
    negotiation matches across ranks regardless of insertion order)."""
    prefix = prefix or ""
    if hasattr(params, "items"):
        items = sorted(params.items())
    elif isinstance(params, (list, tuple)):
        items = list(enumerate(params))
    else:
        raise ValueError("invalid params of type: %s" % type(params))
    for name, p in items:
        # gluon Parameter vs raw NDArray
        tensor = p.data() if hasattr(p, "data") and callable(p.data) else p
        broadcast_(tensor, root_rank=root_rank,
                   name=f"{prefix}param.{name}")


def _split_list(xs, parts: int):
    """Near-equal contiguous split (ref: common/util.py split_list)."""
    n = len(xs)
    k, r = divmod(n, parts)
    out, i = [], 0
    for j in range(parts):
        step = k + (1 if j < r else 0)
        if step:
            out.append(xs[i:i + step])
        i += step
    return out


_CLS_CACHE: dict = {}


def _optimizer_cls():
    if "opt" in _CLS_CACHE:
        return _CLS_CACHE["opt"]
    mx = _mx()
    from ..common.process_sets import global_process_set

    class DistributedOptimizer(mx.optimizer.Optimizer):
        """Wrap an ``mx.optimizer.Optimizer``: allreduce each grad before
        the underlying update (ref: mxnet/__init__.py:42-104 — same
        rescale_grad normalization so the sum-allreduce averages)."""

        def __init__(self, optimizer, gradient_predivide_factor: float = 1.0,
                     num_groups: int = 0, process_set=None):
            self._optimizer = optimizer
            self._process_set = process_set or global_process_set()
            self._optimizer.rescale_grad *= (
                gradient_predivide_factor / self._process_set.size())
            self._gradient_predivide_factor = gradient_predivide_factor
            self._num_groups = num_groups

        def __getattr__(self, item):
            return getattr(self._optimizer, item)

        def create_state(self, index, weight):
            return self._optimizer.create_state(index, weight)

        def create_state_multi_precision(self, index, weight):
            return self._optimizer.create_state_multi_precision(index,
                                                                weight)

        def _do_allreduce(self, index, grad):
            if self._process_set.size() == 1:
                return
            pre = 1.0 / self._gradient_predivide_factor
            if isinstance(index, (tuple, list)):
                if self._num_groups > 0:
                    for i, (grads, indices) in enumerate(zip(
                            _split_list(grad, self._num_groups),
                            _split_list(index, self._num_groups))):
                        grouped_allreduce_(
                            tensors=grads, average=False,
                            name=f"{indices[0]}:{indices[-1]}", priority=-i,
                            prescale_factor=pre,
                            process_set=self._process_set)
                else:
                    for i in range(len(index)):
                        allreduce_(grad[i], average=False,
                                   name=str(index[i]), priority=-i,
                                   prescale_factor=pre,
                                   process_set=self._process_set)
            else:
                allreduce_(grad, average=False, name=str(index),
                           prescale_factor=pre,
                           process_set=self._process_set)

        def update(self, index, weight, grad, state):
            if self._process_set.included():
                self._do_allreduce(index, grad)
            self._optimizer.update(index, weight, grad, state)

        def update_multi_precision(self, index, weight, grad, state):
            if self._process_set.included():
                self._do_allreduce(index, grad)
            self._optimizer.update_multi_precision(index, weight, grad,
                                                   state)

        def set_learning_rate(self, lr):
            self._optimizer.set_learning_rate(lr)

        def set_lr_mult(self, args_lr_mult):
            self._optimizer.set_lr_mult(args_lr_mult)

        def set_wd_mult(self, args_wd_mult):
            self._optimizer.set_wd_mult(args_wd_mult)

    _CLS_CACHE["opt"] = DistributedOptimizer
    return DistributedOptimizer


def _trainer_cls():
    if "trainer" in _CLS_CACHE:
        return _CLS_CACHE["trainer"]
    mx = _mx()
    from ..common.process_sets import global_process_set
    from ..ops.compression import Compression

    class DistributedTrainer(mx.gluon.Trainer):
        """gluon Trainer whose ``_allreduce_grads`` rides our collectives
        instead of kvstore push/pull (ref: mxnet/__init__.py:110-216 —
        same sum+rescale averaging, dtype-homogeneous grouped enqueue,
        optional wire compression)."""

        def __init__(self, params, optimizer, optimizer_params=None,
                     compression=None,
                     gradient_predivide_factor: float = 1.0,
                     prefix: Optional[str] = None, num_groups: int = 0,
                     process_set=None):
            # None -> environment selection (HVDT_COMPRESSION/HVDT_QUANT)
            self._compression = compression or Compression.from_env()
            self._process_set = process_set or global_process_set()
            if isinstance(optimizer, _optimizer_cls()):
                optimizer = optimizer._optimizer
                warnings.warn("DistributedTrainer does not take "
                              "DistributedOptimizer as its optimizer. "
                              "We have unwrapped it for you.")
            # Deterministic parameter order across ranks.  gluon
            # Parameter objects define no ordering, so sequences sort by
            # name when available and otherwise keep the caller's order
            # (already deterministic when built identically per rank).
            if isinstance(params, dict):
                params = OrderedDict(sorted(params.items()))
            elif isinstance(params, (list, tuple)):
                if all(hasattr(p, "name") for p in params):
                    params = sorted(params, key=lambda p: p.name)
                else:
                    params = list(params)
            super().__init__(params, optimizer,
                             optimizer_params=optimizer_params, kvstore=None)
            self._scale *= (gradient_predivide_factor /
                            self._process_set.size())
            self._gradient_predivide_factor = gradient_predivide_factor
            assert prefix is None or isinstance(prefix, str)
            self._prefix = prefix if prefix else ""
            self._num_groups = num_groups

        def _allreduce_grads(self):
            ps = self._process_set
            if ps.size() == 1 or not ps.included():
                return
            pre = 1.0 / self._gradient_predivide_factor
            none = Compression.none
            if self._num_groups > 0:
                grads, names, ctxs = [], [], []
                for i, param in enumerate(self._params):
                    if param.grad_req != "null":
                        tc, ctx = self._compression.compress(
                            param.list_grad()[0])
                        grads.append(tc)
                        ctxs.append(ctx)
                        names.append(self._prefix + str(i))
                for i, (group_grads, group_names) in enumerate(zip(
                        _split_list(grads, self._num_groups),
                        _split_list(names, self._num_groups))):
                    by_dtype = defaultdict(list)
                    for g, n in zip(group_grads, group_names):
                        by_dtype[np.dtype(g.dtype)].append((g, n))
                    for entries in by_dtype.values():
                        gs, ns = zip(*entries)
                        grouped_allreduce_(
                            tensors=list(gs), average=False,
                            name=f"{ns[0]}:{ns[-1]}", priority=-i,
                            prescale_factor=pre, process_set=ps)
                if self._compression is not none:
                    reduced = iter(zip(grads, ctxs))
                    for param in self._params:
                        if param.grad_req != "null":
                            tc, ctx = next(reduced)
                            param.list_grad()[0][:] = _to_np(
                                self._compression.decompress(tc, ctx))
            else:
                for i, param in enumerate(self._params):
                    if param.grad_req != "null":
                        tc, ctx = self._compression.compress(
                            param.list_grad()[0])
                        allreduce_(tc, average=False,
                                   name=self._prefix + str(i), priority=-i,
                                   prescale_factor=pre, process_set=ps)
                        if self._compression is not none:
                            param.list_grad()[0][:] = _to_np(
                                self._compression.decompress(tc, ctx))

    _CLS_CACHE["trainer"] = DistributedTrainer
    return DistributedTrainer
