"""Cross-rank synchronized BatchNorm for torch models.

Re-conception of ref: horovod/torch/sync_batch_norm.py:40-210 — the same
two-piece design: an ``nn.Module`` that runs plain BN when it wouldn't
change anything (eval mode, or world size 1) and a
``torch.autograd.Function`` that synchronizes batch statistics in
forward (count/mean/var summed across ranks through the eager
controller) and the gradient reductions (sum_dy, sum_dy_xmu) in
backward.  The math follows torch's native SyncBatchNorm formulas;
weight/bias gradients stay local (they ride the optimizer's own
gradient allreduce like every other parameter).

This module imports torch at import time (it IS the torch binding);
``interop.torch`` re-exports ``SyncBatchNorm`` lazily.
"""

from __future__ import annotations

import numpy as np
import torch
from torch.nn.modules.batchnorm import _BatchNorm

__all__ = ["SyncBatchNorm"]


def _allreduce_sum(arr: np.ndarray, name: str) -> np.ndarray:
    from ..common.types import ReduceOp
    from ..ops import eager

    return np.asarray(eager.allreduce(arr, name=name, op=ReduceOp.SUM))


class _SyncBNFunction(torch.autograd.Function):
    @staticmethod
    def forward(ctx, x, weight, bias, eps):
        # x: [N, C, *]; reduce over all dims but C
        dims = [0] + list(range(2, x.dim()))
        n_local = x.numel() // x.shape[1]
        s = x.sum(dims)                       # [C]
        ss = (x * x).sum(dims)                # [C]
        # .to(float64) in torch first: half/bf16 tensors have no direct
        # numpy conversion.
        packed = np.concatenate([
            np.asarray([float(n_local)], np.float64),
            s.detach().to(torch.float64).numpy(),
            ss.detach().to(torch.float64).numpy()])
        packed = _allreduce_sum(packed, "sync_bn.stats")
        c = x.shape[1]
        n_total = float(packed[0])
        mean = torch.from_numpy(
            (packed[1:1 + c] / n_total).astype(np.float32))
        var = torch.from_numpy(
            (packed[1 + c:] / n_total).astype(np.float32)) - mean * mean
        invstd = torch.rsqrt(var + eps)

        shape = [1, c] + [1] * (x.dim() - 2)
        # Normalize in the INPUT dtype (half/bf16 models must get
        # half/bf16 out, matching torch's native SyncBatchNorm); the f32
        # mean/var returned for running-stats stay f32.
        out = (x - mean.to(x.dtype).view(shape)) * \
            invstd.to(x.dtype).view(shape)
        if weight is not None:
            out = out * weight.view(shape) + bias.view(shape)
        ctx.save_for_backward(x, weight, mean, invstd)
        ctx.n_total = n_total
        ctx.dims = dims
        ctx.bn_shape = shape
        count = torch.tensor(n_total)
        ctx.mark_non_differentiable(mean, var, count)
        return out, mean, var, count

    @staticmethod
    def backward(ctx, grad_output, _gmean, _gvar, _gcount):
        x, weight, mean, invstd = ctx.saved_tensors
        dims, shape, n = ctx.dims, ctx.bn_shape, ctx.n_total
        xmu = x - mean.to(x.dtype).view(shape)

        sum_dy = grad_output.sum(dims)                     # [C]
        sum_dy_xmu = (grad_output * xmu).sum(dims)         # [C]
        packed = np.concatenate([
            sum_dy.detach().to(torch.float64).numpy(),
            sum_dy_xmu.detach().to(torch.float64).numpy()])
        packed = _allreduce_sum(packed, "sync_bn.grads")
        c = x.shape[1]
        g_sum_dy = torch.from_numpy(
            packed[:c].astype(np.float32)).to(x.dtype)
        g_sum_dy_xmu = torch.from_numpy(
            packed[c:].astype(np.float32)).to(x.dtype)

        w = (weight.to(x.dtype).view(shape) if weight is not None
             else torch.ones_like(invstd, dtype=x.dtype).view(shape))
        inv = invstd.to(x.dtype).view(shape)
        dx = w * inv * (
            grad_output
            - g_sum_dy.view(shape) / n
            - xmu * (inv ** 2) * g_sum_dy_xmu.view(shape) / n)

        if weight is not None:
            dw = (grad_output * xmu * inv).sum(dims)
            db = sum_dy
        else:
            dw = db = None
        return dx, dw, db, None


class SyncBatchNorm(_BatchNorm):
    """Drop-in ``nn.BatchNorm*`` replacement with cross-rank statistics
    (ref: hvd.SyncBatchNorm — same constructor surface).  Module-level
    class: picklable (``torch.save(model)``) and isinstance-able."""

    def __init__(self, num_features: int, eps: float = 1e-5,
                 momentum: float = 0.1, affine: bool = True,
                 track_running_stats: bool = True):
        super().__init__(num_features, eps=eps, momentum=momentum,
                         affine=affine,
                         track_running_stats=track_running_stats)

    def _check_input_dim(self, x):
        if x.dim() < 2:
            raise ValueError(
                f"expected at least 2D input (got {x.dim()}D)")

    def forward(self, x):
        self._check_input_dim(x)
        from ..common import basics

        world = basics.size() if basics.is_initialized() else 1
        if not self.training or world == 1:
            # plain BN (eval mode uses running stats; size-1 sync is a
            # no-op) — ref: _maybe_run_sync_bn fallthrough
            return super().forward(x)
        out, mean, var, count = _SyncBNFunction.apply(
            x, self.weight if self.affine else None,
            self.bias if self.affine else None, self.eps)
        if self.track_running_stats:
            with torch.no_grad():
                self.num_batches_tracked += 1
                if self.momentum is None:
                    # cumulative moving average (torch semantics)
                    m = 1.0 / float(self.num_batches_tracked)
                else:
                    m = self.momentum
                # unbiased correction from the TRUE global count the
                # forward reduced (ragged per-rank batches stay exact)
                n = float(count)
                unbiased = var * (n / max(n - 1.0, 1.0))
                self.running_mean.mul_(1 - m).add_(mean, alpha=m)
                self.running_var.mul_(1 - m).add_(unbiased, alpha=m)
        return out
