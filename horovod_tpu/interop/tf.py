"""TensorFlow interop — the reference's TF binding surface on this
framework's eager controller.

Re-conception of ref: horovod/tensorflow/__init__.py (allreduce :55,
DistributedGradientTape :758-842), tensorflow/functions.py
(broadcast_variables), _keras/callbacks.py (BroadcastGlobalVariables,
MetricAverage).  TF eager tensors cross into the controller as numpy
(same adapter shape as interop/torch.py); collectives are differentiable
via ``tf.custom_gradient`` exactly like the reference registers TF
gradients for its custom ops (ref: tensorflow/mpi_ops.py gradient
registrations).

TensorFlow is imported lazily — importing horovod_tpu.interop.tf only
costs TF when a function is first called.
"""

from __future__ import annotations

from typing import Any, Iterable, Optional, Sequence

import numpy as np

from ..common.types import ReduceOp

__all__ = ["allreduce", "allgather", "broadcast", "broadcast_variables",
           "DistributedGradientTape", "BroadcastGlobalVariablesCallback",
           "MetricAverageCallback"]


def _to_np(t) -> np.ndarray:
    return t.numpy() if hasattr(t, "numpy") else np.asarray(t)


def allreduce(tensor, name: Optional[str] = None,
              op: ReduceOp = ReduceOp.AVERAGE,
              prescale_factor: float = 1.0, postscale_factor: float = 1.0,
              process_set=None):
    """Differentiable allreduce of a TF tensor (ref: tensorflow/
    __init__.py:55 allreduce; gradient = allreduce of the upstream
    gradient with the same op, ref: mpi_ops.py _allreduce_grad)."""
    import tensorflow as tf

    from ..ops import eager

    @tf.custom_gradient
    def _ar(x):
        red = eager.allreduce(_to_np(x), name=name, op=op,
                              prescale_factor=prescale_factor,
                              postscale_factor=postscale_factor,
                              process_set=process_set)
        out = tf.convert_to_tensor(np.asarray(red), dtype=x.dtype)

        def grad(dy):
            # Same op AND the same pre/postscale as the forward op (ref:
            # _allreduce_grad reads both factors off the op attrs).
            g = eager.allreduce(
                _to_np(dy), name=None if name is None else f"{name}.grad",
                op=op, prescale_factor=prescale_factor,
                postscale_factor=postscale_factor,
                process_set=process_set)
            return tf.convert_to_tensor(np.asarray(g), dtype=dy.dtype)

        return out, grad

    return _ar(tf.convert_to_tensor(tensor))


def allgather(tensor, name: Optional[str] = None, process_set=None):
    """Differentiable allgather along dim 0 (ref: tensorflow allgather;
    HorovodAllgatherOp's registered gradient = this rank's row segment of
    the SUM-allreduced upstream gradient)."""
    import tensorflow as tf

    from ..common import basics
    from ..ops import eager

    @tf.custom_gradient
    def _ag(x):
        arr = _to_np(x)
        n_local = arr.shape[0]
        out = np.asarray(eager.allgather(arr, name=name,
                                         process_set=process_set))

        def grad(dy):
            g = np.asarray(eager.allreduce(
                _to_np(dy), name=None if name is None else f"{name}.grad",
                op=ReduceOp.SUM, process_set=process_set))
            rank = (process_set.rank() if process_set is not None
                    else basics.rank())
            # Rows are rank-ordered; ragged sizes require every rank's
            # count, gathered HERE so gradient-free calls (eval loops)
            # pay a single collective — sizes may legitimately differ
            # call to call (last batch), so they cannot be cached.
            counts = np.asarray(eager.allgather(
                np.asarray([n_local], np.int32),
                name=None if name is None else f"{name}.counts",
                process_set=process_set))
            off = int(counts[:rank].sum())
            return tf.convert_to_tensor(g[off:off + n_local],
                                        dtype=dy.dtype)

        return tf.convert_to_tensor(out), grad

    return _ag(tf.convert_to_tensor(tensor))


def broadcast(tensor, root_rank: int = 0, name: Optional[str] = None,
              process_set=None):
    """Differentiable broadcast (ref: HorovodBroadcastOp gradient =
    SUM-allreduced upstream gradient on the root, zeros elsewhere)."""
    import tensorflow as tf

    from ..common import basics
    from ..ops import eager

    @tf.custom_gradient
    def _bc(x):
        out = eager.broadcast(_to_np(x), root_rank, name=name,
                              process_set=process_set)

        def grad(dy):
            g = np.asarray(eager.allreduce(
                _to_np(dy), name=None if name is None else f"{name}.grad",
                op=ReduceOp.SUM, process_set=process_set))
            rank = (process_set.rank() if process_set is not None
                    else basics.rank())
            if rank != root_rank:
                g = np.zeros_like(g)
            return tf.convert_to_tensor(g, dtype=dy.dtype)

        return tf.convert_to_tensor(np.asarray(out)), grad

    return _bc(tf.convert_to_tensor(tensor))


def broadcast_variables(variables: Iterable, root_rank: int = 0,
                        process_set=None) -> None:
    """Assign rank ``root_rank``'s values into ``variables`` on every rank
    (ref: tensorflow/functions.py broadcast_variables)."""
    from ..functions import broadcast_parameters

    variables = list(variables)
    synced = broadcast_parameters([v.numpy() for v in variables],
                                  root_rank=root_rank,
                                  process_set=process_set)
    for v, val in zip(variables, synced):
        v.assign(val)


class DistributedGradientTape:
    """Wrap a ``tf.GradientTape`` so ``gradient()`` returns allreduced
    gradients (ref: tensorflow/__init__.py:758 _DistributedGradientTape).

    Usage::

        with tf.GradientTape() as tape:
            loss = loss_fn(model(x))
        tape = hvd.interop.tf.DistributedGradientTape(tape)
        grads = tape.gradient(loss, model.trainable_variables)
    """

    def __init__(self, tape, op: ReduceOp = ReduceOp.AVERAGE,
                 compression=None, process_set=None,
                 sparse_as_dense: bool = False):
        from ..ops.compression import Compression

        self._tape = tape
        self._op = op
        self._compression = compression or Compression.none
        self._process_set = process_set
        self._sparse_as_dense = sparse_as_dense

    def __getattr__(self, name):
        return getattr(self._tape, name)

    # Implicit dunder lookup bypasses instance __getattr__, so the
    # context-manager protocol must be delegated explicitly for the
    # `with DistributedGradientTape(tf.GradientTape()):` porting pattern.
    def __enter__(self):
        self._tape.__enter__()
        return self

    def __exit__(self, *exc):
        return self._tape.__exit__(*exc)

    def gradient(self, target, sources, output_gradients=None, **kwargs):
        import tensorflow as tf

        from ..ops import eager

        grads = self._tape.gradient(target, sources,
                                    output_gradients=output_gradients,
                                    **kwargs)
        # Arbitrary nests (dict/list-of-lists), like tf.GradientTape
        # itself (ref uses tf.nest the same way).
        flat = tf.nest.flatten(grads)
        handles, ctxs = [], []
        for i, g in enumerate(flat):
            if g is None:
                handles.append(None)
                ctxs.append(None)
                continue
            if isinstance(g, tf.IndexedSlices):
                if not self._sparse_as_dense:
                    raise NotImplementedError(
                        "IndexedSlices gradient (embedding layer?): pass "
                        "sparse_as_dense=True to DistributedGradientTape "
                        "(ref: tensorflow sparse_as_dense) or allreduce "
                        "via hvd.sparse_allreduce")
                g = tf.convert_to_tensor(g)
            arr, ctx = self._compression.compress(_to_np(g))
            ctxs.append(ctx)
            handles.append(eager.allreduce_async(
                np.asarray(arr), name=f"tfgrad.{i}", op=self._op,
                process_set=self._process_set))
        out = []
        for g, h, ctx in zip(flat, handles, ctxs):
            if h is None:
                out.append(None)
                continue
            red = self._compression.decompress(eager.synchronize(h), ctx)
            dtype = (g.dtype if isinstance(g, tf.IndexedSlices)
                     else getattr(g, "dtype", None))
            out.append(tf.convert_to_tensor(np.asarray(red), dtype=dtype))
        return tf.nest.pack_sequence_as(grads, out)


def _keras_callback_base():
    import tensorflow as tf

    return tf.keras.callbacks.Callback


class BroadcastGlobalVariablesCallback:
    """Keras callback: broadcast initial model+optimizer variables from
    ``root_rank`` on the first batch (ref: _keras/callbacks.py:28)."""

    def __new__(cls, root_rank: int = 0, *, process_set=None):
        Base = _keras_callback_base()

        class _Impl(Base):
            def __init__(self):
                super().__init__()
                self._done = False

            def on_train_batch_end(self, batch, logs=None):
                # after the first batch: optimizer slots now exist
                # (ref: broadcast happens on_batch_end of batch 0)
                if self._done:
                    return
                broadcast_variables(self.model.variables,
                                    root_rank=root_rank,
                                    process_set=process_set)
                opt_vars = getattr(self.model.optimizer, "variables", None)
                if callable(opt_vars):
                    opt_vars = opt_vars()
                if opt_vars:
                    broadcast_variables(opt_vars, root_rank=root_rank,
                                        process_set=process_set)
                self._done = True

        return _Impl()


class MetricAverageCallback:
    """Keras callback: allreduce-average epoch metrics across ranks
    (ref: _keras/callbacks.py:49 MetricAverageCallback)."""

    def __new__(cls, *, process_set=None):
        Base = _keras_callback_base()

        class _Impl(Base):
            def on_epoch_end(self, epoch, logs=None):
                from ..ops import eager

                if not logs:
                    return
                for k in sorted(logs):
                    v = logs[k]
                    if isinstance(v, (int, float, np.floating)):
                        logs[k] = float(np.asarray(eager.allreduce(
                            np.float32(v), name=f"metric.{k}",
                            process_set=process_set)))

        return _Impl()
