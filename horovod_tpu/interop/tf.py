"""TensorFlow interop — the reference's TF binding surface on this
framework's eager controller.

Re-conception of ref: horovod/tensorflow/__init__.py (allreduce :55,
DistributedGradientTape :758-842), tensorflow/functions.py
(broadcast_variables), _keras/callbacks.py (BroadcastGlobalVariables,
MetricAverage).  TF eager tensors cross into the controller as numpy
(same adapter shape as interop/torch.py); collectives are differentiable
via ``tf.custom_gradient`` exactly like the reference registers TF
gradients for its custom ops (ref: tensorflow/mpi_ops.py gradient
registrations).

TensorFlow is imported lazily — importing horovod_tpu.interop.tf only
costs TF when a function is first called.
"""

from __future__ import annotations

from typing import Any, Iterable, Optional, Sequence

import numpy as np

from ..common.types import ReduceOp

__all__ = ["allreduce", "allgather", "broadcast", "broadcast_variables",
           "DistributedGradientTape", "DistributedOptimizer", "load_model",
           "BroadcastGlobalVariablesCallback", "MetricAverageCallback",
           "LearningRateScheduleCallback", "LearningRateWarmupCallback",
           "KerasState", "TensorFlowState", "CommitStateCallback",
           "UpdateBatchStateCallback",
           "UpdateEpochStateCallback"]


def _to_np(t) -> np.ndarray:
    return t.numpy() if hasattr(t, "numpy") else np.asarray(t)


def allreduce(tensor, name: Optional[str] = None,
              op: ReduceOp = ReduceOp.AVERAGE,
              prescale_factor: float = 1.0, postscale_factor: float = 1.0,
              process_set=None):
    """Differentiable allreduce of a TF tensor (ref: tensorflow/
    __init__.py:55 allreduce; gradient = allreduce of the upstream
    gradient with the same op, ref: mpi_ops.py _allreduce_grad)."""
    import tensorflow as tf

    from ..ops import eager

    @tf.custom_gradient
    def _ar(x):
        red = eager.allreduce(_to_np(x), name=name, op=op,
                              prescale_factor=prescale_factor,
                              postscale_factor=postscale_factor,
                              process_set=process_set)
        out = tf.convert_to_tensor(np.asarray(red), dtype=x.dtype)

        def grad(dy):
            # Same op AND the same pre/postscale as the forward op (ref:
            # _allreduce_grad reads both factors off the op attrs).
            g = eager.allreduce(
                _to_np(dy), name=None if name is None else f"{name}.grad",
                op=op, prescale_factor=prescale_factor,
                postscale_factor=postscale_factor,
                process_set=process_set)
            return tf.convert_to_tensor(np.asarray(g), dtype=dy.dtype)

        return out, grad

    return _ar(tf.convert_to_tensor(tensor))


def allgather(tensor, name: Optional[str] = None, process_set=None):
    """Differentiable allgather along dim 0 (ref: tensorflow allgather;
    HorovodAllgatherOp's registered gradient = this rank's row segment of
    the SUM-allreduced upstream gradient)."""
    import tensorflow as tf

    from ..common import basics
    from ..ops import eager

    @tf.custom_gradient
    def _ag(x):
        arr = _to_np(x)
        n_local = arr.shape[0]
        out = np.asarray(eager.allgather(arr, name=name,
                                         process_set=process_set))

        def grad(dy):
            g = np.asarray(eager.allreduce(
                _to_np(dy), name=None if name is None else f"{name}.grad",
                op=ReduceOp.SUM, process_set=process_set))
            rank = (process_set.rank() if process_set is not None
                    else basics.rank())
            # Rows are rank-ordered; ragged sizes require every rank's
            # count, gathered HERE so gradient-free calls (eval loops)
            # pay a single collective — sizes may legitimately differ
            # call to call (last batch), so they cannot be cached.
            counts = np.asarray(eager.allgather(
                np.asarray([n_local], np.int32),
                name=None if name is None else f"{name}.counts",
                process_set=process_set))
            off = int(counts[:rank].sum())
            return tf.convert_to_tensor(g[off:off + n_local],
                                        dtype=dy.dtype)

        return tf.convert_to_tensor(out), grad

    return _ag(tf.convert_to_tensor(tensor))


def broadcast(tensor, root_rank: int = 0, name: Optional[str] = None,
              process_set=None):
    """Differentiable broadcast (ref: HorovodBroadcastOp gradient =
    SUM-allreduced upstream gradient on the root, zeros elsewhere)."""
    import tensorflow as tf

    from ..common import basics
    from ..ops import eager

    @tf.custom_gradient
    def _bc(x):
        out = eager.broadcast(_to_np(x), root_rank, name=name,
                              process_set=process_set)

        def grad(dy):
            g = np.asarray(eager.allreduce(
                _to_np(dy), name=None if name is None else f"{name}.grad",
                op=ReduceOp.SUM, process_set=process_set))
            rank = (process_set.rank() if process_set is not None
                    else basics.rank())
            if rank != root_rank:
                g = np.zeros_like(g)
            return tf.convert_to_tensor(g, dtype=dy.dtype)

        return tf.convert_to_tensor(np.asarray(out)), grad

    return _bc(tf.convert_to_tensor(tensor))


def broadcast_variables(variables: Iterable, root_rank: int = 0,
                        process_set=None) -> None:
    """Assign rank ``root_rank``'s values into ``variables`` on every rank
    (ref: tensorflow/functions.py broadcast_variables)."""
    from ..functions import broadcast_parameters

    variables = list(variables)
    synced = broadcast_parameters([v.numpy() for v in variables],
                                  root_rank=root_rank,
                                  process_set=process_set)
    for v, val in zip(variables, synced):
        v.assign(val)


class DistributedGradientTape:
    """Wrap a ``tf.GradientTape`` so ``gradient()`` returns allreduced
    gradients (ref: tensorflow/__init__.py:758 _DistributedGradientTape).

    Usage::

        with tf.GradientTape() as tape:
            loss = loss_fn(model(x))
        tape = hvd.interop.tf.DistributedGradientTape(tape)
        grads = tape.gradient(loss, model.trainable_variables)
    """

    def __init__(self, tape, op: ReduceOp = ReduceOp.AVERAGE,
                 compression=None, process_set=None,
                 sparse_as_dense: bool = False):
        from ..ops.compression import Compression

        self._tape = tape
        self._op = op
        # None -> environment selection (HVDT_COMPRESSION / HVDT_QUANT)
        self._compression = compression or Compression.from_env()
        self._process_set = process_set
        self._sparse_as_dense = sparse_as_dense

    def __getattr__(self, name):
        return getattr(self._tape, name)

    # Implicit dunder lookup bypasses instance __getattr__, so the
    # context-manager protocol must be delegated explicitly for the
    # `with DistributedGradientTape(tf.GradientTape()):` porting pattern.
    def __enter__(self):
        self._tape.__enter__()
        return self

    def __exit__(self, *exc):
        return self._tape.__exit__(*exc)

    def gradient(self, target, sources, output_gradients=None, **kwargs):
        import tensorflow as tf

        from ..ops import eager

        grads = self._tape.gradient(target, sources,
                                    output_gradients=output_gradients,
                                    **kwargs)
        # Arbitrary nests (dict/list-of-lists), like tf.GradientTape
        # itself (ref uses tf.nest the same way).
        flat = tf.nest.flatten(grads)
        handles, ctxs = [], []
        for i, g in enumerate(flat):
            if g is None:
                handles.append(None)
                ctxs.append(None)
                continue
            if isinstance(g, tf.IndexedSlices):
                if not self._sparse_as_dense:
                    raise NotImplementedError(
                        "IndexedSlices gradient (embedding layer?): pass "
                        "sparse_as_dense=True to DistributedGradientTape "
                        "(ref: tensorflow sparse_as_dense) or allreduce "
                        "via hvd.sparse_allreduce")
                g = tf.convert_to_tensor(g)
            arr, ctx = self._compression.compress(_to_np(g))
            ctxs.append(ctx)
            handles.append(eager.allreduce_async(
                np.asarray(arr), name=f"tfgrad.{i}", op=self._op,
                process_set=self._process_set))
        out = []
        for g, h, ctx in zip(flat, handles, ctxs):
            if h is None:
                out.append(None)
                continue
            red = self._compression.decompress(eager.synchronize(h), ctx)
            dtype = (g.dtype if isinstance(g, tf.IndexedSlices)
                     else getattr(g, "dtype", None))
            out.append(tf.convert_to_tensor(np.asarray(red), dtype=dtype))
        return tf.nest.pack_sequence_as(grads, out)


def _keras_callback_base():
    import tensorflow as tf

    return tf.keras.callbacks.Callback


class BroadcastGlobalVariablesCallback:
    """Keras callback: broadcast initial model+optimizer variables from
    ``root_rank`` on the first batch (ref: _keras/callbacks.py:28)."""

    def __new__(cls, root_rank: int = 0, *, process_set=None):
        Base = _keras_callback_base()

        class _Impl(Base):
            def __init__(self):
                super().__init__()
                self._done = False

            def on_train_batch_end(self, batch, logs=None):
                # after the first batch: optimizer slots now exist
                # (ref: broadcast happens on_batch_end of batch 0)
                if self._done:
                    return
                broadcast_variables(self.model.variables,
                                    root_rank=root_rank,
                                    process_set=process_set)
                opt_vars = getattr(self.model.optimizer, "variables", None)
                if callable(opt_vars):
                    opt_vars = opt_vars()
                if opt_vars:
                    broadcast_variables(opt_vars, root_rank=root_rank,
                                        process_set=process_set)
                self._done = True

        return _Impl()


class MetricAverageCallback:
    """Keras callback: allreduce-average epoch metrics across ranks
    (ref: _keras/callbacks.py:49 MetricAverageCallback)."""

    def __new__(cls, *, process_set=None):
        Base = _keras_callback_base()

        class _Impl(Base):
            def on_epoch_end(self, epoch, logs=None):
                from ..ops import eager

                if not logs:
                    return
                for k in sorted(logs):
                    v = logs[k]
                    if isinstance(v, (int, float, np.floating)):
                        logs[k] = float(np.asarray(eager.allreduce(
                            np.float32(v), name=f"metric.{k}",
                            process_set=process_set)))

        return _Impl()


def _wrap_optimizer_class(cls, op=None, compression=None, process_set=None,
                          name_prefix: str = "DistributedOptimizer"):
    """Dynamic keras-optimizer subclass whose ``apply`` allreduces every
    gradient across ranks first (ref: _keras/__init__.py
    create_distributed_optimizer — same dynamic-subclass trick, keyed to
    Keras 3's ``apply`` so both ``apply_gradients`` and ``model.fit``'s
    trainer path are covered).

    Inside a ``tf.function`` graph the reduction runs as a
    ``tf.py_function`` (the eager controller is host-side Python — same
    constraint as the reference's CPU-negotiated ops); XLA-jitted
    training (``jit_compile=True``) is not supported on this interop
    path — use the JAX-native API for compiled training.
    """
    import tensorflow as tf

    from ..ops import eager
    from ..ops.compression import Compression

    # None -> environment selection (HVDT_COMPRESSION / HVDT_QUANT)
    comp = compression or Compression.from_env()

    class _DistributedOptimizer(cls):
        _hvd_wrapped = True

        def apply(self, grads, trainable_variables=None, **kwargs):
            if trainable_variables is None:
                reduced = _reduce_grads(grads, list(range(len(grads))))
                return super().apply(reduced, **kwargs)
            reduced = _reduce_grads(
                grads, [getattr(v, "path", getattr(v, "name", i))
                        for i, v in enumerate(trainable_variables)])
            return super().apply(reduced, trainable_variables, **kwargs)

    def _reduce_all_np(arrs, names):
        """Async-enqueue every gradient, then synchronize — the handles
        overlap through one negotiation cycle instead of paying one
        blocking round trip per tensor (same pattern as
        DistributedGradientTape.gradient)."""
        wires, ctxs = zip(*(comp.compress(a) for a in arrs))
        handles = [eager.allreduce_async(w, name=nm, op=op,
                                         process_set=process_set)
                   for w, nm in zip(wires, names)]
        return [np.asarray(comp.decompress(eager.synchronize(h), c))
                .astype(a.dtype)
                for h, c, a in zip(handles, ctxs, arrs)]

    def _reduce_grads(grads, names):
        dense, full_names, slots = [], [], []
        out = list(grads)
        for i, (g, nm) in enumerate(zip(grads, names)):
            if g is None:
                continue
            if isinstance(g, tf.IndexedSlices):
                # sparse_as_dense (ref default for keras wrappers)
                g = tf.convert_to_tensor(g)
            dense.append(g)
            full_names.append(f"{name_prefix}.grad.{nm}")
            slots.append(i)
        if not dense:
            return out
        if tf.executing_eagerly():
            reduced = [tf.convert_to_tensor(r) for r in _reduce_all_np(
                [_to_np(g) for g in dense], full_names)]
        else:
            # One py_function for the whole bundle: the host call enqueues
            # every allreduce before synchronizing any.
            def _host(*tensors):
                return _reduce_all_np([t.numpy() for t in tensors],
                                      full_names)

            reduced = tf.py_function(_host, dense,
                                     Tout=[g.dtype for g in dense])
            for r, g in zip(reduced, dense):
                r.set_shape(g.shape)
        for i, r in zip(slots, reduced):
            out[i] = r
        return out

    _DistributedOptimizer.__name__ = cls.__name__
    _DistributedOptimizer.__qualname__ = cls.__qualname__
    return _DistributedOptimizer


def DistributedOptimizer(optimizer, name: Optional[str] = None, op=None,
                         compression=None, process_set=None):
    """Wrap a configured ``keras.optimizers.Optimizer`` so every gradient
    is averaged across ranks before the update (ref:
    tensorflow/keras/__init__.py:49 DistributedOptimizer)."""
    cls = _wrap_optimizer_class(
        optimizer.__class__, op=op, compression=compression,
        process_set=process_set,
        name_prefix=name or "DistributedOptimizer")
    return cls.from_config(optimizer.get_config())


def load_model(filepath, custom_optimizers=None, custom_objects=None,
               compression=None, op=None, process_set=None):
    """``keras.models.load_model`` that rebuilds the model's optimizer as
    a :func:`DistributedOptimizer` (ref: tensorflow/keras/__init__.py:216
    load_model).

    The reference injects wrapped classes through ``custom_objects``;
    Keras 3 resolves built-in optimizers by registered name before
    consulting ``custom_objects``, so instead the loaded optimizer is
    re-instantiated as the wrapped subclass AFTER loading, with its
    restored state (iteration count, momentum/slot variables) copied
    over.  ``custom_optimizers`` (a list of custom optimizer classes)
    feeds deserialization of non-builtin optimizers, as in the
    reference."""
    import keras

    co = dict(custom_objects or {})
    for cls in custom_optimizers or []:
        co.setdefault(cls.__name__, cls)
    model = keras.models.load_model(filepath, custom_objects=co)
    opt = getattr(model, "optimizer", None)
    if opt is not None and not getattr(opt, "_hvd_wrapped", False):
        cls = _wrap_optimizer_class(opt.__class__, op=op,
                                    compression=compression,
                                    process_set=process_set)
        new_opt = cls.from_config(opt.get_config())
        if getattr(opt, "built", False):
            new_opt.build(model.trainable_variables)
            for dst, src in zip(new_opt.variables, opt.variables):
                dst.assign(src)
        model.optimizer = new_opt
    return model


class LearningRateScheduleCallback:
    """Keras callback scaling the LR by ``multiplier(epoch)`` relative to
    ``initial_lr`` (ref: _keras/callbacks.py:95 — same staircase /
    fractional-epoch semantics and momentum correction)."""

    def __new__(cls, initial_lr, multiplier, start_epoch: int = 0,
                end_epoch: Optional[int] = None, staircase: bool = True,
                momentum_correction: bool = True,
                steps_per_epoch: Optional[int] = None):
        Base = _keras_callback_base()
        if initial_lr is None:
            raise ValueError("Parameter `initial_lr` is required")
        if not callable(multiplier):
            mult = lambda epoch: multiplier ** (epoch - start_epoch)  # noqa: E731
        else:
            mult = multiplier

        class _Impl(Base):
            def __init__(self):
                super().__init__()
                self.current_epoch = None
                self.restore_momentum = None
                self.steps_per_epoch = steps_per_epoch

            def _lr_var(self):
                return self.model.optimizer.learning_rate

            def _adjust(self, epoch):
                import numpy as _np
                import tensorflow as tf

                opt = self.model.optimizer
                old_lr = float(_np.asarray(self._lr_var()))
                new_lr = initial_lr * mult(epoch)
                self._lr_var().assign(new_lr)
                # Momentum correction (Goyal et al.) only works when the
                # optimizer's momentum is a variable the traced train
                # step actually reads.  Keras-3 built-ins keep momentum
                # as a plain Python float that is constant-folded into
                # the tf.function graph — mutating it there would take
                # effect once at trace time and never restore, so it is
                # skipped (a schedule without correction, not a silently
                # wrong one).
                mom = getattr(opt, "momentum", None)
                if momentum_correction and isinstance(
                        mom, (tf.Variable,)):
                    self.restore_momentum = float(_np.asarray(mom))
                    mom.assign(self.restore_momentum * new_lr /
                               max(old_lr, 1e-30))

            def on_train_begin(self, logs=None):
                if not staircase and not self.steps_per_epoch:
                    self.steps_per_epoch = self.params.get("steps")
                    if not self.steps_per_epoch:
                        raise ValueError(
                            "Could not autodetect steps_per_epoch: pass "
                            "steps_per_epoch= explicitly")

            def on_epoch_begin(self, epoch, logs=None):
                self.current_epoch = epoch

            def on_train_batch_begin(self, batch, logs=None):
                if (self.current_epoch < start_epoch or
                        (end_epoch is not None and
                         self.current_epoch >= end_epoch)):
                    return
                if staircase and batch == 0:
                    self._adjust(self.current_epoch)
                elif not staircase:
                    self._adjust(self.current_epoch +
                                 float(batch) / self.steps_per_epoch)

            def on_train_batch_end(self, batch, logs=None):
                if self.restore_momentum is not None:
                    self.model.optimizer.momentum.assign(
                        self.restore_momentum)
                    self.restore_momentum = None

            def on_epoch_end(self, epoch, logs=None):
                import numpy as _np

                if logs is not None:
                    logs["lr"] = float(_np.asarray(self._lr_var()))

        return _Impl()


class LearningRateWarmupCallback:
    """Gradual linear LR warmup from ``initial_lr / size`` up to
    ``initial_lr`` over ``warmup_epochs`` (ref: _keras/callbacks.py:181
    — Goyal et al.; the multiplier ramps 1/size -> 1, so pass the final
    already-size-scaled LR as ``initial_lr``)."""

    def __new__(cls, initial_lr, warmup_epochs: int = 5,
                momentum_correction: bool = True,
                steps_per_epoch: Optional[int] = None, verbose: int = 0):
        from ..common import basics

        size = basics.size()
        holder = {}

        def multiplier(epoch):
            epoch += 1.0 / holder.get("steps_per_epoch", 1)
            return 1.0 / size * (epoch * (size - 1) / warmup_epochs + 1)

        cb = LearningRateScheduleCallback(
            initial_lr, multiplier, start_epoch=0, end_epoch=warmup_epochs,
            staircase=False, momentum_correction=momentum_correction,
            steps_per_epoch=steps_per_epoch)
        orig_train_begin = cb.on_train_begin
        orig_epoch_end = cb.on_epoch_end

        def on_train_begin(logs=None):
            orig_train_begin(logs)
            holder["steps_per_epoch"] = cb.steps_per_epoch or 1

        def on_epoch_end(epoch, logs=None):
            orig_epoch_end(epoch, logs)
            if epoch == warmup_epochs - 1 and verbose > 0 and \
                    basics.rank() == 0:
                import numpy as _np

                lr = float(_np.asarray(
                    cb.model.optimizer.learning_rate))
                print(f"\nEpoch {epoch + 1}: finished gradual learning "
                      f"rate warmup to {lr:g}.")

        cb.on_train_begin = on_train_begin
        cb.on_epoch_end = on_epoch_end
        return cb


def __getattr__(name):
    """Core-API names (init/rank/size/..., ref: tensorflow/__init__.py
    re-exports) resolve from the top-level package so this module is
    drop-in for ``import horovod.tensorflow as hvd``."""
    from . import core_attr

    found = core_attr(name)
    if found is not None:
        return found
    raise AttributeError(name)


class KerasState:
    """Elastic state of a keras model + optimizer (ref:
    tensorflow/keras/elastic.py KerasState / tensorflow/elastic.py
    TensorFlowKerasState): weights snapshot to host memory on commit,
    restore on rollback, rank-0 broadcast on (re-)sync; extra kwargs ride
    the generic ObjectState payload."""

    def __new__(cls, model, optimizer=None, **kwargs):
        import numpy as _np

        from ..elastic import ObjectState

        opt = optimizer if optimizer is not None else \
            getattr(model, "optimizer", None)

        class _Impl(ObjectState):
            def __init__(self):
                object.__setattr__(self, "model", model)
                object.__setattr__(self, "optimizer", opt)
                object.__setattr__(self, "_saved_weights", None)
                super().__init__(**kwargs)

            def _payload_keys(self):
                return [k for k in super()._payload_keys()
                        if k not in ("model", "optimizer")]

            def _variables(self):
                vs = list(self.model.variables)
                if self.optimizer is not None:
                    ov = getattr(self.optimizer, "variables", None)
                    if callable(ov):
                        ov = ov()
                    vs += list(ov or [])
                return vs

            def save(self):
                object.__setattr__(
                    self, "_saved_weights",
                    [_np.array(v) for v in self._variables()])
                super().save()

            def restore(self):
                if self._saved_weights is not None:
                    for v, w in zip(self._variables(),
                                    self._saved_weights):
                        v.assign(w)
                super().restore()

            def sync(self):
                broadcast_variables(self._variables(), root_rank=0)
                super().sync()

        return _Impl()


class CommitStateCallback:
    """Commit ``state`` every ``batches_per_commit`` batches and at epoch
    end (ref: _keras/elastic.py CommitStateCallbackImpl)."""

    def __new__(cls, state, batches_per_commit: int = 1):
        Base = _keras_callback_base()

        class _Impl(Base):
            def __init__(self):
                super().__init__()
                self.batches_remaining = batches_per_commit

            def on_train_begin(self, logs=None):
                self.batches_remaining = batches_per_commit

            def on_train_batch_end(self, batch, logs=None):
                self.batches_remaining -= 1
                if self.batches_remaining == 0:
                    state.commit()
                    self.batches_remaining = batches_per_commit

            def on_epoch_end(self, epoch, logs=None):
                state.commit()

        return _Impl()


class UpdateBatchStateCallback:
    """Track ``state.batch`` across batches so a restart knows where the
    epoch stood (ref: _keras/elastic.py UpdateBatchStateCallbackImpl).

    The reference shortened the restarted epoch by mutating
    ``params['steps']`` in ``on_epoch_begin``; Keras 3 builds the epoch
    iterator before callbacks fire and treats ``params`` as metadata, so
    that mechanism is dead (verified: all steps still run).  Under
    Keras 3 the RESUME side lives with the caller: on restart pass
    ``steps_per_epoch=total - state.batch`` and skip the consumed data
    (``dataset.skip(state.batch)`` / the ElasticSampler), then this
    callback's tracking keeps ``state.batch`` true for the next commit.
    The legacy params mutation is still applied for tf.keras 2.x, where
    ``params`` was live."""

    def __new__(cls, state):
        Base = _keras_callback_base()

        class _Impl(Base):
            def __init__(self):
                super().__init__()
                self.steps_per_epoch = None

            def on_train_begin(self, logs=None):
                self.steps_per_epoch = None

            def on_epoch_begin(self, epoch, logs=None):
                if self.params.get("steps"):
                    if self.steps_per_epoch is None:
                        self.steps_per_epoch = self.params.get("steps")
                    # effective only on legacy tf.keras (see docstring)
                    self.params["steps"] = self.steps_per_epoch - \
                        state.batch

            def on_train_batch_end(self, batch, logs=None):
                state.batch = batch

            def on_epoch_end(self, epoch, logs=None):
                state.batch = 0

        return _Impl()


class UpdateEpochStateCallback:
    """Track the GLOBAL epoch number across resets in ``state.epoch``
    (ref: _keras/elastic.py UpdateEpochStateCallbackImpl)."""

    def __new__(cls, state):
        Base = _keras_callback_base()

        class _Impl(Base):
            def __init__(self):
                super().__init__()
                self.initial_epoch = state.epoch

            def on_train_begin(self, logs=None):
                self.initial_epoch = state.epoch

            def on_epoch_end(self, epoch, logs=None):
                state.epoch = self.initial_epoch + epoch + 1

        return _Impl()


class TensorFlowState:
    """Elastic state of a plain list of ``tf.Variable``s (ref:
    tensorflow/elastic.py:156 TensorFlowState — the non-Keras TF
    surface; TF2-eager only here, like the rest of this binding)."""

    def __new__(cls, variables, **kwargs):
        import numpy as _np

        from ..elastic import ObjectState

        variables = list(variables)

        class _Impl(ObjectState):
            def __init__(self):
                object.__setattr__(self, "variables", variables)
                object.__setattr__(self, "_saved_values", None)
                super().__init__(**kwargs)

            def _payload_keys(self):
                return [k for k in super()._payload_keys()
                        if k != "variables"]

            def save(self):
                object.__setattr__(self, "_saved_values",
                                   [_np.array(v) for v in self.variables])
                super().save()

            def restore(self):
                if self._saved_values is not None:
                    for v, w in zip(self.variables, self._saved_values):
                        v.assign(w)
                super().restore()

            def sync(self):
                broadcast_variables(self.variables, root_rank=0)
                super().sync()

        return _Impl()
