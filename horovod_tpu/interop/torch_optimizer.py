"""Grad-hook DistributedOptimizer for PyTorch models.

The reference's canonical torch API: wrap any ``torch.optim`` optimizer so
each parameter's gradient, the moment autograd finishes accumulating it,
is enqueued as a named async allreduce; ``step()`` synchronizes the
handles and applies the reduced gradients
(ref: torch/optimizer.py — _DistributedOptimizer grad hooks :131-253,
synchronize :255-302, factory :516-605).

TPU-native translation: the hooks enqueue through THIS framework's eager
controller (negotiation + fusion + response cache), and the bytes ride
whichever host data plane is selected (XLA device mesh or the native TCP
backend) — no NCCL, no DDP.  Because the controller's background thread
negotiates while autograd is still producing later gradients, comm
overlaps backward exactly like the reference.

``backward_passes_per_step=k`` follows the reference contract: call
``backward()`` k times, then ``step()`` once.  Each parameter carries a
delay counter (ref: _allreduce_delay); its hook enqueues the accumulated
gradient (divided by k) on the k-th backward.  Calling ``step()`` or
``zero_grad()`` mid-accumulation raises instead of silently training
wrong.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, Optional, Tuple

import numpy as np

from ..common.types import ReduceOp
from .torch import _to_np

__all__ = ["DistributedOptimizer"]


class _Hooks:
    """Per-parameter async-allreduce state shared by the mixin methods."""

    def __init__(self, optimizer, named_parameters, op, process_set,
                 backward_passes_per_step: int):
        self.op = op
        self.process_set = process_set
        self.k = max(1, int(backward_passes_per_step))
        self._handles: Dict[Any, int] = {}       # param -> eager handle
        self._names: Dict[Any, str] = {}
        self._delay: Dict[Any, int] = {}         # param -> backwards left
        self._hook_refs = []
        self._synchronized = False               # grads already reduced

        params = [p for group in optimizer.param_groups
                  for p in group["params"]]
        if named_parameters is not None:
            seen = set()
            for n, _ in named_parameters:
                if n in seen:
                    raise ValueError(
                        f"duplicate parameter name {n!r} in "
                        "named_parameters — collective names must be "
                        "unique (ref: optimizer.py duplicate check)")
                seen.add(n)
            by_obj = {id(p): n for n, p in named_parameters}
            missing = [p for p in params if id(p) not in by_obj]
            if missing:
                raise ValueError(
                    "named_parameters does not cover all optimized "
                    f"parameters ({len(missing)} missing)")
            names = {p: f"grad.{by_obj[id(p)]}" for p in params}
        else:
            names = {p: f"grad.{i}" for i, p in enumerate(params)}
        self._names = names

        for p in params:
            if not p.requires_grad:
                continue
            self._delay[p] = self.k
            self._hook_refs.append(
                p.register_post_accumulate_grad_hook(self._hook))

    def _hook(self, p) -> None:
        if self._delay.get(p, self.k) <= 0:
            raise RuntimeError(
                f"Gradients for {self._names[p]!r} were computed more "
                f"than backward_passes_per_step={self.k} times before "
                "step()/synchronize() (ref misuse guard).")
        d = self._delay[p] = self._delay.get(p, self.k) - 1
        if d <= 0:
            self._enqueue(p)

    def _enqueue(self, p, zeros: bool = False) -> None:
        from ..ops import eager

        if p in self._handles:          # double-backward past the boundary
            eager.synchronize(self._handles.pop(p))
        if zeros or p.grad is None:
            grad = np.zeros(tuple(p.shape), dtype=_wire_np_dtype(p))
        else:
            g = p.grad.detach()
            # bf16 (and other numpy-less torch dtypes) go over the wire
            # as f32 — matching the zeros path so every rank negotiates
            # the same dtype for a name.
            if not _numpy_compatible(g.dtype):
                g = g.float()
            # Copy: the controller's background thread reads this buffer
            # asynchronously; a zero-copy view of p.grad would race with
            # in-place grad mutation (clip_grad_norm_ etc.).
            grad = np.array(_to_np(g), copy=True)
            if self.k > 1:
                grad /= self.k
        self._handles[p] = eager.allreduce_async(
            grad, name=self._names[p], op=self.op,
            process_set=self.process_set)
        self._synchronized = False

    def mid_accumulation(self) -> bool:
        return any(0 < d < self.k for d in self._delay.values())

    def synchronize(self, optimizer) -> None:
        import torch

        from ..ops import eager

        # Symmetric negotiation: ranks may differ in which params got
        # gradients (data-dependent branches, per-rank frozen modules).
        # Every rank enqueues EVERY optimized param — zeros when no local
        # gradient exists — so no rank's negotiation can hang waiting for
        # a name that never arrives elsewhere.
        for group in optimizer.param_groups:
            for p in group["params"]:
                if p.requires_grad and p not in self._handles:
                    self._enqueue(p, zeros=p.grad is None)
        for p, handle in list(self._handles.items()):
            out = np.asarray(eager.synchronize(handle))
            t = torch.from_numpy(out)
            with torch.no_grad():
                if p.grad is None:
                    p.grad = t.view(p.shape).to(p.dtype).clone()
                else:
                    p.grad.copy_(t.view_as(p.grad))
        self._handles.clear()
        for p in self._delay:
            self._delay[p] = self.k
        self._synchronized = True


def _numpy_compatible(dtype) -> bool:
    import torch

    return dtype in (torch.float32, torch.float64, torch.float16)


def _wire_np_dtype(p):
    import torch

    return {torch.float32: np.float32, torch.float64: np.float64,
            torch.float16: np.float16}.get(p.dtype, np.float32)


def DistributedOptimizer(optimizer,
                         named_parameters: Optional[
                             Iterable[Tuple[str, Any]]] = None,
                         op: ReduceOp = ReduceOp.AVERAGE,
                         process_set=None,
                         backward_passes_per_step: int = 1):
    """Wrap a ``torch.optim`` optimizer with gradient-allreduce hooks
    (ref: torch/optimizer.py:516 DistributedOptimizer — same call shape:
    construct your optimizer, wrap it, train as usual)::

        opt = torch.optim.SGD(model.parameters(), lr=0.1)
        opt = hvd.interop.torch.DistributedOptimizer(
            opt, named_parameters=model.named_parameters())
        loss.backward()   # grads stream into named async allreduces
        opt.step()        # synchronize() then apply

    Returns an object of a dynamic subclass of the wrapped optimizer's
    class, so isinstance checks and schedulers keep working.
    """
    named = list(named_parameters) if named_parameters is not None else None

    base = optimizer.__class__
    cls = type("Distributed" + base.__name__, (base,), {
        "step": _step,
        "synchronize": _synchronize,
        "zero_grad": _zero_grad,
        "_hvdt_base": base,
    })
    optimizer.__class__ = cls
    optimizer._hvdt = _Hooks(optimizer, named, op, process_set,
                             backward_passes_per_step)
    return optimizer


def _step(self, closure=None):
    h = self._hvdt
    if closure is not None:
        # A closure's backward() would enqueue fresh allreduces AFTER the
        # synchronize below, so the update would use unreduced local
        # grads and replicas would silently diverge. Explicit beats
        # silent: restructure as backward() -> step() without a closure.
        raise ValueError(
            "DistributedOptimizer.step() does not support closures: run "
            "backward() first, then call step() with no arguments.")
    if h.mid_accumulation():
        raise RuntimeError(
            f"step() called mid-accumulation: with "
            f"backward_passes_per_step={h.k}, call backward() {h.k} times "
            f"before each step() (ref contract).")
    if not h._synchronized:
        h.synchronize(self)
    out = self._hvdt_base.step(self)
    # The reduced grads were consumed; the next backward must re-sync.
    h._synchronized = False
    return out


def _synchronize(self):
    """Wait for all outstanding gradient allreduces and install the
    reduced gradients (ref: optimizer.py synchronize :255)."""
    self._hvdt.synchronize(self)


def _zero_grad(self, set_to_none: bool = True):
    h = self._hvdt
    if h._handles:
        raise RuntimeError(
            "zero_grad() called with allreduce handles outstanding — "
            "call step() or synchronize() first (matches the reference's "
            "misuse guard).")
    if h.mid_accumulation():
        raise RuntimeError(
            "zero_grad() called mid-accumulation would discard "
            f"gradients: with backward_passes_per_step={h.k}, zero only "
            "after the boundary step().")
    return self._hvdt_base.zero_grad(self, set_to_none=set_to_none)
