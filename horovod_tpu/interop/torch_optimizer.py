"""Grad-hook DistributedOptimizer for PyTorch models.

The reference's canonical torch API: wrap any ``torch.optim`` optimizer so
each parameter's gradient, the moment autograd finishes accumulating it,
is enqueued as a named async allreduce; ``step()`` synchronizes the
handles and applies the reduced gradients
(ref: torch/optimizer.py — _DistributedOptimizer grad hooks :131-253,
synchronize :255-302, factory :516-605).

TPU-native translation: the hooks enqueue through THIS framework's eager
controller (negotiation + fusion + response cache), and the bytes ride
whichever host data plane is selected (XLA device mesh or the native TCP
backend) — no NCCL, no DDP.  Because the controller's background thread
negotiates while autograd is still producing later gradients, comm
overlaps backward exactly like the reference.

``backward_passes_per_step=k`` follows the reference contract: call
``backward()`` k times, then ``step()`` once.  Each parameter carries a
delay counter (ref: _allreduce_delay); its hook enqueues the accumulated
gradient (divided by k) on the k-th backward.  Calling ``step()`` or
``zero_grad()`` mid-accumulation raises instead of silently training
wrong.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, Optional, Tuple

import numpy as np

from ..common.types import ReduceOp
from .torch import _to_np

__all__ = ["DistributedOptimizer"]


class _Hooks:
    """Per-parameter async-allreduce state shared by the mixin methods."""

    def __init__(self, optimizer, named_parameters, op, process_set,
                 backward_passes_per_step: int, compression=None,
                 gradient_predivide_factor: float = 1.0,
                 num_groups: int = 0, groups=None,
                 sparse_as_dense: bool = False):
        from ..ops.compression import Compression

        self.op = op
        self.process_set = process_set
        self.k = max(1, int(backward_passes_per_step))
        # None → environment selection (HVDT_COMPRESSION / HVDT_QUANT);
        # int8 here means on-grid host values (see Int8Compressor).
        self.compression = compression or Compression.from_env()
        self.predivide = float(gradient_predivide_factor)
        if self.predivide != 1.0 and op != ReduceOp.AVERAGE:
            raise ValueError(
                "gradient_predivide_factor requires op=Average "
                "(ref: optimizer.py:560)")
        self.sparse_as_dense = bool(sparse_as_dense)
        self._handles: Dict[Any, int] = {}       # param -> eager handle
        self._names: Dict[Any, str] = {}
        self._delay: Dict[Any, int] = {}         # param -> backwards left
        self._decompress_ctx: Dict[Any, Any] = {}
        self._hook_refs = []
        self._synchronized = False               # grads already reduced

        params = [p for group in optimizer.param_groups
                  for p in group["params"]]
        if named_parameters is not None:
            seen = set()
            for n, _ in named_parameters:
                if n in seen:
                    raise ValueError(
                        f"duplicate parameter name {n!r} in "
                        "named_parameters — collective names must be "
                        "unique (ref: optimizer.py duplicate check)")
                seen.add(n)
            by_obj = {id(p): n for n, p in named_parameters}
            missing = [p for p in params if id(p) not in by_obj]
            if missing:
                raise ValueError(
                    "named_parameters does not cover all optimized "
                    f"parameters ({len(missing)} missing)")
            names = {p: f"grad.{by_obj[id(p)]}" for p in params}
        else:
            names = {p: f"grad.{i}" for i, p in enumerate(params)}
        self._names = names

        # Grouped (all-or-nothing fused) allreduce assignment
        # (ref: optimizer.py num_groups/groups -> grouped allreduces).
        trainable = [p for p in params if p.requires_grad]
        self._group_of: Dict[Any, int] = {}
        self._group_members: Dict[int, list] = {}
        if groups is not None and num_groups:
            raise ValueError("pass either num_groups or groups, not both")
        if groups is not None:
            optimized = {id(p) for p in trainable}
            listed = set()
            for gi, members in enumerate(groups):
                for p in members:
                    if id(p) in listed:
                        raise ValueError("parameter appears in two groups")
                    listed.add(id(p))
                    # Only optimizer-owned trainable params get hooks and
                    # zeros-fill, so only they can complete a group —
                    # intersect, or a group holding frozen/non-optimized
                    # params would never issue.
                    if p.requires_grad and id(p) in optimized:
                        self._group_of[p] = gi
        elif num_groups:
            n = max(1, min(int(num_groups), len(trainable)))
            per = -(-len(trainable) // n)
            for i, p in enumerate(trainable):
                self._group_of[p] = i // per
        for p, gi in self._group_of.items():
            self._group_members.setdefault(gi, []).append(p)
        self._group_pending: Dict[int, Dict[Any, np.ndarray]] = {}
        # Stable cross-rank group ids: allocate NOW, in group-index order.
        # Hook order (and therefore issue order) varies across ranks, so
        # taking a fresh id at issue time would misalign the coordinator's
        # all-or-nothing gate; construction order is deterministic
        # (identical model/optimizer structure on every rank).
        self._group_gid: Dict[int, int] = {}
        if self._group_members:
            from ..ops import eager

            ctl = eager._controller()
            for gi in sorted(self._group_members):
                self._group_gid[gi] = ctl.next_group_id()

        for p in trainable:
            self._delay[p] = self.k
            self._hook_refs.append(
                p.register_post_accumulate_grad_hook(self._hook))

    def _hook(self, p) -> None:
        if self._delay.get(p, self.k) <= 0:
            raise RuntimeError(
                f"Gradients for {self._names[p]!r} were computed more "
                f"than backward_passes_per_step={self.k} times before "
                "step()/synchronize() (ref misuse guard).")
        d = self._delay[p] = self._delay.get(p, self.k) - 1
        if d <= 0:
            self._enqueue(p)

    def _scale_factors(self):
        """op + pre/postscale with gradient_predivide_factor folded in
        (ref: _allreduce_grad_async, optimizer.py:197-204: averaging is
        split into SUM with prescale 1/f and postscale f/size)."""
        if self.predivide == 1.0:
            return self.op, 1.0, 1.0
        from ..common.process_sets import global_process_set

        ps = self.process_set or global_process_set()
        return (ReduceOp.SUM, 1.0 / self.predivide,
                self.predivide / ps.size())

    def _grad_array(self, p, zeros: bool):
        if zeros or p.grad is None:
            grad = np.zeros(tuple(p.shape), dtype=_wire_np_dtype(p))
        else:
            g = p.grad.detach()
            if g.is_sparse:
                if not self.sparse_as_dense:
                    raise NotImplementedError(
                        "sparse gradient for "
                        f"{self._names[p]!r}: pass sparse_as_dense=True "
                        "(ref: optimizer.py sparse_as_dense) or use "
                        "hvd.sparse_allreduce")
                g = g.to_dense()
            # bf16 (and other numpy-less torch dtypes) go over the wire
            # as f32 — matching the zeros path so every rank negotiates
            # the same dtype for a name.
            if not _numpy_compatible(g.dtype):
                g = g.float()
            # Copy: the controller's background thread reads this buffer
            # asynchronously; a zero-copy view of p.grad would race with
            # in-place grad mutation (clip_grad_norm_ etc.).
            grad = np.array(_to_np(g), copy=True)
            if self.k > 1:
                grad /= self.k
        # Wire compression (ref: compression.py fp16) — the zeros path
        # compresses too, so every rank negotiates one dtype per name.
        grad, ctx = self.compression.compress(grad)
        self._decompress_ctx[p] = ctx
        return np.asarray(grad)

    def _enqueue(self, p, zeros: bool = False) -> None:
        from ..ops import eager

        gi = self._group_of.get(p)
        if p in self._handles:          # re-enqueue past the boundary
            if gi is not None:
                # A grouped param cannot re-issue alone (its mates' old
                # handles would desynchronize the all-or-nothing set);
                # the hook's over-backward guard makes this unreachable
                # in practice — refuse loudly if something new hits it.
                raise RuntimeError(
                    f"grouped parameter {self._names[p]!r} re-enqueued "
                    "while its previous grouped allreduce is outstanding "
                    "— call step()/synchronize() first")
            eager.synchronize(self._handles.pop(p))
        grad = self._grad_array(p, zeros)
        op, pre, post = self._scale_factors()
        if gi is None:
            self._handles[p] = eager.allreduce_async(
                grad, name=self._names[p], op=op, prescale_factor=pre,
                postscale_factor=post, process_set=self.process_set)
            self._synchronized = False
            return
        # Grouped mode: buffer until every member of the group has a
        # gradient, then issue one all-or-nothing grouped allreduce.
        # Deterministic name order (sorted by collective name) keeps
        # ranks' request lists aligned regardless of autograd hook order.
        pending = self._group_pending.setdefault(gi, {})
        pending[p] = grad
        if len(pending) == len(self._group_members[gi]):
            members = sorted(pending, key=lambda q: self._names[q])
            handles = eager.grouped_allreduce_async(
                [pending[q] for q in members],
                name=f"grad_group.{gi}", op=op, prescale_factor=pre,
                postscale_factor=post, process_set=self.process_set,
                group_id=self._group_gid[gi])
            for q, h in zip(members, handles):
                self._handles[q] = h
            del self._group_pending[gi]
        self._synchronized = False

    def mid_accumulation(self) -> bool:
        return any(0 < d < self.k for d in self._delay.values())

    def synchronize(self, optimizer) -> None:
        import torch

        from ..ops import eager

        # Symmetric negotiation: ranks may differ in which params got
        # gradients (data-dependent branches, per-rank frozen modules).
        # Every rank enqueues EVERY optimized param — zeros when no local
        # gradient exists — so no rank's negotiation can hang waiting for
        # a name that never arrives elsewhere.
        for group in optimizer.param_groups:
            for p in group["params"]:
                if p.requires_grad and p not in self._handles:
                    self._enqueue(p, zeros=p.grad is None)
        for p, handle in list(self._handles.items()):
            out = eager.synchronize(handle)
            out = self.compression.decompress(out,
                                              self._decompress_ctx.pop(p,
                                                                       None))
            t = torch.from_numpy(np.asarray(out))
            with torch.no_grad():
                if p.grad is None or p.grad.is_sparse:
                    # sparse_as_dense reduced a densified gradient; the
                    # reduced result replaces the sparse grad outright
                    # (ref: _DistributedOptimizer sparse_as_dense).
                    p.grad = t.view(p.shape).to(p.dtype).clone()
                else:
                    p.grad.copy_(t.view_as(p.grad))
        self._handles.clear()
        for p in self._delay:
            self._delay[p] = self.k
        self._synchronized = True


def _numpy_compatible(dtype) -> bool:
    import torch

    return dtype in (torch.float32, torch.float64, torch.float16)


def _wire_np_dtype(p):
    import torch

    return {torch.float32: np.float32, torch.float64: np.float64,
            torch.float16: np.float16}.get(p.dtype, np.float32)


def DistributedOptimizer(optimizer,
                         named_parameters: Optional[
                             Iterable[Tuple[str, Any]]] = None,
                         compression=None,
                         backward_passes_per_step: int = 1,
                         op: ReduceOp = ReduceOp.AVERAGE,
                         gradient_predivide_factor: float = 1.0,
                         num_groups: int = 0,
                         groups=None,
                         sparse_as_dense: bool = False,
                         process_set=None):
    """Wrap a ``torch.optim`` optimizer with gradient-allreduce hooks
    (ref: torch/optimizer.py:516 DistributedOptimizer — same call shape:
    construct your optimizer, wrap it, train as usual)::

        opt = torch.optim.SGD(model.parameters(), lr=0.1)
        opt = hvd.interop.torch.DistributedOptimizer(
            opt, named_parameters=model.named_parameters())
        loss.backward()   # grads stream into named async allreduces
        opt.step()        # synchronize() then apply

    Returns an object of a dynamic subclass of the wrapped optimizer's
    class, so isinstance checks and schedulers keep working.
    """
    named = list(named_parameters) if named_parameters is not None else None

    base = optimizer.__class__
    cls = type("Distributed" + base.__name__, (base,), {
        "step": _step,
        "synchronize": _synchronize,
        "zero_grad": _zero_grad,
        "skip_synchronize": _skip_synchronize,
        "_hvdt_base": base,
    })
    optimizer.__class__ = cls
    optimizer._hvdt = _Hooks(
        optimizer, named, op, process_set, backward_passes_per_step,
        compression=compression,
        gradient_predivide_factor=gradient_predivide_factor,
        num_groups=num_groups, groups=groups,
        sparse_as_dense=sparse_as_dense)
    return optimizer


def _step(self, closure=None):
    h = self._hvdt
    if closure is not None:
        # A closure's backward() would enqueue fresh allreduces AFTER the
        # synchronize below, so the update would use unreduced local
        # grads and replicas would silently diverge. Explicit beats
        # silent: restructure as backward() -> step() without a closure.
        raise ValueError(
            "DistributedOptimizer.step() does not support closures: run "
            "backward() first, then call step() with no arguments.")
    if h.mid_accumulation():
        raise RuntimeError(
            f"step() called mid-accumulation: with "
            f"backward_passes_per_step={h.k}, call backward() {h.k} times "
            f"before each step() (ref contract).")
    if not h._synchronized:
        h.synchronize(self)
    out = self._hvdt_base.step(self)
    # The reduced grads were consumed; the next backward must re-sync.
    h._synchronized = False
    return out


def _synchronize(self):
    """Wait for all outstanding gradient allreduces and install the
    reduced gradients (ref: optimizer.py synchronize :255)."""
    self._hvdt.synchronize(self)


def _skip_synchronize(self):
    """Context manager: tell the following step() not to synchronize
    again — the caller already did, e.g. around gradient clipping
    (ref: optimizer.py skip_synchronize :303-310)::

        opt.synchronize()
        torch.nn.utils.clip_grad_norm_(model.parameters(), 1.0)
        with opt.skip_synchronize():
            opt.step()
    """
    import contextlib

    h = self._hvdt

    @contextlib.contextmanager
    def _ctx():
        # step() itself skips re-synchronizing when h._synchronized is
        # set, so the context only needs the misuse guard.
        if not h._synchronized:
            raise RuntimeError(
                "skip_synchronize() entered without a prior synchronize() "
                "— step() would apply unreduced gradients")
        yield

    return _ctx()


def _zero_grad(self, set_to_none: bool = True):
    h = self._hvdt
    if h._handles:
        raise RuntimeError(
            "zero_grad() called with allreduce handles outstanding — "
            "call step() or synchronize() first (matches the reference's "
            "misuse guard).")
    if h.mid_accumulation():
        raise RuntimeError(
            "zero_grad() called mid-accumulation would discard "
            f"gradients: with backward_passes_per_step={h.k}, zero only "
            "after the boundary step().")
    return self._hvdt_base.zero_grad(self, set_to_none=set_to_none)
