"""Elastic state for torch training (ref: horovod/torch/elastic/state.py).

The same handler design: ``TorchState(model=..., optimizer=..., **misc)``
assigns each kwarg as an attribute and routes save/restore/sync through
a type-matched handler (``nn.Module`` -> state_dict deepcopy +
broadcast_parameters, ``Optimizer`` -> state_dict deepcopy +
broadcast_optimizer_state, ``ElasticSampler`` -> state_dict +
broadcast_object), falling back to plain ObjectState pickling for
everything else.  The handler registry is user-extensible
(``set_handler_registry``), matching the reference surface.

This module imports torch at import time (it IS torch-binding code);
``interop.torch`` and user code reach it lazily.
"""

from __future__ import annotations

import copy
from typing import Any, Dict, List, Tuple

import torch

from ..data.sampler import ElasticSampler
from ..elastic import ObjectState, run  # noqa: F401  (run re-exported)
from ..functions import broadcast_object
from . import torch as _binding

__all__ = ["TorchState", "StateHandler", "ModelStateHandler",
           "OptimizerStateHandler", "SamplerStateHandler",
           "get_handler_registry", "set_handler_registry", "run"]


class StateHandler:
    """Per-type save/restore/sync strategy
    (ref: torch/elastic/state.py:71 StateHandler)."""

    def __init__(self, value):
        self.value = value

    def save(self):
        raise NotImplementedError()

    def restore(self):
        raise NotImplementedError()

    def sync(self):
        raise NotImplementedError()

    def set_value(self, value):
        self.value = value
        self.save()


class ModelStateHandler(StateHandler):
    def __init__(self, model):
        super().__init__(model)
        self._saved = copy.deepcopy(self.value.state_dict())

    def save(self):
        self._saved = copy.deepcopy(self.value.state_dict())

    def restore(self):
        self.value.load_state_dict(self._saved)

    def sync(self):
        _binding.broadcast_parameters(self.value.state_dict(), root_rank=0)


class OptimizerStateHandler(StateHandler):
    def __init__(self, optimizer):
        super().__init__(optimizer)
        self._saved = copy.deepcopy(self.value.state_dict())

    def save(self):
        self._saved = copy.deepcopy(self.value.state_dict())

    def restore(self):
        self.value.load_state_dict(self._saved)

    def sync(self):
        _binding.broadcast_optimizer_state(self.value, root_rank=0)


class SamplerStateHandler(StateHandler):
    def __init__(self, sampler):
        super().__init__(sampler)
        self._saved = copy.deepcopy(self.value.state_dict())

    def save(self):
        self._saved = copy.deepcopy(self.value.state_dict())

    def restore(self):
        self.value.load_state_dict(self._saved)

    def sync(self):
        # Broadcast then load so every rank repartitions identically
        # (ref: SamplerStateHandler.sync).
        self.value.load_state_dict(
            broadcast_object(self.value.state_dict(), root_rank=0,
                             name="torch_sampler_state"))


_handler_registry: List[Tuple[type, type]] = [
    (torch.nn.Module, ModelStateHandler),
    (torch.optim.Optimizer, OptimizerStateHandler),
    (ElasticSampler, SamplerStateHandler),
]


def get_handler_registry():
    return _handler_registry


def set_handler_registry(registry):
    global _handler_registry
    _handler_registry = registry


def _get_handlers(kwargs: Dict[str, Any]):
    handlers, remainder = {}, {}
    for k, v in kwargs.items():
        for handler_type, handler_cls in _handler_registry:
            if isinstance(v, handler_type):
                handlers[k] = handler_cls(v)
                break
        else:
            remainder[k] = v
    return handlers, remainder


class TorchState(ObjectState):
    """State of a torch training process: models, optimizers, samplers
    and arbitrary picklable attributes, with commit/restore/sync routed
    through per-type handlers (ref: torch/elastic/state.py:27
    TorchState — same kwargs contract and attribute exposure)."""

    def __init__(self, model=None, optimizer=None, **kwargs):
        kwargs.update({k: v for k, v in
                       (("model", model), ("optimizer", optimizer))
                       if v is not None})
        handlers, remainder = _get_handlers(kwargs)
        # bypass __setattr__'s handler routing during construction
        object.__setattr__(self, "_handlers", handlers)
        for name, handler in handlers.items():
            object.__setattr__(self, name, handler.value)
        super().__init__(**remainder)

    def _payload_keys(self) -> List[str]:
        return [k for k in super()._payload_keys()
                if k not in self._handlers]

    def save(self) -> None:
        for handler in self._handlers.values():
            handler.save()
        super().save()

    def restore(self) -> None:
        for handler in self._handlers.values():
            handler.restore()
        super().restore()

    def sync(self) -> None:
        for handler in self._handlers.values():
            handler.sync()
        super().sync()

    def __setattr__(self, name, value):
        if hasattr(self, "_handlers") and name in self._handlers:
            self._handlers[name].set_value(value)
        object.__setattr__(self, name, value)
