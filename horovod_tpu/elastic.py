"""Elastic training: State snapshot/commit/restore + the run() retry loop.

Re-conception of ref: horovod/common/elastic.py:1-175 (State, ObjectState,
run_fn retry loop :151-175) and torch/elastic/state.py (TorchState pytree
handlers) for JAX: state lives in pytrees, snapshots are host-memory copies
(``jax.device_get``), restore re-places them on device with the current
sharding, and reset re-initializes the framework topology after a
re-rendezvous.

The contract (ref: docs/elastic.rst):

    state = hvd.elastic.JaxState(params=params, opt_state=opt_state, batch=0)

    @hvd.elastic.run
    def train(state):
        while state.batch < N:
            state.params, state.opt_state = step(state.params, ...)
            state.batch += 1
            if state.batch % 100 == 0:
                state.commit()

* ``HorovodInternalError`` (a collective died — peer preempted): restore
  from the last commit, re-rendezvous, continue.
* ``HostsUpdatedInterrupt`` (driver announced membership change at a
  commit point): keep current state, re-rendezvous, continue.
"""

from __future__ import annotations

import copy
import functools
import os
import pickle
from typing import Any, Callable, Dict, List, Optional

from .common.basics import is_initialized, rank
from .common.exceptions import (HorovodInternalError, HostsUpdatedInterrupt)
from .common.logging_util import get_logger
from .resilience import faults

log = get_logger(__name__)

__all__ = ["State", "ObjectState", "JaxState", "run"]


class State:
    """Base elastic state (ref: common/elastic.py:26 State).

    Subclasses implement save/restore/sync of their payload; this class
    carries the reset-callback machinery and host-update polling.
    """

    def __init__(self) -> None:
        self._reset_callbacks: List[Callable[[], None]] = []
        self._notification_manager = None

    def register_reset_callbacks(self, callbacks) -> None:
        self._reset_callbacks.extend(callbacks)

    def on_reset(self) -> None:
        self._host_messages_pending = False
        self.reset()
        for cb in self._reset_callbacks:
            cb()

    def on_hosts_updated(self) -> None:
        pass

    def commit(self) -> None:
        """Snapshot + check for pending host updates
        (ref: common/elastic.py:60-71 commit/check_host_updates)."""
        self.save()
        self._resilience_check()
        self.check_host_updates()

    def _resilience_check(self) -> None:
        """Commit-point hook for the resilience machinery: fire the
        ``step`` fault-injection point (chaos runs kill/hang/fault the
        worker here) and poll the preemption guard (SIGTERM since the
        last commit → emergency persist + clean exit).  Both are
        None-checks when idle — zero work without a fault plan or
        guard."""
        step = getattr(self, "batch", None)
        if not isinstance(step, int):
            step = None
        inj = faults.get_injector()
        if inj is not None:
            inj.fire("step", step=step)
        guard = getattr(self, "_preempt_guard", None)
        if guard is not None:
            guard.check(step=step)

    def check_host_updates(self) -> None:
        if self._notification_manager is None:
            from .runner.elastic.worker import WorkerNotificationManager

            self._notification_manager = WorkerNotificationManager()
            self._notification_manager.init()
        self._notification_manager.check_for_updates()

    # -- subclass payload hooks -------------------------------------------

    def save(self) -> None:
        raise NotImplementedError

    def restore(self) -> None:
        raise NotImplementedError

    def sync(self) -> None:
        raise NotImplementedError

    def reset(self) -> None:
        pass


class ObjectState(State):
    """Elastic state of arbitrary picklable attributes
    (ref: common/elastic.py:101 ObjectState)."""

    def __init__(self, **kwargs: Any):
        super().__init__()
        self._saved: Dict[str, Any] = {}
        for k, v in kwargs.items():
            setattr(self, k, v)
        self.save()

    def _payload_keys(self) -> List[str]:
        return [k for k in self.__dict__
                if not k.startswith("_")]

    def save(self) -> None:
        self._saved = {k: copy.deepcopy(getattr(self, k))
                       for k in self._payload_keys()}

    def restore(self) -> None:
        for k, v in self._saved.items():
            setattr(self, k, copy.deepcopy(v))

    def sync(self) -> None:
        """Broadcast payload from rank 0 so joining workers align
        (ref: ObjectState.sync → broadcast_object)."""
        if not is_initialized():
            return
        from .functions import broadcast_object

        payload = {k: getattr(self, k) for k in self._payload_keys()}
        payload = broadcast_object(payload, root_rank=0, name="elastic_state")
        for k, v in payload.items():
            setattr(self, k, v)
        self.save()


class JaxState(ObjectState):
    """Elastic state whose array-valued attributes are JAX pytrees
    (ref: torch/elastic/state.py TorchState with Model/Optimizer handlers).

    Snapshots pull arrays to host memory (`jax.device_get`) so a committed
    state survives device loss; restore pushes them back (the next jitted
    step re-shards them under the then-current mesh).

    ``path``: optional disk location for commits.  Under the launcher's
    elastic mode the re-rendezvous model is PROCESS RESTART (a compiled
    XLA world cannot resize in place — SURVEY.md §7 hard parts), so a
    commit must outlive the process: with ``path`` set, every commit also
    writes the host-memory snapshot there atomically, and a freshly
    spawned worker finding the file resumes from it (rank consistency
    comes from the usual sync() broadcast).

    With ``HVDT_PEER_STORE`` set, every commit ALSO publishes the
    snapshot to the peer-replicated RAM tier (resilience/peer_store.py)
    and a respawned worker restores from whichever tier holds the newer
    commit — ties go to the peer tier, so a healthy recovery never
    touches the filesystem.  ``restored_from`` records which tier served
    (``"peer"`` / ``"disk"`` / None).
    """

    def __init__(self, path: Optional[str] = None, **kwargs: Any):
        self._state_path = path
        self.restored_from: Optional[str] = None
        super().__init__(**kwargs)
        self._resume()

    def _resume(self) -> None:
        """Boot-time restore: newest of {peer RAM tier, disk commit}."""
        from .resilience import peer_store as _peer_store
        from .telemetry import step_stats

        import time as _time

        ledger = step_stats.recovery_ledger()
        t0 = _time.perf_counter()
        disk_saved = None
        if self._state_path and os.path.exists(self._state_path):
            with open(self._state_path, "rb") as f:
                disk_saved = pickle.load(f)
        ps = _peer_store.get_peer_store()
        peer = ps.restore() if ps is not None else None
        if peer is not None:
            peer_saved, peer_step = peer
            disk_step = disk_saved.get("batch") if isinstance(
                disk_saved, dict) else None
            if not isinstance(disk_step, int) or peer_step >= disk_step:
                self._saved = peer_saved
                self.restore()
                self.restored_from = "peer"
                log.info("elastic state resumed from the peer RAM tier "
                         "at step %s", peer_step)
                disk_saved = None
        if disk_saved is not None:
            self._saved = disk_saved
            self.restore()
            self.restored_from = "disk"
            log.info("elastic state resumed from %s", self._state_path)
        if ledger is not None and self.restored_from is not None:
            ledger.charge_phase("restore", _time.perf_counter() - t0)

    def _payload_keys(self) -> List[str]:
        return [k for k in super()._payload_keys() if k != "path"]

    def persist(self) -> None:
        """Write the committed snapshot to ``path`` (atomic rename)."""
        if not self._state_path:
            return
        tmp = f"{self._state_path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            pickle.dump(self._saved, f)
        os.replace(tmp, self._state_path)

    def commit(self) -> None:
        self.save()
        self.persist()
        # Peer tier rides the same commit point: publish this commit's
        # snapshot over the rendezvous KV and refresh the watched peer's
        # RAM replica (None-check when HVDT_PEER_STORE is unset).
        from .resilience import peer_store as _peer_store

        ps = _peer_store.get_peer_store()
        if ps is not None:
            step = getattr(self, "batch", None)
            ps.commit(step if isinstance(step, int) else 0, self._saved)
        # After persist: an injected crash or a preemption exit at the
        # commit point leaves this commit restorable on disk.
        self._resilience_check()
        self.check_host_updates()

    def _split(self, payload: Dict[str, Any]):
        import jax

        arrays, objects = {}, {}
        for k, v in payload.items():
            leaves = jax.tree.leaves(v)
            if leaves and all(hasattr(l, "shape") and hasattr(l, "dtype")
                              for l in leaves):
                arrays[k] = v
            else:
                objects[k] = v
        return arrays, objects

    def save(self) -> None:
        import jax

        payload = {k: getattr(self, k) for k in self._payload_keys()}
        arrays, objects = self._split(payload)
        saved = {k: copy.deepcopy(v) for k, v in objects.items()}
        for k, v in arrays.items():
            saved[k] = jax.device_get(v)   # host-memory numpy snapshot
        self._saved = saved

    def restore(self) -> None:
        for k, v in self._saved.items():
            setattr(self, k, copy.deepcopy(v))

    def sync(self) -> None:
        if not is_initialized():
            return
        import jax

        from .functions import broadcast_object, broadcast_parameters

        payload = {k: getattr(self, k) for k in self._payload_keys()}
        arrays, objects = self._split(payload)
        if objects:
            objects = broadcast_object(objects, root_rank=0,
                                       name="elastic_objs")
            for k, v in objects.items():
                setattr(self, k, v)
        for k, tree in arrays.items():
            leaves, treedef = jax.tree.flatten(tree)
            leaves = broadcast_parameters(leaves, root_rank=0)
            setattr(self, k, jax.tree.unflatten(treedef, leaves))
        self.save()


def run(func: Callable) -> Callable:
    """Elastic retry-loop decorator (ref: common/elastic.py:151 run_fn).

    ``func(state, *args, **kwargs)`` is re-entered after recoverable
    failures: HorovodInternalError ⇒ restore-from-commit;
    HostsUpdatedInterrupt ⇒ continue with current state.  Each re-entry
    re-initializes the framework and calls state.on_reset()/sync().
    """

    @functools.wraps(func)
    def wrapper(state: State, *args, **kwargs):
        _install_preemption_guard(state)
        skip_sync = False
        while True:
            if not skip_sync:
                state.sync()
            try:
                return func(state, *args, **kwargs)
            except HorovodInternalError:
                log.info("collective failure — restoring last commit")
                with _recovery_phase("restore"):
                    state.restore()
                skip_sync = False
                if _launcher_managed():
                    _exit_for_respawn(state)
            except HostsUpdatedInterrupt as e:
                log.info("hosts updated — re-rendezvous without rollback")
                skip_sync = e.skip_sync
                if _launcher_managed():
                    _exit_for_respawn(state)
            with _recovery_phase("rendezvous"):
                _reset(state)

    return wrapper


def _recovery_phase(name: str):
    """Recovery-budget attribution for the in-process retry path — a
    null context when telemetry is off (the ledger's zero-overhead
    contract; the launcher-managed path attributes in the respawned
    process instead, see JaxState._resume)."""
    import contextlib

    from .telemetry import step_stats

    ledger = step_stats.recovery_ledger()
    if ledger is None:
        return contextlib.nullcontext()
    return ledger.phase(name)


def _install_preemption_guard(state: State):
    """Under the elastic launcher, arm a SIGTERM/SIGINT preemption guard
    for the worker: the grace window becomes an emergency
    save+persist and a clean PREEMPT_EXIT_CODE exit that the driver
    treats as host removal, not failure (resilience/preempt.py).  Plain
    (non-launcher) runs keep default signal semantics."""
    if not _launcher_managed():
        return None
    from .resilience.preempt import PreemptionGuard

    def emergency():
        state.save()
        persist = getattr(state, "persist", None)
        if persist is not None:
            persist()

    guard = PreemptionGuard(on_preempt=emergency)
    try:
        guard.install()
    except ValueError:      # not the main thread — guard unavailable
        return None
    state._preempt_guard = guard
    return guard


def _launcher_managed() -> bool:
    """True under `hvdtrun --elastic`: the driver owns worker lifecycles
    and re-rendezvous means PROCESS RESTART (the driver respawns every
    slot each generation; a fresh process gets the new topology via the
    env contract and resumes from the disk commit)."""
    from .common import config

    return (config.get_bool("HVDT_ELASTIC")
            and bool(config.get_str("HVDT_RENDEZVOUS_ADDR")))


def _exit_for_respawn(state: State) -> None:
    import sys

    from .runner.elastic.driver import RESTART_EXIT_CODE

    persist = getattr(state, "persist", None)
    if persist is not None:
        persist()
    log.info("exiting for respawn under the new generation")
    sys.stdout.flush()
    sys.stderr.flush()
    # os._exit, not sys.exit: interpreter teardown runs the JAX
    # distributed client's shutdown barrier, which waits on every peer —
    # and on the collective-failure path a peer is DEAD, so the barrier
    # blocks until its ~100s heartbeat timeout and then aborts the
    # process, turning a clean restart into a failure exit.  The commit
    # is already persisted; the process is being replaced, not torn down.
    os._exit(RESTART_EXIT_CODE)


def _reset(state: State) -> None:
    """Tear down and re-initialize the runtime for the new cluster
    (ref: common/elastic.py reset() → shutdown + re-init; on TPU this
    re-reads the launcher contract and rebuilds topology/mesh)."""
    from .common import basics
    from .ops import eager

    try:
        eager.shutdown_controller()
    except Exception:
        pass
    if basics.is_initialized():
        basics.shutdown()
    basics.init()
    state.on_reset()
