"""Per-tensor Chrome-tracing timeline profiler.

TPU-native re-conception of the reference's Timeline subsystem
(ref: common/timeline.{h,cc} — TimelineWriter timeline.h:48, Timeline
timeline.h:108, TimelineController timeline.h:165; JSON emission
timeline.cc:217-294; "tensors as pids" timeline.cc:244-266).

Phases mirror the reference lifecycle (common.h:72-105): NEGOTIATE_<OP>,
QUEUE, FUSE, <BACKEND> activity, with an end marker carrying the output
shape.  Events are pushed onto a queue consumed by a dedicated writer
thread, so the hot path never blocks on file IO (same design as
TimelineWriter's record queue).

Enable via ``HVDT_TIMELINE=<path>`` or dynamically with
``timeline.start_timeline`` / ``stop_timeline``
(ref: horovod_start_timeline operations.cc:1032-1064).

For device-side tracing, see ``jax.profiler`` integration in
``horovod_tpu.ops.eager`` — each fused collective executes under a named
``jax.profiler.TraceAnnotation`` so XPlane traces carry the same names.
"""

from __future__ import annotations

import json
import os
import queue
import threading
import time
from typing import Dict, List, Optional

from .common import config
from .common.logging_util import get_logger

__all__ = ["Timeline", "start_timeline", "stop_timeline", "get_timeline"]

log = get_logger(__name__)


class _Event:
    __slots__ = ("phase", "tensor", "marker", "args", "ts")

    def __init__(self, phase: str, tensor: str, marker: str,
                 args: Optional[dict], ts: float):
        self.phase = phase      # 'B' begin, 'E' end, 'i' instant, 'M' meta
        self.tensor = tensor
        self.marker = marker
        self.args = args
        self.ts = ts


class Timeline:
    """Chrome-tracing JSON writer with an async writer thread.

    Each tensor gets its own "pid" row; activities nest as duration events
    (ref: timeline.cc:244-266).
    """

    def __init__(self, path: str, mark_cycles: bool = False):
        self.path = path
        self.mark_cycles = mark_cycles
        self._queue: "queue.Queue[Optional[_Event]]" = queue.Queue()
        self._tensor_pids: Dict[str, int] = {}
        self._next_pid = 1
        self._lock = threading.Lock()
        self._start = time.perf_counter()
        self._file = open(path, "w")
        self._file.write("[\n")
        self._first = True
        self._closed = False
        self._writer = threading.Thread(target=self._writer_loop,
                                        name="hvdt-timeline-writer",
                                        daemon=True)
        self._writer.start()

    # -- recording API (hot path: enqueue only) -----------------------------
    def _now_us(self) -> float:
        return (time.perf_counter() - self._start) * 1e6

    def start_activity(self, tensor: str, activity: str,
                       args: Optional[dict] = None) -> None:
        self._queue.put(_Event("B", tensor, activity, args, self._now_us()))

    def end_activity(self, tensor: str, args: Optional[dict] = None) -> None:
        self._queue.put(_Event("E", tensor, "", args, self._now_us()))

    def instant(self, tensor: str, marker: str,
                args: Optional[dict] = None) -> None:
        self._queue.put(_Event("i", tensor, marker, args, self._now_us()))

    def mark_cycle(self) -> None:
        if self.mark_cycles:
            self.instant("_cycle", "CYCLE")

    # -- writer thread ------------------------------------------------------
    def _pid_for(self, tensor: str) -> int:
        pid = self._tensor_pids.get(tensor)
        if pid is None:
            pid = self._next_pid
            self._next_pid += 1
            self._tensor_pids[tensor] = pid
            self._emit({"name": "process_name", "ph": "M", "pid": pid,
                        "args": {"name": tensor}})
        return pid

    def _emit(self, record: dict) -> None:
        if not self._first:
            self._file.write(",\n")
        self._first = False
        self._file.write(json.dumps(record))

    def _writer_loop(self) -> None:
        # Open B..E spans per tensor row, so completed spans can
        # double-record into the telemetry latency summaries
        # (hvdt_phase_<PHASE>_seconds) — aggregate percentiles exist
        # without opening the trace in a viewer.  All on the writer
        # thread: the hot path still only enqueues.
        open_spans: Dict[int, List] = {}
        while True:
            ev = self._queue.get()
            if ev is None:
                break
            pid = self._pid_for(ev.tensor)
            rec = {"ph": ev.phase, "pid": pid, "tid": 0,
                   "ts": round(ev.ts, 3)}
            if ev.phase in ("B", "i"):
                rec["name"] = ev.marker
            if ev.phase == "i":
                rec["s"] = "p"
            if ev.args:
                rec["args"] = ev.args
            self._emit(rec)
            if ev.phase == "B":
                open_spans.setdefault(pid, []).append((ev.marker, ev.ts))
            elif ev.phase == "E":
                stack = open_spans.get(pid)
                if stack:
                    marker, t0 = stack.pop()
                    from .telemetry.instrument import get_recorder
                    from .telemetry.trace import get_tracer

                    dur_s = (ev.ts - t0) / 1e6
                    recorder = get_recorder()
                    if recorder is not None:
                        recorder.observe_phase(marker, dur_s)
                    tracer = get_tracer()
                    if tracer is not None:
                        # Same span, cross-rank view: the distributed
                        # tracer's buffer feeds the driver-side merged
                        # trace (rank as pid) while this file keeps the
                        # per-tensor single-rank view.
                        tracer.complete(marker, dur_s, cat="timeline",
                                        args={"tensor": ev.tensor})

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._queue.put(None)
        self._writer.join(timeout=5)
        self._file.write("\n]\n")
        self._file.close()


# -- module-level singleton control (ref: TimelineController) ---------------

_timeline: Optional[Timeline] = None
_tl_lock = threading.Lock()


def current() -> Optional[Timeline]:
    """The active timeline, if any — cheap read for hot paths (no lock, no
    env auto-start).  Callers needing auto-start use get_timeline() once."""
    return _timeline


def get_timeline() -> Optional[Timeline]:
    """Active timeline, auto-starting from HVDT_TIMELINE on first call."""
    global _timeline
    with _tl_lock:
        if _timeline is None:
            path = config.get_str("HVDT_TIMELINE")
            if path:
                _timeline = Timeline(
                    path, config.get_bool("HVDT_TIMELINE_MARK_CYCLES"))
        return _timeline


def start_timeline(path: str, mark_cycles: bool = False) -> None:
    """Start recording dynamically (ref: operations.cc:1032
    horovod_start_timeline)."""
    global _timeline
    with _tl_lock:
        if _timeline is not None:
            log.warning("timeline already active; ignoring start_timeline")
            return
        _timeline = Timeline(path, mark_cycles)


def stop_timeline() -> None:
    global _timeline
    with _tl_lock:
        if _timeline is not None:
            _timeline.close()
            _timeline = None
