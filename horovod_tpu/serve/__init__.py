"""Online inference serving: turn a checkpointed pytree into an endpoint.

The subsystem the training stack feeds (ROADMAP: "serves heavy traffic
from millions of users").  Layering, bottom up:

* :mod:`~horovod_tpu.serve.metrics` — Prometheus-text counters / gauges /
  latency summaries (no new dependencies);
* :mod:`~horovod_tpu.serve.engine`  — :class:`InferenceEngine`: jit per
  shape bucket, pad-to-bucket, persistent-compile-cache reuse, hot
  weight swap, optional mesh sharding;
* :mod:`~horovod_tpu.serve.batcher` — :class:`DynamicBatcher`: bounded
  admission queue + linger-based micro-batching ahead of the engine;
* :mod:`~horovod_tpu.serve.llm`     — :class:`ContinuousLLMEngine`:
  continuous-batching LLM decode (paged KV cache, per-iteration
  scheduler, interactive/batch tenant quotas), selected with
  ``HVDT_SERVE_ENGINE=continuous``;
* :mod:`~horovod_tpu.serve.reload`  — :class:`CheckpointWatcher`: polls a
  ``CheckpointManager`` directory and hot-swaps newer steps;
* :mod:`~horovod_tpu.serve.server`  — :class:`ModelServer`: stdlib HTTP
  front end (``/predict``, ``/healthz``, ``/metrics``) with 503
  backpressure and SIGTERM graceful drain;
* :mod:`~horovod_tpu.serve.replica` — :class:`ReplicaRegistrar`: KV
  heartbeats (load + p99) that wire one replica into the elastic
  serving control plane, plus the ``--replica-worker`` entry;
* :mod:`~horovod_tpu.serve.router`  — :class:`Router`: the front tier —
  discovers live replicas from the rendezvous KV, load-balances
  ``/predict`` with retries/hedging, ejects SLO-breaching replicas;
* :mod:`~horovod_tpu.serve.autoscale` — :class:`ServeDriver` +
  :class:`AutoscalePolicy`: the driver-side replica autoscaler on the
  pod-aware elastic machinery (discovery, blacklist-with-cooldown,
  drain-then-exit-83 clean removal).

Entry points: ``python -m horovod_tpu.serve`` and ``hvdtrun serve``
(:func:`main`; ``--replicas``/``--autoscale`` switch to the elastic
control plane); in-process embedding via :class:`ModelServer` directly
(the test rig and bench.py --serve do this).
"""

from .batcher import (BackpressureError, DispatcherDied,  # noqa: F401
                      DynamicBatcher, RequestDeadlineExceeded)
from .engine import InferenceEngine, parse_buckets  # noqa: F401
from .metrics import MetricsRegistry  # noqa: F401
from .reload import CheckpointWatcher  # noqa: F401
from .server import ModelServer  # noqa: F401

__all__ = [
    "InferenceEngine", "DynamicBatcher", "BackpressureError",
    "DispatcherDied", "RequestDeadlineExceeded",
    "CheckpointWatcher", "ModelServer", "MetricsRegistry",
    "parse_buckets", "ContinuousLLMEngine", "main",
]


def __getattr__(name):
    # Lazy: serve.llm pulls in jax at engine-build time; the fleet layer
    # (router/autoscale) must stay importable without touching it.
    if name == "ContinuousLLMEngine":
        from .llm import ContinuousLLMEngine

        return ContinuousLLMEngine
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def main(argv=None) -> int:
    """CLI entry (``python -m horovod_tpu.serve`` / ``hvdtrun serve``)."""
    from .__main__ import main as _main

    return _main(argv)
