"""Replica autoscaler: the serving side of the pod-aware elastic driver.

ROADMAP item 1(d): training and serving share ONE control plane.  This
module is the driver half — it reuses the elastic machinery piece by
piece rather than forking it:

* **Discovery + blacklist-with-cooldown** —
  :class:`runner.elastic.discovery.HostManager`: the same
  ``host[:slots][@pod]`` discovery source, the same doubling cooldown
  for a host whose replica crashed (a flaky serve host converges toward
  exclusion; a transiently bad one rejoins).
* **Pod drains** — :class:`runner.elastic.pods.PodTracker`: a replica
  taking the preemption exit drains its whole pod from placement, so
  the autoscaler never scales *onto* a slice the platform is reclaiming.
* **Exit taxonomy** — :data:`resilience.preempt.PREEMPT_EXIT_CODE`
  (83) = clean removal (drained replica, preempted host: no blacklist,
  no removal event); anything else failing = a **replica-removal
  event** (host blacklisted with cooldown, replacement spawned),
  correlated per pod inside the PodTracker window so one dying host
  costs one event, not one per replica.

The scaling decision itself (:class:`AutoscalePolicy`) reads the same
KV heartbeats the router routes on (``/serve/replicas/<id>``: queue
depth + p99): queue rows per replica above ``HVDT_SERVE_QUEUE_HI``
or fleet p99 over the SLO scales up; an idle queue with healthy p99
scales down — one step per ``HVDT_SERVE_SCALE_COOLDOWN_S``, clamped to
``[min, HVDT_SERVE_MAX_REPLICAS]``.  Scale-down is **graceful by
construction**: the driver writes ``/serve/drain/<id>``, the replica
stops admitting, finishes its in-flight batches, deregisters, and exits
83 — the router re-routes from the first 503, so a resize drops zero
requests.

Operators (and the autotuner, ROADMAP item 5) can force a target by
writing ``/serve/target_replicas`` on the rendezvous KV; the policy
resumes from there when the key is cleared.

The key has two on-wire forms and a fixed precedence
(``fleet.scheduler.read_target`` decodes both): a **raw int** is the
operator's out-of-band override and beats everything, including the
``--target-file`` channel; a **seq-guarded JSON doc**
(``{"target": n, "seq": k, "writer": ...}``, written by the fleet
scheduler — the PR-18 controller's ``scale_replicas`` hint routes
through it) ranks between the file override and the autoscale policy.
Every adoption stamps ``last_target_writer`` / ``last_target_seq`` —
the audit trail for "who scaled the fleet last".
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ..common import config
from ..common.logging_util import get_logger
from ..runner import hosts as hosts_mod
from ..runner.elastic import pods as pods_mod
from ..runner.elastic.discovery import HostManager
from .replica import DRAIN_KV_PREFIX, REPLICA_KV_PREFIX

__all__ = ["AutoscalePolicy", "ServeDriver", "run_serve_elastic",
           "TARGET_KV_KEY"]

log = get_logger(__name__)

TARGET_KV_KEY = "/serve/target_replicas"


class AutoscalePolicy:
    """Pure scale decision over replica heartbeat snapshots.

    Deterministic and clock-injectable so tests drive it directly; the
    driver owns when it runs and what it does with the answer.
    """

    def __init__(self, *,
                 min_replicas: int = 1,
                 max_replicas: Optional[int] = None,
                 slo_p99_ms: Optional[float] = None,
                 queue_hi: Optional[float] = None,
                 queue_lo: Optional[float] = None,
                 cooldown_s: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.min_replicas = max(1, int(min_replicas))
        self.max_replicas = int(
            max_replicas if max_replicas is not None
            else config.get_int("HVDT_SERVE_MAX_REPLICAS"))
        self.slo_p99_ms = float(
            slo_p99_ms if slo_p99_ms is not None
            else config.get_float("HVDT_SERVE_SLO_P99_MS"))
        self.queue_hi = float(
            queue_hi if queue_hi is not None
            else config.get_float("HVDT_SERVE_QUEUE_HI"))
        self.queue_lo = float(
            queue_lo if queue_lo is not None
            else config.get_float("HVDT_SERVE_QUEUE_LO"))
        self.cooldown_s = float(
            cooldown_s if cooldown_s is not None
            else config.get_float("HVDT_SERVE_SCALE_COOLDOWN_S"))
        self._clock = clock
        self._last_change: Optional[float] = None
        self.last_reason = ""

    def decide(self, current: int,
               snapshots: Dict[int, Dict[str, Any]]) -> int:
        """Desired replica count given the live heartbeat snapshots.
        ``current`` is the driver's present target.  Returns a value in
        [min_replicas, max_replicas]; == ``current`` means hold."""
        now = self._clock()
        lo = max(self.min_replicas, 1)
        hi = max(self.max_replicas, lo)
        clamped = min(hi, max(lo, current))
        if clamped != current:
            self.last_reason = f"clamped to [{lo}, {hi}]"
            return clamped
        if self._last_change is not None and \
                now - self._last_change < self.cooldown_s:
            return current
        live = [s for s in snapshots.values() if not s.get("draining")]
        if not live:
            return current
        queue_per = sum(float(s.get("queue_depth") or 0.0)
                        for s in live) / max(1, len(live))
        p99s = [float(s["p99_ms"]) for s in live
                if s.get("p99_ms") is not None]
        worst_p99 = max(p99s) if p99s else None
        if current < hi and (
                queue_per > self.queue_hi
                or (self.slo_p99_ms > 0 and worst_p99 is not None
                    and worst_p99 > self.slo_p99_ms)):
            self._last_change = now
            self.last_reason = (
                f"queue {queue_per:.1f} rows/replica"
                if queue_per > self.queue_hi
                else f"p99 {worst_p99:.0f}ms > SLO {self.slo_p99_ms:.0f}ms")
            return current + 1
        if current > lo and queue_per < self.queue_lo and (
                self.slo_p99_ms <= 0 or worst_p99 is None
                or worst_p99 < 0.5 * self.slo_p99_ms):
            self._last_change = now
            self.last_reason = (f"idle: queue {queue_per:.1f} "
                                f"rows/replica")
            return current - 1
        return current


class _Replica:
    __slots__ = ("id", "slot", "thread", "started_at", "draining")

    def __init__(self, replica_id: int, slot: hosts_mod.SlotInfo,
                 thread: threading.Thread):
        self.id = replica_id
        self.slot = slot
        self.thread = thread
        self.started_at = time.monotonic()
        self.draining = False


def localhost_host_manager(slots: int) -> HostManager:
    """The default serve "fleet": one localhost entry with
    ``max_replicas`` slots — the single-box deploy.  Real fleets pass a
    discovery script exactly like elastic training."""
    return HostManager(
        lambda: [hosts_mod.HostInfo("localhost", max(1, int(slots)))])


class ServeDriver:
    """Drives replica worker lifecycles against a target count.

    ``spawn_fn(slot, replica_id)`` starts one replica worker and blocks
    until it exits, returning the exit code — injectable, so unit tests
    fake whole serve fleets in-process (the ElasticDriver test strategy).
    """

    def __init__(self, kv_server: Any,
                 spawn_fn: Callable[[hosts_mod.SlotInfo, int], int],
                 *,
                 host_manager: Optional[HostManager] = None,
                 replicas: Optional[int] = None,
                 max_replicas: Optional[int] = None,
                 autoscale: Optional[bool] = None,
                 policy: Optional[AutoscalePolicy] = None,
                 pod_tracker: Optional[pods_mod.PodTracker] = None,
                 target_file: Optional[str] = None,
                 interval: float = 0.25):
        self._kv = kv_server
        self._spawn_fn = spawn_fn
        self.max_replicas = int(
            max_replicas if max_replicas is not None
            else config.get_int("HVDT_SERVE_MAX_REPLICAS"))
        self._hm = host_manager or localhost_host_manager(self.max_replicas)
        self._autoscale = bool(
            autoscale if autoscale is not None
            else config.get_bool("HVDT_SERVE_AUTOSCALE"))
        self.policy = policy or AutoscalePolicy(
            max_replicas=self.max_replicas)
        self._pods = pod_tracker or pods_mod.PodTracker()
        self._target_file = target_file
        self._interval = interval
        self._lock = threading.Lock()
        self._live: Dict[int, _Replica] = {}
        self._target = max(1, int(
            replicas if replicas is not None
            else config.get_int("HVDT_SERVE_REPLICAS")))
        self._target = min(self._target, self.max_replicas)
        self._next_id = 0
        self._shutdown = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._no_slot_warned = False
        self.removal_events = 0     # audit: replica-removal events
        self.scale_events: List[str] = []
        # Last adopted /serve/target_replicas writer (audit trail for
        # the one-key-many-writers reconciliation): "operator" for the
        # raw-int / --target-file channels, the doc's writer field
        # ("fleet", "controller", ...) otherwise.
        self.last_target_writer: Optional[str] = None
        self.last_target_seq: Optional[int] = None

    # -- introspection -----------------------------------------------------

    @property
    def target(self) -> int:
        with self._lock:
            return self._target

    def live_replicas(self) -> List[int]:
        with self._lock:
            return sorted(r.id for r in self._live.values()
                          if not r.draining)

    def replica_snapshots(self) -> Dict[int, Dict[str, Any]]:
        """The serve fleet's heartbeats out of the rendezvous KV — the
        serving analog of ``ElasticDriver.telemetry_snapshots``."""
        out: Dict[int, Dict[str, Any]] = {}
        with self._kv.lock:
            items = {k: v for k, v in self._kv.store.items()
                     if k.startswith(REPLICA_KV_PREFIX)}
        for key, raw in items.items():
            try:
                out[int(key[len(REPLICA_KV_PREFIX):])] = \
                    json.loads(raw.decode())
            except (ValueError, UnicodeDecodeError):
                continue
        return out

    # -- scaling -----------------------------------------------------------

    def set_target(self, n: int, reason: str = "operator") -> int:
        """Clamp + adopt a new replica target; logs the scale event (the
        control-plane audit line scenario tests assert on)."""
        n = min(self.max_replicas, max(1, int(n)))
        with self._lock:
            old = self._target
            if n == old:
                return old
            self._target = n
        msg = f"serve: scaling {old} -> {n} ({reason})"
        self.scale_events.append(msg)
        print(msg, file=sys.stderr)
        return n

    def _kv_target_doc(self) -> Optional[Dict[str, Any]]:
        """The decoded ``/serve/target_replicas`` doc (raw operator int
        or seq-guarded fleet doc), or None when unset/garbage."""
        from ..fleet.scheduler import read_target

        return read_target(self._kv.get_local(TARGET_KV_KEY))

    def _kv_target_override(self) -> Optional[int]:
        """The raw-int operator form only — the highest-precedence
        channel (a fleet doc on the key is NOT an operator override)."""
        doc = self._kv_target_doc()
        if doc is not None and doc.get("seq") is None:
            return doc["target"]
        return None

    def _file_target_override(self) -> Optional[int]:
        """Operator override from ``--target-file`` (a plain int in a
        file): the out-of-band control channel for operators and
        harnesses outside the launcher's secret domain — ``echo 3 >
        target`` resizes the fleet."""
        if not self._target_file:
            return None
        try:
            with open(self._target_file) as f:
                return int(f.read().strip())
        except (OSError, ValueError):
            return None

    def _free_slot(self) -> Optional[hosts_mod.SlotInfo]:
        """A placement for one more replica: the first discovered,
        non-blacklisted host (skipping drained pods) with spare slots."""
        drained = self._pods.drained_pods()
        with self._lock:
            used: Dict[str, int] = {}
            for r in self._live.values():
                used[r.slot.hostname] = used.get(r.slot.hostname, 0) + 1
        for h in self._hm.current.hosts:
            if self._hm.is_blacklisted(h.hostname):
                continue
            if self._hm.pod_of(h.hostname) in drained or \
                    (h.pod and h.pod in drained):
                continue
            n_used = used.get(h.hostname, 0)
            if n_used < h.slots:
                return hosts_mod.SlotInfo(
                    hostname=h.hostname, rank=0, local_rank=n_used,
                    cross_rank=0, size=self.target, local_size=h.slots,
                    cross_size=1, pod=h.pod or "")
        return None

    def _start_replica(self, slot: hosts_mod.SlotInfo) -> None:
        with self._lock:
            rid = self._next_id
            self._next_id += 1

        def _run():
            try:
                code = self._spawn_fn(slot, rid)
            except Exception as e:
                print(f"serve: replica {rid} spawn error: {e}",
                      file=sys.stderr)
                code = 1
            self.record_exit(rid, code)

        t = threading.Thread(target=_run, daemon=True,
                             name=f"hvdt-serve-replica-{rid}")
        with self._lock:
            self._live[rid] = _Replica(rid, slot, t)
        print(f"serve: replica {rid} starting on {slot.hostname}"
              f"[{slot.local_rank}]", file=sys.stderr)
        t.start()

    def _drain_replica(self, rid: int) -> None:
        with self._lock:
            rep = self._live.get(rid)
            if rep is None or rep.draining:
                return
            rep.draining = True
        print(f"serve: draining replica {rid} (scale-down)",
              file=sys.stderr)
        self._kv.put_local(f"{DRAIN_KV_PREFIX}{rid}", b"drain")

    def record_exit(self, rid: int, code: int) -> None:
        from ..resilience.preempt import PREEMPT_EXIT_CODE

        with self._lock:
            rep = self._live.pop(rid, None)
        if rep is None:
            return
        # Scrub the heartbeat (a crashed replica's stale record must not
        # linger a full liveness window) but leave a drain TOMBSTONE on
        # the id: a worker process that somehow outlived its wrapper
        # (orphaned `sh -c` child, split-brain respawn) keeps beating
        # and would re-enter routing as untracked capacity — the
        # tombstone makes it drain itself at its next beat.  Replica ids
        # are never reused, so tombstones cannot block a replacement.
        with self._kv.lock:
            self._kv.store.pop(f"{REPLICA_KV_PREFIX}{rid}", None)
            self._kv.store[f"{DRAIN_KV_PREFIX}{rid}"] = b"fence"
        if code == PREEMPT_EXIT_CODE:
            # Clean removal: a drained scale-down or a preempted host.
            # Preemption reclaims whole slices — drain the pod from
            # placement like the training driver does.
            if not rep.draining:
                pod = rep.slot.pod or self._hm.pod_of(rep.slot.hostname)
                if self._pods.drain(pod):
                    print(f"serve: pod {pod} draining (replica {rid} "
                          f"preempted on {rep.slot.hostname}, clean "
                          f"removal)", file=sys.stderr)
            print(f"serve: replica {rid} exited clean "
                  f"({'drained' if rep.draining else 'preempted'})",
                  file=sys.stderr)
            return
        if code == 0:
            print(f"serve: replica {rid} exited 0", file=sys.stderr)
            return
        # Failure: one replica-removal event, pod-correlated (the
        # PodTracker window folds a dying host's replicas into one),
        # host blacklisted with cooldown, replacement spawned by the
        # next reconcile pass.
        pod = rep.slot.pod or self._hm.pod_of(rep.slot.hostname)
        if self._pods.record_failure(pod):
            self.removal_events += 1
            print(f"serve: replica-removal event for replica {rid} "
                  f"(exit {code} on {rep.slot.hostname}); correlated "
                  f"exits within the window fold into this event",
                  file=sys.stderr)
            self._hm.blacklist(rep.slot.hostname)
            self._hm.update_available_hosts()
        else:
            print(f"serve: replica {rid} exit {code} folded into the "
                  f"open removal event for pod {pod}", file=sys.stderr)

    def reconcile(self) -> None:
        """One control pass: adopt overrides/policy, then converge the
        live set toward the target (spawn up, drain down)."""
        doc = self._kv_target_doc()
        override = doc["target"] if doc is not None \
            and doc.get("seq") is None else None
        if override is None:
            override = self._file_target_override()
            doc = None if override is not None else doc
        if override is not None:
            self.set_target(override, reason="operator override")
            self.last_target_writer = "operator"
            self.last_target_seq = None
        elif doc is not None:
            # The fleet scheduler's seq-guarded doc (or a controller
            # hint it routed): below the operator channels, above the
            # local autoscale policy.
            self.set_target(doc["target"],
                            reason=f"fleet: {doc.get('writer', '?')} "
                                   f"seq={doc.get('seq')}")
            self.last_target_writer = str(doc.get("writer", "?"))
            self.last_target_seq = doc.get("seq")
        elif self._autoscale:
            snaps = self.replica_snapshots()
            desired = self.policy.decide(self.target, snaps)
            if desired != self.target:
                self.set_target(desired,
                                reason=f"autoscale: "
                                       f"{self.policy.last_reason}")
                self.last_target_writer = "autoscale"
                self.last_target_seq = None
        with self._lock:
            live = [r for r in self._live.values() if not r.draining]
            target = self._target
        if len(live) < target:
            for _ in range(target - len(live)):
                slot = self._free_slot()
                if slot is None:
                    # Once per starvation episode, not once per 0.25s
                    # reconcile tick: the condition clears on its own
                    # (cooldown/drain-grace expiry), the log should not
                    # scroll the real events away while it does.
                    if not self._no_slot_warned:
                        self._no_slot_warned = True
                        log.warning("serve: want %d replicas, no "
                                    "placeable slot (blacklist/drained "
                                    "pods?)", target)
                    break
                self._no_slot_warned = False
                self._start_replica(slot)
        elif len(live) > target:
            # Drain the newest first: the oldest replicas have the
            # warmest compile caches and the longest uptime evidence.
            doomed = sorted(live, key=lambda r: r.started_at,
                            reverse=True)[:len(live) - target]
            for rep in doomed:
                self._drain_replica(rep.id)

    def _loop(self) -> None:
        while not self._shutdown.wait(self._interval):
            try:
                self._hm.update_available_hosts()
                self.reconcile()
            except Exception:   # pragma: no cover - defensive
                log.exception("serve driver control loop error")

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        self._hm.update_available_hosts()
        self.reconcile()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="hvdt-serve-driver")
        self._thread.start()

    def stop(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Shut the fleet down; with ``drain`` every replica finishes
        its in-flight work (exit 83) before the driver returns."""
        self._shutdown.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if drain:
            with self._lock:
                rids = list(self._live)
            for rid in rids:
                self._drain_replica(rid)
            from ..resilience.retry import Backoff

            drain_wait = Backoff(first=0.05, cap=0.5, deadline_s=timeout)
            while True:
                with self._lock:
                    if not self._live:
                        return
                if not drain_wait.sleep():
                    break
            with self._lock:
                leftover = sorted(self._live)
            if leftover:
                log.warning("serve driver stop: replicas %s did not "
                            "drain within %.0fs", leftover, timeout)


def run_serve_elastic(args, replica_argv: List[str]) -> int:
    """``hvdtrun serve --replicas N [--autoscale]`` — the elastic
    serving control plane: rendezvous KV + replica fleet + router, one
    process group.

    ``replica_argv`` is the serve CLI argv each replica worker re-parses
    (minus the control-plane flags, plus ``--replica-worker``)."""
    import shlex
    import signal as _signal
    import socket

    from ..runner.http_kv import RendezvousServer, new_secret
    from ..runner.safe_shell_exec import safe_execute
    from .router import Router

    server = RendezvousServer(secret=new_secret())
    port = server.start()
    addr = "127.0.0.1"
    try:
        addr = socket.gethostbyname(socket.gethostname())
    except OSError:
        pass

    max_replicas = int(args.max_replicas
                       if args.max_replicas is not None
                       else config.get_int("HVDT_SERVE_MAX_REPLICAS"))
    if args.host_discovery_script:
        hm = HostManager.from_script(args.host_discovery_script)
    else:
        hm = localhost_host_manager(max_replicas)

    worker_cmd = [sys.executable, "-m", "horovod_tpu.serve",
                  *replica_argv, "--replica-worker"]

    def spawn_fn(slot: hosts_mod.SlotInfo, rid: int) -> int:
        env = dict(os.environ)
        env.update(slot.to_env())
        env.update({
            "HVDT_RENDEZVOUS_ADDR": addr,
            "HVDT_RENDEZVOUS_PORT": str(port),
            "HVDT_SECRET": server.secret.hex(),
            "HVDT_SERVE_REPLICA_ID": str(rid),
            "HVDT_RANK": str(rid),
        })
        cmd = " ".join(shlex.quote(c) for c in worker_cmd)
        return safe_execute(cmd, env=env, prefix=f"[replica {rid}]")

    slo = (args.slo_p99_ms if args.slo_p99_ms is not None
           else config.get_float("HVDT_SERVE_SLO_P99_MS"))
    driver = ServeDriver(
        server, spawn_fn, host_manager=hm,
        replicas=args.replicas, max_replicas=max_replicas,
        autoscale=args.autoscale or None,
        target_file=getattr(args, "target_file", None),
        policy=AutoscalePolicy(max_replicas=max_replicas,
                               slo_p99_ms=slo))
    router = Router(server, port=args.router_port, slo_p99_ms=slo)

    stop = threading.Event()
    for sig in (_signal.SIGTERM, _signal.SIGINT):
        try:
            _signal.signal(sig, lambda signum, frame: stop.set())
        except ValueError:
            pass

    try:
        driver.start()
        rport = router.start()
        print(f"serve: router on http://{router.host}:{rport} "
              f"(replicas={driver.target}, max={max_replicas}, "
              f"autoscale={'on' if driver._autoscale else 'off'}, "
              f"slo_p99_ms={slo or 'off'})", file=sys.stderr, flush=True)
        while not stop.wait(0.5):
            pass
        return 0
    finally:
        print("serve: control plane shutting down (draining replicas)",
              file=sys.stderr, flush=True)
        router.stop()
        driver.stop(drain=True)
        server.stop()
