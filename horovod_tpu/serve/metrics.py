"""Serving metrics — thin re-export of :mod:`horovod_tpu.telemetry.metrics`.

The Counter/Gauge/Summary/MetricsRegistry primitives started life here
(the serving plane needed RED-triple observability first) and were
promoted to the shared telemetry subsystem once training grew the same
need.  This module keeps the historical import path working — serving
code and tests continue to ``from horovod_tpu.serve.metrics import
MetricsRegistry`` and get the exact same classes.

Note the registry-scoping difference between the planes: serving builds
a registry **per engine** (an inference replica scrapes its own engine),
while training instrumentation shares the process-wide
``telemetry.metrics.default_registry()`` behind the per-worker
``/metrics`` exporter.

Percentile reads: the continuous LLM engine's ``hvdt_engine_*``
summaries (decode/prefill step time, per-tenant submit-to-first-token
``hvdt_engine_wait_ms_<tenant>``) are scraped by roll-ups that may run
before any observation exists — use ``Summary.percentile(q)`` there
(total: empty window reads 0.0).  ``Summary.quantile(q)`` keeps its
``None``-when-empty contract for callers that must distinguish "no data
yet" (the router's SLO ejection does).
"""

from __future__ import annotations

from ..telemetry.metrics import (  # noqa: F401
    Counter,
    Gauge,
    MetricsRegistry,
    Summary,
)

__all__ = ["Counter", "Gauge", "Summary", "MetricsRegistry"]
