"""Shape-bucketed inference engine: jit-per-bucket, pad-to-bucket, hot swap.

The TPU-concurrency study (arXiv:2011.03641) is blunt about what kills
served-model latency on XLA backends: it is not the chip, it is the host —
every novel input shape triggers a full XLA recompile (seconds) and
host-side dispatch of a program the compile cache has never seen.  A
request path whose batch size floats freely (real traffic) therefore
recompiles forever.  The engine's contract engineers that away:

* **Shape buckets** — the apply fn is jitted once per bucket size from a
  small fixed ladder (default 1/8/32, knob ``HVDT_SERVE_BUCKETS``); every
  batch is padded up to the smallest admitting bucket.  Steady-state
  traffic touches only warm buckets ⇒ zero steady-state compiles, and the
  ``serve_compiles_total`` counter is the regression alarm.
* **Persistent compile cache** — bucket compiles also go through
  ``step_pipeline.enable_compilation_cache``, so a server *restart* reuses
  the previous process's XLA programs (the PR-1 substrate).
* **Hot weight swap** — :meth:`swap_params` replaces the param pytree
  between batches under the engine lock.  In-flight batches keep the
  reference they captured; nothing is dropped mid-request.  jitted
  programs are keyed by shape/dtype only, so a swap never recompiles.
* **Mesh sharding** — given a mesh (``parallel/sharding.py``), params are
  replicated across it and batches whose bucket divides the mesh are
  split over the data axes, so one engine drives a multi-chip slice.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Optional, Sequence, Tuple

import numpy as np

from ..common import config
from ..common.logging_util import get_logger
from .metrics import MetricsRegistry

__all__ = ["InferenceEngine", "parse_buckets"]

log = get_logger(__name__)


def parse_buckets(spec: Optional[str] = None) -> Tuple[int, ...]:
    """Bucket ladder from a comma list (default: the HVDT_SERVE_BUCKETS
    knob).  Sorted ascending, deduplicated, all >= 1."""
    if spec is None:
        spec = config.get_str("HVDT_SERVE_BUCKETS")
    sizes = sorted({int(s) for s in str(spec).split(",") if s.strip()})
    if not sizes or sizes[0] < 1:
        raise ValueError(f"invalid bucket spec {spec!r}: need sizes >= 1")
    return tuple(sizes)


class InferenceEngine:
    """Serve ``apply_fn(params, x) -> y`` with bucketed batch shapes.

    ``apply_fn`` must be shape-polymorphic over the leading (batch) dim of
    ``x`` — exactly the contract of ``models.mlp.mlp_apply`` and
    ``models.transformer.transformer_apply`` — and pure (jit-able).

    The engine is thread-safe: any number of threads may call
    :meth:`infer` while another calls :meth:`swap_params`.  Compiled
    programs are cached by ``(bucket, feature shape, dtype)``; only cache
    misses compile (counted in ``serve_compiles_total``).
    """

    def __init__(self, apply_fn: Callable[[Any, Any], Any], params: Any, *,
                 buckets: Optional[Sequence[int]] = None,
                 mesh: Optional[Any] = None,
                 metrics: Optional[MetricsRegistry] = None,
                 compile_cache: Optional[str] = None):
        from ..step_pipeline import enable_compilation_cache

        enable_compilation_cache(compile_cache)
        self._apply_fn = apply_fn
        self.buckets = parse_buckets(",".join(map(str, buckets))
                                     if buckets is not None else None)
        self.mesh = mesh
        self.metrics = metrics if metrics is not None else MetricsRegistry()
        self._compiles = self.metrics.counter(
            "serve_compiles_total",
            "XLA compilations triggered by inference (flat after warmup "
            "means the shape buckets are doing their job)")
        self._infers = self.metrics.counter(
            "serve_engine_batches_total", "Batches executed by the engine")
        self._pad_rows = self.metrics.counter(
            "serve_pad_rows_total",
            "Padding rows added to reach bucket sizes (wasted compute)")
        self._lock = threading.Lock()
        self._jitted = {}            # (bucket, feat_shape, dtype) -> fn
        self._params = self._place_params(params)
        self._version = 0

    # ---- params ---------------------------------------------------------
    def _place_params(self, params: Any) -> Any:
        """Device placement: replicate over the mesh when one is given
        (weights live on every chip; the batch dim carries parallelism),
        plain device_put otherwise."""
        import jax

        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            sharding = NamedSharding(self.mesh, PartitionSpec())
            return jax.tree.map(
                lambda l: jax.device_put(l, sharding), params)
        return jax.device_put(params)

    def swap_params(self, params: Any) -> int:
        """Atomically replace the served weights; returns the new version.

        In-flight :meth:`infer` calls finish on the params reference they
        captured — the swap only changes what *subsequent* batches see, so
        a reload never fails a request.
        """
        placed = self._place_params(params)
        with self._lock:
            self._params = placed
            self._version += 1
            return self._version

    @property
    def params_version(self) -> int:
        with self._lock:
            return self._version

    # ---- inference ------------------------------------------------------
    def bucket_for(self, n: int) -> int:
        """Smallest bucket admitting ``n`` rows (the largest bucket when
        ``n`` exceeds the ladder — callers then chunk)."""
        for b in self.buckets:
            if n <= b:
                return b
        return self.buckets[-1]

    def _jitted_for(self, bucket: int, feat_shape: Tuple[int, ...],
                    dtype) -> Callable:
        import jax

        key = (bucket, feat_shape, str(dtype))
        with self._lock:
            fn = self._jitted.get(key)
        if fn is not None:
            return fn
        jfn = jax.jit(self._apply_fn)
        with self._lock:
            # Double-checked: a racing thread may have built it first.
            fn = self._jitted.get(key)
            if fn is None:
                fn = jfn
                self._jitted[key] = fn
                self._compiles.inc()
                log.info("serve: compiling bucket=%d feat=%s dtype=%s",
                         bucket, feat_shape, dtype)
        return fn

    def _batch_sharding(self, bucket: int):
        """NamedSharding for the padded batch under the mesh: the batch
        dim splits over the data-parallel axes (dp/fsdp — the
        ``parallel/sharding.py`` rule table, same as training inputs)
        when the bucket divides them, else replicated (correct, just not
        parallel).  Param-sharding axes (tp/…) never split the batch."""
        if self.mesh is None:
            return None
        from jax.sharding import NamedSharding, PartitionSpec

        from ..parallel.sharding import batch_spec, transformer_rules

        spec = batch_spec(self.mesh, rules=transformer_rules(fsdp=True))
        axes = spec[0] if len(spec) else None
        if axes:
            if isinstance(axes, str):
                axes = (axes,)
            total = int(np.prod([self.mesh.shape[a] for a in axes]))
            if total > 1 and bucket % total == 0:
                return NamedSharding(self.mesh, PartitionSpec(axes))
        return NamedSharding(self.mesh, PartitionSpec())

    def _run_bucket(self, x: np.ndarray) -> np.ndarray:
        """One padded-bucket execution; returns host outputs for the
        un-padded prefix."""
        import jax

        n = x.shape[0]
        bucket = self.bucket_for(n)
        if n < bucket:
            pad = np.zeros((bucket - n,) + x.shape[1:], x.dtype)
            xb = np.concatenate([x, pad], axis=0)
            self._pad_rows.inc(bucket - n)
        else:
            xb = x
        with self._lock:
            params = self._params
        sharding = self._batch_sharding(bucket)
        if sharding is not None:
            xb = jax.device_put(xb, sharding)
        fn = self._jitted_for(bucket, x.shape[1:], x.dtype)
        y = fn(params, xb)
        self._infers.inc()
        return np.asarray(jax.device_get(y))[:n]

    def infer(self, x) -> np.ndarray:
        """Run a batch of ``n`` rows; rows past the largest bucket are
        chunked through it.  Returns host numpy of shape [n, ...]."""
        x = np.asarray(x)
        if x.ndim < 1 or x.shape[0] == 0:
            raise ValueError(f"infer needs a non-empty batch, got shape "
                             f"{x.shape}")
        top = self.buckets[-1]
        if x.shape[0] <= top:
            return self._run_bucket(x)
        outs = [self._run_bucket(x[i:i + top])
                for i in range(0, x.shape[0], top)]
        return np.concatenate(outs, axis=0)

    def warmup(self, feat_shape: Tuple[int, ...],
               dtype=np.float32) -> None:
        """Pre-compile every bucket for one feature shape so the first
        real request never pays a compile."""
        for b in self.buckets:
            self._run_bucket(np.zeros((b,) + tuple(feat_shape), dtype))

    def compile_count(self) -> int:
        return int(self._compiles.value())
