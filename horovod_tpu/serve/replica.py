"""Replica-side wiring into the elastic serving control plane.

A serve replica is one :class:`~horovod_tpu.serve.server.ModelServer`
process registered with the rendezvous KV the elastic driver already
runs.  The contract mirrors the training worker's (heartbeats in,
membership decisions out) so training and serving share ONE control
plane:

* **Heartbeat** — :class:`ReplicaRegistrar` publishes
  ``/serve/replicas/<id>`` every ``HVDT_SERVE_HEARTBEAT_S / 3`` seconds:
  endpoint (host, port) plus the load/latency roll-up the router routes
  on and the autoscaler scales on
  (:func:`telemetry.exporter.serve_snapshot_dict` — queue depth, predict
  p50/p99, draining).  A heartbeat older than ``2 x HVDT_SERVE_HEARTBEAT_S``
  means the replica is dead: the router stops routing to it and the
  driver's exit handling takes over.
* **Drain** — the driver requests a scale-down by writing
  ``/serve/drain/<id>``; the registrar notices at its next beat, the
  worker drains (admission 503s, in-flight batches finish), deregisters,
  and exits :data:`~horovod_tpu.resilience.preempt.PREEMPT_EXIT_CODE`
  (83) — the same "clean removal, don't blacklist me" convention the
  preemption guard established, so the serving driver reuses the
  training driver's exit taxonomy unchanged.
* **Preemption** — SIGTERM installs the drain flag
  (``ModelServer.install_drain_handlers``); the replica loop performs
  the same drain → deregister → exit-83 sequence, so a preempted serve
  host leaves without dropping a request.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable, Dict, Optional

from ..common import config
from ..common.logging_util import get_logger

__all__ = ["REPLICA_KV_PREFIX", "DRAIN_KV_PREFIX", "ReplicaRegistrar",
           "run_replica"]

log = get_logger(__name__)

REPLICA_KV_PREFIX = "/serve/replicas/"
DRAIN_KV_PREFIX = "/serve/drain/"


class ReplicaRegistrar:
    """Publishes one replica's heartbeat to the rendezvous KV and polls
    its drain key.

    ``kv`` is any client with ``put/get/delete`` (``runner.http_kv
    .KVClient`` in workers; a ``RendezvousServer`` adapter in tests).
    Heartbeats are best-effort — a flaky control network must degrade to
    "router may briefly route stale", never to a replica crash — but
    consecutive failures are counted and logged once past a streak.
    """

    _FAIL_WARN_STREAK = 5

    def __init__(self, kv: Any, replica_id: int, host: str, port: int,
                 server: Any = None,
                 heartbeat_s: Optional[float] = None,
                 on_drain: Optional[Callable[[], None]] = None):
        self._kv = kv
        self.replica_id = int(replica_id)
        self.host = host
        self.port = int(port)
        self._server = server
        self.heartbeat_s = float(
            heartbeat_s if heartbeat_s is not None
            else config.get_float("HVDT_SERVE_HEARTBEAT_S"))
        self._on_drain = on_drain
        self._stop = threading.Event()
        self._drain_seen = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._fail_streak = 0
        self.beats = 0   # audit: successful heartbeats

    # -- heartbeat payload -------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        doc: Dict[str, Any] = {
            "id": self.replica_id,
            "host": self.host,
            "port": self.port,
            "pid": os.getpid(),
            "ts": time.time(),
        }
        pod = os.environ.get("HVDT_POD")
        if pod:
            doc["pod"] = pod
        if self._server is not None:
            from ..telemetry.exporter import serve_snapshot_dict

            doc.update(serve_snapshot_dict(self._server.metrics))
            doc["draining"] = bool(getattr(self._server, "draining",
                                           False) or doc.get("draining"))
            doc["model_version"] = self._server.engine.params_version
            doc["engine"] = ("continuous"
                             if getattr(self._server, "continuous", False)
                             else "static")
        return doc

    # -- lifecycle ---------------------------------------------------------

    def publish(self) -> bool:
        """One heartbeat put (best-effort).  Returns True on success."""
        try:
            self._kv.put(f"{REPLICA_KV_PREFIX}{self.replica_id}",
                         json.dumps(self.snapshot()).encode())
        except Exception as e:
            self._fail_streak += 1
            if self._fail_streak == self._FAIL_WARN_STREAK:
                log.warning("replica %d: %d consecutive heartbeat "
                            "failures (%s) — router will treat this "
                            "replica as dead past the liveness window",
                            self.replica_id, self._fail_streak, e)
            return False
        self._fail_streak = 0
        self.beats += 1
        return True

    def drain_requested(self) -> bool:
        """True once the driver wrote this replica's drain key (sticky)."""
        if self._drain_seen.is_set():
            return True
        try:
            raw = self._kv.get(f"{DRAIN_KV_PREFIX}{self.replica_id}")
        except Exception:
            return False
        if raw is not None:
            self._drain_seen.set()
            return True
        return False

    def _loop(self) -> None:
        # Beat at a third of the liveness period: two beats may be lost
        # to control-network flakes before the router writes us off.
        period = max(0.05, self.heartbeat_s / 3.0)
        while not self._stop.wait(period):
            self.publish()
            if self.drain_requested() and self._on_drain is not None:
                cb, self._on_drain = self._on_drain, None   # fire once
                cb()

    def start(self) -> "ReplicaRegistrar":
        self.publish()   # registration beat — visible before traffic
        self._thread = threading.Thread(
            target=self._loop, name=f"hvdt-replica-hb-{self.replica_id}",
            daemon=True)
        self._thread.start()
        return self

    def deregister(self) -> None:
        """Stop beating and remove the KV record — the clean-exit half
        of the liveness contract (a crash leaves the record to age out)."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        try:
            self._kv.delete(f"{REPLICA_KV_PREFIX}{self.replica_id}")
        except Exception as e:
            log.debug("replica %d deregister failed: %s",
                      self.replica_id, e)


def run_replica(args) -> int:
    """The ``--replica-worker`` entry: one serve replica under the
    elastic serving driver (spawned by ``serve/autoscale.py``).

    Env contract (set by the driver, mirrors the training worker's):
    ``HVDT_RENDEZVOUS_ADDR/PORT``, ``HVDT_SECRET``, ``HVDT_RANK`` (the
    replica id).  The replica binds an ephemeral port (the heartbeat
    publishes the real endpoint — no port plan needed), serves until
    drained (KV key or SIGTERM), then exits 83 for clean removal.
    """
    from ..resilience.preempt import PREEMPT_EXIT_CODE
    from ..runner.http_kv import KVClient
    from .__main__ import build_server

    replica_id = int(os.environ.get("HVDT_SERVE_REPLICA_ID",
                                    os.environ.get("HVDT_RANK", "0")))
    args.port = 0   # ephemeral: many replicas per host must not collide
    server, feat_shape = build_server(args)
    if server.watcher is not None:
        server.watcher.check_once()
    if not getattr(args, "no_warmup", False):
        import numpy as np

        server.engine.warmup(feat_shape, dtype=np.dtype(server.input_dtype))
    port = server.start()
    try:
        server.install_drain_handlers()
    except ValueError:          # not the main thread (test embedding)
        pass
    kv = KVClient.from_env()
    registrar = ReplicaRegistrar(kv, replica_id, server.host, port,
                                 server=server)
    registrar.start()
    log.info("replica %d serving on http://%s:%d", replica_id,
             server.host, port)
    print(f"serve-replica {replica_id}: ready on {server.host}:{port}",
          flush=True)
    try:
        from ..resilience.retry import Backoff

        # Drain-wait poll: jittered 50ms -> 250ms cap keeps drain
        # latency low while a fleet of replicas decorrelates.
        drain_poll = Backoff(first=0.05, cap=0.25)
        while not (server.draining or registrar.drain_requested()):
            drain_poll.sleep()
    except KeyboardInterrupt:
        pass
    # Drain: admission 503s from here (server.draining), in-flight
    # batches complete, a last draining=true beat tells the router
    # explicitly, and only then does the endpoint leave the KV.
    log.info("replica %d draining", replica_id)
    server._draining.set()
    registrar.publish()
    server.drain()
    registrar.deregister()
    server.uninstall_drain_handlers()
    server.stop()
    print(f"serve-replica {replica_id}: drained, exiting {PREEMPT_EXIT_CODE}",
          flush=True)
    return PREEMPT_EXIT_CODE
