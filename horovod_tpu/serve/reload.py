"""Hot checkpoint reload: watch a ``CheckpointManager`` directory, swap
weights into a live engine between batches.

A production endpoint cannot restart to pick up a new model — a restart
drops every in-flight request and repays every XLA compile.  The watcher
closes the training→serving loop instead: training keeps writing
``step_NNN`` checkpoints with :class:`~horovod_tpu.checkpoint.
CheckpointManager`; the serving process polls the same directory
(``CheckpointManager.latest_step()`` discovery), restores any newer step
with ``broadcast=False`` (a serving replica is its own process — no
training collective to ride), and hands the tree to
``InferenceEngine.swap_params``.  The swap is a reference flip under the
engine lock: batches already dispatched finish on the weights they
captured, the next batch sees the new ones, and because jitted programs
key on shapes — not weights — a reload triggers **zero** recompiles.

Failure policy: a half-written or corrupt checkpoint must never kill the
serving loop.  Restore errors are logged, counted
(``serve_reload_failures_total``), and retried; consecutive failures
back the poll off exponentially (capped) instead of hammering a broken
directory at ``poll_interval_s``, and the ``serve_last_good_step`` gauge
exposes the training side's LAST_GOOD pointer so operators can see the
newest checkpoint that *fully* saved next to the failure counter.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Optional, Union

from ..checkpoint import CheckpointManager, restore_checkpoint
from ..common import config
from ..common.logging_util import get_logger
from ..resilience import faults
from ..resilience.retry import Backoff
from .metrics import MetricsRegistry

__all__ = ["CheckpointWatcher"]

log = get_logger(__name__)


class CheckpointWatcher:
    """Poll a checkpoint directory; hot-swap newer steps into the engine.

    ``directory`` may be a path or an existing
    :class:`~horovod_tpu.checkpoint.CheckpointManager`.  ``template``
    supplies the restore tree structure (typically the params the engine
    was constructed with).  ``on_reload(tree, step)`` — by default the
    engine's ``swap_params`` — may be any callable, so the watcher also
    drives non-engine consumers (e.g. an eval worker).
    """

    def __init__(self, directory: Union[str, CheckpointManager],
                 engine: Optional[Any] = None, template: Any = None, *,
                 poll_interval_s: Optional[float] = None,
                 on_reload: Optional[Callable[[Any, int], None]] = None,
                 metrics: Optional[MetricsRegistry] = None):
        if isinstance(directory, CheckpointManager):
            self.manager = directory
        else:
            self.manager = CheckpointManager(directory)
        if on_reload is None:
            if engine is None:
                raise ValueError("need an engine or an on_reload callback")
            on_reload = lambda tree, step: engine.swap_params(tree)  # noqa: E731
        self._on_reload = on_reload
        self._template = template
        self.poll_interval_s = float(
            poll_interval_s if poll_interval_s is not None
            else config.get_float("HVDT_SERVE_RELOAD_INTERVAL_S"))
        self.metrics = metrics if metrics is not None else MetricsRegistry()
        self._reloads = self.metrics.counter(
            "serve_reloads_total", "Successful hot weight reloads")
        self._failures = self.metrics.counter(
            "serve_reload_failures_total",
            "Reload attempts that failed (serving continues on the "
            "previous weights)")
        self._skipped_unverified = self.metrics.counter(
            "serve_skipped_unverified_total",
            "Steps skipped because their integrity manifest failed "
            "verification (fell back to the previous good step without "
            "charging the reload-failure backoff)")
        self._step_gauge = self.metrics.gauge(
            "serve_checkpoint_step", "Step of the currently served weights")
        last_good = self.metrics.gauge(
            "serve_last_good_step",
            "Training-side LAST_GOOD pointer: newest step whose save "
            "fully completed (manifest + pointer); -1 when none")
        last_good.set_function(self._last_good_value)
        self.current_step: Optional[int] = None
        self._fail_streak = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _last_good_value(self) -> float:
        try:
            step = self.manager.last_good_step()
        except OSError:
            step = None
        return float(step) if step is not None else -1.0

    def check_once(self) -> Optional[int]:
        """One poll: reload if a newer *verified* step exists.  Returns
        the step loaded, or None when already current / nothing to load
        / the restore failed (failure is counted and logged, never
        raised — the polling loop and the serving path share this
        method).

        A step whose integrity manifest fails verification is SKIPPED —
        counted in ``serve_skipped_unverified_total`` and logged, but
        not charged against the reload-failure backoff: a corrupt
        newest step means "fall back to the previous good step now",
        not "probe the directory ever more slowly"."""
        try:
            candidates = self.manager.all_steps()
        except OSError as e:
            log.warning("serve reload: cannot list %s: %r",
                        self.manager.directory, e)
            return None
        latest = None
        for cand in reversed(candidates):
            if self.current_step is not None and cand <= self.current_step:
                break
            if self.manager.verify_step(cand):
                latest = cand
                break
            self._skipped_unverified.inc()
            log.warning("serve reload: step %d failed manifest "
                        "verification; falling back to an older step",
                        cand)
        if latest is None:
            return None
        path = self.manager.step_path(latest)
        try:
            inj = faults.get_injector()
            if inj is not None:
                inj.fire("serve.reload", step=latest, path=path)
            tree, step = restore_checkpoint(path, self._template,
                                            broadcast=False)
            self._on_reload(tree, latest)
        except Exception as e:
            self._failures.inc()
            self._fail_streak += 1
            log.warning("serve reload of %s failed (still serving step "
                        "%s): %r", path, self.current_step, e)
            return None
        self.current_step = latest
        self._fail_streak = 0
        self._step_gauge.set(latest)
        self._reloads.inc()
        log.info("serve: hot-reloaded weights from step %d", latest)
        return latest

    def _loop(self) -> None:
        # Healthy polling runs at poll_interval_s; consecutive reload
        # failures back off exponentially (capped at 16x) so a broken
        # checkpoint writer is probed, not hammered.  Any success (or a
        # quiet no-op poll) snaps back to the base interval.
        backoff: Optional[Backoff] = None
        while True:
            if self._fail_streak:
                if backoff is None:
                    backoff = Backoff(first=self.poll_interval_s,
                                      cap=self.poll_interval_s * 16,
                                      jitter=0.25)
                delay = backoff.next_delay()
            else:
                backoff = None
                delay = self.poll_interval_s
            if self._stop.wait(delay):
                return
            self.check_once()

    def start(self, load_initial: bool = False) -> "CheckpointWatcher":
        """Start the polling thread (idempotent).  ``load_initial`` does a
        synchronous first check before the thread spins up, so callers can
        fail fast when the directory holds nothing loadable."""
        if load_initial:
            self.check_once()
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="hvdt-serve-reload", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
