"""Paged KV-cache allocator: fixed blocks, free list, copy-on-write.

The host-side half of the continuous-batching engine's memory plane (the
device-side math is ``models/transformer.py``'s ``*_paged`` functions).
Device KV storage is a pool of ``num_blocks`` fixed-size physical blocks
— ``[layers, num_blocks, block_size, kv_heads, head_dim]`` — and every
sequence owns a *block table*: the ordered list of physical blocks its
token positions map into (position ``p`` lives in table entry
``p // block_size``, offset ``p % block_size``).  Paging is what turns
admission/eviction into pure host bookkeeping: the decode step's shapes
never change, only the integer tables fed to it (the vLLM insight, built
here on the repo's own zero-recompile serving contract).

Three properties the scheduler leans on:

* **Exact accounting** — every block is either on the free list or held
  by ``refcount >= 1`` table entries; :meth:`PagedKVAllocator.check`
  asserts ``free + in_use == capacity`` and the audit counters satisfy
  ``blocks_allocated == blocks_freed + in_use`` over ANY
  admission/eviction/fork history (the property test drives random
  traces against this).
* **Copy-on-write prefix sharing** — :meth:`fork` clones a sequence by
  reference: both tables point at the same physical blocks, refcounts
  bumped.  The first *write* into a shared block (a fork decoding past
  the shared prefix) triggers CoW: a fresh block is allocated, the
  caller is handed a ``(src, dst)`` device-copy instruction, and the
  writer's table is repointed — the sibling never observes the write.
* **Sink block 0** — physical block 0 is RESERVED (never allocated,
  never freed).  Inactive decode slots and padded prefill lanes scatter
  their k/v there, so masked lanes in the fixed-shape device step write
  harmlessly instead of forcing dynamic shapes.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from ...common import config

__all__ = ["PagedKVAllocator", "SINK_BLOCK", "make_kv_cache"]

#: Physical block 0 — the write sink for masked lanes; never allocated.
SINK_BLOCK = 0


class PagedKVAllocator:
    """Free-list block allocator with refcounted copy-on-write sharing.

    All methods are single-threaded by contract: the engine serializes
    scheduler iterations under one lock, and the allocator is only
    touched from there (same ownership story as the batcher's dispatch
    thread).  Failed allocations return ``None`` and mutate NOTHING —
    the caller evicts a victim and retries.
    """

    def __init__(self, num_blocks: Optional[int] = None,
                 block_size: Optional[int] = None):
        self.num_blocks = int(num_blocks if num_blocks is not None
                              else config.get_int("HVDT_KV_BLOCKS"))
        self.block_size = int(block_size if block_size is not None
                              else config.get_int("HVDT_KV_BLOCK_SIZE"))
        if self.num_blocks < 2:
            raise ValueError(
                f"need >= 2 blocks (block 0 is the sink), got "
                f"{self.num_blocks}")
        if self.block_size < 1:
            raise ValueError(f"block_size must be >= 1, got "
                             f"{self.block_size}")
        # Low ids leave the free list first (pop() from the tail).
        self._free: List[int] = list(range(self.num_blocks - 1, 0, -1))
        self._ref: List[int] = [0] * self.num_blocks
        # Audit counters — the exact-accounting ledger.
        self.blocks_allocated = 0    # free list -> a table
        self.blocks_freed = 0        # refcount hit 0 -> free list
        self.cow_copies = 0          # shared-block writes resolved

    # -- capacity ----------------------------------------------------------

    @property
    def capacity(self) -> int:
        """Allocatable blocks (the sink is not capacity)."""
        return self.num_blocks - 1

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def used_blocks(self) -> int:
        return self.capacity - len(self._free)

    def blocks_for(self, n_tokens: int) -> int:
        """Blocks covering ``n_tokens`` positions."""
        return -(-max(0, int(n_tokens)) // self.block_size)

    def can_admit(self, n_tokens: int) -> bool:
        return self.blocks_for(n_tokens) <= len(self._free)

    # -- allocation --------------------------------------------------------

    def _take(self) -> int:
        blk = self._free.pop()
        self._ref[blk] = 1
        self.blocks_allocated += 1
        return blk

    def allocate(self, n_tokens: int) -> Optional[List[int]]:
        """A fresh block table covering ``n_tokens`` positions, or
        ``None`` (all-or-nothing) when the free list is short."""
        need = self.blocks_for(n_tokens)
        if need > len(self._free):
            return None
        return [self._take() for _ in range(need)]

    def append_token(self, table: List[int],
                     position: int) -> Optional[List[Tuple[int, int]]]:
        """Make ``position`` writable in ``table`` before a decode step
        scatters there.  Grows the table by one block at a block
        boundary; resolves copy-on-write when the covering block is
        shared.  Returns the (possibly empty) list of ``(src, dst)``
        device block copies to apply BEFORE the write, or ``None`` when
        a needed block could not be allocated (nothing mutated — evict
        and retry)."""
        idx = int(position) // self.block_size
        if idx > len(table):
            raise ValueError(
                f"position {position} skips past the table "
                f"({len(table)} blocks of {self.block_size})")
        if idx == len(table):
            if not self._free:
                return None
            table.append(self._take())
            return []
        blk = table[idx]
        if self._ref[blk] == 1:
            return []
        # Shared block: copy-on-write.  The sibling keeps `blk`; this
        # sequence writes into its own copy from here on.
        if not self._free:
            return None
        dst = self._take()
        self._ref[blk] -= 1
        table[idx] = dst
        self.cow_copies += 1
        return [(blk, dst)]

    def fork(self, table: List[int]) -> List[int]:
        """Clone a sequence's table by reference (shared prefix): every
        block's refcount is bumped, no device copy happens.  Writes by
        either side later resolve through :meth:`append_token` CoW."""
        for blk in table:
            if self._ref[blk] < 1:
                raise RuntimeError(
                    f"fork of a table holding unreferenced block {blk}")
            self._ref[blk] += 1
        return list(table)

    def free(self, table: List[int]) -> int:
        """Release a table (eviction, completion).  Blocks whose
        refcount hits 0 return to the free list; shared blocks survive
        for their siblings.  Clears ``table`` in place (a cleared table
        cannot be double-freed).  Returns blocks actually recycled."""
        recycled = 0
        for blk in table:
            if blk == SINK_BLOCK or self._ref[blk] < 1:
                raise RuntimeError(
                    f"double free (or sink free) of block {blk}")
            self._ref[blk] -= 1
            if self._ref[blk] == 0:
                self._free.append(blk)
                self.blocks_freed += 1
                recycled += 1
        table.clear()
        return recycled

    # -- audit -------------------------------------------------------------

    def check(self) -> None:
        """Assert the exact-accounting invariants; raises on leak,
        double-free residue, or ledger drift."""
        in_use = sum(1 for b in range(1, self.num_blocks)
                     if self._ref[b] > 0)
        if self._ref[SINK_BLOCK] != 0:
            raise AssertionError("sink block acquired a refcount")
        if len(self._free) + in_use != self.capacity:
            raise AssertionError(
                f"block leak: free={len(self._free)} in_use={in_use} "
                f"capacity={self.capacity}")
        if len(set(self._free)) != len(self._free):
            raise AssertionError("free list holds a duplicate block")
        if any(self._ref[b] > 0 for b in self._free):
            raise AssertionError("freed block still referenced")
        if self.blocks_allocated != self.blocks_freed + in_use:
            raise AssertionError(
                f"ledger drift: allocated={self.blocks_allocated} != "
                f"freed={self.blocks_freed} + in_use={in_use}")


def make_kv_cache(cfg, num_blocks: int, block_size: int, dtype=None):
    """Device KV pool pair ``(kc, vc)``, each ``[layers, num_blocks,
    block_size, kv_heads, head_dim]``, zero-initialized (the sink block
    must start finite — masked lanes read as exp-masked zeros, never
    NaN).  ``dtype`` defaults to the model's activation dtype."""
    import jax.numpy as jnp

    shape = (cfg.layers, num_blocks, block_size, cfg.kv_heads,
             cfg.head_dim)
    dt = dtype if dtype is not None else cfg.dtype
    return jnp.zeros(shape, dt), jnp.zeros(shape, dt)
