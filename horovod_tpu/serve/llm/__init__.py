"""Continuous-batching LLM serving (paged KV cache + per-iteration
scheduling).

Selected per replica with ``HVDT_SERVE_ENGINE=continuous`` (the default
``static`` keeps the shape-bucket :mod:`~horovod_tpu.serve.engine`); the
fleet layer — router, autoscaler, drain, reload — is engine-agnostic.

* :mod:`.kv_cache` — paged block allocator: free list, per-sequence
  block tables, refcounted copy-on-write prefix sharing, exact
  accounting.
* :mod:`.scheduler` — per-iteration admission/eviction under the block
  budget; prefill/decode disaggregation; interactive-vs-batch tenant
  quotas adapted off the telemetry time-series plane.
* :mod:`.engine` — the fixed-shape jitted programs (paged decode,
  chunked prefill, CoW copies, optional ring-attention long-context
  prefill) and the worker loop that runs the iterations.
"""

from .engine import ContinuousLLMEngine
from .kv_cache import SINK_BLOCK, PagedKVAllocator, make_kv_cache
from .scheduler import IterationPlan, IterationScheduler, Sequence

__all__ = [
    "ContinuousLLMEngine", "PagedKVAllocator", "SINK_BLOCK",
    "make_kv_cache", "IterationScheduler", "IterationPlan", "Sequence",
]
