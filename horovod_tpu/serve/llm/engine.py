"""Continuous-batching LLM engine: jitted paged decode + chunked prefill.

The device half of the subsystem.  Exactly four jitted programs exist,
every one with FIXED shapes in every argument, so admission, eviction,
fork, and completion of sequences can never change what XLA runs — the
static bucket engine's zero-steady-state-recompile contract
(``serve_compiles_total`` flat after warmup), carried into decode:

* **decode** — one token for every active slot ``[S]`` over block-table
  gathers (``models.transformer.transformer_decode_paged``); greedy
  argmax in-graph so the per-iteration host transfer is S ints.
* **prefill** — one ``HVDT_SERVE_PREFILL_CHUNK``-token chunk of ONE
  sequence into its blocks; long prompts stream through across
  iterations while decode keeps running (the disaggregation that holds
  interactive p99).
* **copy** — a fixed-length list of block copies (CoW resolutions),
  padded with harmless ``(0, 0)`` sink self-copies.
* **ring prefill** (optional, ``HVDT_SERVE_RING_PREFILL > 1``) — a
  whole-prompt pass under ``shard_map`` over an ``sp`` mesh axis so
  attention runs as ``parallel/ring_attention.py``'s exact ring; the
  collected per-layer k/v slabs scatter into the paged cache in one
  shot.  Long-context prompts prefill in one iteration at ring-attention
  memory cost instead of ``O(chunks)`` iterations.

Weights serve optionally as int8 (``HVDT_SERVE_INT8``): eligible leaves
are block-scale quantized once per swap via ``quant/kernels.py`` and
dequantized INSIDE the jitted programs, so replica HBM holds 1-byte
weights (plus scales) — the replica-density play — while matmuls run in
the model dtype.

Threading: submitters enqueue under the engine lock and a worker thread
runs scheduler iterations; everything device-facing happens on the
worker (or whoever calls :meth:`step` in tests).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from concurrent.futures import Future
from typing import Any, Dict, List, Optional, Sequence as Seq, Tuple

import numpy as np

from ...common import config
from ...common.logging_util import get_logger
from ...models.transformer import (TransformerConfig,
                                   transformer_decode_paged,
                                   transformer_prefill_collect,
                                   transformer_prefill_paged)
from ..batcher import BackpressureError, RequestDeadlineExceeded
from ..metrics import MetricsRegistry
from .kv_cache import SINK_BLOCK, PagedKVAllocator, make_kv_cache
from .scheduler import TENANTS, IterationScheduler, Sequence

__all__ = ["ContinuousLLMEngine"]

log = get_logger(__name__)


class ContinuousLLMEngine:
    """Continuous-batching engine for ``models/transformer.py`` weights.

    Mirrors the static :class:`~horovod_tpu.serve.engine.InferenceEngine`
    surface that ``server.py``/``replica.py``/healthz rely on
    (``swap_params``, ``params_version``, ``warmup``, ``compile_count``,
    ``metrics``, ``buckets``) so the fleet layer — router, autoscaler,
    drain — works unchanged; requests enter through :meth:`submit`
    (token ids in, generated token ids out) instead of the batcher.
    """

    is_continuous = True

    def __init__(self, params: Any, cfg: TransformerConfig, *,
                 metrics: Optional[MetricsRegistry] = None,
                 decode_slots: Optional[int] = None,
                 num_blocks: Optional[int] = None,
                 block_size: Optional[int] = None,
                 seq_blocks: Optional[int] = None,
                 prefill_chunk: Optional[int] = None,
                 batch_quota: Optional[float] = None,
                 int8: Optional[bool] = None,
                 ring_prefill: Optional[int] = None,
                 max_queue: int = 256,
                 auto_start: bool = True,
                 compile_cache: Optional[str] = None):
        from ...step_pipeline import enable_compilation_cache

        enable_compilation_cache(compile_cache)
        # The serving config is single-sequence-parallel and remat-free;
        # the ring degree applies only inside the ring-prefill program.
        self._cfg = dataclasses.replace(cfg, sp=1, pp=1, remat=False)
        self.block_size = int(block_size if block_size is not None
                              else config.get_int("HVDT_KV_BLOCK_SIZE"))
        self.num_blocks = int(num_blocks if num_blocks is not None
                              else config.get_int("HVDT_KV_BLOCKS"))
        self.seq_blocks = int(seq_blocks if seq_blocks is not None
                              else config.get_int("HVDT_KV_SEQ_BLOCKS"))
        self.decode_slots = int(
            decode_slots if decode_slots is not None
            else config.get_int("HVDT_SERVE_DECODE_SLOTS"))
        self.prefill_chunk = int(
            prefill_chunk if prefill_chunk is not None
            else config.get_int("HVDT_SERVE_PREFILL_CHUNK"))
        self.default_max_new = config.get_int("HVDT_SERVE_MAX_NEW_TOKENS")
        self._int8 = bool(int8 if int8 is not None
                          else config.get_bool("HVDT_SERVE_INT8"))
        self._ring = int(ring_prefill if ring_prefill is not None
                         else config.get_int("HVDT_SERVE_RING_PREFILL"))
        self.max_queue = int(max_queue)
        self.max_context = self.seq_blocks * self.block_size

        self.alloc = PagedKVAllocator(self.num_blocks, self.block_size)
        self.sched = IterationScheduler(
            self.alloc, decode_slots=self.decode_slots,
            prefill_chunk=self.prefill_chunk, seq_blocks=self.seq_blocks,
            batch_quota=batch_quota)
        self._kc, self._vc = make_kv_cache(self._cfg, self.num_blocks,
                                           self.block_size)

        self.metrics = metrics if metrics is not None else MetricsRegistry()
        self._build_metrics()
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._stopping = False
        self._worker: Optional[threading.Thread] = None
        self._auto_start = bool(auto_start)
        self._version = 0
        self._seen_sigs: Dict[str, set] = {}
        self._tps_ema = 0.0
        self._treedef = None
        self._plan: Tuple = ()
        self._packed: List[Any] = []
        self._set_params(params)
        self._build_jits()
        self._ring_built = False

    # -- metrics -----------------------------------------------------------

    def _build_metrics(self) -> None:
        m = self.metrics
        self._compiles = m.counter(
            "serve_compiles_total",
            "XLA compilations triggered by inference (flat after warmup "
            "means the shape buckets are doing their job)")
        self._requests = m.counter(
            "serve_requests_total", "Requests accepted by the server")
        self._expired = m.counter(
            "serve_deadline_expired_total",
            "Requests failed with RequestDeadlineExceeded before "
            "dispatch")
        self._iterations = m.counter(
            "hvdt_engine_iterations_total",
            "Continuous-batching scheduler iterations executed")
        self._decode_tokens = m.counter(
            "hvdt_engine_decode_tokens_total",
            "Tokens emitted by the paged decode step")
        self._prefill_tokens = m.counter(
            "hvdt_engine_prefill_tokens_total",
            "Prompt tokens written into the paged KV cache")
        self._preempt_total = m.counter(
            "hvdt_engine_preemptions_total",
            "Sequences evicted (blocks reclaimed; recompute on return)")
        self._prefix_hits = m.counter(
            "hvdt_engine_prefix_hits_total",
            "Admissions served by forking a live prompt's block table "
            "(copy-on-write prefix sharing; prefill skipped)")
        self._admissions = m.counter(
            "hvdt_engine_admissions_total",
            "Sequences admitted to the block budget, by tenant")
        self._tps = m.gauge(
            "hvdt_engine_tokens_per_sec",
            "Decode throughput (EMA over iterations)")
        self._g_blocks_total = m.gauge(
            "hvdt_engine_kv_blocks_total",
            "Allocatable KV blocks (sink excluded)")
        self._g_blocks_total.set(float(self.alloc.capacity))
        g_used = m.gauge("hvdt_engine_kv_blocks_in_use",
                         "KV blocks held by live block tables (live probe)")
        g_used.set_function(lambda: self.alloc.used_blocks)
        g_live = m.gauge("hvdt_engine_active_seqs",
                         "Admitted (prefilling or decoding) sequences "
                         "(live probe)")
        g_live.set_function(lambda: len(self.sched.admitted))
        self._g_quota = m.gauge(
            "hvdt_engine_batch_quota_slots",
            "Decode slots the batch tenant may hold (adaptive)")
        self._g_queue = m.gauge(
            "hvdt_engine_queue_depth",
            "Waiting (not yet admitted) sequences, by tenant")
        # The autoscaler's leading load signal; the batcher registers
        # this on the static path — here waiting sequences are the queue.
        g_depth = m.gauge(
            "serve_queue_depth",
            "Requests admitted and not yet dispatched")
        g_depth.set_function(lambda: self.sched.queue_depth())
        self._s_decode = m.summary(
            "hvdt_engine_decode_step_seconds",
            "Wall time of one paged decode iteration")
        self._s_prefill = m.summary(
            "hvdt_engine_prefill_chunk_seconds",
            "Wall time of one prefill chunk (or ring prefill shot)")
        self._s_wait = {
            t: m.summary(f"hvdt_engine_wait_ms_{t}",
                         f"Submit-to-first-token latency, {t} tenant (ms)")
            for t in TENANTS}

    # -- params / int8 packing ---------------------------------------------

    def _set_params(self, params: Any) -> None:
        import jax
        import jax.numpy as jnp

        leaves, treedef = jax.tree.flatten(params)
        plan: List[Optional[Tuple]] = []
        packed: List[Any] = []
        if self._int8:
            from ...quant.kernels import quant_block_size, quantize_flat

            qb = quant_block_size()
            self._qblock = qb
            for leaf in leaves:
                arr = jnp.asarray(leaf)
                if (jnp.issubdtype(arr.dtype, jnp.floating)
                        and arr.size >= qb and arr.size % qb == 0):
                    q, s = quantize_flat(
                        jnp.ravel(arr).astype(jnp.float32), qb)
                    plan.append((arr.shape, arr.dtype))
                    packed.append((q, s))
                else:
                    plan.append(None)
                    packed.append(arr)
        else:
            self._qblock = 0
            for leaf in leaves:
                plan.append(None)
                packed.append(jnp.asarray(leaf))
        self._treedef = treedef
        self._plan = tuple(plan)
        self._packed = packed

    def _materialize(self, packed):
        """Rebuild the param pytree inside a traced program (dequantizing
        int8 leaves in-graph — HBM holds bytes, matmuls see floats)."""
        import jax

        from ...quant.kernels import dequantize_flat

        leaves = []
        for spec, item in zip(self._plan, packed):
            if spec is None:
                leaves.append(item)
            else:
                shape, dt = spec
                q, s = item
                leaves.append(dequantize_flat(q, s, self._qblock)
                              .reshape(shape).astype(dt))
        return jax.tree.unflatten(self._treedef, leaves)

    def swap_params(self, params: Any) -> int:
        """Hot weight swap (reload watcher contract): repack (and
        requantize) under the lock; in-flight iterations finish on the
        reference they captured.  Same shapes ⇒ zero recompiles."""
        with self._lock:
            self._set_params(params)
            self._version += 1
            return self._version

    @property
    def params_version(self) -> int:
        return self._version

    @property
    def buckets(self) -> Tuple[int, ...]:
        """Shape-bucket ladder analogue: one fixed decode batch."""
        return (self.decode_slots,)

    # -- jitted programs ---------------------------------------------------

    def _counted(self, name: str, jfn):
        """Count compiles by argument signature — same contract as the
        bucket engine's ``serve_compiles_total``: a new (shape, dtype)
        set means XLA compiled, anything else must hit cache."""
        import jax

        seen = self._seen_sigs.setdefault(name, set())

        def call(*args):
            sig = tuple(
                (tuple(getattr(l, "shape", ())),
                 str(getattr(l, "dtype", type(l).__name__)))
                for l in jax.tree.leaves(args))
            if sig not in seen:
                seen.add(sig)
                self._compiles.inc()
                log.info("serve/llm: compiling %s", name)
            return jfn(*args)

        return call

    def _build_jits(self) -> None:
        import jax

        cfg, bs = self._cfg, self.block_size

        def decode(packed, tokens, tables, lens, kc, vc):
            p = self._materialize(packed)
            return transformer_decode_paged(p, tokens, tables, lens,
                                            kc, vc, cfg, bs)

        def prefill(packed, tokens, start, n_valid, table, kc, vc):
            p = self._materialize(packed)
            return transformer_prefill_paged(p, tokens, start, n_valid,
                                             table, kc, vc, cfg, bs)

        def copy_blocks(kc, vc, src, dst):
            return (kc.at[:, dst].set(kc[:, src]),
                    vc.at[:, dst].set(vc[:, src]))

        self._jits = {
            "decode": jax.jit(decode, donate_argnums=(4, 5)),
            "prefill": jax.jit(prefill, donate_argnums=(5, 6)),
            "copy": jax.jit(copy_blocks, donate_argnums=(0, 1)),
        }
        self._decode_fn = self._counted("decode", self._jits["decode"])
        self._prefill_fn = self._counted("prefill", self._jits["prefill"])
        self._copy_fn = self._counted("copy", self._jits["copy"])

    # -- ring (long-context) prefill ---------------------------------------

    def ring_enabled(self) -> bool:
        import jax

        return (self._ring > 1
                and len(jax.devices()) >= self._ring
                and self.max_context % self._ring == 0)

    def _build_ring(self) -> None:
        if self._ring_built:
            return
        import jax

        try:
            from jax import shard_map
        except ImportError:      # pragma: no cover - old jax
            from jax.experimental.shard_map import shard_map
        from jax.sharding import Mesh, PartitionSpec as P

        sp = self._ring
        mesh = Mesh(np.array(jax.devices()[:sp]), ("sp",))
        rcfg = dataclasses.replace(self._cfg, sp=sp)

        def collect(packed, tokens):
            p = self._materialize(packed)
            return transformer_prefill_collect(p, tokens, rcfg)

        def run(packed, tokens):
            return shard_map(
                collect, mesh=mesh,
                in_specs=(P(), P(None, "sp")),
                out_specs=(P(None, None, "sp"),
                           P(None, None, "sp")))(packed, tokens)

        def scatter(k_all, v_all, blk, off, kc, vc):
            kc = kc.at[:, blk, off].set(k_all[:, 0].astype(kc.dtype))
            vc = vc.at[:, blk, off].set(v_all[:, 0].astype(vc.dtype))
            return kc, vc

        self._jits["ring_prefill"] = jax.jit(run)
        self._jits["ring_scatter"] = jax.jit(scatter,
                                             donate_argnums=(4, 5))
        self._ring_fn = self._counted("ring_prefill",
                                      self._jits["ring_prefill"])
        self._ring_scatter = self._counted("ring_scatter",
                                           self._jits["ring_scatter"])
        self._ring_built = True

    def _ring_eligible(self, seq: Sequence, start: int) -> bool:
        """Whole-prompt ring prefill: only from position 0 and only for
        prompts long enough that one-chunk-per-iteration streaming would
        take many iterations (>= half the context bound)."""
        return (self.ring_enabled() and start == 0
                and len(seq.tokens) - 1 >= self.max_context // 2)

    def _run_ring_prefill(self, seq: Sequence) -> None:
        self._build_ring()
        n = len(seq.tokens) - 1           # last token enters via decode
        s_pad = self.max_context
        toks = np.zeros((1, s_pad), np.int32)
        toks[0, :n] = seq.tokens[:n]
        p = np.arange(s_pad)
        table = np.full(self.seq_blocks, SINK_BLOCK, np.int32)
        table[:len(seq.table)] = seq.table
        blk = np.where(p < n, table[p // self.block_size],
                       SINK_BLOCK).astype(np.int32)
        off = (p % self.block_size).astype(np.int32)
        k_all, v_all = self._ring_fn(self._packed, toks)
        self._kc, self._vc = self._ring_scatter(
            k_all, v_all, blk, off, self._kc, self._vc)
        seq.prefilled = n
        self._prefill_tokens.inc(n)

    # -- request surface ---------------------------------------------------

    def submit(self, tokens: Seq[int], *,
               max_new_tokens: Optional[int] = None,
               tenant: str = "interactive",
               deadline_s: Optional[float] = None) -> "Future":
        """Enqueue one sequence; the Future resolves to the generated
        token ids.  Raises :class:`BackpressureError` when the waiting
        queue is at bound (callers see 503, same as the batcher path)."""
        fut: Future = Future()
        seq = Sequence(list(tokens),
                       tenant=tenant,
                       max_new=(max_new_tokens if max_new_tokens
                                else self.default_max_new),
                       future=fut, deadline_s=deadline_s)
        with self._cv:
            if self._stopping:
                raise RuntimeError("engine is stopping")
            if self.sched.queue_depth() >= self.max_queue:
                raise BackpressureError(
                    f"waiting queue at bound ({self.max_queue})")
            self.sched.add(seq)       # validates context bound
            self._requests.inc()
            self._cv.notify_all()
        if self._auto_start:
            self._ensure_worker()
        return fut

    def generate(self, prompts: Seq[Seq[int]], *,
                 timeout: float = 120.0, **kw) -> List[List[int]]:
        """Synchronous convenience: submit all, wait for all."""
        futs = [self.submit(p, **kw) for p in prompts]
        return [f.result(timeout=timeout) for f in futs]

    # -- the iteration -----------------------------------------------------

    def _fail(self, seq: Sequence, exc: Exception) -> None:
        if seq.future is not None and not seq.future.done():
            seq.future.set_exception(exc)

    def _finish(self, seq: Sequence) -> None:
        out = list(seq.generated)
        self.sched.release(seq)
        if seq.future is not None and not seq.future.done():
            seq.future.set_result(out)

    def step(self) -> int:
        """One scheduler iteration + its device work.  Returns tokens
        decoded (0 means the engine is idle).  Thread-safe; the worker
        loop calls this, tests may call it directly."""
        import jax

        with self._lock:
            return self._step_locked()

    def _step_locked(self) -> int:
        import jax

        t_start = time.perf_counter()
        pre_preempt = self.sched.preemptions
        pre_prefix = self.sched.prefix_hits
        pre_admit = dict(self.sched.admissions)
        plan = self.sched.plan(t_start)
        self._iterations.inc()
        self._preempt_total.inc(self.sched.preemptions - pre_preempt)
        self._prefix_hits.inc(self.sched.prefix_hits - pre_prefix)
        for t in TENANTS:
            d = self.sched.admissions[t] - pre_admit[t]
            if d:
                self._admissions.inc(d, tenant=t)
            self._g_queue.set(float(len(self.sched.waiting[t])), tenant=t)
        self._g_quota.set(float(self.sched.batch_quota_slots()))
        for seq in plan.expired:
            self._expired.inc()
            self._fail(seq, RequestDeadlineExceeded(
                f"deadline exceeded before admission "
                f"(waited {time.perf_counter() - seq.t_submit:.3f}s)"))

        if plan.copies:
            src = np.zeros(self.decode_slots, np.int32)
            dst = np.zeros(self.decode_slots, np.int32)
            for i, (s, d) in enumerate(plan.copies[:self.decode_slots]):
                src[i], dst[i] = s, d
            self._kc, self._vc = self._copy_fn(self._kc, self._vc,
                                               src, dst)

        if plan.prefill is not None:
            seq, start, n = plan.prefill
            t0 = time.perf_counter()
            if self._ring_eligible(seq, start):
                self._run_ring_prefill(seq)
            else:
                toks = np.zeros(self.prefill_chunk, np.int32)
                toks[:n] = seq.tokens[start:start + n]
                table = np.full(self.seq_blocks, SINK_BLOCK, np.int32)
                table[:len(seq.table)] = seq.table
                self._kc, self._vc = self._prefill_fn(
                    self._packed, toks, np.int32(start), np.int32(n),
                    table, self._kc, self._vc)
                seq.prefilled += n
                self._prefill_tokens.inc(n)
            self._s_prefill.observe(time.perf_counter() - t0)

        n_decoded = 0
        if plan.decode:
            tokens = np.zeros(self.decode_slots, np.int32)
            tables = np.full((self.decode_slots, self.seq_blocks),
                             SINK_BLOCK, np.int32)
            lens = np.zeros(self.decode_slots, np.int32)
            for slot, seq in plan.decode:
                tokens[slot] = seq.tokens[-1]
                lens[slot] = len(seq.tokens)
                tables[slot, :len(seq.table)] = seq.table
            t0 = time.perf_counter()
            nxt, self._kc, self._vc = self._decode_fn(
                self._packed, tokens, tables, lens, self._kc, self._vc)
            nxt = np.asarray(jax.device_get(nxt))
            dt = time.perf_counter() - t0
            self._s_decode.observe(dt)
            now = time.perf_counter()
            for slot, seq in plan.decode:
                seq.tokens.append(int(nxt[slot]))
                # The decode kernel wrote k/v at the position of the token
                # we just consumed, so the cache now covers everything up
                # to (but not including) the freshly appended token.
                seq.prefilled = len(seq.tokens) - 1
                if seq.t_first_token is None:
                    seq.t_first_token = now
                    self._s_wait[seq.tenant].observe(
                        (now - seq.t_submit) * 1000.0)
                if seq.finished():
                    self._finish(seq)
            n_decoded = len(plan.decode)
            self._decode_tokens.inc(n_decoded)
            if dt > 0:
                inst = n_decoded / dt
                self._tps_ema = (0.8 * self._tps_ema + 0.2 * inst
                                 if self._tps_ema else inst)
                self._tps.set(self._tps_ema)
        return n_decoded

    # -- worker loop -------------------------------------------------------

    def _ensure_worker(self) -> None:
        with self._lock:
            if self._worker is not None and self._worker.is_alive():
                return
            if self._stopping:
                return
            self._worker = threading.Thread(
                target=self._worker_loop, name="llm-engine", daemon=True)
            self._worker.start()

    def _worker_loop(self) -> None:
        while True:
            with self._cv:
                while not self._stopping and not self.sched.has_work():
                    self._cv.wait(timeout=0.1)
                if self._stopping:
                    return
            try:
                did = self.step()
            except Exception as e:           # pragma: no cover - safety
                log.exception("llm engine iteration failed: %s", e)
                self._abort_all(e)
                return
            if not did:
                # Work exists but none ran (e.g. waiting sequences the
                # budget cannot admit yet) — park on the condition so a
                # submit/release wakes us instead of spinning.
                with self._cv:
                    self._cv.wait(timeout=0.001)

    def _abort_all(self, exc: Exception) -> None:
        with self._lock:
            seqs = list(self.sched.admitted)
            for q in self.sched.waiting.values():
                seqs.extend(q)
                q.clear()
            for seq in seqs:
                if seq in self.sched.admitted:
                    self.sched.release(seq)
                self._fail(seq, exc)

    def stop(self, timeout: float = 5.0) -> None:
        """Drain-free shutdown: fail whatever is still queued/running."""
        with self._cv:
            self._stopping = True
            self._cv.notify_all()
        if self._worker is not None:
            self._worker.join(timeout=timeout)
        self._abort_all(RuntimeError("engine stopped"))

    close = stop

    # -- warmup / introspection --------------------------------------------

    def warmup(self, feat_shape: Optional[Tuple[int, ...]] = None,
               dtype=None) -> None:
        """Pre-compile every fixed-shape program with inert inputs (all
        slots inactive, zero-valid prefill, sink self-copies) so the
        first real request never pays a compile.  ``feat_shape``/
        ``dtype`` are accepted for bucket-engine signature compatibility
        and ignored — this engine has exactly one shape per program."""
        import jax

        with self._lock:
            tokens = np.zeros(self.decode_slots, np.int32)
            tables = np.full((self.decode_slots, self.seq_blocks),
                             SINK_BLOCK, np.int32)
            lens = np.zeros(self.decode_slots, np.int32)
            nxt, self._kc, self._vc = self._decode_fn(
                self._packed, tokens, tables, lens, self._kc, self._vc)
            jax.block_until_ready(nxt)
            ctoks = np.zeros(self.prefill_chunk, np.int32)
            ctable = np.full(self.seq_blocks, SINK_BLOCK, np.int32)
            self._kc, self._vc = self._prefill_fn(
                self._packed, ctoks, np.int32(0), np.int32(0), ctable,
                self._kc, self._vc)
            src = np.zeros(self.decode_slots, np.int32)
            self._kc, self._vc = self._copy_fn(self._kc, self._vc,
                                               src, src)
            if self.ring_enabled():
                self._build_ring()
                rtoks = np.zeros((1, self.max_context), np.int32)
                k_all, v_all = self._ring_fn(self._packed, rtoks)
                p = np.arange(self.max_context)
                blk = np.full(self.max_context, SINK_BLOCK, np.int32)
                off = (p % self.block_size).astype(np.int32)
                self._kc, self._vc = self._ring_scatter(
                    k_all, v_all, blk, off, self._kc, self._vc)
            jax.block_until_ready(self._kc)

    def compile_count(self) -> int:
        return int(self._compiles.value())

    def queue_depth(self) -> int:
        with self._lock:
            return self.sched.queue_depth()
