"""Per-iteration scheduler: admit/evict sequences between decode steps.

Continuous batching inverts the static batcher's unit of work: instead
of gathering REQUESTS into one fixed batch that runs to completion, the
engine runs ITERATIONS — one fixed-shape decode step over whatever
sequences currently hold decode slots — and this scheduler decides,
between iterations, which sequences hold slots, which prefill, and which
get evicted when the block budget runs dry.  Decisions are pure host
bookkeeping against the :class:`~.kv_cache.PagedKVAllocator`; the device
program never changes shape.

Policy, in priority order:

* **Prefill/decode disaggregation** — at most ONE prefill chunk
  (``HVDT_SERVE_PREFILL_CHUNK`` tokens) runs per iteration, and decode
  runs EVERY iteration.  A 10k-token prompt streams through in chunks
  while in-flight decodes keep emitting a token per iteration — decode
  p99 is bounded by one chunk's compute, not one prompt's.
* **Tenant classes** — ``interactive`` outranks ``batch`` at every
  decision point (admission order, prefill order, slot assignment,
  eviction victims).  Batch holds at most ``quota`` decode slots; the
  quota adapts off a :class:`~horovod_tpu.telemetry.history.Series` of
  interactive queue wait (the PR-15 time-series plane): sustained
  interactive waiting halves the batch share down to an
  anti-starvation floor of one slot, an idle interactive queue restores
  it toward ``HVDT_SERVE_BATCH_QUOTA`` — and with no interactive demand
  at all, batch is work-conserving over every slot.
* **Eviction = recompute** — a preempted sequence releases its blocks
  and re-enters the FRONT of its tenant queue with everything generated
  so far as its new prompt; re-admission re-prefills (chunked) and
  continues.  Newest batch sequences are preempted first, newest
  interactive only when no batch victim remains.
* **Prefix sharing** — an admitted prompt identical to a live
  sequence's prompt forks that sequence's block table (refcounts, no
  copy) and skips prefill entirely; the first divergent write resolves
  through the allocator's copy-on-write.
"""

from __future__ import annotations

import collections
import dataclasses
import itertools
import time
from typing import Deque, Dict, List, Optional, Tuple

from ...common import config
from ...telemetry.history import Series
from .kv_cache import PagedKVAllocator

__all__ = ["Sequence", "IterationPlan", "IterationScheduler", "TENANTS"]

TENANTS = ("interactive", "batch")

_uid = itertools.count()


class Sequence:
    """One request's lifetime through the engine.

    ``tokens`` is the full token list so far (prompt then generated);
    ``n_prompt`` marks the boundary.  ``prefilled`` counts positions
    whose k/v sit in the cache — decode is legal once ``prefilled ==
    len(tokens) - 1`` (the LAST token enters through the decode step,
    which scatters its k/v and emits the first new token in one pass).
    Preemption resets ``prefilled`` to 0 and keeps ``tokens``: the
    recompute path re-prefills prompt+generated as one longer prompt.
    """

    __slots__ = ("uid", "tokens", "n_prompt", "tenant", "max_new",
                 "table", "prefilled", "slot", "future", "t_submit",
                 "deadline", "preemptions", "prefix_shared",
                 "t_first_token", "admit_order")

    def __init__(self, tokens: List[int], *, tenant: str = "interactive",
                 max_new: int = 16, future=None,
                 deadline_s: Optional[float] = None):
        if tenant not in TENANTS:
            raise ValueError(f"unknown tenant {tenant!r}; "
                             f"valid: {TENANTS}")
        if not tokens:
            raise ValueError("empty prompt")
        self.uid = next(_uid)
        self.tokens: List[int] = [int(t) for t in tokens]
        self.n_prompt = len(self.tokens)
        self.tenant = tenant
        self.max_new = int(max_new)
        self.table: List[int] = []
        self.prefilled = 0
        self.slot: Optional[int] = None
        self.future = future
        self.t_submit = time.perf_counter()
        self.deadline = (self.t_submit + deadline_s
                         if deadline_s and deadline_s > 0 else None)
        self.preemptions = 0
        self.prefix_shared = False
        self.t_first_token: Optional[float] = None
        self.admit_order = -1

    @property
    def n_generated(self) -> int:
        return len(self.tokens) - self.n_prompt

    @property
    def generated(self) -> List[int]:
        return self.tokens[self.n_prompt:]

    @property
    def decode_ready(self) -> bool:
        return self.prefilled >= len(self.tokens) - 1

    def finished(self) -> bool:
        return self.n_generated >= self.max_new

    def expired(self, now: float) -> bool:
        return self.deadline is not None and now > self.deadline


@dataclasses.dataclass
class IterationPlan:
    """What the engine executes this iteration (device work only; all
    bookkeeping already committed to the allocator)."""

    copies: List[Tuple[int, int]]                    # CoW block copies
    prefill: Optional[Tuple[Sequence, int, int]]     # (seq, start, n)
    decode: List[Tuple[int, Sequence]]               # (slot, seq)
    expired: List[Sequence]                          # deadline failures


class IterationScheduler:
    """Owns the waiting queues, the decode slots, and the block budget.

    Single-threaded by contract (the engine's worker loop); ``add`` is
    the one entry point the engine may call under its own lock from
    submitter threads.
    """

    def __init__(self, allocator: PagedKVAllocator, *,
                 decode_slots: Optional[int] = None,
                 prefill_chunk: Optional[int] = None,
                 seq_blocks: Optional[int] = None,
                 batch_quota: Optional[float] = None,
                 wait_hi_ms: float = 25.0,
                 history_window: int = 256):
        self.alloc = allocator
        self.decode_slots = int(
            decode_slots if decode_slots is not None
            else config.get_int("HVDT_SERVE_DECODE_SLOTS"))
        self.prefill_chunk = int(
            prefill_chunk if prefill_chunk is not None
            else config.get_int("HVDT_SERVE_PREFILL_CHUNK"))
        self.seq_blocks = int(
            seq_blocks if seq_blocks is not None
            else config.get_int("HVDT_KV_SEQ_BLOCKS"))
        self.quota_ceiling = float(
            batch_quota if batch_quota is not None
            else config.get_float("HVDT_SERVE_BATCH_QUOTA"))
        self.quota_ceiling = min(1.0, max(0.0, self.quota_ceiling))
        self.wait_hi_ms = float(wait_hi_ms)
        self.max_context = self.seq_blocks * self.alloc.block_size

        self.waiting: Dict[str, Deque[Sequence]] = {
            t: collections.deque() for t in TENANTS}
        self.slots: List[Optional[Sequence]] = [None] * self.decode_slots
        self.admitted: List[Sequence] = []    # admission order
        self.iteration = 0
        self._admit_seq = itertools.count()
        self._quota_frac = self.quota_ceiling
        # PR-15 time-series plane: the quota is SCHEDULED off these, not
        # off instantaneous queue length — a single burst doesn't thrash
        # the batch tenant, sustained pressure does.
        self.wait_series = Series("serve_interactive_wait_ms",
                                  history_window)
        self.quota_series = Series("serve_batch_quota_slots",
                                   history_window)
        # Audit counters the engine mirrors into metrics.
        self.preemptions = 0
        self.prefix_hits = 0
        self.admissions: Dict[str, int] = {t: 0 for t in TENANTS}

    # -- submitter side ----------------------------------------------------

    def add(self, seq: Sequence) -> None:
        need = len(seq.tokens) + seq.max_new
        if need > self.max_context:
            raise ValueError(
                f"sequence needs {need} positions > context bound "
                f"{self.max_context} (HVDT_KV_SEQ_BLOCKS * "
                f"HVDT_KV_BLOCK_SIZE)")
        self.waiting[seq.tenant].append(seq)

    def queue_depth(self, tenant: Optional[str] = None) -> int:
        if tenant is not None:
            return len(self.waiting[tenant])
        return sum(len(q) for q in self.waiting.values())

    def live_sequences(self) -> int:
        return len(self.admitted) + self.queue_depth()

    # -- quota -------------------------------------------------------------

    def batch_quota_slots(self) -> int:
        """Decode slots the batch tenant may hold right now."""
        interactive_demand = (len(self.waiting["interactive"]) +
                              sum(1 for s in self.admitted
                                  if s.tenant == "interactive"))
        if interactive_demand == 0:
            return self.decode_slots         # work-conserving when idle
        q = int(round(self._quota_frac * self.decode_slots))
        return max(1, min(self.decode_slots, q))   # anti-starvation floor

    def _adapt_quota(self, now: float) -> None:
        """Record the interactive wait signal and adapt the batch share
        off the recent window (AIMD: halve under sustained pressure,
        creep back while quiet)."""
        q = self.waiting["interactive"]
        wait_ms = (now - q[0].t_submit) * 1000.0 if q else 0.0
        self.wait_series.append(time.time(), self.iteration, wait_ms)
        recent = self.wait_series.values()[-8:]
        mean = sum(recent) / len(recent) if recent else 0.0
        if mean > self.wait_hi_ms:
            self._quota_frac = max(0.0, self._quota_frac * 0.5)
        elif mean < self.wait_hi_ms * 0.25:
            self._quota_frac = min(self.quota_ceiling,
                                   self._quota_frac
                                   + 0.25 / self.decode_slots)
        self.quota_series.append(time.time(), self.iteration,
                                 float(self.batch_quota_slots()))

    # -- eviction ----------------------------------------------------------

    def _victim(self, spare: Sequence, allow_interactive: bool,
                exclude=()) -> Optional[Sequence]:
        """Newest admitted batch sequence (then newest interactive when
        allowed), never ``spare`` nor anything in ``exclude`` (work
        already committed to this iteration's plan must not lose its
        blocks mid-plan)."""
        for tenant in (("batch", "interactive") if allow_interactive
                       else ("batch",)):
            for seq in reversed(self.admitted):
                if (seq is not spare and seq.tenant == tenant
                        and seq not in exclude):
                    return seq
        return None

    def preempt(self, seq: Sequence) -> None:
        """Evict: release blocks, requeue at the FRONT of its tenant
        queue with prompt+generated as the new (recompute) prompt."""
        self.alloc.free(seq.table)
        if seq.slot is not None:
            self.slots[seq.slot] = None
            seq.slot = None
        seq.prefilled = 0
        seq.preemptions += 1
        self.preemptions += 1
        self.admitted.remove(seq)
        self.waiting[seq.tenant].appendleft(seq)

    def release(self, seq: Sequence) -> None:
        """Finished sequence: free blocks, vacate the slot."""
        self.alloc.free(seq.table)
        if seq.slot is not None:
            self.slots[seq.slot] = None
            seq.slot = None
        if seq in self.admitted:
            self.admitted.remove(seq)

    # -- admission ---------------------------------------------------------

    def _find_prefix_parent(self, seq: Sequence) -> Optional[Sequence]:
        """A live sequence whose PROMPT is identical and fully in cache
        — its block table can be forked (CoW) and prefill skipped."""
        for cand in self.admitted:
            if (cand.n_prompt == seq.n_prompt
                    and cand.prefilled >= cand.n_prompt - 1
                    and len(cand.table) >= self.alloc.blocks_for(
                        cand.n_prompt)
                    and cand.tokens[:cand.n_prompt] == seq.tokens):
                return cand
        return None

    def _admit(self, seq: Sequence) -> bool:
        parent = self._find_prefix_parent(seq)
        if parent is not None:
            nb = self.alloc.blocks_for(seq.n_prompt)
            seq.table = self.alloc.fork(parent.table[:nb])
            seq.prefilled = seq.n_prompt - 1
            seq.prefix_shared = True
            self.prefix_hits += 1
        else:
            table = self.alloc.allocate(len(seq.tokens))
            if table is None:
                return False
            seq.table = table
            seq.prefilled = 0
        seq.admit_order = next(self._admit_seq)
        self.admitted.append(seq)
        self.admissions[seq.tenant] += 1
        return True

    def _admission_pass(self, now: float) -> None:
        batch_cap = self.batch_quota_slots()
        for tenant in TENANTS:
            q = self.waiting[tenant]
            while q:
                if len(self.admitted) >= self.decode_slots + 2:
                    # A couple prefilling ahead is plenty — but an
                    # interactive arrival may bump a batch resident
                    # rather than wait behind it.
                    if tenant != "interactive":
                        return
                    victim = self._victim(q[0], allow_interactive=False)
                    if victim is None:
                        return
                    self.preempt(victim)
                if tenant == "batch":
                    n_batch = sum(1 for s in self.admitted
                                  if s.tenant == "batch")
                    if n_batch >= batch_cap:
                        break
                seq = q[0]
                if not self._admit(seq):
                    if tenant == "interactive":
                        victim = self._victim(seq,
                                              allow_interactive=False)
                        if victim is not None:
                            self.preempt(victim)
                            continue   # retry the same head-of-queue
                    break              # budget truly exhausted
                q.popleft()
                if tenant == "interactive":
                    self.wait_series.append(
                        time.time(), self.iteration,
                        (now - seq.t_submit) * 1000.0)

    # -- the per-iteration decision ----------------------------------------

    def plan(self, now: Optional[float] = None) -> IterationPlan:
        now = time.perf_counter() if now is None else now
        self.iteration += 1
        expired: List[Sequence] = []
        for q in self.waiting.values():
            keep: List[Sequence] = []
            while q:
                seq = q.popleft()
                (expired if seq.expired(now) else keep).append(seq)
            q.extend(keep)
        self._adapt_quota(now)
        self._admission_pass(now)

        # One prefill chunk, interactive-admitted first then admit order.
        prefill: Optional[Tuple[Sequence, int, int]] = None
        pending = [s for s in self.admitted if not s.decode_ready]
        pending.sort(key=lambda s: (s.tenant != "interactive",
                                    s.admit_order))
        if pending:
            seq = pending[0]
            n = min(self.prefill_chunk,
                    (len(seq.tokens) - 1) - seq.prefilled)
            prefill = (seq, seq.prefilled, n)

        # Slot assignment: ready sequences, interactive first, batch
        # under quota.  A shrunken quota preempts the newest batch
        # holder when an interactive sequence needs its slot.
        batch_cap = self.batch_quota_slots()
        ready = [s for s in self.admitted
                 if s.decode_ready and s.slot is None]
        ready.sort(key=lambda s: (s.tenant != "interactive",
                                  s.admit_order))
        for seq in ready:
            n_batch = sum(1 for s in self.slots
                          if s is not None and s.tenant == "batch")
            if seq.tenant == "batch" and n_batch >= batch_cap:
                continue
            free = [i for i, s in enumerate(self.slots) if s is None]
            if not free and seq.tenant == "interactive":
                victims = [s for s in self.slots
                           if s is not None and s.tenant == "batch"]
                if victims:
                    self.preempt(max(victims,
                                     key=lambda s: s.admit_order))
                    free = [i for i, s in enumerate(self.slots)
                            if s is None]
            if not free:
                break
            seq.slot = free[0]
            self.slots[seq.slot] = seq

        # Decode capacity: every slotted sequence must own (unshared)
        # the block its next write lands in.  Victims must come from
        # OUTSIDE the work already committed this iteration — evicting a
        # sequence the plan will decode (or prefill) would hand the
        # engine a freed block table.
        copies: List[Tuple[int, int]] = []
        decode: List[Tuple[int, Sequence]] = []
        committed = {prefill[0]} if prefill is not None else set()
        for slot, seq in enumerate(self.slots):
            if seq is None:
                continue
            got = self.alloc.append_token(seq.table, len(seq.tokens) - 1)
            while got is None:
                victim = self._victim(
                    seq, allow_interactive=(seq.tenant == "interactive"),
                    exclude=committed)
                if victim is None:
                    self.preempt(seq)      # nobody to evict but itself
                    break
                self.preempt(victim)
                got = self.alloc.append_token(seq.table,
                                              len(seq.tokens) - 1)
            if got is None:
                continue
            copies.extend(got)
            decode.append((slot, seq))
            committed.add(seq)
        return IterationPlan(copies=copies, prefill=prefill,
                             decode=decode, expired=expired)

    def has_work(self) -> bool:
        return bool(self.admitted) or self.queue_depth() > 0
