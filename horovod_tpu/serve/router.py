"""SLO-routing serving front tier: one endpoint over N elastic replicas.

The router is the piece that turns "a replica crashed" from a dropped
request into a retry nobody noticed.  It discovers live replicas from
the rendezvous KV the elastic driver already runs (``/serve/replicas/
<id>`` heartbeats, serve/replica.py), load-balances ``/predict`` across
them, and routes *around* trouble — the TPU-concurrency study's
fleet-level lesson (PAPERS.md): utilization is won by not waiting on
slow or dead participants.

Routing policy, in the order it saves a request:

* **Least-inflight pick** — the router tracks its own in-flight count
  per replica (its view of load is fresher than any heartbeat) and
  routes to the least-loaded admitting replica.
* **Retry budget** — a dispatch that dies on the wire (connection
  refused/reset, 5xx) is retried on a *different* replica under a
  jittered :class:`~horovod_tpu.resilience.retry.Backoff` bounded by
  the request deadline.  ``/predict`` is idempotent (pure inference);
  callers that disagree send ``X-HVDT-No-Retry: 1``.
* **Hedging** — a request still unanswered past the hedge threshold
  (``HVDT_SERVE_HEDGE_MS``; 0 = adaptive ~2x observed p99) is
  duplicated to a second replica and the first response wins — the
  tail-at-scale answer to one replica having a bad moment.
* **Ejection** — a replica is pulled from routing when its heartbeat
  goes stale (missed ``2 x HVDT_SERVE_HEARTBEAT_S``), its health probe
  fails, its reported p99 breaches ``HVDT_SERVE_SLO_P99_MS``, or a
  dispatch to it fails; ejections sit out
  ``HVDT_SERVE_EJECT_COOLDOWN_S`` (doubling per repeat — the elastic
  blacklist-cooldown idiom, reusing
  :class:`runner.elastic.discovery.HostState`) and re-admit once the
  heartbeat is fresh again.

Chaos seam: every dispatch fires the ``serve.dispatch`` fault point
(``HVDT_FAULT_PLAN=serve_crash@point=serve.dispatch`` /
``slow_replica@...``), so the router is testable under the same
deterministic fault plans as the training stack.
"""

from __future__ import annotations

import http.client
import json
import queue
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple

from ..common import config
from ..common.logging_util import get_logger
from ..resilience import faults
from ..resilience.retry import Backoff
from ..runner.elastic.discovery import HostState
from .metrics import MetricsRegistry
from .replica import REPLICA_KV_PREFIX

__all__ = ["Router", "ReplicaView", "NoReplicaAvailable"]

log = get_logger(__name__)

# The SLO classes the continuous engine schedules (serve/llm/scheduler
# TENANTS) plus the bucket everything else lands in — a fixed set so
# request bodies can't mint unbounded label cardinality.
_TENANTS = ("interactive", "batch", "default")


class NoReplicaAvailable(RuntimeError):
    """No admitting replica in the routing set (all dead, draining, or
    ejected) — the router's 503."""


class ReplicaView:
    """The router's working state for one discovered replica."""

    def __init__(self, replica_id: int, eject_cooldown_s: float):
        self.id = replica_id
        self.doc: Dict[str, Any] = {}
        self.inflight = 0
        self.fail_streak = 0
        self.state = HostState(cooldown_s=eject_cooldown_s)
        self.ejected = False          # currently serving an eject cooldown
        self.last_seen = 0.0          # monotonic at last fresh heartbeat

    @property
    def host(self) -> str:
        return self.doc.get("host", "")

    @property
    def port(self) -> int:
        return int(self.doc.get("port", 0))

    @property
    def draining(self) -> bool:
        return bool(self.doc.get("draining"))

    def describe(self) -> Dict[str, Any]:
        return {
            "id": self.id, "host": self.host, "port": self.port,
            "inflight": self.inflight, "draining": self.draining,
            "ejected": self.state.is_blacklisted,
            "p99_ms": self.doc.get("p99_ms"),
            "queue_depth": self.doc.get("queue_depth"),
        }


class _Handler(BaseHTTPRequestHandler):
    router: "Router"
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):
        log.debug("router http: " + fmt, *args)

    def _reply(self, status: int, body: bytes,
               content_type: str = "application/json",
               extra_headers: Optional[dict] = None) -> None:
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        for k, v in (extra_headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        rt = self.router
        route = self.path.split("?")[0]
        if route == "/healthz":
            self._reply(200, json.dumps(rt.describe()).encode())
        elif route == "/metrics":
            self._reply(200, rt.metrics.render().encode(),
                        content_type="text/plain; version=0.0.4")
        else:
            self._reply(404, json.dumps(
                {"error": f"no route {self.path!r}"}).encode())

    def do_POST(self):
        rt = self.router
        t0 = time.perf_counter()
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length)   # always consume: keep-alive
        if self.path.split("?")[0] != "/predict":
            self._reply(404, json.dumps(
                {"error": f"no route {self.path!r}"}).encode())
            return
        retry_ok = self.headers.get("X-HVDT-No-Retry", "") not in ("1",
                                                                   "true")
        tenant = rt.tenant_of(body)
        try:
            status, payload, replica_id = rt.dispatch(body,
                                                      retry=retry_ok,
                                                      tenant=tenant)
        except NoReplicaAvailable as e:
            rt._no_replica.inc()
            self._reply(503, json.dumps({"error": str(e)}).encode(),
                        extra_headers={"Retry-After": "1"})
            rt._observe("predict", t0, 503, tenant=tenant)
            return
        headers = {}
        if replica_id is not None:
            headers["X-HVDT-Replica"] = str(replica_id)
        if status == 503:
            headers["Retry-After"] = "1"
        self._reply(status, payload, extra_headers=headers)
        rt._observe("predict", t0, status, tenant=tenant)


class _HTTPServer(ThreadingHTTPServer):
    daemon_threads = True
    request_queue_size = 256


class Router:
    """The assembled front tier.

    ``kv`` must expose the rendezvous server's ``lock``/``store`` (the
    router runs in the driver process, next to the
    :class:`~horovod_tpu.runner.http_kv.RendezvousServer`) — replica
    discovery is a prefix scan, which the KV's HTTP client surface does
    not offer.
    """

    def __init__(self, kv: Any, *,
                 host: Optional[str] = None, port: Optional[int] = None,
                 heartbeat_s: Optional[float] = None,
                 slo_p99_ms: Optional[float] = None,
                 hedge_ms: Optional[float] = None,
                 eject_cooldown_s: Optional[float] = None,
                 request_timeout_s: Optional[float] = None,
                 probe: bool = True,
                 metrics: Optional[MetricsRegistry] = None):
        if not (hasattr(kv, "lock") and hasattr(kv, "store")):
            raise TypeError("Router needs the rendezvous KV *server* "
                            "(lock/store) for replica prefix scans")
        self._kv = kv
        self.host = host if host is not None \
            else config.get_str("HVDT_SERVE_HOST")
        self.port = int(port if port is not None
                        else config.get_int("HVDT_SERVE_ROUTER_PORT"))
        self.heartbeat_s = float(
            heartbeat_s if heartbeat_s is not None
            else config.get_float("HVDT_SERVE_HEARTBEAT_S"))
        self.slo_p99_ms = float(
            slo_p99_ms if slo_p99_ms is not None
            else config.get_float("HVDT_SERVE_SLO_P99_MS"))
        self.hedge_ms = float(
            hedge_ms if hedge_ms is not None
            else config.get_float("HVDT_SERVE_HEDGE_MS"))
        self.eject_cooldown_s = float(
            eject_cooldown_s if eject_cooldown_s is not None
            else config.get_float("HVDT_SERVE_EJECT_COOLDOWN_S"))
        self.request_timeout_s = float(
            request_timeout_s if request_timeout_s is not None
            else config.get_float("HVDT_SERVE_REQUEST_TIMEOUT_S"))
        self._probe = probe
        self.metrics = metrics if metrics is not None else MetricsRegistry()
        m = self.metrics
        self._requests = m.counter(
            "hvdt_router_requests_total",
            "Requests through the router by route, upstream status and "
            "tenant class")
        self._latency = m.summary(
            "hvdt_router_request_latency_ms",
            "End-to-end router /predict latency (ms), retries and "
            "hedges included")
        # Per-tenant latency rides the same name-family idiom as the
        # continuous engine's hvdt_engine_wait_ms_<tenant> (a Summary
        # carries no labels); tenant classes come from the request body
        # the continuous engine already carries.
        self._tenant_latency = {
            t: m.summary(f"hvdt_router_request_latency_ms_{t}",
                         f"End-to-end /predict latency, {t} tenant (ms)")
            for t in _TENANTS}
        self._upstream = m.summary(
            "hvdt_router_upstream_latency_ms",
            "Single-attempt replica round-trip latency (ms) — feeds "
            "the adaptive hedge threshold")
        self._retries = m.counter(
            "hvdt_router_retries_total",
            "Dispatch attempts retried on another replica after a "
            "wire/5xx failure, by tenant")
        self._hedges = m.counter(
            "hvdt_router_hedges_total",
            "Hedge requests issued past the hedge threshold, by tenant")
        self._hedge_wins = m.counter(
            "hvdt_router_hedge_wins_total",
            "Hedge requests that answered before the primary, by tenant")
        self._ejections = m.counter(
            "hvdt_router_ejections_total",
            "Replicas pulled from routing, labelled reason="
            "heartbeat|probe|slo|dispatch and the tenant whose traffic "
            "triggered it (tenant=control for control-loop ejections)")
        self._readmissions = m.counter(
            "hvdt_router_readmissions_total",
            "Ejected replicas re-admitted after cooldown with a fresh "
            "heartbeat")
        self._no_replica = m.counter(
            "hvdt_router_no_replica_total",
            "Requests shed 503 because no admitting replica existed")
        m.gauge(
            "hvdt_router_replicas_live",
            "Replicas currently admitting traffic through the router"
        ).set_function(lambda: float(len(self._routable())))
        m.gauge(
            "hvdt_router_inflight",
            "Requests currently in flight through the router"
        ).set_function(lambda: float(self._inflight_total()))

        self._lock = threading.Lock()
        self._replicas: Dict[int, ReplicaView] = {}
        self._seq = 0
        self._stop = threading.Event()
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._http_thread: Optional[threading.Thread] = None
        self._control_thread: Optional[threading.Thread] = None

    # -- discovery / control ----------------------------------------------

    def _scan_kv(self) -> Dict[int, Dict[str, Any]]:
        with self._kv.lock:
            items = {k: v for k, v in self._kv.store.items()
                     if k.startswith(REPLICA_KV_PREFIX)}
        out: Dict[int, Dict[str, Any]] = {}
        for key, raw in items.items():
            try:
                rid = int(key[len(REPLICA_KV_PREFIX):])
                out[rid] = json.loads(raw.decode())
            except (ValueError, UnicodeDecodeError):
                continue
        return out

    def refresh(self) -> None:
        """One discovery pass: fold fresh heartbeats in, age out dead
        replicas, apply SLO ejection, count re-admissions."""
        docs = self._scan_kv()
        now = time.monotonic()
        liveness = 2.0 * self.heartbeat_s
        with self._lock:
            for rid, doc in docs.items():
                view = self._replicas.get(rid)
                if view is None:
                    view = ReplicaView(rid, self.eject_cooldown_s)
                    self._replicas[rid] = view
                    log.info("router: discovered replica %d at %s:%s",
                             rid, doc.get("host"), doc.get("port"))
                prev_ts = view.doc.get("ts")
                view.doc = doc
                if doc.get("ts") != prev_ts:
                    view.last_seen = now
                if view.last_seen == 0.0:
                    view.last_seen = now
            views = list(self._replicas.items())
        for rid, view in views:
            if rid not in docs or now - view.last_seen > liveness:
                # The replica left the KV (clean deregistration) or its
                # heartbeat went stale (it died without saying goodbye).
                # Remove it outright — a rejoin under the same id
                # re-enters through discovery.  Only the no-goodbye case
                # is an ejection event; a drained replica leaving is the
                # control plane working.
                with self._lock:
                    self._replicas.pop(rid, None)
                if rid not in docs and view.draining:
                    log.info("router: replica %d deregistered after "
                             "drain", rid)
                else:
                    self._ejections.inc(reason="heartbeat",
                                        tenant="control")
                    log.warning("router: replica %d heartbeat stale "
                                "(> %.1fs) — removed from routing",
                                rid, liveness)
                continue
            if view.ejected and not view.state.is_blacklisted:
                view.ejected = False
                view.fail_streak = 0
                self._readmissions.inc()
                log.info("router: replica %d re-admitted after eject "
                         "cooldown", rid)
            p99 = view.doc.get("p99_ms")
            if (self.slo_p99_ms > 0 and p99 and not view.ejected
                    and float(p99) > self.slo_p99_ms):
                self._eject(view, "slo",
                            f"reported p99 {float(p99):.1f}ms breaches "
                            f"SLO {self.slo_p99_ms:.1f}ms")

    def _eject(self, view: ReplicaView, reason: str, why: str,
               tenant: str = "control") -> None:
        view.state.blacklist()
        view.ejected = True
        self._ejections.inc(reason=reason, tenant=tenant)
        log.warning("router: ejecting replica %d (%s: %s; cooldown "
                    "%.1fs base)", view.id, reason, why,
                    self.eject_cooldown_s)

    def probe_replicas(self) -> None:
        """Active /healthz probes of routable replicas — catches a hung
        process whose heartbeat thread still beats."""
        for view in self._routable():
            try:
                conn = http.client.HTTPConnection(
                    view.host, view.port, timeout=max(1.0,
                                                      self.heartbeat_s))
                try:
                    conn.request("GET", "/healthz")
                    r = conn.getresponse()
                    r.read()
                    ok = r.status == 200
                finally:
                    conn.close()
            except (ConnectionError, OSError):
                ok = False
            if not ok:
                self._eject(view, "probe", "health probe failed")

    def _control_loop(self) -> None:
        period = max(0.05, self.heartbeat_s / 2.0)
        while not self._stop.wait(period):
            try:
                self.refresh()
                self._check_traffic_faults()
                if self._probe:
                    self.probe_replicas()
            except Exception:   # pragma: no cover - defensive
                log.exception("router control loop error")

    def _check_traffic_faults(self) -> None:
        """Fire the ``serve.traffic`` injection point (``traffic_spike``
        faults arm here; ``step`` = the dispatch count, matching the
        ``serve_crash`` convention) and account any open spike windows
        as synthetic offered load — the rps shows up in ``describe()``
        and the fleet scheduler's pressure picture, so a chaos plan can
        force a flash crowd without a load generator."""
        from ..resilience import faults

        inj = faults.get_injector()
        if inj is None:
            self.synthetic_rps = 0.0
            return
        with self._lock:
            seq = getattr(self, "_dispatch_seq", 0)
        inj.fire("serve.traffic", step=seq)
        self.synthetic_rps = inj.extra_rps()

    # -- routing -----------------------------------------------------------

    def _routable(self) -> List[ReplicaView]:
        with self._lock:
            return [v for v in self._replicas.values()
                    if v.doc and not v.draining
                    and not v.state.is_blacklisted]

    def _inflight_total(self) -> int:
        with self._lock:
            return sum(v.inflight for v in self._replicas.values())

    def _pick(self, exclude: Optional[set] = None
              ) -> Optional[ReplicaView]:
        """Least-inflight admitting replica (router-local view), ties
        broken by a rotating sequence so equal replicas share load."""
        candidates = [v for v in self._routable()
                      if not exclude or v.id not in exclude]
        if not candidates:
            return None
        with self._lock:
            self._seq += 1
            seq = self._seq
        return min(candidates,
                   key=lambda v: (v.inflight, (v.id + seq) % 997))

    def _hedge_delay(self) -> Optional[float]:
        """Seconds before a hedge fires, or None when hedging is off."""
        if self.hedge_ms < 0:
            return None
        if self.hedge_ms > 0:
            return self.hedge_ms / 1000.0
        # Adaptive: past ~2x the observed upstream p99, floored — but
        # only once there is enough signal to call anything "slow".
        if self._upstream.count < 20:
            return None
        p99 = self._upstream.quantile(0.99)
        if p99 is None:
            return None
        return max(0.05, 2.0 * p99 / 1000.0)

    def _forward_once(self, view: ReplicaView, body: bytes,
                      timeout: float) -> Tuple[int, bytes]:
        """One upstream round trip.  Raises ConnectionError/OSError on
        wire death (the retryable class); returns (status, payload)
        otherwise."""
        with self._lock:
            view.inflight += 1
        t0 = time.perf_counter()
        try:
            conn = http.client.HTTPConnection(view.host, view.port,
                                              timeout=timeout)
            try:
                conn.request("POST", "/predict", body=body,
                             headers={"Content-Type": "application/json"})
                r = conn.getresponse()
                payload = r.read()
                status = r.status
            finally:
                conn.close()
        except (ConnectionError, OSError):
            with self._lock:
                view.inflight -= 1
                view.fail_streak += 1
            raise
        ms = (time.perf_counter() - t0) * 1000.0
        self._upstream.observe(ms)
        with self._lock:
            view.inflight -= 1
            view.fail_streak = 0
        return status, payload

    def _forward_hedged(self, view: ReplicaView, body: bytes,
                        timeout: float, tenant: str = "default"
                        ) -> Tuple[int, bytes, int]:
        """Forward with tail hedging: fire a duplicate to a second
        replica past the hedge threshold; first completion wins, a
        failed first completion falls back to the other."""
        hedge_after = self._hedge_delay()
        if hedge_after is None or hedge_after >= timeout:
            status, payload = self._forward_once(view, body, timeout)
            return status, payload, view.id

        results: "queue.Queue" = queue.Queue()

        def attempt(v: ReplicaView, is_hedge: bool) -> None:
            try:
                results.put((v, self._forward_once(v, body, timeout),
                             None, is_hedge))
            except BaseException as e:
                results.put((v, None, e, is_hedge))

        threading.Thread(target=attempt, args=(view, False),
                         daemon=True).start()
        outstanding = 1
        deadline = time.monotonic() + timeout
        hedged = False
        first_err: Optional[BaseException] = None
        while outstanding > 0:
            budget = deadline - time.monotonic()
            if budget <= 0:
                break
            if not hedged:
                budget = min(budget, hedge_after)
            try:
                v, res, err, was_hedge = results.get(timeout=budget)
            except queue.Empty:
                if hedged:
                    break
                hedged = True
                second = self._pick(exclude={view.id})
                if second is None:
                    continue    # nobody to hedge to; keep waiting
                self._hedges.inc(tenant=tenant)
                threading.Thread(target=attempt, args=(second, True),
                                 daemon=True).start()
                outstanding += 1
                continue
            outstanding -= 1
            if err is None:
                # Any completed HTTP exchange wins the hedge race —
                # status handling (5xx retry-elsewhere) is dispatch()'s
                # job; the hedge only fights latency.
                status, payload = res
                if was_hedge:
                    self._hedge_wins.inc(tenant=tenant)
                return status, payload, v.id
            first_err = err
        if first_err is not None:
            raise first_err if isinstance(
                first_err, (ConnectionError, OSError)) else \
                ConnectionError(str(first_err))
        raise TimeoutError(f"no replica answered within "
                           f"{timeout:.1f}s")

    def dispatch(self, body: bytes, retry: bool = True,
                 tenant: Optional[str] = None
                 ) -> Tuple[int, bytes, Optional[int]]:
        """Route one /predict body.  Returns (status, payload,
        replica_id).  Raises :class:`NoReplicaAvailable` when the
        routing set is (and stays) empty."""
        if tenant is None:
            tenant = self.tenant_of(body)
        inj = faults.get_injector()
        if inj is not None:
            with self._lock:
                self._dispatch_seq = getattr(self, "_dispatch_seq", 0) + 1
                seq = self._dispatch_seq
            inj.fire("serve.dispatch", step=seq)
        deadline = time.monotonic() + self.request_timeout_s
        backoff = Backoff(first=0.02, cap=0.25,
                          deadline_s=self.request_timeout_s)
        tried: set = set()
        last_status: Optional[Tuple[int, bytes, int]] = None
        while True:
            view = self._pick(exclude=tried)
            if view is None and tried:
                # Every distinct replica failed once; widen back out —
                # a respawn/readmission may have landed meanwhile.
                tried = set()
                view = self._pick()
            if view is None:
                if time.monotonic() >= deadline or not backoff.sleep():
                    raise NoReplicaAvailable(
                        "no admitting replica (all dead, draining, or "
                        "ejected)")
                continue
            try:
                status, payload, rid = self._forward_hedged(
                    view, body, max(0.05, deadline - time.monotonic()),
                    tenant=tenant)
            except (ConnectionError, OSError, TimeoutError) as e:
                # Wire death mid-request: the replica is suspect — eject
                # (cooldown applies) and retry the request elsewhere.
                # This is THE zero-dropped-request path for a crash.
                if isinstance(e, (ConnectionError, OSError)):
                    self._eject(view, "dispatch", repr(e), tenant=tenant)
                tried.add(view.id)
                if not retry or time.monotonic() >= deadline:
                    return 502, json.dumps(
                        {"error": f"replica {view.id} failed: {e}"}
                    ).encode(), view.id
                self._retries.inc(tenant=tenant)
                backoff.sleep()
                continue
            if status >= 500 or status == 503:
                # Upstream said no (draining 503, engine 5xx): retryable
                # on another replica within the budget.
                last_status = (status, payload, rid)
                tried.add(view.id)
                if not retry or time.monotonic() >= deadline:
                    return last_status
                self._retries.inc(tenant=tenant)
                if not backoff.sleep():
                    return last_status
                continue
            return status, payload, rid

    # -- HTTP front --------------------------------------------------------

    @staticmethod
    def tenant_of(body: bytes) -> str:
        """The request's tenant class for metric attribution: the
        ``tenant`` field the continuous engine carries in the /predict
        JSON, folded into the fixed class set.  Bodies without one (the
        static engine, non-JSON payloads) attribute to ``default`` —
        and skip the JSON parse entirely."""
        if b'"tenant"' not in body:
            return "default"
        try:
            t = json.loads(body.decode("utf-8", "replace")).get("tenant")
        except (ValueError, AttributeError):
            return "default"
        return t if t in _TENANTS else "default"

    def _observe(self, route: str, t0: float, status: int,
                 tenant: str = "default") -> None:
        ms = (time.perf_counter() - t0) * 1000.0
        self._latency.observe(ms)
        lat = self._tenant_latency.get(tenant)
        if lat is not None:
            lat.observe(ms)
        self._requests.inc(route=route, status=str(status),
                           tenant=tenant)

    def describe(self) -> Dict[str, Any]:
        with self._lock:
            views = list(self._replicas.values())
        routable = {v.id for v in self._routable()}
        return {
            "status": "ok" if routable else "degraded",
            "replicas": [v.describe() for v in views],
            "routable": sorted(routable),
            "slo_p99_ms": self.slo_p99_ms,
            "synthetic_rps": getattr(self, "synthetic_rps", 0.0),
        }

    def start(self) -> int:
        handler = type("Handler", (_Handler,), {"router": self})
        self._httpd = _HTTPServer((self.host, self.port), handler)
        self.port = self._httpd.server_address[1]
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, name="hvdt-router-http",
            daemon=True)
        self._http_thread.start()
        self.refresh()
        self._control_thread = threading.Thread(
            target=self._control_loop, name="hvdt-router-control",
            daemon=True)
        self._control_thread.start()
        log.info("router on http://%s:%d (slo_p99_ms=%s)", self.host,
                 self.port, self.slo_p99_ms or "off")
        return self.port

    def stop(self) -> None:
        self._stop.set()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        for t in (self._http_thread, self._control_thread):
            if t is not None:
                t.join(timeout=5)
        self._http_thread = self._control_thread = None
