"""``python -m horovod_tpu.serve`` / ``hvdtrun serve`` — serve a
checkpointed model over HTTP.

Minimal deploy::

    python -m horovod_tpu.serve --checkpoint /ckpts --model mlp \
        --mlp-sizes 784,256,128,10 --port 8000

The checkpoint directory is a ``CheckpointManager`` tree (``step_NNN/``
subdirectories, as written by training); the newest step is loaded at
startup and newer steps are hot-swapped in while serving (--reload-interval).
Flag defaults come from the ``HVDT_SERVE_*`` knobs, so a launcher can
configure a fleet purely through the env contract.
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional

__all__ = ["main", "parse_args", "build_server"]


def parse_args(argv: Optional[List[str]] = None) -> argparse.Namespace:
    p = argparse.ArgumentParser(
        prog="python -m horovod_tpu.serve",
        description="Serve a checkpointed model over HTTP "
                    "(/predict, /healthz, /metrics).")
    p.add_argument("--checkpoint", required=True,
                   help="CheckpointManager directory (holds step_NNN/ "
                        "subdirectories).")
    p.add_argument("--model", choices=("mlp", "transformer"), default="mlp")
    p.add_argument("--mlp-sizes", default="784,256,128,10",
                   help="Comma layer sizes for --model mlp.")
    p.add_argument("--vocab", type=int, default=32000)
    p.add_argument("--layers", type=int, default=4)
    p.add_argument("--d-model", type=int, default=512)
    p.add_argument("--heads", type=int, default=8)
    p.add_argument("--d-ff", type=int, default=2048)
    p.add_argument("--seq", type=int, default=128,
                   help="Serving sequence length for --model transformer.")
    p.add_argument("--host", default=None,
                   help="Bind address (default: HVDT_SERVE_HOST).")
    p.add_argument("--port", type=int, default=None,
                   help="Bind port, 0 = ephemeral (default: "
                        "HVDT_SERVE_PORT).")
    p.add_argument("--buckets", default=None,
                   help="Comma batch-size bucket ladder (default: "
                        "HVDT_SERVE_BUCKETS).")
    p.add_argument("--engine", choices=("static", "continuous"),
                   default=None,
                   help="Inference engine: 'static' shape buckets or the "
                        "'continuous' paged-KV LLM decode engine "
                        "(transformer only; default: HVDT_SERVE_ENGINE).")
    p.add_argument("--max-batch-size", type=int, default=None)
    p.add_argument("--max-delay-ms", type=float, default=None)
    p.add_argument("--max-queue-depth", type=int, default=None)
    p.add_argument("--reload-interval", type=float, default=None,
                   help="Seconds between checkpoint polls (default: "
                        "HVDT_SERVE_RELOAD_INTERVAL_S).")
    p.add_argument("--compilation-cache-dir", default=None,
                   help="Persistent XLA compile cache (restart reuses "
                        "compiled buckets).")
    p.add_argument("--no-warmup", action="store_true",
                   help="Skip pre-compiling every bucket at startup.")
    # --- elastic serving control plane (serve/autoscale.py + router) ---
    p.add_argument("--replicas", type=int, default=None,
                   help="Run the elastic serving control plane with this "
                        "many replicas behind the router (default: "
                        "HVDT_SERVE_REPLICAS; omit for the single-"
                        "replica direct server).")
    p.add_argument("--max-replicas", type=int, default=None,
                   help="Replica ceiling for the autoscaler / localhost "
                        "slot count (default: HVDT_SERVE_MAX_REPLICAS).")
    p.add_argument("--autoscale", action="store_true",
                   help="Enable the replica autoscaler (queue depth / "
                        "p99-vs-SLO from the KV heartbeats; implies the "
                        "elastic control plane).")
    p.add_argument("--slo-p99-ms", type=float, default=None,
                   help="p99 latency SLO in ms: the router ejects "
                        "breaching replicas, the autoscaler scales "
                        "while the fleet breaches (default: "
                        "HVDT_SERVE_SLO_P99_MS; 0 = off).")
    p.add_argument("--router-port", type=int, default=None,
                   help="Router bind port (default: "
                        "HVDT_SERVE_ROUTER_PORT; 0 = ephemeral).")
    p.add_argument("--host-discovery-script", default=None,
                   help="Discovery executable printing host[:slots]"
                        "[@pod] lines for the replica fleet (default: "
                        "localhost with --max-replicas slots).")
    p.add_argument("--target-file", default=None,
                   help="Operator override: a file holding the desired "
                        "replica count, polled by the driver (echo 3 > "
                        "FILE resizes the fleet; remove to hand control "
                        "back to the autoscaler).")
    # Internal: set by the serve driver on spawned replica workers
    # (rendezvous env contract; heartbeats, drains, exits 83).
    p.add_argument("--replica-worker", action="store_true",
                   help=argparse.SUPPRESS)
    return p.parse_args(argv)


_CONTROL_FLAGS = {"--replicas": 1, "--max-replicas": 1, "--autoscale": 0,
                  "--slo-p99-ms": 1, "--router-port": 1,
                  "--host-discovery-script": 1, "--target-file": 1,
                  "--replica-worker": 0}


def strip_control_flags(argv):
    """The serve argv minus the control-plane flags — what the driver
    hands each spawned replica worker (which adds --replica-worker)."""
    out, skip = [], 0
    for tok in argv:
        if skip:
            skip -= 1
            continue
        flag = tok.split("=", 1)[0]
        if flag in _CONTROL_FLAGS:
            skip = _CONTROL_FLAGS[flag] if "=" not in tok else 0
            continue
        out.append(tok)
    return out


def build_server(args):
    """Assemble (server, feature_shape) from parsed args — split out so
    tests and bench.py can drive the exact CLI path in-process."""
    import jax
    import numpy as np

    from ..common import config
    from .engine import InferenceEngine, parse_buckets
    from .server import ModelServer

    engine_kind = (args.engine if getattr(args, "engine", None)
                   else config.get_str("HVDT_SERVE_ENGINE"))
    if engine_kind not in ("static", "continuous"):
        raise ValueError(f"HVDT_SERVE_ENGINE={engine_kind!r}: expected "
                         "'static' or 'continuous'")
    if engine_kind == "continuous" and args.model != "transformer":
        raise ValueError("--engine continuous requires --model "
                         "transformer (paged KV decode is an LLM path)")
    buckets = parse_buckets(args.buckets)
    if args.model == "mlp":
        from ..models.mlp import mlp_apply, mlp_init

        sizes = [int(s) for s in args.mlp_sizes.split(",")]
        template = mlp_init(jax.random.PRNGKey(0), sizes)
        apply_fn, feat_shape = mlp_apply, (sizes[0],)
        input_dtype = np.float32
    else:
        from ..models.transformer import (TransformerConfig,
                                          transformer_apply,
                                          transformer_init)

        cfg = TransformerConfig(vocab=args.vocab, layers=args.layers,
                                d_model=args.d_model, heads=args.heads,
                                kv_heads=args.heads, d_ff=args.d_ff,
                                max_seq=args.seq)
        template = transformer_init(jax.random.PRNGKey(0), cfg)
        apply_fn = lambda p, x: transformer_apply(p, x, cfg)  # noqa: E731
        feat_shape = (args.seq,)
        input_dtype = np.int32

    if engine_kind == "continuous":
        from .llm import ContinuousLLMEngine

        engine = ContinuousLLMEngine(
            template, cfg, compile_cache=args.compilation_cache_dir)
    else:
        engine = InferenceEngine(apply_fn, template, buckets=buckets,
                                 compile_cache=args.compilation_cache_dir)
    server = ModelServer(
        engine, host=args.host, port=args.port,
        checkpoint_dir=args.checkpoint, template=template,
        max_batch_size=args.max_batch_size,
        max_delay_ms=args.max_delay_ms,
        max_queue_depth=args.max_queue_depth,
        input_dtype=input_dtype)
    if server.watcher is not None and args.reload_interval is not None:
        server.watcher.poll_interval_s = float(args.reload_interval)
    return server, feat_shape


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:]) if argv is None else list(argv)
    args = parse_args(argv)
    if args.replica_worker:
        # One replica under the serving driver: heartbeat into the
        # rendezvous KV, serve until drained, exit 83 for clean removal.
        from .replica import run_replica

        return run_replica(args)
    if args.replicas is not None or args.autoscale:
        # The elastic serving control plane: driver + replica fleet +
        # router in this process group (serve/autoscale.py).
        from .autoscale import run_serve_elastic

        return run_serve_elastic(args, strip_control_flags(argv))
    server, feat_shape = build_server(args)
    # Load the newest checkpoint BEFORE binding: a replica that cannot
    # find weights should say so immediately, then (deliberately) still
    # come up on the init template — a smoke deploy with an empty
    # directory is a supported first-run path.
    loaded = server.watcher.check_once() if server.watcher else None
    if loaded is None and (server.watcher is None
                           or server.watcher.current_step is None):
        print(f"serve: no checkpoint under {args.checkpoint!r} yet — "
              "serving freshly-initialized weights until one appears",
              file=sys.stderr)
    if not args.no_warmup:
        dtype = server.input_dtype
        import numpy as np

        server.engine.warmup(feat_shape, dtype=np.dtype(dtype))
    port = server.start()
    print(f"serving {args.model} on http://{server.host}:{port} "
          f"(buckets={list(server.engine.buckets)}, "
          f"checkpoint={args.checkpoint})", file=sys.stderr)
    try:
        while True:
            import time

            time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
