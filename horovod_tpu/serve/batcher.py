"""Dynamic micro-batcher: coalesce concurrent requests into bucket-shaped
batches.

An endpoint's requests arrive one at a time; the chip wants them 32 at a
time.  The batcher is the standard serving answer (TF-Serving's
``BatchingSession``, Triton's dynamic batcher): admit requests into a
bounded queue, have ONE dispatch thread gather everything waiting — up to
``max_batch_size`` rows or ``max_delay_ms`` of linger for the first
request — and run them through the engine as a single padded-bucket
batch.  The linger bound caps the latency cost of batching; the row bound
caps the padding waste; the queue bound is the admission control valve:
past it, :meth:`submit` raises :class:`BackpressureError` *immediately*
(the server maps it to HTTP 503) instead of letting the queue grow into
an OOM — shed load at the door, not in the kernel.

Requests within one gather are grouped by feature shape/dtype (different
shapes cannot concatenate); each group is one engine call, and results
are sliced back per request.  The dispatch thread is the only engine
caller, so device execution is naturally serialized — the concurrency
lives in the waiting futures, not in racing dispatches.
"""

from __future__ import annotations

import collections
import concurrent.futures
import threading
import time
from typing import Any, Callable, Deque, List, Optional

import numpy as np

from ..common import config
from ..common.logging_util import get_logger
from .metrics import MetricsRegistry

__all__ = ["DynamicBatcher", "BackpressureError", "DispatcherDied",
           "RequestDeadlineExceeded"]

log = get_logger(__name__)


class BackpressureError(RuntimeError):
    """Raised by submit() when the bounded queue is full — the caller
    should shed the request (HTTP 503), not wait."""


class DispatcherDied(RuntimeError):
    """The batcher's dispatch thread is gone (killed by a catastrophic
    error, or the batcher was torn down under the caller — e.g. the
    router ejecting this replica mid-flight).  Raised by submit() and
    set on every still-pending future so HTTP handlers fail fast
    instead of parking on a future nobody will ever complete."""


class RequestDeadlineExceeded(TimeoutError):
    """Set on a request's future when its per-request deadline expired
    before (or while) the dispatch thread got to it — the batcher-side
    half of the server's 504, so a stalled engine cannot strand handler
    threads forever."""


class _Request:
    __slots__ = ("x", "future", "enqueued_at", "deadline")

    def __init__(self, x: np.ndarray, deadline_s: Optional[float] = None):
        self.x = x
        self.future: "concurrent.futures.Future" = concurrent.futures.Future()
        self.enqueued_at = time.perf_counter()
        self.deadline = (self.enqueued_at + deadline_s
                         if deadline_s and deadline_s > 0 else None)

    def expired(self, now: Optional[float] = None) -> bool:
        return self.deadline is not None and \
            (now if now is not None else time.perf_counter()) > self.deadline

    def fail(self, exc: BaseException) -> None:
        if not self.future.cancelled() and not self.future.done():
            self.future.set_exception(exc)


class DynamicBatcher:
    """Bounded-queue micro-batcher in front of an ``infer(x)->y`` callable.

    Parameters default to the ``HVDT_SERVE_*`` knobs.  ``max_batch_size``
    counts *rows* (a request may carry several rows); a single oversized
    request still dispatches — the engine chunks it.
    """

    def __init__(self, infer_fn: Callable[[np.ndarray], np.ndarray], *,
                 max_batch_size: Optional[int] = None,
                 max_delay_ms: Optional[float] = None,
                 max_queue_depth: Optional[int] = None,
                 deadline_s: Optional[float] = None,
                 metrics: Optional[MetricsRegistry] = None):
        self._infer = infer_fn
        # Per-request deadline: a request the dispatch thread cannot get
        # to in time fails fast (RequestDeadlineExceeded) instead of
        # holding its handler thread behind a stalled engine.  Defaults
        # to the server's request timeout so the batcher gives up no
        # later than the HTTP layer would.
        self.deadline_s = float(
            deadline_s if deadline_s is not None
            else config.get_float("HVDT_SERVE_REQUEST_TIMEOUT_S"))
        self.max_batch_size = int(
            max_batch_size if max_batch_size is not None
            else config.get_int("HVDT_SERVE_MAX_BATCH_SIZE"))
        self.max_delay_s = float(
            max_delay_ms if max_delay_ms is not None
            else config.get_float("HVDT_SERVE_MAX_DELAY_MS")) / 1000.0
        self.max_queue_depth = int(
            max_queue_depth if max_queue_depth is not None
            else config.get_int("HVDT_SERVE_MAX_QUEUE_DEPTH"))
        self.metrics = metrics if metrics is not None else MetricsRegistry()
        self._queue_gauge = self.metrics.gauge(
            "serve_queue_depth", "Requests admitted and not yet dispatched")
        self._queue_gauge.set_function(self.queue_depth)
        self._rejected = self.metrics.counter(
            "serve_rejected_total",
            "Requests shed by admission control (queue full -> 503)")
        self._requests = self.metrics.counter(
            "serve_requests_total", "Requests admitted to the batch queue")
        self._batches = self.metrics.counter(
            "serve_batches_total", "Dispatched micro-batches")
        self._fill = self.metrics.summary(
            "serve_batch_fill",
            "Rows per dispatched batch / max_batch_size (how full "
            "micro-batches run)")
        self._wait = self.metrics.summary(
            "serve_queue_wait_seconds", "Admission-to-dispatch queue wait")
        self._expired = self.metrics.counter(
            "serve_deadline_expired_total",
            "Requests failed with RequestDeadlineExceeded before "
            "dispatch completed")

        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._pending: Deque[_Request] = collections.deque()
        self._closed = False
        self._stopped = threading.Event()
        self._thread = threading.Thread(target=self._dispatch_loop,
                                        name="hvdt-serve-batcher",
                                        daemon=True)
        self._thread.start()
        # Deadline watchdog: the dispatch loop expires queued requests
        # when it runs, but a dispatch thread WEDGED inside the engine
        # never runs — the watchdog is what keeps the deadline promise
        # then (fail fast beats a handler parked forever).
        self._watchdog: Optional[threading.Thread] = None
        if self.deadline_s > 0:
            self._watchdog = threading.Thread(
                target=self._watchdog_loop, name="hvdt-serve-deadline",
                daemon=True)
            self._watchdog.start()

    # ---- client side ----------------------------------------------------
    def queue_depth(self) -> int:
        with self._lock:
            return sum(r.x.shape[0] for r in self._pending)

    def submit(self, x) -> "concurrent.futures.Future":
        """Admit one request (``[rows, ...feature]``); returns a Future of
        the per-request output.  Raises :class:`BackpressureError` when
        the queue is at bound, ``RuntimeError`` after close()."""
        x = np.asarray(x)
        if x.ndim < 1 or x.shape[0] == 0:
            raise ValueError(f"request needs >=1 rows, got shape {x.shape}")
        req = _Request(x, deadline_s=self.deadline_s)
        with self._lock:
            if self._closed:
                raise RuntimeError("batcher is closed")
            if not self._thread.is_alive():
                # Liveness check: a dead dispatch thread means this
                # future would never complete — refuse admission with
                # the typed error instead of hanging the handler.
                raise DispatcherDied("batcher dispatch thread is dead")
            depth = sum(r.x.shape[0] for r in self._pending)
            if depth + x.shape[0] > self.max_queue_depth:
                self._rejected.inc()
                raise BackpressureError(
                    f"queue at bound ({depth}/{self.max_queue_depth} rows)")
            self._pending.append(req)
            self._requests.inc()
            self._not_empty.notify()
        return req.future

    def infer(self, x, timeout: Optional[float] = None) -> np.ndarray:
        """Blocking convenience wrapper over submit()."""
        return self.submit(x).result(timeout=timeout)

    # ---- dispatch side --------------------------------------------------
    def _expire_pending(self) -> int:
        """Fail every queued request past its deadline (typed).  Shared
        by the watchdog and close(); the gather loop does the same
        inline at pop time."""
        now = time.perf_counter()
        with self._lock:
            expired = [r for r in self._pending if r.expired(now)]
            for r in expired:
                self._pending.remove(r)
        for r in expired:
            self._expired.inc()
            r.fail(RequestDeadlineExceeded(
                f"request waited past its {self.deadline_s:.1f}s "
                f"deadline"))
        return len(expired)

    def _watchdog_loop(self) -> None:
        period = max(0.05, min(0.5, self.deadline_s / 4.0))
        while not self._stopped.wait(period):
            self._expire_pending()

    def _gather(self) -> List[_Request]:
        """Block for the first request, linger up to max_delay_s for more,
        then take up to max_batch_size rows (never splitting a request)."""
        with self._not_empty:
            while not self._pending and not self._closed:
                self._not_empty.wait(timeout=0.1)
            if not self._pending:
                return []
            deadline = (self._pending[0].enqueued_at + self.max_delay_s)
            while True:
                rows = sum(r.x.shape[0] for r in self._pending)
                remaining = deadline - time.perf_counter()
                if rows >= self.max_batch_size or remaining <= 0 \
                        or self._closed:
                    break
                self._not_empty.wait(timeout=remaining)
            batch: List[_Request] = []
            rows = 0
            now = time.perf_counter()
            while self._pending:
                nxt_req = self._pending[0]
                if nxt_req.expired(now):
                    # Fail fast at the dispatch seam: the handler that
                    # submitted this is (or will shortly be) giving up;
                    # running it anyway would burn a chip batch slot on
                    # an answer nobody reads.
                    self._pending.popleft()
                    self._expired.inc()
                    nxt_req.fail(RequestDeadlineExceeded(
                        f"request waited past its {self.deadline_s:.1f}s "
                        f"deadline"))
                    continue
                nxt = nxt_req.x.shape[0]
                if batch and rows + nxt > self.max_batch_size:
                    break
                rows += nxt
                batch.append(self._pending.popleft())
            return batch

    def _dispatch(self, batch: List[_Request]) -> None:
        try:
            self._dispatch_groups(batch)
        except BaseException as e:
            # A non-Exception (SystemExit, KeyboardInterrupt, ...) is
            # taking the dispatch thread down mid-batch: every popped
            # request that has no result yet must be failed HERE — they
            # left _pending, so no other path can reach them.
            for r in batch:
                if not r.future.done() and not r.future.cancelled():
                    r.future.set_exception(DispatcherDied(
                        f"dispatch thread dying mid-batch: {e!r}"))
            raise

    def _dispatch_groups(self, batch: List[_Request]) -> None:
        now = time.perf_counter()
        for r in batch:
            self._wait.observe(now - r.enqueued_at)
        # Deadline re-check at dispatch time: _gather expires requests
        # when it pops them, but a request can outlive its deadline
        # BETWEEN gather and here (a slow linger window, a long compile
        # on the previous group) — running it anyway would burn a batch
        # slot on an answer nobody is waiting for.
        live: List[_Request] = []
        for r in batch:
            if r.expired(now):
                self._expired.inc()
                r.fail(RequestDeadlineExceeded(
                    f"request expired after gather, before dispatch "
                    f"(waited {now - r.enqueued_at:.3f}s)"))
            else:
                live.append(r)
        batch = live
        # Group by feature signature: only same-shaped rows concatenate.
        groups: "collections.OrderedDict[Any, List[_Request]]" = \
            collections.OrderedDict()
        for r in batch:
            groups.setdefault((r.x.shape[1:], r.x.dtype.str), []).append(r)
        for _, reqs in groups.items():
            rows = sum(r.x.shape[0] for r in reqs)
            self._batches.inc()
            self._fill.observe(rows / float(self.max_batch_size))
            try:
                x = (reqs[0].x if len(reqs) == 1
                     else np.concatenate([r.x for r in reqs], axis=0))
                y = np.asarray(self._infer(x))
            except Exception as e:
                for r in reqs:
                    if not r.future.cancelled():
                        r.future.set_exception(e)
                continue
            off = 0
            for r in reqs:
                n = r.x.shape[0]
                if not r.future.cancelled():
                    r.future.set_result(y[off:off + n])
                off += n

    def _dispatch_loop(self) -> None:
        try:
            while True:
                batch = self._gather()
                if not batch:
                    with self._lock:
                        if self._closed and not self._pending:
                            return
                    continue
                try:
                    self._dispatch(batch)
                except Exception:    # defensive: the loop must never die
                    log.exception("serve batcher dispatch failed")
        except BaseException as e:
            # The loop itself died (MemoryError, interpreter teardown,
            # anything past the per-batch guard).  Every parked future
            # must learn about it NOW — an HTTP handler waiting on one
            # of these would otherwise hang until its own timeout, and
            # callers without a timeout would hang forever.
            self.fail_pending(DispatcherDied(
                f"batcher dispatch thread died: {e!r}"))
            raise

    def fail_pending(self, exc: Optional[BaseException] = None) -> int:
        """Fail every admitted-but-unfinished request with ``exc``
        (default :class:`DispatcherDied`).  Used by the dispatch loop's
        crash path and by owners abandoning the batcher wholesale (a
        router ejecting this replica, a drain that ran out of grace).
        Returns the number of futures failed."""
        exc = exc if exc is not None else DispatcherDied(
            "batcher abandoned with requests in flight")
        with self._lock:
            doomed = list(self._pending)
            self._pending.clear()
        n = 0
        for r in doomed:
            r.fail(exc)
            n += 1
        return n

    def close(self, timeout: float = 10.0) -> None:
        """Stop admitting; drain what's queued; join the thread.  If the
        drain does not finish inside ``timeout`` the leftover futures
        are failed (typed) rather than abandoned."""
        with self._lock:
            self._closed = True
            self._not_empty.notify_all()
        self._thread.join(timeout=timeout)
        self._stopped.set()
        if not self._thread.is_alive():
            # Normal exit path: nothing should remain, but a dispatch
            # loop killed between gather and dispatch leaves strays.
            self.fail_pending()
            return
        n = self.fail_pending(DispatcherDied(
            f"batcher close() timed out after {timeout}s with requests "
            f"in flight"))
        if n:
            log.warning("serve batcher close: failed %d in-flight "
                        "request(s) after drain timeout", n)
