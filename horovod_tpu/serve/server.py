"""HTTP serving front end: /predict, /healthz, /metrics, 503 backpressure.

Stdlib-only (``http.server.ThreadingHTTPServer``) — the serving plane
must not grow dependencies the training container doesn't have, and a
thread-per-connection front end is exactly right for this architecture:
handler threads only parse JSON and park on a batcher future; the real
concurrency problem (coalescing requests into chip-shaped batches) is the
batcher's, and admission control is enforced *before* any memory is
committed to a request's batch slot.

Routes:

* ``POST /predict``  — body ``{"inputs": [[...], ...]}`` (one row per
  inner list).  200 → ``{"outputs": [...], "model_version": N}``;
  503 + ``Retry-After`` when admission control sheds the request;
  400 on malformed bodies; 504 when a request exceeds its deadline.
* ``GET /healthz``   — liveness/readiness: 200 once the engine has
  weights, with the served checkpoint step and params version.
* ``GET /metrics``   — Prometheus text (latency summaries per route,
  queue depth, batch fill, compile / reload counters).
"""

from __future__ import annotations

import concurrent.futures
import json
import signal
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Optional

import numpy as np

from ..common import config
from ..common.logging_util import get_logger
from ..resilience import faults
from .batcher import (BackpressureError, DispatcherDied, DynamicBatcher,
                      RequestDeadlineExceeded)
from .engine import InferenceEngine
from .metrics import MetricsRegistry
from .reload import CheckpointWatcher

__all__ = ["ModelServer"]

log = get_logger(__name__)


class _Handler(BaseHTTPRequestHandler):
    # Set by ModelServer on the subclass it builds.
    server_ref: "ModelServer"
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # route access logs to our logger
        log.debug("serve http: " + fmt, *args)

    # ---- helpers --------------------------------------------------------
    def _reply(self, status: int, payload: Any,
               content_type: str = "application/json",
               extra_headers: Optional[dict] = None) -> None:
        body = (payload if isinstance(payload, bytes)
                else json.dumps(payload).encode())
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        for k, v in (extra_headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _observe(self, route: str, t0: float, status: int) -> None:
        ms = (time.perf_counter() - t0) * 1000.0
        srv = self.server_ref
        srv.metrics.summary(
            f"serve_request_latency_ms_{route}",
            f"End-to-end {route} handler latency (ms)").observe(ms)
        srv.metrics.counter(
            "serve_http_responses_total",
            "HTTP responses by route and status").inc(
                route=route, status=str(status))

    # ---- routes ---------------------------------------------------------
    def do_GET(self):
        srv = self.server_ref
        t0 = time.perf_counter()
        if self.path.split("?")[0] == "/healthz":
            payload = {
                # "draining" tells the router/registrar to stop sending
                # work while in-flight batches finish; still HTTP 200 —
                # a draining replica is healthy, just leaving.
                "status": "draining" if srv.draining else "ok",
                "model_version": srv.engine.params_version,
                "checkpoint_step": (srv.watcher.current_step
                                    if srv.watcher else None),
                "buckets": list(srv.engine.buckets),
                "engine": ("continuous" if srv.continuous else "static"),
            }
            self._reply(200, payload)
            self._observe("healthz", t0, 200)
        elif self.path.split("?")[0] == "/metrics":
            self._reply(200, srv.metrics.render().encode(),
                        content_type="text/plain; version=0.0.4")
            self._observe("metrics", t0, 200)
        else:
            self._reply(404, {"error": f"no route {self.path!r}"})

    def do_POST(self):
        if self.path.split("?")[0] != "/predict":
            self._reply(404, {"error": f"no route {self.path!r}"})
            return
        srv = self.server_ref
        t0 = time.perf_counter()
        # Admission gate BEFORE any parsing work: a draining replica
        # sheds with a retryable 503 (the router re-routes; a direct
        # client honors Retry-After) — never a dropped connection.  The
        # body is still consumed: leaving it unread would desync the
        # keep-alive stream (the next "request line" would be JSON).
        if srv.draining:
            self.rfile.read(int(self.headers.get("Content-Length", 0)))
            self._reply(503, {"error": "replica draining"},
                        extra_headers={"Retry-After": "1"})
            self._observe("predict", t0, 503)
            return
        srv._inflight_enter()
        try:
            self._do_predict(srv, t0)
        finally:
            srv._inflight_exit()

    def _do_predict(self, srv: "ModelServer", t0: float) -> None:
        if srv.continuous:
            self._do_predict_llm(srv, t0)
            return
        try:
            length = int(self.headers.get("Content-Length", 0))
            doc = json.loads(self.rfile.read(length))
            inputs = np.asarray(doc["inputs"], dtype=srv.input_dtype)
            if inputs.ndim < 1 or inputs.shape[0] == 0:
                raise ValueError("inputs must hold >= 1 rows")
        except (ValueError, KeyError, TypeError, json.JSONDecodeError) as e:
            self._reply(400, {"error": f"bad request: {e}"})
            self._observe("predict", t0, 400)
            return
        # Serving-plane chaos seam: serve_crash / slow_replica fire here
        # (step = this replica's admitted-request count) so replica
        # death and degradation are injected mid-request, exactly where
        # production failures land.
        inj = faults.get_injector()
        if inj is not None:
            inj.fire("serve.predict", step=srv.request_seq())
        try:
            version = srv.engine.params_version
            future = srv.batcher.submit(inputs)
        except BackpressureError as e:
            self._reply(503, {"error": str(e)},
                        extra_headers={"Retry-After": "1"})
            self._observe("predict", t0, 503)
            return
        except DispatcherDied as e:
            # Typed, retryable: this replica's dispatch plane is gone
            # (dying or torn down) — tell the caller to go elsewhere.
            self._reply(503, {"error": str(e)},
                        extra_headers={"Retry-After": "1"})
            self._observe("predict", t0, 503)
            return
        try:
            outputs = future.result(timeout=srv.request_timeout_s)
        except (concurrent.futures.TimeoutError, TimeoutError,
                RequestDeadlineExceeded):
            future.cancel()
            self._reply(504, {"error": "deadline exceeded"})
            self._observe("predict", t0, 504)
            return
        except DispatcherDied as e:
            self._reply(503, {"error": str(e)},
                        extra_headers={"Retry-After": "1"})
            self._observe("predict", t0, 503)
            return
        except Exception as e:
            self._reply(500, {"error": f"inference failed: {e}"})
            self._observe("predict", t0, 500)
            return
        self._reply(200, {"outputs": np.asarray(outputs).tolist(),
                          "model_version": version})
        self._observe("predict", t0, 200)

    def _do_predict_llm(self, srv: "ModelServer", t0: float) -> None:
        """Continuous-engine predict: rows are token-id prompts; the
        response carries the generated token ids per row.  Same status
        contract as the batcher path (400/503/504/500), so the router
        and autoscaler need no engine awareness."""
        try:
            length = int(self.headers.get("Content-Length", 0))
            doc = json.loads(self.rfile.read(length))
            prompts = doc["inputs"]
            if (not isinstance(prompts, list) or not prompts
                    or not all(isinstance(p, list) and p
                               for p in prompts)):
                raise ValueError("inputs must hold >= 1 non-empty "
                                 "token-id rows")
            max_new = doc.get("max_new_tokens")
            tenant = doc.get("tenant", "interactive")
        except (ValueError, KeyError, TypeError, json.JSONDecodeError) as e:
            self._reply(400, {"error": f"bad request: {e}"})
            self._observe("predict", t0, 400)
            return
        inj = faults.get_injector()
        if inj is not None:
            inj.fire("serve.predict", step=srv.request_seq())
        try:
            version = srv.engine.params_version
            futures = [
                srv.engine.submit(
                    [int(t) for t in p],
                    max_new_tokens=(int(max_new) if max_new else None),
                    tenant=tenant,
                    deadline_s=srv.request_timeout_s)
                for p in prompts]
        except BackpressureError as e:
            self._reply(503, {"error": str(e)},
                        extra_headers={"Retry-After": "1"})
            self._observe("predict", t0, 503)
            return
        except ValueError as e:        # tenant/context-bound validation
            self._reply(400, {"error": f"bad request: {e}"})
            self._observe("predict", t0, 400)
            return
        try:
            outputs = [f.result(timeout=srv.request_timeout_s)
                       for f in futures]
        except (concurrent.futures.TimeoutError, TimeoutError,
                RequestDeadlineExceeded):
            self._reply(504, {"error": "deadline exceeded"})
            self._observe("predict", t0, 504)
            return
        except Exception as e:
            self._reply(500, {"error": f"inference failed: {e}"})
            self._observe("predict", t0, 500)
            return
        self._reply(200, {"outputs": outputs, "model_version": version})
        self._observe("predict", t0, 200)


class _HTTPServer(ThreadingHTTPServer):
    daemon_threads = True
    # Default listen(5) drops connections the moment a traffic burst
    # outruns accept() — the kernel backlog must cover the concurrency
    # the admission queue is sized for (503s are OUR backpressure signal;
    # an RST from the TCP layer is just an outage).
    request_queue_size = 256


class ModelServer:
    """The assembled serving stack: engine + batcher + watcher + HTTP.

    ::

        engine = InferenceEngine(mlp_apply, params, buckets=(1, 8, 32))
        srv = ModelServer(engine, checkpoint_dir="/ckpts")
        port = srv.start()          # in-process, returns the bound port
        ...
        srv.stop()

    All sizing parameters default to the ``HVDT_SERVE_*`` knobs.  Pass
    ``port=0`` (default knob value) to bind an ephemeral port — the test
    rig and multi-replica launches both need collision-free binds.
    """

    def __init__(self, engine: InferenceEngine, *,
                 host: Optional[str] = None, port: Optional[int] = None,
                 checkpoint_dir: Optional[str] = None,
                 template: Any = None,
                 max_batch_size: Optional[int] = None,
                 max_delay_ms: Optional[float] = None,
                 max_queue_depth: Optional[int] = None,
                 request_timeout_s: Optional[float] = None,
                 input_dtype=np.float32,
                 metrics: Optional[MetricsRegistry] = None):
        self.engine = engine
        self.metrics = metrics if metrics is not None else engine.metrics
        self.host = host if host is not None \
            else config.get_str("HVDT_SERVE_HOST")
        self.port = int(port if port is not None
                        else config.get_int("HVDT_SERVE_PORT"))
        self.request_timeout_s = float(
            request_timeout_s if request_timeout_s is not None
            else config.get_float("HVDT_SERVE_REQUEST_TIMEOUT_S"))
        self.input_dtype = np.dtype(input_dtype)
        # Engine selection (HVDT_SERVE_ENGINE): the continuous LLM
        # engine does its own per-iteration batching — a request-level
        # gather in front of it would just re-serialize admissions — so
        # the batcher only exists on the static path.
        self.continuous = bool(getattr(engine, "is_continuous", False))
        self.batcher: Optional[DynamicBatcher] = None
        if not self.continuous:
            self.batcher = DynamicBatcher(
                engine.infer, max_batch_size=max_batch_size,
                max_delay_ms=max_delay_ms, max_queue_depth=max_queue_depth,
                metrics=self.metrics)
        self.watcher: Optional[CheckpointWatcher] = None
        if checkpoint_dir is not None:
            self.watcher = CheckpointWatcher(
                checkpoint_dir, engine, template, metrics=self.metrics)
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        # Graceful-drain state (resilience/preempt.py idiom: the signal
        # handler only sets a flag; the heavy work happens in main flow).
        self._draining = threading.Event()
        self._inflight = 0
        self._inflight_lock = threading.Lock()
        self._inflight_zero = threading.Condition(self._inflight_lock)
        self._request_seq = 0
        self._prev_handlers: dict = {}
        self._drain_gauge = self.metrics.gauge(
            "serve_draining", "1 while this replica drains (admission "
            "closed, in-flight requests completing), else 0")
        self._drain_gauge.set_function(
            lambda: 1.0 if self.draining else 0.0)

    def start(self) -> int:
        """Bind + serve in a daemon thread; starts the reload watcher.
        Returns the bound port."""
        handler = type("Handler", (_Handler,), {"server_ref": self})
        self._httpd = _HTTPServer((self.host, self.port), handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="hvdt-serve-http",
            daemon=True)
        self._thread.start()
        if self.watcher is not None:
            self.watcher.start(load_initial=True)
        log.info("serving on http://%s:%d (buckets=%s)", self.host,
                 self.port, list(self.engine.buckets))
        return self.port

    # ---- graceful drain --------------------------------------------------

    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    def request_seq(self) -> int:
        """Monotone admitted-request count — the ``step`` coordinate of
        the serving fault plan (``serve_crash@step=N``)."""
        with self._inflight_lock:
            return self._request_seq

    def _inflight_enter(self) -> None:
        with self._inflight_lock:
            self._inflight += 1
            self._request_seq += 1

    def _inflight_exit(self) -> None:
        with self._inflight_zero:
            self._inflight -= 1
            if self._inflight <= 0:
                self._inflight_zero.notify_all()

    @property
    def inflight(self) -> int:
        with self._inflight_lock:
            return self._inflight

    def drain(self, timeout: float = 30.0) -> bool:
        """Flip to draining and wait for in-flight requests to finish.

        From the flip on: ``/healthz`` reports ``draining``, ``/predict``
        sheds with 503 + Retry-After, and requests already past
        admission run to completion.  The listener socket stays OPEN the
        whole time — a close here would RST exactly the connections the
        drain exists to protect.  Returns True when in-flight hit zero
        inside ``timeout``."""
        self._draining.set()
        deadline = time.monotonic() + timeout
        with self._inflight_zero:
            while self._inflight > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    log.warning("serve drain: %d request(s) still in "
                                "flight after %.1fs", self._inflight,
                                timeout)
                    return False
                self._inflight_zero.wait(remaining)
        return True

    def install_drain_handlers(self,
                               signals=(signal.SIGTERM, signal.SIGINT)
                               ) -> None:
        """SIGTERM/SIGINT → drain flag (main thread only).  The handler
        is trivial by design; whoever owns the serving loop (the replica
        worker, ``serve_forever``) polls :attr:`draining` and performs
        the actual drain + exit in main flow."""
        for sig in signals:
            self._prev_handlers[sig] = signal.signal(
                sig, lambda signum, frame: self._draining.set())

    def uninstall_drain_handlers(self) -> None:
        for sig, prev in self._prev_handlers.items():
            try:
                signal.signal(sig, prev)
            except (ValueError, OSError):   # non-main thread / teardown
                pass
        self._prev_handlers.clear()

    def stop(self, drain_timeout: float = 30.0) -> None:
        """Graceful teardown: stop admitting (drain flag), let in-flight
        requests complete, then stop the watcher, the HTTP listener, and
        the batcher.  Ordering matters: the socket closes only after the
        last in-flight response was written — zero connection resets."""
        self._draining.set()
        self.drain(timeout=drain_timeout)
        if self.watcher is not None:
            self.watcher.stop()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self.batcher is not None:
            self.batcher.close()
        elif hasattr(self.engine, "stop"):
            self.engine.stop()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None

    def serve_forever(self) -> None:
        """start() + block until KeyboardInterrupt or a drain signal
        (the CLI entry path installs SIGTERM/SIGINT drain handlers, so a
        preempted replica finishes its in-flight work before exiting)."""
        self.start()
        try:
            self.install_drain_handlers()
        except ValueError:      # not the main thread (embedded use)
            pass
        try:
            while not self._draining.wait(1.0):
                pass
        except KeyboardInterrupt:
            pass
        finally:
            self.uninstall_drain_handlers()
            self.stop()
