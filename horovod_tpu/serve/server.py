"""HTTP serving front end: /predict, /healthz, /metrics, 503 backpressure.

Stdlib-only (``http.server.ThreadingHTTPServer``) — the serving plane
must not grow dependencies the training container doesn't have, and a
thread-per-connection front end is exactly right for this architecture:
handler threads only parse JSON and park on a batcher future; the real
concurrency problem (coalescing requests into chip-shaped batches) is the
batcher's, and admission control is enforced *before* any memory is
committed to a request's batch slot.

Routes:

* ``POST /predict``  — body ``{"inputs": [[...], ...]}`` (one row per
  inner list).  200 → ``{"outputs": [...], "model_version": N}``;
  503 + ``Retry-After`` when admission control sheds the request;
  400 on malformed bodies; 504 when a request exceeds its deadline.
* ``GET /healthz``   — liveness/readiness: 200 once the engine has
  weights, with the served checkpoint step and params version.
* ``GET /metrics``   — Prometheus text (latency summaries per route,
  queue depth, batch fill, compile / reload counters).
"""

from __future__ import annotations

import concurrent.futures
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Optional

import numpy as np

from ..common import config
from ..common.logging_util import get_logger
from .batcher import BackpressureError, DynamicBatcher
from .engine import InferenceEngine
from .metrics import MetricsRegistry
from .reload import CheckpointWatcher

__all__ = ["ModelServer"]

log = get_logger(__name__)


class _Handler(BaseHTTPRequestHandler):
    # Set by ModelServer on the subclass it builds.
    server_ref: "ModelServer"
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # route access logs to our logger
        log.debug("serve http: " + fmt, *args)

    # ---- helpers --------------------------------------------------------
    def _reply(self, status: int, payload: Any,
               content_type: str = "application/json",
               extra_headers: Optional[dict] = None) -> None:
        body = (payload if isinstance(payload, bytes)
                else json.dumps(payload).encode())
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        for k, v in (extra_headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _observe(self, route: str, t0: float, status: int) -> None:
        ms = (time.perf_counter() - t0) * 1000.0
        srv = self.server_ref
        srv.metrics.summary(
            f"serve_request_latency_ms_{route}",
            f"End-to-end {route} handler latency (ms)").observe(ms)
        srv.metrics.counter(
            "serve_http_responses_total",
            "HTTP responses by route and status").inc(
                route=route, status=str(status))

    # ---- routes ---------------------------------------------------------
    def do_GET(self):
        srv = self.server_ref
        t0 = time.perf_counter()
        if self.path.split("?")[0] == "/healthz":
            payload = {
                "status": "ok",
                "model_version": srv.engine.params_version,
                "checkpoint_step": (srv.watcher.current_step
                                    if srv.watcher else None),
                "buckets": list(srv.engine.buckets),
            }
            self._reply(200, payload)
            self._observe("healthz", t0, 200)
        elif self.path.split("?")[0] == "/metrics":
            self._reply(200, srv.metrics.render().encode(),
                        content_type="text/plain; version=0.0.4")
            self._observe("metrics", t0, 200)
        else:
            self._reply(404, {"error": f"no route {self.path!r}"})

    def do_POST(self):
        if self.path.split("?")[0] != "/predict":
            self._reply(404, {"error": f"no route {self.path!r}"})
            return
        srv = self.server_ref
        t0 = time.perf_counter()
        try:
            length = int(self.headers.get("Content-Length", 0))
            doc = json.loads(self.rfile.read(length))
            inputs = np.asarray(doc["inputs"], dtype=srv.input_dtype)
            if inputs.ndim < 1 or inputs.shape[0] == 0:
                raise ValueError("inputs must hold >= 1 rows")
        except (ValueError, KeyError, TypeError, json.JSONDecodeError) as e:
            self._reply(400, {"error": f"bad request: {e}"})
            self._observe("predict", t0, 400)
            return
        try:
            version = srv.engine.params_version
            future = srv.batcher.submit(inputs)
        except BackpressureError as e:
            self._reply(503, {"error": str(e)},
                        extra_headers={"Retry-After": "1"})
            self._observe("predict", t0, 503)
            return
        try:
            outputs = future.result(timeout=srv.request_timeout_s)
        except (concurrent.futures.TimeoutError, TimeoutError):
            future.cancel()
            self._reply(504, {"error": "deadline exceeded"})
            self._observe("predict", t0, 504)
            return
        except Exception as e:
            self._reply(500, {"error": f"inference failed: {e}"})
            self._observe("predict", t0, 500)
            return
        self._reply(200, {"outputs": np.asarray(outputs).tolist(),
                          "model_version": version})
        self._observe("predict", t0, 200)


class _HTTPServer(ThreadingHTTPServer):
    daemon_threads = True
    # Default listen(5) drops connections the moment a traffic burst
    # outruns accept() — the kernel backlog must cover the concurrency
    # the admission queue is sized for (503s are OUR backpressure signal;
    # an RST from the TCP layer is just an outage).
    request_queue_size = 256


class ModelServer:
    """The assembled serving stack: engine + batcher + watcher + HTTP.

    ::

        engine = InferenceEngine(mlp_apply, params, buckets=(1, 8, 32))
        srv = ModelServer(engine, checkpoint_dir="/ckpts")
        port = srv.start()          # in-process, returns the bound port
        ...
        srv.stop()

    All sizing parameters default to the ``HVDT_SERVE_*`` knobs.  Pass
    ``port=0`` (default knob value) to bind an ephemeral port — the test
    rig and multi-replica launches both need collision-free binds.
    """

    def __init__(self, engine: InferenceEngine, *,
                 host: Optional[str] = None, port: Optional[int] = None,
                 checkpoint_dir: Optional[str] = None,
                 template: Any = None,
                 max_batch_size: Optional[int] = None,
                 max_delay_ms: Optional[float] = None,
                 max_queue_depth: Optional[int] = None,
                 request_timeout_s: Optional[float] = None,
                 input_dtype=np.float32,
                 metrics: Optional[MetricsRegistry] = None):
        self.engine = engine
        self.metrics = metrics if metrics is not None else engine.metrics
        self.host = host if host is not None \
            else config.get_str("HVDT_SERVE_HOST")
        self.port = int(port if port is not None
                        else config.get_int("HVDT_SERVE_PORT"))
        self.request_timeout_s = float(
            request_timeout_s if request_timeout_s is not None
            else config.get_float("HVDT_SERVE_REQUEST_TIMEOUT_S"))
        self.input_dtype = np.dtype(input_dtype)
        self.batcher = DynamicBatcher(
            engine.infer, max_batch_size=max_batch_size,
            max_delay_ms=max_delay_ms, max_queue_depth=max_queue_depth,
            metrics=self.metrics)
        self.watcher: Optional[CheckpointWatcher] = None
        if checkpoint_dir is not None:
            self.watcher = CheckpointWatcher(
                checkpoint_dir, engine, template, metrics=self.metrics)
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> int:
        """Bind + serve in a daemon thread; starts the reload watcher.
        Returns the bound port."""
        handler = type("Handler", (_Handler,), {"server_ref": self})
        self._httpd = _HTTPServer((self.host, self.port), handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="hvdt-serve-http",
            daemon=True)
        self._thread.start()
        if self.watcher is not None:
            self.watcher.start(load_initial=True)
        log.info("serving on http://%s:%d (buckets=%s)", self.host,
                 self.port, list(self.engine.buckets))
        return self.port

    def stop(self) -> None:
        """Graceful teardown: stop admitting, drain the batcher, stop the
        watcher and the HTTP listener."""
        if self.watcher is not None:
            self.watcher.stop()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        self.batcher.close()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None

    def serve_forever(self) -> None:
        """start() + block until KeyboardInterrupt (the CLI entry path)."""
        self.start()
        try:
            while True:
                time.sleep(3600)
        except KeyboardInterrupt:
            pass
        finally:
            self.stop()
