"""Topology declarations + the link-tier constants of the static cost
model.

This module is one half of ROADMAP item 5(b) — the deterministic
topology simulator.  It owns:

* :class:`TopologySpec` — a declared chip topology (``pods`` ×
  ``chips_per_pod``), the thing that makes a 256-chip mesh *testable on
  CPU*: the cost model evaluates a collective schedule against a spec,
  never against the devices the process happens to see.  Axis classes
  follow the mesh convention (``parallel/mesh.py``): the ``dcn`` tier
  spans pods, the ``ici`` tier spans chips within a pod.

* :class:`LinkConstants` — the per-tier (alpha, beta, gamma) terms of
  the alpha-beta model: per-hop launch/latency seconds, per-wire-byte
  seconds (inverse bandwidth), and per-logical-byte quantize/dequantize
  compute seconds for compressed wires.

* ``DEFAULT_TIER_CONSTANTS`` — order-of-magnitude fallbacks used ONLY
  when the fitted calibration file has no matching group.  Real
  constants come from :func:`analysis.costmodel.fit_from_bench` over
  measured ``bench_allreduce.py --json-out`` rows — policies are
  measured, not guessed (the ``HVDT_AUTOTUNE_*_SEED`` principle).

Single-source-of-truth contract: device peak-FLOPs/HBM numbers live in
``telemetry/step_stats.PEAK_BY_DEVICE_KIND`` (imported here, never
duplicated); link-level latency/bandwidth literals live HERE.  The
``magic-peak-flops`` lint rule (analysis/lint.py) flags hardware-rate
literals anywhere else in the package, so the MFU gauge and the cost
model can never drift apart.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, Optional, Sequence, Tuple

__all__ = [
    "LinkConstants", "TopologySpec", "DEFAULT_TIER_CONSTANTS",
    "TIER_ICI", "TIER_DCN", "TIERS", "classify_axis", "tier_sizes",
    "chip_peak_flops", "NOMINAL_SIM_PEAK_FLOPS",
]

TIER_ICI = "ici"
TIER_DCN = "dcn"
TIERS: Tuple[str, ...] = (TIER_ICI, TIER_DCN)


@dataclasses.dataclass(frozen=True)
class LinkConstants:
    """Alpha-beta-gamma terms for one transport tier.

    ``seconds = alpha * hops + beta * wire_bytes + gamma * logical_bytes``

    * ``alpha_s`` — per-hop latency/launch cost (the latency term a
      tree algorithm minimises);
    * ``beta_s_per_byte`` — per-wire-byte transfer cost, i.e. inverse
      link bandwidth (the term a ring algorithm minimises);
    * ``gamma_s_per_byte`` — per-logical-byte quantize/dequantize
      compute charged by compressed wires (0 for f32).
    """

    alpha_s: float
    beta_s_per_byte: float
    gamma_s_per_byte: float = 0.0

    def to_dict(self) -> Dict[str, float]:
        return {"alpha_s": self.alpha_s,
                "beta_s_per_byte": self.beta_s_per_byte,
                "gamma_s_per_byte": self.gamma_s_per_byte}

    @classmethod
    def from_dict(cls, d: Dict[str, float]) -> "LinkConstants":
        return cls(alpha_s=float(d.get("alpha_s", 0.0)),
                   beta_s_per_byte=float(d.get("beta_s_per_byte", 0.0)),
                   gamma_s_per_byte=float(d.get("gamma_s_per_byte", 0.0)))


# Fallback tier constants — public TPU-generation order-of-magnitude
# figures (ICI: ~100 GB/s per link, ~1 us hop; DCN: ~25 GB/s per host,
# ~10 us hop).  The fitted calibration always wins; these only keep the
# model total when a (tier, algorithm, wire) group was never measured.
DEFAULT_TIER_CONSTANTS: Dict[str, LinkConstants] = {
    TIER_ICI: LinkConstants(alpha_s=1.0e-6,
                            beta_s_per_byte=1.0 / 100.0e9),
    TIER_DCN: LinkConstants(alpha_s=10.0e-6,
                            beta_s_per_byte=1.0 / 25.0e9),
}

# The magic-peak-flops lint rule's classification window: numeric
# literals in [floor, ceil] look like hardware rates (the table above
# spans 46e12..2765e9; nothing real exceeds 1e16 yet) — masking
# sentinels like -1e30 and unit conversions like 1e9 fall outside.
# The rule imports these so its bounds live where the constants do.
PEAK_LITERAL_FLOOR = 1e11
PEAK_LITERAL_CEIL = 1e16

# Nominal peak FLOP/s when no real device kind matches the table (CPU
# simulator) — the HVDT_PEAK_FLOPS default and report_pipeline_mfu
# fallback.  Any consistent value works there (MFU is a ratio); it
# lives HERE so the magic-peak-flops rule keeps it single-sourced.
NOMINAL_SIM_PEAK_FLOPS = 1e12

# Per-logical-byte quantize/dequantize fallback for compressed wires
# (block-scaled int8/int4 kernels run near HBM speed — the packed int4
# wire pays the same per-element pass plus the nibble pack/unpack;
# bf16/fp16 casts are cheaper still).  Fitted gamma from quantized-wire
# bench rows overrides.
DEFAULT_QUANT_GAMMA_S_PER_BYTE: Dict[str, float] = {
    "int8": 1.0 / 400.0e9,
    "int4": 1.0 / 400.0e9,
    "bf16": 1.0 / 800.0e9,
    "fp16": 1.0 / 800.0e9,
}


# Reference per-chip step workload for scaling curves (ResNet-50 at
# the BENCH batch size: 25.6M f32 params -> ~102 MB of gradients, and
# the XLA cost-analysis flops bench.py reports).  Living here keeps the
# curve's magnitudes out of the magic-peak-flops window elsewhere.
REFERENCE_STEP_WORKLOAD: Dict[str, float] = {
    "grad_bytes": 102.4e6,
    "flops_per_step": 2.164e11,
}


@dataclasses.dataclass(frozen=True)
class TopologySpec:
    """A declared chip topology the model evaluates schedules against.

    ``TopologySpec(pods=16, chips_per_pod=16)`` is a 256-chip mesh —
    evaluable on a 1-CPU container, which is the point.  ``device_kind``
    keys the compute-side peak table
    (``telemetry/step_stats.PEAK_BY_DEVICE_KIND``) for scaling curves
    that need a compute term next to the comm term.
    """

    pods: int = 1
    chips_per_pod: int = 8
    device_kind: str = "v5 lite"

    def __post_init__(self):
        if self.pods < 1 or self.chips_per_pod < 1:
            raise ValueError(
                f"TopologySpec needs pods >= 1 and chips_per_pod >= 1, "
                f"got pods={self.pods} chips_per_pod={self.chips_per_pod}")

    @property
    def total_chips(self) -> int:
        return self.pods * self.chips_per_pod

    def tier_size(self, tier: str) -> int:
        """Extent of one transport tier: ``dcn`` spans pods, ``ici``
        spans chips within a pod."""
        if tier == TIER_DCN:
            return self.pods
        if tier == TIER_ICI:
            return self.chips_per_pod
        raise ValueError(f"unknown tier {tier!r}; valid: {TIERS}")

    def describe(self) -> str:
        return (f"{self.pods}x{self.chips_per_pod} "
                f"({self.total_chips} chips, {self.device_kind})")

    @classmethod
    def from_env(cls, default: Optional["TopologySpec"] = None
                 ) -> "TopologySpec":
        """Topology from the elastic launcher's pod contract
        (``HVDT_NUM_PODS`` contract var + ``HVDT_POD_SIZE`` knob), else
        ``default`` (a single 8-chip pod)."""
        default = default or cls()
        try:
            pods = int(os.environ.get("HVDT_NUM_PODS", "") or 0)
            chips = int(os.environ.get("HVDT_POD_SIZE", "") or 0)
        except ValueError:
            return default
        if pods >= 1 and chips >= 1:
            return cls(pods=pods, chips_per_pod=chips,
                       device_kind=default.device_kind)
        return default

    def to_dict(self) -> Dict[str, object]:
        return {"pods": self.pods, "chips_per_pod": self.chips_per_pod,
                "device_kind": self.device_kind}


def classify_axis(axis: str, axes: Sequence[str]) -> str:
    """Transport tier of one mesh axis within its reduce group.

    Literal ``ici``/``dcn`` names classify themselves (the pod mesh
    contract names its axes exactly that); the 4D pod axes follow the
    ``pod_mesh_spec`` placement contract — ``pp`` carves whole pod
    groups (its ppermute ticks cross DCN), ``ep`` carves chips inside a
    pod (its expert a2a rides ICI); anything else falls back to the
    ``parallel/mesh.py`` position convention — innermost axis rides
    ICI, outer axes cross DCN."""
    if axis in TIERS:
        return axis
    from ..parallel import mesh as _mesh

    if axis == _mesh.AXIS_PP:
        return TIER_DCN
    if axis == _mesh.AXIS_EP:
        return TIER_ICI
    return _mesh.axis_transport_class(axis, axes)


def tier_sizes(axes: Sequence[str], topo: TopologySpec
               ) -> Dict[str, int]:
    """Per-tier group extents for a reduce group on ``topo``: every
    axis contributes its tier's declared extent (multi-axis tiers
    multiply, matching a (pipe, dp)-style stacked dcn extent)."""
    sizes: Dict[str, int] = {}
    for ax in axes:
        tier = classify_axis(ax, axes)
        sizes[tier] = sizes.get(tier, 1) * topo.tier_size(tier)
    return sizes


def chip_peak_flops(device_kind: str) -> Optional[float]:
    """Per-chip bf16 peak FLOP/s from the ONE peak table
    (``telemetry/step_stats.peak_flops_for``) — never a literal here."""
    from ..telemetry.step_stats import peak_flops_for

    flops, _ = peak_flops_for(device_kind)
    return flops
