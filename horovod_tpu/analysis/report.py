"""Post-mortem run report: anomaly event log (+ trace artifacts) →
markdown.

``python -m horovod_tpu.analysis --report <event-log|trace-dir>``
renders the run's observability artifacts into one human-readable
document: the run timeline reconstructed from the JSONL anomaly event
log (``HVDT_EVENT_LOG``), a per-kind anomaly summary, and — when the
target is a directory — an inventory of the forensics files found next
to it (Chrome traces, desync reports, more event logs).

Pure stdlib, no jax: a post-mortem must render on any laptop from a
copied artifact directory.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["render_report", "collect_artifacts"]


def _fmt_ts(ts: Optional[float]) -> str:
    if not ts:
        return "?"
    return time.strftime("%Y-%m-%d %H:%M:%S", time.gmtime(float(ts)))


def collect_artifacts(target: str) -> Tuple[List[str], List[str]]:
    """(event_log_paths, other_artifact_paths) under ``target``.

    A file target is taken as one event log; a directory is scanned
    for ``*.jsonl`` event logs plus the known forensics artifacts
    (``trace_*.json``, ``trace_merged.json``, ``desync_report*.json``).
    """
    if os.path.isfile(target):
        return [target], []
    logs: List[str] = []
    other: List[str] = []
    try:
        names = sorted(os.listdir(target))
    except OSError:
        return [], []
    for name in names:
        path = os.path.join(target, name)
        if not os.path.isfile(path):
            continue
        if name.endswith(".jsonl"):
            logs.append(path)
        elif (name.startswith(("trace_", "desync_report"))
              and name.endswith(".json")):
            other.append(path)
    return logs, other


def _event_row(ev: Dict[str, Any]) -> str:
    who = []
    if ev.get("rank") is not None:
        who.append(f"rank {ev['rank']}")
    if ev.get("pod"):
        who.append(f"pod {ev['pod']}")
    ratio = ev.get("ratio")
    return ("| " + " | ".join([
        _fmt_ts(ev.get("ts")),
        str(ev.get("step", "")),
        str(ev.get("kind", "")),
        str(ev.get("scope", "")),
        ", ".join(who) or "—",
        f"{ratio:.2f}x" if isinstance(ratio, (int, float)) else "—",
        str(ev.get("message", "")).replace("|", "\\|"),
    ]) + " |")


def render_report(target: str) -> str:
    """Markdown post-mortem for an event-log file or artifact
    directory."""
    from ..telemetry.anomaly import read_event_log

    logs, artifacts = collect_artifacts(target)
    events: List[Dict[str, Any]] = []
    for path in logs:
        events.extend(read_event_log(path))
    events.sort(key=lambda e: (float(e.get("ts") or 0),
                               int(e.get("step") or 0)))

    lines: List[str] = [
        "# Run post-mortem report",
        "",
        f"Source: `{target}`  ",
        f"Event logs: {len(logs)} — {len(events)} event(s)",
        "",
    ]

    if events:
        first, last = events[0], events[-1]
        dur = float(last.get("ts") or 0) - float(first.get("ts") or 0)
        steps = [int(e["step"]) for e in events
                 if e.get("step") is not None]
        lines += [
            "## Run timeline",
            "",
            f"* first event: {_fmt_ts(first.get('ts'))} "
            f"(step {first.get('step', '?')})",
            f"* last event:  {_fmt_ts(last.get('ts'))} "
            f"(step {last.get('step', '?')})",
            f"* span: {dur:.1f}s"
            + (f", steps {min(steps)}–{max(steps)}" if steps else ""),
            "",
            "| time (UTC) | step | kind | scope | who | ratio |"
            " message |",
            "|---|---|---|---|---|---|---|",
        ]
        lines += [_event_row(e) for e in events]
        lines.append("")

        counts: Dict[str, List[int]] = {}
        for e in events:
            kind = str(e.get("kind", "?"))
            counts.setdefault(kind, []).append(int(e.get("step") or 0))
        lines += [
            "## Anomaly summary",
            "",
            "| kind | count | first step | last step |",
            "|---|---|---|---|",
        ]
        for kind in sorted(counts):
            steps_k = counts[kind]
            lines.append(f"| {kind} | {len(steps_k)} | {min(steps_k)} "
                         f"| {max(steps_k)} |")
        lines.append("")

        fleet = [e for e in events
                 if e.get("kind") in ("fleet_decision", "fleet_outcome")]
        if fleet:
            lines += [
                "## Fleet scheduler",
                "",
                "| step | record | move | predicted gain | pressure |"
                " outcome |",
                "|---|---|---|---|---|---|",
            ]
            for e in fleet:
                if e.get("kind") == "fleet_decision":
                    chosen = e.get("chosen") or {}
                    move = chosen.get("move") or {}
                    gain = chosen.get("predicted_gain")
                    press = (e.get("trigger") or {}).get("ratio")
                else:
                    move = e.get("move") or {}
                    gain = e.get("predicted_gain")
                    before = e.get("pressure_before")
                    after = e.get("pressure_after")
                    press = (f"{before:.2f}→{after:.2f}"
                             if isinstance(before, (int, float))
                             and isinstance(after, (int, float))
                             else None)
                move_s = (f"{move.get('kind', '?')}({move.get('pod')})"
                          if move else "—")
                lines.append("| " + " | ".join([
                    str(e.get("step", "")),
                    str(e.get("kind", "")),
                    move_s,
                    (f"{gain:+.3f}"
                     if isinstance(gain, (int, float)) else "—"),
                    (f"{press:.2f}"
                     if isinstance(press, (int, float))
                     else press or "—"),
                    str(e.get("outcome", "")),
                ]) + " |")
            lines.append("")
    else:
        lines += ["## Run timeline", "",
                  "No anomaly events found — either a clean run, or "
                  "`HVDT_EVENT_LOG` was not set.", ""]

    if artifacts:
        lines += ["## Forensics artifacts", ""]
        for path in artifacts:
            note = ""
            if os.path.basename(path).startswith("desync_report"):
                try:
                    with open(path) as fh:
                        doc = json.load(fh)
                    note = (f" — first divergent seq "
                            f"{doc.get('first_divergent_seq')}, missing "
                            f"ranks {doc.get('missing_ranks')}")
                except (OSError, ValueError):
                    note = " — unreadable"
            lines.append(f"* `{os.path.basename(path)}`{note}")
        lines.append("")
    return "\n".join(lines)
