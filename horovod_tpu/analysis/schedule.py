"""Static collective-schedule extraction + distributed-correctness
verification.

Every desync the framework can diagnose today is caught at *runtime*:
the flight recorder (telemetry/flight_recorder.py) names the first
divergent seq only after the stall escalator's abort rung fires.  But on
a jaxpr/HLO stack the collective schedule is a **traceable artifact** —
the reference negotiates collective order at runtime precisely because
frameworks could not prove it statically (ref: controller negotiation,
operations.cc RunLoopOnce), while here the whole issue order is sitting
in the jaxpr before a single step runs.  This module extracts it:

* :func:`extract_schedule` traces a step function and walks the jaxpr
  (descending into ``shard_map`` / ``pjit`` / ``cond`` / ``while`` /
  ``scan`` / custom-VJP sub-jaxprs, in equation order — which IS the
  issue order) collecting every collective primitive into an ordered
  :class:`ScheduleFingerprint`: op kind, axis names, dtype, element
  count, bytes, wire, control-flow context, and whether the collective
  sits downstream of an ``optimization_barrier`` pin.

* Verifier passes over the fingerprint assert the contracts the rest of
  the codebase relies on by convention:

  - :func:`verify_bucket_plan_invariance` — the fusion bucket plan is a
    pure function of the leaf sequence and invariant under dtype-order
    interleaving (two ranks flattening the same tree must issue the
    same buckets — the determinism the per-rank seq alignment needs);
  - :func:`verify_flip_compat` — an autotune leg pair declared
    hot-swappable keeps ONE optimizer state treedef and identical
    output avals, so flipping the leg is a re-jit and never a state
    migration (the AutotunedStep contract for all seven dimensions);
  - :func:`verify_post_pin_psum_family` — in a hierarchical-transport
    program every collective issued after a pin barrier is psum-family
    (barriers erase replication tracking; only psum-family terminals
    re-establish it — the PR-8/9 invariance contract
    transport/hierarchy.py documents);
  - :func:`verify_no_data_dependent_collectives` — a collective under
    one branch of ``cond`` or inside ``while`` executes a
    data-dependent number of times: if host data diverges across
    ranks, so does the issue order — the classic mismatched-collective
    hang, flagged before it ever runs.

* The fingerprint exports to JSON (:meth:`ScheduleFingerprint.save`)
  and is cross-checked at **runtime** by the flight recorder:
  ``HVDT_EXPECTED_SCHEDULE`` names the exported file and
  ``emit_desync_report`` then reports static-expected vs
  runtime-observed (:func:`first_schedule_deviation`), not just
  observed-vs-observed.

jax-0.4.37 guard: only ``jax.make_jaxpr`` / ``jax.jit(...).lower`` and
jaxpr-object introspection — no ``jax.typeof`` / ``lax.pcast`` /
``shard_map``-API dependence anywhere here.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import re
from collections import Counter
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "CollectiveEvent", "ScheduleFingerprint", "extract_schedule",
    "hlo_collective_counts", "verify_bucket_plan_invariance",
    "verify_flip_compat", "verify_post_pin_psum_family",
    "verify_no_data_dependent_collectives", "verify_a2a_ppermute_pairing",
    "first_schedule_deviation",
    "load_fingerprint", "COLLECTIVE_PRIMS", "PSUM_FAMILY",
]

FINGERPRINT_VERSION = 1

# jaxpr primitive name -> canonical collective kind (probed on the
# container's jax 0.4.37: lax.psum_scatter traces as `reduce_scatter`).
COLLECTIVE_PRIMS: Dict[str, str] = {
    "psum": "psum",
    "reduce_scatter": "reduce_scatter",
    "psum_scatter": "reduce_scatter",      # newer jax spelling
    "all_gather": "all_gather",
    "all_to_all": "all_to_all",
    "ppermute": "ppermute",
    "pmax": "pmax",
    "pmin": "pmin",
}

# Collectives whose terminal op re-establishes replication over the
# reduce group after an optimization_barrier pin (barriers erase
# replication tracking — transport/hierarchy.py InflightHierarchical).
# The repo's invariant allgather lowers to a psum of a displaced buffer
# (ops/device.invariant_allgather_shards), so it lands in this set
# by construction.
PSUM_FAMILY = frozenset({"psum", "reduce_scatter"})

# Control-flow contexts whose body executes a data-dependent number of
# times (or on a data-dependent branch): a collective under one of
# these is a cross-rank desync hazard.  `scan` is excluded — its trip
# count is a trace-time constant, identical on every rank.
DATA_DEPENDENT_CONTEXTS = frozenset({"cond", "while"})

# fingerprint op kind -> the op name the flight recorder books
# (telemetry feed sites: "allreduce"/"reduce_scatter"/"allgather"/...).
EVENT_OP_NAMES = {
    "psum": "allreduce",
    "reduce_scatter": "reduce_scatter",
    "all_gather": "allgather",
    "all_to_all": "alltoall",
    "ppermute": "ppermute",
    "pmax": "allreduce",
    "pmin": "allreduce",
}


@dataclasses.dataclass(frozen=True)
class CollectiveEvent:
    """One collective in the static schedule, in issue order."""

    index: int                       # position in the schedule
    op: str                          # canonical kind (COLLECTIVE_PRIMS)
    axes: Tuple[str, ...]            # mesh axes reduced/exchanged over
    dtype: str                       # operand dtype name
    count: int                       # operand element count
    nbytes: int                      # operand bytes
    context: Tuple[str, ...]         # enclosing control-flow primitives
    post_barrier: bool               # downstream of optimization_barrier
    # How many optimization_barriers were issued before this collective
    # — the overlap pipeline's bucket slot (events sharing a value were
    # issued in the same flight window).  Metadata like nbytes: the
    # cost model consumes it, the digest does not.
    barriers_before: int = 0

    @property
    def event_op(self) -> str:
        """The op name the flight recorder would book for this entry."""
        return EVENT_OP_NAMES.get(self.op, self.op)

    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        d["axes"] = list(self.axes)
        d["context"] = list(self.context)
        d["event_op"] = self.event_op
        return d


class ScheduleFingerprint:
    """Canonical, ordered collective schedule of one traced program.

    The digest hashes exactly the fields two ranks must agree on for
    their per-rank seq counters to align (op kind, axes, dtype, element
    count, control-flow context) — byte counts and barrier positions
    ride along as metadata but a pure metadata change (e.g. a different
    wire estimate) does not change identity.
    """

    def __init__(self, events: Sequence[CollectiveEvent],
                 n_barriers: int = 0, label: str = ""):
        self.events: List[CollectiveEvent] = list(events)
        self.n_barriers = int(n_barriers)
        self.label = str(label)

    @property
    def digest(self) -> str:
        core = [(e.op, list(e.axes), e.dtype, e.count, list(e.context))
                for e in self.events]
        return hashlib.sha256(
            json.dumps(core, sort_keys=True).encode()).hexdigest()

    def counts(self) -> Counter:
        return Counter(e.op for e in self.events)

    def total_bytes(self) -> int:
        return sum(e.nbytes for e in self.events)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "version": FINGERPRINT_VERSION,
            "label": self.label,
            "digest": self.digest,
            "n_barriers": self.n_barriers,
            "events": [e.to_dict() for e in self.events],
        }

    def to_json(self, indent: Optional[int] = 2) -> str:
        return json.dumps(self.to_dict(), indent=indent)

    def save(self, path: str) -> str:
        with open(path, "w") as fh:
            fh.write(self.to_json())
        return path

    @classmethod
    def from_dict(cls, doc: Dict[str, Any]) -> "ScheduleFingerprint":
        events = [
            CollectiveEvent(
                index=int(e.get("index", i)), op=str(e["op"]),
                axes=tuple(e.get("axes", ())), dtype=str(e.get("dtype", "")),
                count=int(e.get("count", 0)), nbytes=int(e.get("nbytes", 0)),
                context=tuple(e.get("context", ())),
                post_barrier=bool(e.get("post_barrier", False)),
                barriers_before=int(e.get(
                    "barriers_before",
                    1 if e.get("post_barrier") else 0)))
            for i, e in enumerate(doc.get("events", []))]
        return cls(events, n_barriers=int(doc.get("n_barriers", 0)),
                   label=str(doc.get("label", "")))

    def summary(self) -> str:
        c = self.counts()
        ops = " ".join(f"{k}={v}" for k, v in sorted(c.items()))
        return (f"schedule[{self.label or 'step'}]: "
                f"{len(self.events)} collectives ({ops or 'none'}), "
                f"{self.n_barriers} barriers, digest {self.digest[:12]}")


def load_fingerprint(path: str) -> ScheduleFingerprint:
    with open(path) as fh:
        return ScheduleFingerprint.from_dict(json.load(fh))


# ---------------------------------------------------------------------------
# Extraction
# ---------------------------------------------------------------------------


def _axes_of(params: Dict[str, Any]) -> Tuple[str, ...]:
    raw = params.get("axes", params.get("axis_name", ()))
    if raw is None:
        raw = ()
    if isinstance(raw, (str, int)):
        raw = (raw,)
    return tuple(str(a) for a in raw)


def _sub_jaxprs(eqn) -> List[Tuple[str, Any]]:
    """(context_name, jaxpr) pairs for every sub-jaxpr an equation
    carries — cond branches, while cond/body, scan/shard_map/pjit
    bodies, custom-VJP call jaxprs."""
    from jax.core import ClosedJaxpr, Jaxpr

    out: List[Tuple[str, Any]] = []
    name = eqn.primitive.name
    for k, v in eqn.params.items():
        vals = v if isinstance(v, (tuple, list)) else [v]
        for sub in vals:
            if isinstance(sub, ClosedJaxpr):
                out.append((name, sub.jaxpr))
            elif isinstance(sub, Jaxpr):
                out.append((name, sub))
    return out


class _Walker:
    def __init__(self) -> None:
        self.events: List[CollectiveEvent] = []
        self.n_barriers = 0

    def walk(self, jaxpr, context: Tuple[str, ...] = ()) -> None:
        import numpy as np

        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            if name == "optimization_barrier":
                self.n_barriers += 1
                continue
            kind = COLLECTIVE_PRIMS.get(name)
            if kind is not None:
                aval = eqn.invars[0].aval if eqn.invars else None
                shape = tuple(getattr(aval, "shape", ()) or ())
                dtype = getattr(aval, "dtype", None)
                count = int(np.prod(shape)) if shape else 1
                itemsize = np.dtype(dtype).itemsize if dtype is not None \
                    else 0
                self.events.append(CollectiveEvent(
                    index=len(self.events), op=kind,
                    axes=_axes_of(eqn.params),
                    dtype=(np.dtype(dtype).name if dtype is not None
                           else ""),
                    count=count, nbytes=count * itemsize,
                    context=context,
                    post_barrier=self.n_barriers > 0,
                    barriers_before=self.n_barriers))
                continue
            for sub_name, sub in _sub_jaxprs(eqn):
                # Transparent wrappers (pjit, closed_call, remat,
                # custom-AD calls, shard_map) keep the parent context;
                # genuine control flow is recorded by primitive name.
                if sub_name in ("cond", "while", "scan"):
                    self.walk(sub, context + (sub_name,))
                else:
                    self.walk(sub, context)


def extract_schedule(fn: Callable, *args: Any, label: str = "",
                     **kwargs: Any) -> ScheduleFingerprint:
    """Trace ``fn(*args, **kwargs)`` and extract its ordered collective
    schedule.  Pure trace — nothing executes on devices.  Call under
    the same mesh/axis bindings the real step uses (a ``shard_map``-
    wrapping fn binds its own axes and needs no context manager)."""
    import jax

    jaxpr = jax.make_jaxpr(fn)(*args, **kwargs)
    w = _Walker()
    w.walk(jaxpr.jaxpr)
    return ScheduleFingerprint(w.events, n_barriers=w.n_barriers,
                               label=label)


_HLO_COLLECTIVES = re.compile(
    r"\b(all[-_]reduce|reduce[-_]scatter|all[-_]gather|all[-_]to[-_]all|"
    r"collective[-_]permute)\b")


def hlo_collective_counts(fn: Callable, *args: Any,
                          **kwargs: Any) -> Counter:
    """Collective-op histogram of the *lowered* HLO/StableHLO text —
    the cross-check that what the jaxpr schedules is what XLA was
    handed (post-lowering fusion/CSE may legally shrink these counts;
    they must never grow)."""
    import jax

    txt = jax.jit(fn).lower(*args, **kwargs).as_text()
    canon = {"all-reduce": "all_reduce", "reduce-scatter": "reduce_scatter",
             "all-gather": "all_gather", "all-to-all": "all_to_all",
             "collective-permute": "collective_permute"}
    c: Counter = Counter()
    for m in _HLO_COLLECTIVES.finditer(txt):
        tok = m.group(1).replace("-", "_")
        c[canon.get(tok, tok)] += 1
    return c


# ---------------------------------------------------------------------------
# Verifier passes.  Each returns a list of finding dicts; empty = pass.
# ---------------------------------------------------------------------------


def _finding(check: str, message: str, **extra: Any) -> Dict[str, Any]:
    d = {"check": check, "message": message}
    d.update(extra)
    return d


def verify_no_data_dependent_collectives(
        fp: ScheduleFingerprint) -> List[Dict[str, Any]]:
    """Flag collectives under ``cond``/``while``: their issue count is
    data-dependent, so host-data divergence across ranks becomes a
    mismatched-collective hang (the desync class PR 6's forensics can
    only diagnose after the fact — this names it before it runs)."""
    out = []
    for e in fp.events:
        bad = [c for c in e.context if c in DATA_DEPENDENT_CONTEXTS]
        if bad:
            out.append(_finding(
                "data-dependent-collective",
                f"collective #{e.index} ({e.op} over {list(e.axes)}) is "
                f"issued under data-dependent control flow "
                f"{'/'.join(bad)} — a cross-rank desync hazard; hoist "
                f"the collective out of the branch or make the "
                f"predicate replicated-by-construction",
                event=e.to_dict()))
    return out


def verify_post_pin_psum_family(
        fp: ScheduleFingerprint) -> List[Dict[str, Any]]:
    """For hierarchical-transport programs: every collective issued
    after an ``optimization_barrier`` pin must be psum-family, because
    the pin erases replication tracking and only psum-family terminals
    re-establish it (the transport/hierarchy.py invariance contract)."""
    out = []
    for e in fp.events:
        if e.post_barrier and e.op not in PSUM_FAMILY:
            out.append(_finding(
                "post-pin-collective",
                f"collective #{e.index} ({e.op} over {list(e.axes)}) is "
                f"issued after a pin barrier but is not psum-family "
                f"({sorted(PSUM_FAMILY)}) — it cannot re-establish "
                f"replication over the reduce group",
                event=e.to_dict()))
    return out


def verify_bucket_plan_invariance(
        leaves: Sequence[Any],
        threshold_bytes: Optional[int] = None) -> List[Dict[str, Any]]:
    """The fusion bucket plan must be a pure function of the leaf
    sequence: repeat-stable, and invariant under dtype-order
    *interleaving* (the planner groups by canonical dtype name, so
    which dtype happens to appear first must not change the plan).
    Two ranks flattening the same pytree rely on exactly this to issue
    identical buckets."""
    from ..ops import device as dev
    from ..ops.overlap import overlap_schedule

    leaves = list(leaves)
    if not leaves:
        return []
    t = dev._validated_threshold(threshold_bytes)
    out = []

    plan_a = dev.fused_allreduce_buckets(leaves, t)
    plan_b = dev.fused_allreduce_buckets(leaves, t)
    if plan_a != plan_b:
        out.append(_finding(
            "bucket-plan-unstable",
            "fused_allreduce_buckets returned different plans for the "
            "same leaf sequence — nondeterministic planning breaks "
            "cross-rank seq alignment"))

    # Interleave dtypes differently while preserving within-dtype
    # order (the planner's documented equivalence class): round-robin
    # across the dtype groups instead of the original interleaving.
    import numpy as np

    groups: Dict[str, List[int]] = {}
    for i, leaf in enumerate(leaves):
        groups.setdefault(np.dtype(
            getattr(leaf, "dtype", np.float32)).name, []).append(i)
    if len(groups) > 1:
        queues = [list(v) for _, v in sorted(groups.items())]
        perm: List[int] = []
        while any(queues):
            for q in queues:
                if q:
                    perm.append(q.pop(0))
        permuted = [leaves[i] for i in perm]
        plan_p = dev.fused_allreduce_buckets(permuted, t)
        # Map the permuted plan back to original indices; bucket
        # composition must be identical.
        mapped = sorted(tuple(sorted(perm[i] for i in b)) for b in plan_p)
        orig = sorted(tuple(sorted(b)) for b in plan_a)
        if mapped != orig:
            out.append(_finding(
                "bucket-plan-permutation",
                "bucket plan changed under dtype-order interleaving of "
                "the same leaves — the plan depends on encounter order, "
                "not canonical dtype order"))

    # The overlap plan must stay the documented reversal of the fused
    # plan (bucket 0 = the leaves whose grads exist first).
    n = len(leaves)
    rev = dev.fused_allreduce_buckets(list(reversed(leaves)), t)
    expect = [[n - 1 - i for i in b] for b in rev]
    if overlap_schedule(leaves, t) != expect:
        out.append(_finding(
            "overlap-plan-drift",
            "overlap_schedule no longer equals the reverse-topological "
            "mapping of fused_allreduce_buckets — the issue order the "
            "barrier chain pins has drifted from the plan"))
    return out


def verify_a2a_ppermute_pairing(
        fp: ScheduleFingerprint) -> List[Dict[str, Any]]:
    """The 4D-schedule closure checks.

    * **a2a pairing** — MoE combine reverses dispatch, so every
      ``all_to_all`` signature (axes, dtype, element count) must appear
      an EVEN number of times per control-flow context: an odd count
      means tokens were scattered onto the expert axis and never
      gathered back (or a combine exchanges a different payload than
      its dispatch — either way the expert-parallel layout leaks out of
      the MoE block).  The int8 dispatch wire issues two a2a per leg
      (payload + scales); each signature still pairs across
      dispatch/combine, so the parity check holds for every wire.
    * **ppermute clocking** — every ``ppermute`` must sit under a
      ``scan`` context: the 1F1B microbatch clock is a ``lax.scan``,
      and a hand-rolled ppermute outside it runs outside the
      warmup/steady/cooldown accounting, so its ticks are invisible to
      the bubble-fraction telemetry the cost model is validated
      against."""
    out = []
    a2a: Dict[Tuple, List[CollectiveEvent]] = {}
    for e in fp.events:
        if e.op == "all_to_all":
            key = (e.axes, e.dtype, e.count, e.context)
            a2a.setdefault(key, []).append(e)
        elif e.op == "ppermute" and "scan" not in e.context:
            out.append(_finding(
                "ppermute-outside-scan",
                f"collective #{e.index} (ppermute over {list(e.axes)}) "
                f"is issued outside a scan body — it runs outside the "
                f"1F1B microbatch clock, so its ticks escape the "
                f"warmup/steady/cooldown phase accounting",
                event=e.to_dict()))
    for key, evs in sorted(a2a.items()):
        if len(evs) % 2 != 0:
            axes, dtype, count, _ = key
            out.append(_finding(
                "unpaired-all-to-all",
                f"all_to_all signature (axes={list(axes)}, dtype={dtype}, "
                f"count={count}) appears {len(evs)} time(s) — "
                f"dispatch/combine must pair, an odd count means the "
                f"expert-parallel layout leaks out of the MoE block",
                indices=[e.index for e in evs]))
    return out


def verify_flip_compat(step_a: Callable, step_b: Callable,
                       args: Sequence[Any], *,
                       state_a: Any = None, state_b: Any = None,
                       dim: str = "") -> Dict[str, Any]:
    """Verify an autotune leg pair is hot-swap compatible: identical
    optimizer-state treedefs (the one-state-tree contract every
    ``HVDT_AUTOTUNE_*`` dimension declares) and identical output avals,
    so the flip is a re-jit — a *schedule* delta only, never a state
    migration or a recompile-unsafe signature change.

    Returns ``{"compatible", "findings", "delta", "digest_a",
    "digest_b"}`` where ``delta`` is the per-op schedule count
    difference between the legs (legs legitimately lower differently —
    that is the point of the dimension)."""
    import jax

    findings: List[Dict[str, Any]] = []
    label = dim or "leg"
    if (state_a is None) != (state_b is None):
        findings.append(_finding(
            "flip-state-treedef",
            f"{label}: one leg produced optimizer state and the other "
            f"did not"))
    elif state_a is not None:
        td_a = jax.tree.structure(state_a)
        td_b = jax.tree.structure(state_b)
        if td_a != td_b:
            findings.append(_finding(
                "flip-state-treedef",
                f"{label}: optimizer state treedefs differ between legs "
                f"({td_a} vs {td_b}) — flipping mid-run would be a "
                f"state migration, not a re-jit"))
        else:
            shapes_a = [(getattr(l, "shape", None),
                         str(getattr(l, "dtype", "")))
                        for l in jax.tree.leaves(state_a)]
            shapes_b = [(getattr(l, "shape", None),
                         str(getattr(l, "dtype", "")))
                        for l in jax.tree.leaves(state_b)]
            if shapes_a != shapes_b:
                findings.append(_finding(
                    "flip-state-shapes",
                    f"{label}: optimizer state leaf shapes/dtypes differ "
                    f"between legs"))

    jaxpr_a = jax.make_jaxpr(step_a)(*args)
    jaxpr_b = jax.make_jaxpr(step_b)(*args)
    out_a = [(tuple(getattr(v, "shape", ())), str(getattr(v, "dtype", "")))
             for v in jaxpr_a.out_avals]
    out_b = [(tuple(getattr(v, "shape", ())), str(getattr(v, "dtype", "")))
             for v in jaxpr_b.out_avals]
    if out_a != out_b:
        findings.append(_finding(
            "flip-output-avals",
            f"{label}: step output avals differ between legs "
            f"({out_a} vs {out_b}) — the caller's downstream program "
            f"would need recompilation beyond the step itself"))

    wa, wb = _Walker(), _Walker()
    wa.walk(jaxpr_a.jaxpr)
    wb.walk(jaxpr_b.jaxpr)
    fp_a = ScheduleFingerprint(wa.events, wa.n_barriers, f"{label}:a")
    fp_b = ScheduleFingerprint(wb.events, wb.n_barriers, f"{label}:b")
    delta = dict(Counter(fp_b.counts()) - Counter(fp_a.counts()))
    delta.update({f"-{k}": v for k, v in
                  (Counter(fp_a.counts()) - Counter(fp_b.counts())).items()})
    return {
        "compatible": not findings,
        "findings": findings,
        "delta": delta,
        "digest_a": fp_a.digest,
        "digest_b": fp_b.digest,
    }


# ---------------------------------------------------------------------------
# Runtime cross-check: static-expected vs flight-recorder-observed
# ---------------------------------------------------------------------------


def first_schedule_deviation(
        events: Sequence[Dict[str, Any]],
        expected: Sequence[Dict[str, Any]],
        cyclic: bool = True) -> Optional[Dict[str, Any]]:
    """First flight-recorder event that disagrees with the static
    schedule, or None when every observed event matches.

    ``events`` are flight-recorder dicts (seq/op/dtype/...);
    ``expected`` are fingerprint event dicts.  The static schedule is
    one *step*; a run's seq stream repeats it, so matching is cyclic by
    seq (seq k matches expected entry ``(k-1) % len(expected)``).
    Op names compare via the recorder vocabulary (``event_op``); dtype
    compares only when both sides carry one."""
    if not expected:
        return None
    n = len(expected)
    for ev in sorted(events, key=lambda e: int(e.get("seq", 0))):
        seq = int(ev.get("seq", 0))
        idx = (seq - 1) % n if cyclic else seq - 1
        if idx < 0 or idx >= n:
            continue
        exp = expected[idx]
        exp_op = exp.get("event_op") or EVENT_OP_NAMES.get(
            str(exp.get("op", "")), str(exp.get("op", "")))
        obs_op = str(ev.get("op", "")).lower()
        mismatch = None
        if obs_op and exp_op and obs_op != exp_op:
            mismatch = f"op {obs_op!r} != expected {exp_op!r}"
        else:
            exp_dt = str(exp.get("dtype", ""))
            obs_dt = str(ev.get("dtype", ""))
            if exp_dt and obs_dt and exp_dt != obs_dt:
                mismatch = f"dtype {obs_dt!r} != expected {exp_dt!r}"
        if mismatch:
            return {
                "seq": seq,
                "reason": mismatch,
                "expected": dict(exp),
                "observed": {k: ev.get(k) for k in
                             ("op", "name", "dtype", "shape", "nbytes")},
            }
    return None
