"""``hvdt-lint`` — AST-based project lint engine with a rule registry.

Every correctness contract this codebase relies on is (was) enforced by
convention and hand-written tests: knobs must be declared in
``common/config.py``, version-sensitive jax APIs must be guarded for the
container's jax 0.4.37 (the exact set that broke PRs 1/3), env-gated
subsystems must keep a ``None``-when-unset zero-overhead path, nothing
feeding collective issue order may iterate a ``set``, and transient-
failure polls must ride ``resilience.retry.Backoff`` instead of bare
``time.sleep`` loops.  This module turns each convention into a checked
rule.

Ratcheting baseline: pre-existing violations are suppressed in a
baseline file (``.hvdt-lint-baseline.json`` at the repo root) **with a
written reason each**; anything not in the baseline fails the gate, so
the violation count can only go down.  Baseline keys hash the offending
source line (not its line number), so unrelated edits never churn the
file.

Pure stdlib (``ast``) — no jax import, safe to run anywhere, fast
enough to gate every CI run (``python -m horovod_tpu.analysis``).
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import json
import os
import re
from typing import Any, Dict, Iterable, List, Optional, Sequence, Set, Tuple

__all__ = [
    "Finding", "Rule", "RULES", "register", "lint_source", "lint_paths",
    "default_paths", "load_baseline", "save_baseline", "apply_baseline",
    "run_lint", "knob_table_markdown", "write_knob_table",
    "check_knob_docs", "declared_knobs", "metric_table_markdown",
    "write_metric_table", "check_metric_docs",
]

_KNOB_RE = re.compile(r"^HVDT_[A-Z0-9]+(?:_[A-Z0-9]+)*$")
_DOC_TOKEN_RE = re.compile(r"HVDT_[A-Z0-9_]*[A-Z0-9]")

# The jax APIs that broke the container repeatedly (jax 0.4.37 has none
# of them): attribute uses and imports must sit under a try/except or a
# getattr/hasattr probe (PRs 1/3; ops/device._axis_size_static is the
# blessed guarded helper).
VERSION_SENSITIVE_APIS = ("typeof", "pcast", "axis_size", "shard_map")


@dataclasses.dataclass
class Finding:
    """One lint violation.  ``key`` identifies it across edits: rule +
    path + a hash of the stripped source line + an occurrence index (for
    identical lines in one file) — line numbers are display-only."""

    rule: str
    path: str
    line: int
    message: str
    snippet: str = ""
    occurrence: int = 0

    @property
    def key(self) -> str:
        h = hashlib.sha1(self.snippet.strip().encode()).hexdigest()[:12]
        return f"{self.rule}:{self.path}:{h}:{self.occurrence}"

    def format(self) -> str:
        return (f"{self.path}:{self.line}: [{self.rule}] {self.message}")


class Rule:
    """Base lint rule: subclass, set ``name``/``doc``, implement
    :meth:`check` yielding :class:`Finding`."""

    name = "base"
    doc = ""

    def check(self, tree: ast.Module, src: str, path: str,
              ctx: "LintContext") -> Iterable[Finding]:
        raise NotImplementedError


RULES: List[Rule] = []


def register(cls):
    RULES.append(cls())
    return cls


@dataclasses.dataclass
class LintContext:
    """Shared facts rules consult (knob registry, repo root)."""

    declared: Set[str]
    contract: Set[str]
    root: str = ""


def _line_of(src_lines: List[str], lineno: int) -> str:
    if 1 <= lineno <= len(src_lines):
        return src_lines[lineno - 1]
    return ""


def _parent_map(tree: ast.AST) -> Dict[ast.AST, ast.AST]:
    parents: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


def _ancestors(node: ast.AST,
               parents: Dict[ast.AST, ast.AST]) -> Iterable[ast.AST]:
    cur = parents.get(node)
    while cur is not None:
        yield cur
        cur = parents.get(cur)


def _attr_chain(node: ast.AST) -> Tuple[str, ...]:
    """('jax', 'lax', 'pcast') for nested Attribute/Name access."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return tuple(reversed(parts))


def _in_try(node: ast.AST, parents: Dict[ast.AST, ast.AST]) -> bool:
    return any(isinstance(a, ast.Try) and a.handlers
               for a in _ancestors(node, parents))


def _enclosing_function(node: ast.AST, parents: Dict[ast.AST, ast.AST]
                        ) -> Optional[ast.AST]:
    for a in _ancestors(node, parents):
        if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return a
    return None


def _has_version_probe(scope: ast.AST) -> bool:
    """True when ``scope`` contains a getattr/hasattr probe for any
    version-sensitive API name — the function is version-aware and its
    direct uses are reachable only on capable jax builds."""
    for n in ast.walk(scope):
        if (isinstance(n, ast.Call) and isinstance(n.func, ast.Name)
                and n.func.id in ("getattr", "hasattr")):
            for arg in n.args:
                if (isinstance(arg, ast.Constant)
                        and isinstance(arg.value, str)
                        and arg.value in VERSION_SENSITIVE_APIS):
                    return True
    return False


# ---------------------------------------------------------------------------
# Rules
# ---------------------------------------------------------------------------


@register
class KnobDriftRule(Rule):
    """Every ``HVDT_*`` name read anywhere in the tree must be declared
    in ``common/config.py`` — as a :class:`Knob` (operator-facing, doc'd
    in the knob table) or a ``CONTRACT_VARS`` entry (launcher/driver
    internal wiring).  An undeclared read is a knob that silently does
    nothing when the operator typos it and never shows up in docs."""

    name = "knob-drift"
    doc = ("HVDT_* env reads must be declared in common/config.py "
           "(Knob or CONTRACT_VARS)")

    def check(self, tree, src, path, ctx):
        if path.endswith(os.path.join("common", "config.py")):
            return
        lines = src.splitlines()
        parents = _parent_map(tree)
        seen: Dict[str, int] = {}
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Constant)
                    and isinstance(node.value, str)
                    and _KNOB_RE.match(node.value)):
                continue
            # Skip docstrings / bare string statements.
            parent = parents.get(node)
            if isinstance(parent, ast.Expr):
                continue
            name = node.value
            if name in ctx.declared or name in ctx.contract:
                continue
            snippet = _line_of(lines, node.lineno)
            occ = seen.get(name, 0)
            seen[name] = occ + 1
            yield Finding(
                self.name, path, node.lineno,
                f"env var {name!r} is read but not declared in "
                f"common/config.py (add a Knob, or a CONTRACT_VARS "
                f"entry if it is launcher-internal wiring)",
                snippet=snippet, occurrence=occ)


@register
class UnguardedJaxApiRule(Rule):
    """``jax.typeof`` / ``lax.pcast`` / ``lax.axis_size`` /
    ``jax.shard_map`` (and shard_map imports) raise AttributeError or
    ImportError on the container's jax 0.4.37 unless guarded by
    try/except or a getattr/hasattr probe — the exact breakage class of
    PRs 1/3.  Use ``ops.device._axis_size_static`` and the guarded
    import idiom instead."""

    name = "unguarded-jax-api"
    doc = ("version-sensitive jax APIs (typeof/pcast/axis_size/"
           "shard_map) must be guarded for jax 0.4.37")

    _SENSITIVE_TAILS = {
        ("jax", "typeof"), ("lax", "pcast"), ("lax", "axis_size"),
        ("jax", "shard_map"),
    }

    def _is_sensitive(self, chain: Tuple[str, ...]) -> bool:
        if len(chain) < 2:
            return False
        tail2 = chain[-2:]
        if tail2 in self._SENSITIVE_TAILS:
            return True
        # jax.lax.pcast / jax.lax.axis_size
        return (len(chain) >= 3 and chain[-3] == "jax"
                and chain[-2] == "lax"
                and chain[-1] in ("pcast", "axis_size"))

    def check(self, tree, src, path, ctx):
        lines = src.splitlines()
        parents = _parent_map(tree)
        seen: Dict[str, int] = {}

        def emit(node, what):
            snippet = _line_of(lines, node.lineno)
            occ = seen.get(what, 0)
            seen[what] = occ + 1
            return Finding(
                self.name, path, node.lineno,
                f"{what} is absent on jax 0.4.37 — guard with "
                f"try/except or getattr (see "
                f"ops.device._axis_size_static / the guarded "
                f"shard_map import idiom)",
                snippet=snippet, occurrence=occ)

        for node in ast.walk(tree):
            if isinstance(node, ast.Attribute):
                chain = _attr_chain(node)
                if not self._is_sensitive(chain):
                    continue
                if _in_try(node, parents):
                    continue
                fn = _enclosing_function(node, parents)
                if fn is not None and _has_version_probe(fn):
                    continue
                yield emit(node, ".".join(chain))
            elif isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                if mod in ("jax", "jax.experimental.shard_map",
                           "jax.experimental"):
                    for alias in node.names:
                        if alias.name == "shard_map" and \
                                not _in_try(node, parents):
                            yield emit(
                                node, f"'from {mod} import shard_map'")


@register
class ZeroOverheadGateRule(Rule):
    """An env-gated singleton accessor (module-level ``get_*`` that
    reads ``os.environ``) must carry a ``None``-when-unset path — the
    zero-overhead identity contract every optional subsystem
    (overlap/transport/faults/flight-recorder/telemetry) pins: feed
    sites branch on ``is None`` and the off path stays the exact
    pre-existing code objects."""

    name = "zero-overhead-gate"
    doc = ("env-gated get_*() accessors must have a None-when-unset "
           "path (zero-overhead identity contract)")

    def check(self, tree, src, path, ctx):
        lines = src.splitlines()
        for node in tree.body:
            if not (isinstance(node, ast.FunctionDef)
                    and re.match(r"^get_\w+$", node.name)):
                continue
            reads_env = any(
                _attr_chain(n)[-2:] == ("os", "environ")
                for n in ast.walk(node))
            if not reads_env:
                continue
            has_none = any(
                isinstance(n, ast.Constant) and n.value is None
                for n in ast.walk(node))
            if not has_none:
                yield Finding(
                    self.name, path, node.lineno,
                    f"{node.name}() reads os.environ but has no "
                    f"None-when-unset path — the disabled state must "
                    f"cost one env read and return None so feed sites "
                    f"can branch on `is None`",
                    snippet=_line_of(lines, node.lineno))


@register
class NondeterministicIterationRule(Rule):
    """Iterating a ``set``/``frozenset`` yields a hash-seed-dependent
    order.  Anything order-sensitive downstream — bucket plans,
    collective issue order, broadcast payloads — then differs across
    ranks, which IS the mismatched-collective desync.  Wrap in
    ``sorted(...)``."""

    name = "nondet-iteration"
    doc = ("no bare set/frozenset iteration (hash-order differs "
           "across ranks) — wrap in sorted()")

    def _is_set_expr(self, node: ast.AST) -> bool:
        if isinstance(node, (ast.Set, ast.SetComp)):
            return True
        return (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id in ("set", "frozenset"))

    def check(self, tree, src, path, ctx):
        lines = src.splitlines()
        seen: Dict[str, int] = {}
        for node in ast.walk(tree):
            target = None
            if isinstance(node, ast.For) and self._is_set_expr(node.iter):
                target = node.iter
            elif isinstance(node, (ast.ListComp, ast.SetComp,
                                   ast.GeneratorExp, ast.DictComp)):
                for gen in node.generators:
                    if self._is_set_expr(gen.iter):
                        target = gen.iter
                        break
            if target is None:
                continue
            snippet = _line_of(lines, target.lineno)
            occ = seen.get(snippet, 0)
            seen[snippet] = occ + 1
            yield Finding(
                self.name, path, target.lineno,
                "iterating a set/frozenset: hash order is per-process "
                "— if this order feeds collective issue order or any "
                "cross-rank payload it desyncs; wrap in sorted(...)",
                snippet=snippet, occurrence=occ)


@register
class MagicPeakFlopsRule(Rule):
    """Hardware peak-rate literals (device FLOP/s, HBM/link byte/s —
    anything >= 1e11) have exactly two homes: the
    ``telemetry/step_stats.py`` device-peak table (the MFU gauge) and
    the ``analysis/topology.py`` link-constants module (the cost
    model).  A peak literal anywhere else is a second source of truth
    that silently drifts when a new TPU generation lands — the fitter
    and the MFU gauge must read the same numbers."""

    name = "magic-peak-flops"
    doc = ("no hardware peak-rate literals (the topology.py "
           "PEAK_LITERAL window) outside telemetry/step_stats.py and "
           "analysis/topology.py")

    _ALLOWED = (os.path.join("telemetry", "step_stats.py"),
                os.path.join("analysis", "topology.py"))

    def check(self, tree, src, path, ctx):
        if any(path.endswith(a) for a in self._ALLOWED):
            return
        # The classification window itself lives in the constants
        # module this rule enforces — no literal here either.
        from .topology import PEAK_LITERAL_CEIL, PEAK_LITERAL_FLOOR

        lines = src.splitlines()
        seen: Dict[str, int] = {}
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Constant)
                    and isinstance(node.value, (int, float))
                    and not isinstance(node.value, bool)):
                continue
            try:
                v = abs(float(node.value))
            except OverflowError:
                v = float("inf")
            if not (PEAK_LITERAL_FLOOR <= v <= PEAK_LITERAL_CEIL):
                continue
            snippet = _line_of(lines, node.lineno)
            occ = seen.get(snippet, 0)
            seen[snippet] = occ + 1
            yield Finding(
                self.name, path, node.lineno,
                f"hardware-rate-sized literal {node.value!r}: peak "
                f"FLOP/s / bandwidth numbers live in telemetry/"
                f"step_stats.PEAK_BY_DEVICE_KIND or analysis/topology "
                f"constants — import them so the MFU gauge and the "
                f"cost model can never disagree",
                snippet=snippet, occurrence=occ)


@register
class MetricDriftRule(Rule):
    """Every metric the package constructs by literal name
    (``registry.counter("hvdt_...")`` / ``Counter("hvdt_...")`` /
    ``.gauge`` / ``.summary``) must be declared in the
    ``telemetry/metrics.py`` CATALOG — the registry ``docs/metrics.md``
    is generated from.  An undeclared construction is a metric that
    never reaches the docs and silently forks the naming scheme
    (the knob-drift contract applied to metrics)."""

    name = "metric-drift"
    doc = ("hvdt_*/serve_* metric constructions must be declared in "
           "telemetry/metrics.py CATALOG")

    _METHODS = ("counter", "gauge", "summary")
    _CLASSES = ("Counter", "Gauge", "Summary")
    _PREFIXES = ("hvdt_", "serve_")

    def check(self, tree, src, path, ctx):
        # The catalog module itself declares, it doesn't construct.
        if path.endswith(os.path.join("telemetry", "metrics.py")):
            return
        from ..telemetry.metrics import declared_metric

        lines = src.splitlines()
        seen: Dict[str, int] = {}
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            fn = node.func
            is_metric_call = (
                (isinstance(fn, ast.Attribute)
                 and fn.attr in self._METHODS)
                or (isinstance(fn, ast.Name) and fn.id in self._CLASSES))
            if not is_metric_call:
                continue
            arg = node.args[0]
            if not (isinstance(arg, ast.Constant)
                    and isinstance(arg.value, str)):
                continue   # dynamic names ride catalog wildcards
            name = arg.value
            if not name.startswith(self._PREFIXES):
                continue   # collections.Counter & friends
            if declared_metric(name):
                continue
            snippet = _line_of(lines, node.lineno)
            occ = seen.get(name, 0)
            seen[name] = occ + 1
            yield Finding(
                self.name, path, node.lineno,
                f"metric {name!r} is constructed but not declared in "
                f"telemetry/metrics.py CATALOG — add a MetricSpec "
                f"(name/kind/labels/doc) and regenerate docs/metrics.md "
                f"(python -m horovod_tpu.analysis --metric-table "
                f"--write docs/metrics.md)",
                snippet=snippet, occurrence=occ)


@register
class SleepPollRule(Rule):
    """A ``time.sleep`` inside a ``while`` loop is a hand-rolled poll:
    fixed-interval retries synchronize into thundering herds and have
    no deadline.  ``resilience.retry.Backoff`` (exponential, jittered,
    deadline-bounded) is the mandated primitive."""

    name = "sleep-poll"
    doc = ("no bare time.sleep polling loops — use "
           "resilience.retry.Backoff")

    _EXEMPT = (os.path.join("resilience", "retry.py"),)

    def check(self, tree, src, path, ctx):
        if any(path.endswith(e) for e in self._EXEMPT):
            return
        lines = src.splitlines()
        parents = _parent_map(tree)
        from_time_sleep = any(
            isinstance(n, ast.ImportFrom) and n.module == "time"
            and any(a.name == "sleep" for a in n.names)
            for n in ast.walk(tree))
        seen: Dict[str, int] = {}
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            is_sleep = (_attr_chain(node.func)[-2:] == ("time", "sleep")
                        or (from_time_sleep
                            and isinstance(node.func, ast.Name)
                            and node.func.id == "sleep"))
            if not is_sleep:
                continue
            if not any(isinstance(a, (ast.While, ast.For))
                       for a in _ancestors(node, parents)):
                continue
            snippet = _line_of(lines, node.lineno)
            occ = seen.get(snippet, 0)
            seen[snippet] = occ + 1
            yield Finding(
                self.name, path, node.lineno,
                "bare time.sleep inside a loop — polling must ride "
                "resilience.retry.Backoff (exponential + full jitter "
                "+ deadline) so concurrent retriers decorrelate and "
                "dead dependencies cannot hang the caller",
                snippet=snippet, occurrence=occ)


# ---------------------------------------------------------------------------
# Engine
# ---------------------------------------------------------------------------


def declared_knobs() -> Tuple[Set[str], Set[str]]:
    """(knob names, contract var names) from the live registry."""
    from ..common import config

    contract = set(getattr(config, "CONTRACT_VARS", ()))
    return set(config.KNOBS), contract


def _make_context(root: str) -> LintContext:
    declared, contract = declared_knobs()
    return LintContext(declared=declared, contract=contract, root=root)


def lint_source(src: str, path: str,
                ctx: Optional[LintContext] = None,
                rules: Optional[Sequence[Rule]] = None) -> List[Finding]:
    """Run the rule registry over one source string (the unit-test
    entry point — fixtures feed crafted sources through here)."""
    ctx = ctx or _make_context("")
    tree = ast.parse(src)
    out: List[Finding] = []
    for rule in (rules if rules is not None else RULES):
        out.extend(rule.check(tree, src, path, ctx))
    return sorted(out, key=lambda f: (f.path, f.line, f.rule))


def default_paths(root: str) -> List[str]:
    """The lint scan set: every .py under horovod_tpu/ (the package
    lints itself, analysis/ included)."""
    pkg = os.path.join(root, "horovod_tpu")
    out: List[str] = []
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for f in sorted(filenames):
            if f.endswith(".py"):
                out.append(os.path.join(dirpath, f))
    return out


def lint_paths(paths: Sequence[str], root: str = "",
               rules: Optional[Sequence[Rule]] = None) -> List[Finding]:
    ctx = _make_context(root)
    out: List[Finding] = []
    for p in paths:
        try:
            with open(p, encoding="utf-8") as fh:
                src = fh.read()
        except OSError:
            continue
        rel = os.path.relpath(p, root) if root else p
        try:
            tree = ast.parse(src)
        except SyntaxError as e:
            out.append(Finding("syntax", rel, e.lineno or 0,
                               f"unparseable: {e.msg}"))
            continue
        for rule in (rules if rules is not None else RULES):
            out.extend(rule.check(tree, src, rel, ctx))
    return sorted(out, key=lambda f: (f.path, f.line, f.rule))


# ---------------------------------------------------------------------------
# Ratcheting baseline
# ---------------------------------------------------------------------------

BASELINE_NAME = ".hvdt-lint-baseline.json"


def load_baseline(path: str) -> Dict[str, str]:
    """key -> reason map; missing file = empty baseline."""
    try:
        with open(path) as fh:
            doc = json.load(fh)
    except OSError:
        return {}
    return {s["key"]: s.get("reason", "")
            for s in doc.get("suppressions", [])}


def save_baseline(path: str, findings: Sequence[Finding],
                  reasons: Optional[Dict[str, str]] = None,
                  keep: Optional[Dict[str, str]] = None) -> None:
    """Write the ratchet file: current findings (with any reasons
    already on record) plus ``keep`` — non-lint suppressions (lock
    cycles) carried through an update."""
    reasons = reasons or {}
    doc = {
        "version": 1,
        "comment": ("hvdt-lint ratchet baseline: pre-existing "
                    "violations, each with a written reason.  New "
                    "findings FAIL the gate — fix them or add a "
                    "reasoned entry here.  Regenerate keys with "
                    "`python -m horovod_tpu.analysis --lint "
                    "--update-baseline`."),
        "suppressions": [
            {"key": f.key, "rule": f.rule, "path": f.path,
             "line": f.line,
             "reason": reasons.get(f.key, "baselined pre-existing "
                                   "violation — needs a written reason")}
            for f in findings] + [
            {"key": k, "rule": k.split(":", 1)[0], "reason": r}
            for k, r in sorted((keep or {}).items())],
    }
    with open(path, "w") as fh:
        json.dump(doc, fh, indent=2)
        fh.write("\n")


def apply_baseline(findings: Sequence[Finding], baseline: Dict[str, str]
                   ) -> Tuple[List[Finding], List[Finding], List[str]]:
    """(new, suppressed, stale_keys): findings not in the baseline fail
    the gate; baseline keys matching nothing are stale (the violation
    was fixed — prune them to ratchet down)."""
    new, suppressed = [], []
    live_keys = set()
    for f in findings:
        if f.key in baseline:
            suppressed.append(f)
            live_keys.add(f.key)
        else:
            new.append(f)
    stale = sorted(k for k in baseline if k not in live_keys)
    return new, suppressed, stale


def run_lint(root: str, baseline_path: Optional[str] = None,
             update_baseline: bool = False,
             paths: Optional[Sequence[str]] = None
             ) -> Tuple[List[Finding], List[Finding], List[str]]:
    """Lint the repo against the ratchet baseline.  Returns
    (new, suppressed, stale_keys); the CI gate fails on any new."""
    bp = baseline_path or os.path.join(root, BASELINE_NAME)
    findings = lint_paths(paths or default_paths(root), root=root)
    baseline = load_baseline(bp)
    if update_baseline:
        save_baseline(bp, findings, reasons=baseline)
        return [], findings, []
    return apply_baseline(findings, baseline)


# ---------------------------------------------------------------------------
# Knob table: generated docs + drift check (the knob-table satellite)
# ---------------------------------------------------------------------------

_GENERATED_MARK = ("<!-- generated by `python -m horovod_tpu.analysis "
                   "--knob-table --write docs/knobs.md` — do not edit "
                   "by hand -->")


def _squash(doc: str) -> str:
    return re.sub(r"\s+", " ", doc).strip().replace("|", "\\|")


def knob_table_markdown() -> str:
    """The full knob registry as one markdown table (the docs rows the
    knob-drift killer generates instead of letting humans chase 125+
    knobs by hand)."""
    from ..common import config

    lines = ["| Knob | Default | Description |", "|---|---|---|"]
    for name in sorted(config.KNOBS):
        k = config.KNOBS[name]
        lines.append(f"| `{name}` | `{k.default!r}` | {_squash(k.doc)} |")
    contract = getattr(config, "CONTRACT_VARS", {})
    if contract:
        lines += ["", "### Internal env contract (not operator knobs)",
                  "",
                  "| Var | Set by / meaning |", "|---|---|"]
        for name in sorted(contract):
            lines.append(f"| `{name}` | {_squash(contract[name])} |")
    return "\n".join(lines)


def render_knob_doc() -> str:
    return "\n".join([
        "# Runtime knob registry",
        "",
        _GENERATED_MARK,
        "",
        "Single source of truth: `horovod_tpu/common/config.py`.  "
        "Precedence: CLI > env > config file > built-in default "
        "(docs/launcher.md).  `python -m horovod_tpu.analysis "
        "--knob-table --check` gates drift between this table, the "
        "registry, and every `HVDT_*` mention across docs/.",
        "",
        knob_table_markdown(),
        "",
    ])


def write_knob_table(path: str) -> str:
    with open(path, "w") as fh:
        fh.write(render_knob_doc())
    return path


_METRIC_MARK = ("<!-- generated by `python -m horovod_tpu.analysis "
                "--metric-table --write docs/metrics.md` — do not edit "
                "by hand -->")


def metric_table_markdown() -> str:
    """The metric CATALOG as markdown tables grouped by kind (the
    docs/knobs.md pattern applied to metrics)."""
    from ..telemetry.metrics import CATALOG

    lines = ["| Metric | Type | Labels | Description |",
             "|---|---|---|---|"]
    for name in sorted(CATALOG):
        s = CATALOG[name]
        labels = ", ".join(f"`{lb}`" for lb in s.labels) or "—"
        lines.append(f"| `{name}` | {s.kind} | {labels} | "
                     f"{_squash(s.doc)} |")
    return "\n".join(lines)


def render_metric_doc() -> str:
    return "\n".join([
        "# Metric registry",
        "",
        _METRIC_MARK,
        "",
        "Single source of truth: the CATALOG in "
        "`horovod_tpu/telemetry/metrics.py`.  Every "
        "Counter/Gauge/Summary the package constructs must be declared "
        "there — the `metric-drift` lint rule fails CI on any literal "
        "metric name missing from the catalog, and `python -m "
        "horovod_tpu.analysis --metric-table --check` gates drift "
        "between the catalog and this table.  Names ending in `*` are "
        "prefix wildcards for dynamically-formatted families.  See "
        "docs/observability.md for semantics and scrape examples.",
        "",
        metric_table_markdown(),
        "",
    ])


def write_metric_table(path: str) -> str:
    with open(path, "w") as fh:
        fh.write(render_metric_doc())
    return path


def check_metric_docs(root: str) -> List[str]:
    """Freshness check: docs/metrics.md must match the generated
    catalog table."""
    problems: List[str] = []
    metrics_md = os.path.join(root, "docs", "metrics.md")
    try:
        current = open(metrics_md).read()
    except OSError:
        problems.append("docs/metrics.md missing — generate it with "
                        "`python -m horovod_tpu.analysis --metric-table "
                        "--write docs/metrics.md`")
        current = ""
    if current and current.strip() != render_metric_doc().strip():
        problems.append("docs/metrics.md is stale vs telemetry/metrics."
                        "py CATALOG — regenerate with `python -m "
                        "horovod_tpu.analysis --metric-table --write "
                        "docs/metrics.md`")
    return problems


def check_knob_docs(root: str) -> List[str]:
    """Drift check between the registry and the docs tree.  Failures:

    * ``docs/knobs.md`` missing or stale vs the generated table (every
      declared knob therefore appears in a docs knob table);
    * any ``HVDT_*`` token anywhere in ``docs/*.md`` that names neither
      a declared knob, a contract var, nor a declared-name prefix
      (wildcard mentions like ``HVDT_SERVE_*``).
    """
    problems: List[str] = []
    declared, contract = declared_knobs()
    known = declared | set(contract)

    knobs_md = os.path.join(root, "docs", "knobs.md")
    try:
        current = open(knobs_md).read()
    except OSError:
        problems.append("docs/knobs.md missing — generate it with "
                        "`python -m horovod_tpu.analysis --knob-table "
                        "--write docs/knobs.md`")
        current = ""
    if current and current.strip() != render_knob_doc().strip():
        problems.append("docs/knobs.md is stale vs common/config.py — "
                        "regenerate with `python -m horovod_tpu."
                        "analysis --knob-table --write docs/knobs.md`")

    docs_dir = os.path.join(root, "docs")
    try:
        md_files = sorted(f for f in os.listdir(docs_dir)
                          if f.endswith(".md"))
    except OSError:
        md_files = []
    for f in md_files:
        text = open(os.path.join(docs_dir, f)).read()
        for tok in sorted(set(_DOC_TOKEN_RE.findall(text))):
            if tok in known:
                continue
            if any(name.startswith(tok + "_") for name in known):
                continue   # prefix/wildcard mention (HVDT_SERVE_*)
            problems.append(
                f"docs/{f}: mentions {tok!r} which is neither a "
                f"declared knob nor a CONTRACT_VARS entry")
    return problems
