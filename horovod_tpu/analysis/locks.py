"""Static lock-order graph over the threaded control plane.

The serving router, dynamic batcher, replica autoscaler, async
checkpoint writer, stall escalator, and the eager controller loop are
all lock-per-object threaded code.  A deadlock between two of them
would present exactly like a training stall — the escalator would abort
and the flight recorder would show *nothing* divergent, because the
hang is host-side.  This module makes the acquisition order a checked
artifact instead of a convention:

* :func:`extract_lock_graph` walks each module's AST collecting nested
  ``with <lock>:`` acquisitions (``with a: ... with b:`` and
  ``with a, b:`` both record the edge ``a -> b``).  A context
  expression counts as a lock when its terminal name ends in ``lock``
  or ``mutex`` (``self._lock``, ``kv_server.lock``, ``_cache_lock``).
  Locks are keyed per class (``module.Class.name``) so two classes'
  private ``_lock`` attributes stay distinct nodes.

* :func:`find_cycles` runs SCC detection over the merged graph; any
  cycle is a potential ABBA deadlock.  Cycles are reported with every
  edge's acquisition site and gated through the same ratcheting
  baseline as the lint rules (cycle keys are canonical rotations, so
  unrelated edits never churn them).

Static analysis cannot see acquisitions made through function calls or
locks aliased through locals — the graph is a *lower bound*.  That is
the useful direction for a ratchet: every edge it does see is real, so
a new cycle is a real ordering inversion introduced by the change under
review.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

__all__ = ["LockEdge", "extract_lock_graph", "find_cycles",
           "cycle_key", "run_locks", "format_edge"]

_LOCK_SUFFIXES = ("lock", "mutex")


def _lock_name(expr: ast.AST) -> Optional[str]:
    """Terminal dotted name when ``expr`` looks like a lock, else None.
    ``with self._lock:`` -> 'self._lock'; ``with lock:`` -> 'lock'."""
    parts: List[str] = []
    node = expr
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    else:
        return None
    parts.reverse()
    leaf = parts[-1].lower().lstrip("_")
    if any(leaf == s or leaf.endswith("_" + s) for s in _LOCK_SUFFIXES):
        return ".".join(parts)
    return None


@dataclasses.dataclass(frozen=True)
class LockEdge:
    """outer acquired, then inner, while outer still held."""

    outer: str
    inner: str
    path: str
    line: int


def format_edge(e: LockEdge) -> str:
    return f"{e.outer} -> {e.inner} ({e.path}:{e.line})"


class _LockWalker(ast.NodeVisitor):
    def __init__(self, relpath: str, modname: str):
        self.relpath = relpath
        self.modname = modname
        self.class_stack: List[str] = []
        self.held: List[str] = []
        self.edges: List[LockEdge] = []

    def _qualify(self, name: str) -> str:
        scope = ".".join([self.modname] + self.class_stack) \
            if self.class_stack else self.modname
        return f"{scope}:{name}"

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.class_stack.append(node.name)
        self.generic_visit(node)
        self.class_stack.pop()

    def visit_With(self, node: ast.With) -> None:
        acquired: List[str] = []
        for item in node.items:
            name = _lock_name(item.context_expr)
            if name is None:
                continue
            q = self._qualify(name)
            for held in self.held + acquired:
                if held != q:
                    self.edges.append(LockEdge(held, q, self.relpath,
                                               node.lineno))
            acquired.append(q)
        self.held.extend(acquired)
        for stmt in node.body:
            self.visit(stmt)
        for _ in acquired:
            self.held.pop()

    visit_AsyncWith = visit_With  # type: ignore[assignment]


def extract_lock_graph(paths: Sequence[str], root: str = ""
                       ) -> List[LockEdge]:
    """Every statically-visible nested lock acquisition across
    ``paths`` (deduplicated by (outer, inner, site))."""
    edges: List[LockEdge] = []
    seen: Set[Tuple[str, str, str, int]] = set()
    for p in paths:
        try:
            src = open(p, encoding="utf-8").read()
            tree = ast.parse(src)
        except (OSError, SyntaxError):
            continue
        rel = os.path.relpath(p, root) if root else p
        modname = os.path.splitext(rel.replace(os.sep, "/"))[0]
        w = _LockWalker(rel, modname)
        w.visit(tree)
        for e in w.edges:
            k = (e.outer, e.inner, e.path, e.line)
            if k not in seen:
                seen.add(k)
                edges.append(e)
    return edges


def cycle_key(cycle: Sequence[str]) -> str:
    """Canonical (rotation-invariant) identity of a lock cycle — the
    baseline key that survives unrelated edits."""
    nodes = list(cycle)
    i = nodes.index(min(nodes))
    rot = nodes[i:] + nodes[:i]
    return "lock-cycle:" + "->".join(rot)


def find_cycles(edges: Iterable[LockEdge]) -> List[List[str]]:
    """Elementary cycles over the acquisition-order graph (DFS per SCC;
    multi-node SCCs are reported as their shortest constituent cycle
    per back edge).  Any cycle = two code paths that can interleave
    into an ABBA deadlock."""
    graph: Dict[str, Set[str]] = {}
    for e in edges:
        graph.setdefault(e.outer, set()).add(e.inner)
        graph.setdefault(e.inner, set())

    cycles: List[List[str]] = []
    seen_keys: Set[str] = set()

    def dfs(start: str) -> None:
        stack: List[Tuple[str, List[str]]] = [(start, [start])]
        while stack:
            node, path = stack.pop()
            for nxt in sorted(graph.get(node, ())):
                if nxt == start:
                    key = cycle_key(path)
                    if key not in seen_keys:
                        seen_keys.add(key)
                        cycles.append(list(path))
                elif nxt not in path and len(path) < 8:
                    stack.append((nxt, path + [nxt]))

    for n in sorted(graph):
        dfs(n)
    return sorted(cycles, key=cycle_key)


def run_locks(root: str, paths: Optional[Sequence[str]] = None,
              baseline: Optional[Dict[str, str]] = None
              ) -> Tuple[List[List[str]], List[LockEdge]]:
    """(new cycles not in baseline, full edge list)."""
    from .lint import default_paths

    edges = extract_lock_graph(paths or default_paths(root), root=root)
    baseline = baseline or {}
    new = [c for c in find_cycles(edges)
           if cycle_key(c) not in baseline]
    return new, edges
