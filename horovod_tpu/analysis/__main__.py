"""``python -m horovod_tpu.analysis`` — the static-analysis CI gate.

Modes (``--all`` = lint + locks + knob-table check + schedule
self-check; the default with no flags):

* ``--lint``            run the AST rule registry against the ratchet
                        baseline (``.hvdt-lint-baseline.json``)
* ``--locks``           lock-order graph; new cycles fail
* ``--knob-table``      print the generated knob table
  (``--write PATH``    write it, e.g. ``--write docs/knobs.md``;
  ``--check``          fail on registry/docs drift)
* ``--metric-table``    print the generated metric-catalog table
  (``--write PATH``    write it, e.g. ``--write docs/metrics.md``;
  ``--check``          fail on catalog/docs drift)
* ``--report PATH``     render a post-mortem markdown report from an
                        anomaly event log (HVDT_EVENT_LOG JSONL) or an
                        artifact directory (``--report-out`` writes it)
* ``--selfcheck``       trace the reference overlapped + hierarchical
                        step and run every schedule verifier pass
* ``--schedule OUT``    export the self-check step's fingerprint JSON
                        (feed it to ``HVDT_EXPECTED_SCHEDULE``)
* ``--update-baseline`` re-key the baseline from current findings
                        (keeps written reasons and lock suppressions)
* ``--dump-locks``      print the full acquisition-order edge list

Exit code 0 = every requested gate clean; 1 = violations.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

__all__ = ["main"]


def _repo_root(explicit: Optional[str]) -> str:
    if explicit:
        return os.path.abspath(explicit)
    # package lives at <root>/horovod_tpu/analysis/__main__.py
    here = os.path.dirname(os.path.abspath(__file__))
    return os.path.dirname(os.path.dirname(here))


def _gate_lint(root: str, baseline: str, update: bool,
               fail_on_stale: bool = False) -> int:
    from .lint import run_lint

    new, suppressed, stale = run_lint(root, baseline_path=baseline,
                                      update_baseline=update)
    if update:
        print(f"hvdt-lint: baseline rewritten with "
              f"{len(suppressed)} suppression(s) -> {baseline}")
        return 0
    for f in new:
        print(f.format())
    # Lock-cycle suppressions are keyed/validated by the locks gate,
    # not by lint findings — never count them stale here.
    stale_hard = [k for k in stale if not k.startswith("lock-cycle:")]
    if stale:
        verdict = ("FAIL stale-baseline" if fail_on_stale and stale_hard
                   else "stale")
        print(f"hvdt-lint: {verdict} — {len(stale)} baseline "
              f"suppression(s) match no current source line "
              f"(violation fixed or line edited; prune with "
              f"--update-baseline):")
        for k in stale:
            print(f"  {k}")
    print(f"hvdt-lint: {len(new)} new, {len(suppressed)} baselined, "
          f"{len(stale)} stale")
    return 1 if (new or (fail_on_stale and stale_hard)) else 0


def _gate_locks(root: str, baseline: str, dump: bool) -> int:
    from .lint import load_baseline
    from .locks import find_cycles, format_edge, run_locks

    cycles, edges = run_locks(root, baseline=load_baseline(baseline))
    if dump:
        for e in edges:
            print(format_edge(e))
    n_total = len(find_cycles(edges))
    for c in cycles:
        print("lock-order cycle: " + " -> ".join(c + [c[0]]))
    print(f"hvdt-locks: {len(edges)} acquisition edge(s), "
          f"{n_total} cycle(s), {len(cycles)} new")
    return 1 if cycles else 0


def _gate_knobs(root: str, check: bool, write: Optional[str]) -> int:
    from .lint import check_knob_docs, knob_table_markdown, write_knob_table

    if write:
        path = write if os.path.isabs(write) else os.path.join(root, write)
        write_knob_table(path)
        print(f"hvdt-knobs: wrote {path}")
        return 0
    if check:
        problems = check_knob_docs(root)
        for p in problems:
            print(f"hvdt-knobs: {p}")
        print(f"hvdt-knobs: {len(problems)} drift problem(s)")
        return 1 if problems else 0
    print(knob_table_markdown())
    return 0


def _gate_metrics(root: str, check: bool, write: Optional[str]) -> int:
    from .lint import (check_metric_docs, metric_table_markdown,
                       write_metric_table)

    if write:
        path = write if os.path.isabs(write) else os.path.join(root, write)
        write_metric_table(path)
        print(f"hvdt-metrics: wrote {path}")
        return 0
    if check:
        problems = check_metric_docs(root)
        for p in problems:
            print(f"hvdt-metrics: {p}")
        print(f"hvdt-metrics: {len(problems)} drift problem(s)")
        return 1 if problems else 0
    print(metric_table_markdown())
    return 0


def _gate_report(target: str, out: Optional[str]) -> int:
    from .report import render_report

    md = render_report(target)
    if out:
        with open(out, "w") as fh:
            fh.write(md)
        print(f"hvdt-report: wrote {out}")
    else:
        print(md)
    return 0


def _selfcheck_step(zero: bool = False):
    """Build the reference program pair for the schedule self-check:
    the overlapped bucketed exchange on a two-tier (dcn, ici) mesh —
    once plain, once under the hierarchical transport policy; with
    ``zero`` the program additionally routes a ZeRO reduce-scatter-wire
    exchange over the fast tier (the composed overlapped + hierarchical
    + ZeRO reference the perf gate prices).  Runs on however many
    devices exist (axis sizes degrade to 1; the jaxpr still carries
    every collective)."""
    import inspect

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh
    from jax.sharding import PartitionSpec as P

    try:
        from jax import shard_map
    except ImportError:                     # jax 0.4.x
        from jax.experimental.shard_map import shard_map

    devs = jax.devices()
    n = len(devs)
    inner = 4 if n % 4 == 0 else (2 if n % 2 == 0 else 1)
    mesh = Mesh(np.asarray(devs, dtype=object).reshape(n // inner, inner),
                ("dcn", "ici"))
    smap_kw = {}
    sig = inspect.signature(shard_map).parameters
    if "check_rep" in sig:
        smap_kw["check_rep"] = False
    elif "check_vma" in sig:
        smap_kw["check_vma"] = False

    rows = mesh.shape["dcn"] * mesh.shape["ici"]
    tree = {
        "w": jnp.zeros((rows, 96), jnp.float32),
        "b": jnp.zeros((rows, 17), jnp.float32),
        "i": jnp.zeros((rows, 8), jnp.int32),
    }
    leaves = list(tree.values())

    def traced(*ls):
        from ..common.types import ReduceOp
        from ..ops.overlap import OverlapScheduler

        out = OverlapScheduler().exchange(
            list(ls), axis=("dcn", "ici"), op=ReduceOp.AVERAGE,
            threshold_bytes=4096)
        if zero:
            from ..ops import zero as zero_mod

            z = zero_mod.rs_exchange(
                {"z": ls[0] * 2.0}, axis="ici", op=ReduceOp.AVERAGE,
                threshold_bytes=4096)
            return tuple(out) + (z["z"],)
        return tuple(out)

    n_out = len(leaves) + (1 if zero else 0)

    def step(*ls):
        return shard_map(traced, mesh=mesh,
                         in_specs=(P(("dcn", "ici")),) * len(ls),
                         out_specs=(P(),) * n_out, **smap_kw)(*ls)

    return step, leaves, tree


def _parallel4d_step():
    """Build the 4D reference program: a 1F1B pipeline whose stage body
    is an MoE layer — every a2a is issued inside the microbatch scan, so
    the fingerprint carries the composed a2a+ppermute pairs the 4D
    schedule closure verifies — over a ``(pp, ep, dp)`` mesh, with the
    loss psum-reduced over ``dp`` (the reduce group the pipeline/expert
    axes are excluded from).  Axis extents degrade to 1 on small hosts
    exactly like :func:`_selfcheck_step`; the jaxpr carries every
    collective regardless."""
    import inspect

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh
    from jax.sharding import PartitionSpec as P

    try:
        from jax import shard_map
    except ImportError:                     # jax 0.4.x
        from jax.experimental.shard_map import shard_map

    devs = jax.devices()
    n = len(devs)
    pp = 2 if n % 2 == 0 else 1
    ep = 2 if (n // pp) % 2 == 0 else 1
    dp = n // (pp * ep)
    mesh = Mesh(np.asarray(devs, dtype=object).reshape(pp, ep, dp),
                ("pp", "ep", "dp"))
    smap_kw = {}
    sig = inspect.signature(shard_map).parameters
    if "check_rep" in sig:
        smap_kw["check_rep"] = False
    elif "check_vma" in sig:
        smap_kw["check_vma"] = False

    num_mb, tok, dim = 4, 8, 16
    n_experts = ep                         # one expert per ep rank
    stage_w = jnp.zeros((pp, dim, dim), jnp.float32)
    router_w = jnp.zeros((pp, dim, n_experts), jnp.float32)
    microbatches = jnp.zeros((dp, num_mb, tok, dim), jnp.float32)

    def local(w, rw, mbs):
        from ..parallel.moe import moe_dispatch_combine
        from ..parallel.pipeline import pipeline_1f1b

        def stage_fn(params, x):
            sw, srw = params
            h = x @ sw
            # The aux-loss pmeans trace into the scan body jaxpr even
            # though only the combined activations leave the stage.
            y, _aux = moe_dispatch_combine(
                h, h @ srw, lambda blk: blk * 2.0, axis="ep",
                experts_per_rank=1, capacity_factor=1.25, top_k=1)
            return y

        out = pipeline_1f1b(stage_fn, (w[0], rw[0]), mbs[0], axis="pp")
        return jax.lax.pmean(jnp.mean(out * out), "dp")

    def step(w, rw, mbs):
        return shard_map(
            local, mesh=mesh,
            in_specs=(P("pp"), P("pp"), P("dp")),
            out_specs=P(), **smap_kw)(w, rw, mbs)

    return step, (stage_w, router_w, microbatches)


def _gate_selfcheck(export: Optional[str], root: str) -> int:
    from . import schedule as sched

    problems: List[str] = []
    old_env = {k: os.environ.get(k)
               for k in ("HVDT_OVERLAP", "HVDT_TRANSPORT")}
    try:
        os.environ["HVDT_OVERLAP"] = "on"
        os.environ.pop("HVDT_TRANSPORT", None)
        from ..ops import overlap as ovl
        from ..transport import policy as tpolicy

        ovl.reset()
        tpolicy.reset()
        step, leaves, tree = _selfcheck_step()

        fp1 = sched.extract_schedule(step, *leaves, label="overlap-plain")
        fp2 = sched.extract_schedule(step, *leaves, label="overlap-plain")
        if fp1.digest != fp2.digest:
            problems.append("schedule fingerprint unstable across two "
                            "traces of the same program")
        if not fp1.events:
            problems.append("self-check step traced no collectives")
        problems.extend(
            f["message"]
            for f in sched.verify_no_data_dependent_collectives(fp1))
        problems.extend(
            f["message"]
            for f in sched.verify_bucket_plan_invariance(leaves, 4096))

        # Hierarchical leg: post-pin collectives must stay psum-family.
        os.environ["HVDT_TRANSPORT"] = \
            "ici:ring:f32:64M,dcn:ring:f32:64M"
        tpolicy.reset()
        step_h, leaves_h, _ = _selfcheck_step()
        fp_h = sched.extract_schedule(step_h, *leaves_h,
                                      label="overlap-hier")
        problems.extend(
            f["message"]
            for f in sched.verify_post_pin_psum_family(fp_h))
        problems.extend(
            f["message"]
            for f in sched.verify_no_data_dependent_collectives(fp_h))

        # 4D leg: MoE-inside-1F1B on the (pp, ep, dp) mesh with the
        # int8 expert dispatch wire — the a2a/ppermute closure gate.
        os.environ["HVDT_TRANSPORT"] = "ep:ring:int8:64M"
        tpolicy.reset()
        step4, args4 = _parallel4d_step()
        fp4 = sched.extract_schedule(step4, *args4, label="parallel4d")
        if not any(e.op == "all_to_all" for e in fp4.events):
            problems.append(
                "parallel4d fingerprint traced no all_to_all — the MoE "
                "dispatch/combine pair is missing from the schedule")
        if not any(e.op == "ppermute" for e in fp4.events):
            problems.append(
                "parallel4d fingerprint traced no ppermute — the 1F1B "
                "clock is missing from the schedule")
        problems.extend(
            f["message"]
            for f in sched.verify_a2a_ppermute_pairing(fp4))
        problems.extend(
            f["message"]
            for f in sched.verify_no_data_dependent_collectives(fp4))
        print(f"hvdt-schedule: {fp4.summary()}")

        if export:
            path = export if os.path.isabs(export) \
                else os.path.join(root, export)
            fp1.save(path)
            print(f"hvdt-schedule: exported {fp1.summary()} -> {path}")
        print(f"hvdt-schedule: {fp1.summary()}")
        print(f"hvdt-schedule: {fp_h.summary()}")
    finally:
        for k, v in old_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        from ..ops import overlap as ovl
        from ..transport import policy as tpolicy

        ovl.reset()
        tpolicy.reset()
    for p in problems:
        print(f"hvdt-schedule: FAIL {p}")
    print(f"hvdt-schedule: {len(problems)} problem(s)")
    return 1 if problems else 0


PERF_BASELINE_NAME = ".hvdt-perf-baseline.json"

# Ratchet tolerances: predictions are deterministic given one
# calibration + one fingerprint, so drift means the SCHEDULE changed —
# keep the bands tight.
_PERF_TOLERANCES = {
    "exposed_comm_rel": 0.10,     # predicted exposed-comm seconds
    "wire_bytes_rel": 0.01,       # per-axis wire bytes (near-exact)
    "overlap_fraction_abs": 0.05,  # hidden/total fraction
}
_REFERENCE_TOPOLOGY = {"pods": 2, "chips_per_pod": 4}   # the mesh-8 CI sim
_SPEEDUP_REL_TOLERANCE = 0.25    # model vs measured hier speedup


def _force_sim_devices() -> None:
    """The perf gate prices the mesh-8 reference fingerprints: force
    the 8-device CPU sim BEFORE the first jax backend init so the
    committed baseline holds on any host (the conftest idiom)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()


def _reference_fingerprints() -> list:
    """Trace the perf gate's reference programs under pinned env: the
    overlapped exchange plain, hierarchical, and hierarchical + ZeRO —
    the three comm compositions the repo ships."""
    from . import schedule as sched

    old_env = {k: os.environ.get(k)
               for k in ("HVDT_OVERLAP", "HVDT_TRANSPORT", "HVDT_ZERO",
                         "HVDT_QUANT_BLOCK")}
    from ..ops import overlap as ovl
    from ..transport import policy as tpolicy

    out = []
    try:
        os.environ["HVDT_OVERLAP"] = "on"
        os.environ.pop("HVDT_TRANSPORT", None)
        os.environ.pop("HVDT_ZERO", None)
        os.environ.pop("HVDT_QUANT_BLOCK", None)
        ovl.reset()
        tpolicy.reset()
        step, leaves, _ = _selfcheck_step()
        out.append(sched.extract_schedule(step, *leaves,
                                          label="overlap-plain"))
        # dcn rides the packed int4 wire: the reference fingerprint
        # prices the repo's best shipping slow-axis config, ratcheting
        # the dcn wire-byte baseline down with each wire generation.
        # The quant block scales with the toy CI payload (~24 f32 per
        # dcn shard) the same way 256 matches production payloads —
        # otherwise the block quantum, not the packed ratio, is what
        # gets priced.
        os.environ["HVDT_TRANSPORT"] = \
            "ici:ring:f32:64M,dcn:ring:int4:64M"
        os.environ["HVDT_QUANT_BLOCK"] = "16"
        tpolicy.reset()
        step, leaves, _ = _selfcheck_step()
        out.append(sched.extract_schedule(step, *leaves,
                                          label="overlap-hier"))
        step, leaves, _ = _selfcheck_step(zero=True)
        out.append(sched.extract_schedule(step, *leaves,
                                          label="overlap-hier-zero"))
        # The 4D composition: MoE dispatch/combine inside the 1F1B
        # scan on the (pp, ep, dp) mesh, expert a2a on the int8 wire —
        # prices a2a seconds and the ppermute tick stream so the
        # ratchet covers 4D schedules.
        os.environ["HVDT_TRANSPORT"] = "ep:ring:int8:64M"
        os.environ.pop("HVDT_QUANT_BLOCK", None)
        tpolicy.reset()
        step4, args4 = _parallel4d_step()
        out.append(sched.extract_schedule(step4, *args4,
                                          label="parallel4d"))
    finally:
        for k, v in old_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        ovl.reset()
        tpolicy.reset()
    return out


def _perf_baseline_path(root: str, explicit: Optional[str]) -> str:
    if explicit:
        return explicit
    env = os.environ.get("HVDT_PERF_BASELINE", "").strip()
    if env:
        return env
    return os.path.join(root, PERF_BASELINE_NAME)


def _gate_perf(root: str, baseline_path: str, update: bool,
               fingerprint_paths: Optional[List[str]] = None) -> int:
    """The static perf-regression gate: evaluate the reference
    fingerprints (or explicitly supplied exported ones) with the fitted
    cost model on the reference topology, validate the model against
    its own measured calibration sweep, assert the weak-scaling curve
    shape, and ratchet against the committed perf baseline."""
    import json as _json

    from . import costmodel as cm
    from . import schedule as sched
    from . import topology as tp

    problems: List[str] = []
    cal = cm.load_calibration(cm.default_calibration_path(root))
    if cal.meta.get("degraded"):
        problems.append(
            f"cost-model calibration unavailable "
            f"({cal.meta['degraded']}) — regenerate with "
            f"tools/fit_costmodel.py")
    model = cm.CostModel(cal)

    try:
        with open(baseline_path) as fh:
            baseline = _json.load(fh)
    except (OSError, ValueError):
        baseline = None
    topo_doc = (baseline or {}).get("topology", _REFERENCE_TOPOLOGY)
    topo = tp.TopologySpec(pods=int(topo_doc["pods"]),
                           chips_per_pod=int(topo_doc["chips_per_pod"]))

    if fingerprint_paths:
        fps = [sched.load_fingerprint(p) for p in fingerprint_paths]
    else:
        fps = _reference_fingerprints()
    costs = {fp.label: model.evaluate(fp, topo) for fp in fps}
    for c in costs.values():
        print(f"hvdt-perf: {c.summary()}")

    # Hard gate: every a2a/ppermute the 4D schedules issue must come
    # back PRICED — a zero-second expert exchange or pipeline tick
    # means the event's axes escaped tier classification (or a new op
    # bypassed collective_geometry) and the ratchet would silently
    # stop covering it.
    for label, c in sorted(costs.items()):
        for ec in c.events:
            if ec.op in ("all_to_all", "ppermute") and ec.seconds <= 0:
                problems.append(
                    f"{label}: collective #{ec.index} ({ec.op}) is "
                    f"unpriced (0 s) — its axes did not map onto a "
                    f">1-member tier group on the reference topology")

    # (c) model-vs-measured validation: the fitted model must reproduce
    # the measured hierarchical speedup its calibration sweep recorded.
    meas = cal.meta.get("measured_hier_speedup")
    if isinstance(meas, dict) and meas.get("value"):
        mesh = meas.get("mesh", {}) or {}
        vtopo = tp.TopologySpec(
            pods=int(mesh.get("dcn", topo.pods)),
            chips_per_pod=int(mesh.get("ici", topo.chips_per_pod)))
        pred = model.hierarchical_speedup(
            float(meas.get("at_bytes", 0) or 1), vtopo)
        rel = abs(pred - float(meas["value"])) / float(meas["value"])
        verdict = "ok" if rel <= _SPEEDUP_REL_TOLERANCE else "FAIL"
        print(f"hvdt-perf: hier-speedup model {pred:.3f} vs measured "
              f"{meas['value']:.3f} at {meas.get('at_bytes')}B "
              f"({rel:.1%} off, {verdict})")
        if rel > _SPEEDUP_REL_TOLERANCE:
            problems.append(
                f"model hierarchical_speedup_vs_flat_at_peak {pred:.3f} "
                f"deviates {rel:.1%} from the measured {meas['value']} "
                f"(tolerance {_SPEEDUP_REL_TOLERANCE:.0%}) — refit the "
                f"calibration or fix the model")

    # Weak-scaling curve: deterministic, monotone in comm fraction
    # (the concurrency-paper shape).
    wl = tp.REFERENCE_STEP_WORKLOAD
    curve = model.weak_scaling_curve(wl["grad_bytes"],
                                     wl["flops_per_step"])
    frs = [r["comm_fraction"] for r in curve]
    print("hvdt-perf: weak-scaling comm fraction "
          + " ".join(f"{r['chips']}:{r['comm_fraction']:.4f}"
                     for r in curve))
    if any(b < a for a, b in zip(frs, frs[1:])):
        problems.append(
            "weak-scaling curve is not monotone in comm fraction — "
            "the model lost the scaling shape the concurrency paper "
            "pins")

    if update:
        doc = {
            "version": 1,
            "comment": ("static perf-regression baseline: model-"
                        "predicted exposed-comm seconds, per-axis wire "
                        "bytes and overlap fraction for the reference "
                        "fingerprints.  `python -m horovod_tpu."
                        "analysis --perf` fails on regressions beyond "
                        "the tolerances; regenerate with "
                        "--update-perf-baseline after an intentional "
                        "schedule change."),
            "topology": topo.to_dict(),
            "tolerances": _PERF_TOLERANCES,
            "entries": {
                label: {
                    "exposed_comm_s": c.exposed_comm_s,
                    "total_comm_s": c.total_comm_s,
                    "overlap_fraction": c.overlap_fraction,
                    "wire_bytes_by_axis": dict(c.wire_bytes_by_axis),
                    "n_collectives": len(c.events),
                } for label, c in sorted(costs.items())},
        }
        with open(baseline_path, "w") as fh:
            _json.dump(doc, fh, indent=1, sort_keys=True)
            fh.write("\n")
        print(f"hvdt-perf: baseline written -> {baseline_path}")
        return 0

    if baseline is None:
        problems.append(
            f"no perf baseline at {baseline_path} — run "
            f"`python -m horovod_tpu.analysis --perf "
            f"--update-perf-baseline`")
    else:
        tol = {**_PERF_TOLERANCES, **baseline.get("tolerances", {})}
        entries = baseline.get("entries", {})
        for label, c in sorted(costs.items()):
            base = entries.get(label)
            if base is None:
                problems.append(
                    f"{label}: no baseline entry — run "
                    f"--update-perf-baseline to admit the new "
                    f"reference fingerprint")
                continue
            b_exp = float(base.get("exposed_comm_s", 0.0))
            if c.exposed_comm_s > b_exp * (1 + tol["exposed_comm_rel"]):
                problems.append(
                    f"{label}: exposed-comm regression "
                    f"{b_exp * 1e6:.1f}us -> "
                    f"{c.exposed_comm_s * 1e6:.1f}us "
                    f"(> +{tol['exposed_comm_rel']:.0%})")
            elif b_exp and c.exposed_comm_s < b_exp * (
                    1 - tol["exposed_comm_rel"]):
                print(f"hvdt-perf: note {label}: exposed comm improved "
                      f"{b_exp * 1e6:.1f}us -> "
                      f"{c.exposed_comm_s * 1e6:.1f}us — ratchet down "
                      f"with --update-perf-baseline")
            b_wire = base.get("wire_bytes_by_axis", {}) or {}
            for axis in sorted(set(b_wire) | set(c.wire_bytes_by_axis)):
                cur = int(c.wire_bytes_by_axis.get(axis, 0))
                was = int(b_wire.get(axis, 0))
                if cur > was * (1 + tol["wire_bytes_rel"]):
                    problems.append(
                        f"{label}: {axis} wire bytes regression "
                        f"{was} -> {cur} "
                        f"(> +{tol['wire_bytes_rel']:.0%})")
            b_ovl = float(base.get("overlap_fraction", 0.0))
            if c.overlap_fraction < b_ovl - tol["overlap_fraction_abs"]:
                problems.append(
                    f"{label}: overlap fraction dropped "
                    f"{b_ovl:.2f} -> {c.overlap_fraction:.2f} "
                    f"(> -{tol['overlap_fraction_abs']:.2f} abs)")

    for p in problems:
        print(f"hvdt-perf: FAIL {p}")
    print(f"hvdt-perf: {len(problems)} problem(s)")
    return 1 if problems else 0


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m horovod_tpu.analysis",
        description="Static distributed-correctness analysis "
                    "(collective-schedule verifier + hvdt-lint + "
                    "lock-order graph).")
    p.add_argument("--all", action="store_true",
                   help="lint + locks + knob-table and metric-table "
                        "drift checks + schedule self-check (the CI "
                        "gate; default when no mode flag is given)")
    p.add_argument("--lint", action="store_true")
    p.add_argument("--locks", action="store_true")
    p.add_argument("--knob-table", action="store_true",
                   help="print the generated knob table")
    p.add_argument("--metric-table", action="store_true",
                   help="print the generated metric-catalog table "
                        "(telemetry/metrics.py CATALOG)")
    p.add_argument("--check", action="store_true",
                   help="with --knob-table/--metric-table: fail on "
                        "docs drift")
    p.add_argument("--write", default=None, metavar="PATH",
                   help="with --knob-table/--metric-table: write the "
                        "generated doc (give exactly one table flag)")
    p.add_argument("--report", default=None, metavar="PATH",
                   help="render a post-mortem markdown report from an "
                        "anomaly event log (JSONL) or artifact "
                        "directory")
    p.add_argument("--report-out", default=None, metavar="OUT.md",
                   help="with --report: write the markdown here "
                        "instead of stdout")
    p.add_argument("--selfcheck", action="store_true",
                   help="trace the reference step and run the "
                        "schedule verifier passes")
    p.add_argument("--schedule", default=None, metavar="OUT.json",
                   help="export the self-check fingerprint (implies "
                        "--selfcheck)")
    p.add_argument("--perf", action="store_true",
                   help="static perf-regression gate: price the "
                        "reference fingerprints with the fitted cost "
                        "model and ratchet exposed-comm seconds / "
                        "per-axis wire bytes / overlap fraction "
                        "against the committed perf baseline")
    p.add_argument("--update-perf-baseline", action="store_true",
                   help="rewrite the perf baseline from the current "
                        "model predictions (implies --perf)")
    p.add_argument("--perf-fingerprint", action="append", default=None,
                   metavar="FP.json",
                   help="with --perf: evaluate these exported "
                        "fingerprint files (matched to baseline "
                        "entries by label) instead of tracing the "
                        "reference programs; repeatable")
    p.add_argument("--perf-baseline", default=None, metavar="PATH",
                   help="perf baseline file (default: "
                        "HVDT_PERF_BASELINE or "
                        "<root>/.hvdt-perf-baseline.json)")
    p.add_argument("--baseline", default=None, metavar="PATH",
                   help="ratchet baseline file (default: "
                        "<root>/.hvdt-lint-baseline.json)")
    p.add_argument("--update-baseline", action="store_true")
    p.add_argument("--dump-locks", action="store_true")
    p.add_argument("--root", default=None,
                   help="repo root (default: the checkout containing "
                        "this package)")
    args = p.parse_args(argv)

    root = _repo_root(args.root)
    from .lint import BASELINE_NAME

    baseline = args.baseline or os.path.join(root, BASELINE_NAME)

    if args.report:
        return _gate_report(args.report, args.report_out)

    perf_mode = (args.perf or args.update_perf_baseline
                 or bool(args.perf_fingerprint))
    any_mode = (args.lint or args.locks or args.knob_table
                or args.metric_table or args.selfcheck or args.schedule
                or args.dump_locks or perf_mode)
    if args.all or not any_mode:
        args.all = True
        args.lint = args.locks = args.selfcheck = True
        args.knob_table, args.metric_table, args.check = True, True, True
    if perf_mode and not args.perf_fingerprint:
        # Tracing the reference fingerprints needs the deterministic
        # 8-device sim; evaluating exported files is jax-free.
        _force_sim_devices()

    rc = 0
    if args.update_baseline:
        # Re-key lint findings; carry lock-cycle suppressions through.
        from .lint import (default_paths, lint_paths, load_baseline,
                           save_baseline)

        old = load_baseline(baseline)
        keep = {k: v for k, v in old.items()
                if k.startswith("lock-cycle:")}
        all_findings = lint_paths(default_paths(root), root=root)
        save_baseline(baseline, all_findings, reasons=old, keep=keep)
        print(f"hvdt-lint: baseline rewritten with "
              f"{len(all_findings)} lint + {len(keep)} lock "
              f"suppression(s) -> {baseline}")
        return 0

    if args.lint:
        # --all runs the hard ratchet: stale suppressions (source line
        # edited or violation fixed) fail until pruned.
        rc |= _gate_lint(root, baseline, update=False,
                         fail_on_stale=args.all)
    if args.locks or args.dump_locks:
        rc |= _gate_locks(root, baseline, dump=args.dump_locks)
    if args.knob_table:
        rc |= _gate_knobs(root, check=args.check,
                          write=(None if args.metric_table
                                 else args.write))
    if args.metric_table:
        rc |= _gate_metrics(root, check=args.check,
                            write=(None if args.knob_table
                                   else args.write))
    if args.selfcheck or args.schedule:
        rc |= _gate_selfcheck(args.schedule, root)
    if perf_mode:
        rc |= _gate_perf(root,
                         _perf_baseline_path(root, args.perf_baseline),
                         update=args.update_perf_baseline,
                         fingerprint_paths=args.perf_fingerprint)
    print(f"hvdt-analysis: {'CLEAN' if rc == 0 else 'VIOLATIONS'}")
    return rc


if __name__ == "__main__":
    sys.exit(main())
