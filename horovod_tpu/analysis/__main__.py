"""``python -m horovod_tpu.analysis`` — the static-analysis CI gate.

Modes (``--all`` = lint + locks + knob-table check + schedule
self-check; the default with no flags):

* ``--lint``            run the AST rule registry against the ratchet
                        baseline (``.hvdt-lint-baseline.json``)
* ``--locks``           lock-order graph; new cycles fail
* ``--knob-table``      print the generated knob table
  (``--write PATH``    write it, e.g. ``--write docs/knobs.md``;
  ``--check``          fail on registry/docs drift)
* ``--selfcheck``       trace the reference overlapped + hierarchical
                        step and run every schedule verifier pass
* ``--schedule OUT``    export the self-check step's fingerprint JSON
                        (feed it to ``HVDT_EXPECTED_SCHEDULE``)
* ``--update-baseline`` re-key the baseline from current findings
                        (keeps written reasons and lock suppressions)
* ``--dump-locks``      print the full acquisition-order edge list

Exit code 0 = every requested gate clean; 1 = violations.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

__all__ = ["main"]


def _repo_root(explicit: Optional[str]) -> str:
    if explicit:
        return os.path.abspath(explicit)
    # package lives at <root>/horovod_tpu/analysis/__main__.py
    here = os.path.dirname(os.path.abspath(__file__))
    return os.path.dirname(os.path.dirname(here))


def _gate_lint(root: str, baseline: str, update: bool) -> int:
    from .lint import run_lint

    new, suppressed, stale = run_lint(root, baseline_path=baseline,
                                      update_baseline=update)
    if update:
        print(f"hvdt-lint: baseline rewritten with "
              f"{len(suppressed)} suppression(s) -> {baseline}")
        return 0
    for f in new:
        print(f.format())
    if stale:
        print(f"hvdt-lint: {len(stale)} stale baseline suppression(s) "
              f"(violation fixed — prune to ratchet down):")
        for k in stale:
            print(f"  {k}")
    print(f"hvdt-lint: {len(new)} new, {len(suppressed)} baselined, "
          f"{len(stale)} stale")
    return 1 if new else 0


def _gate_locks(root: str, baseline: str, dump: bool) -> int:
    from .lint import load_baseline
    from .locks import find_cycles, format_edge, run_locks

    cycles, edges = run_locks(root, baseline=load_baseline(baseline))
    if dump:
        for e in edges:
            print(format_edge(e))
    n_total = len(find_cycles(edges))
    for c in cycles:
        print("lock-order cycle: " + " -> ".join(c + [c[0]]))
    print(f"hvdt-locks: {len(edges)} acquisition edge(s), "
          f"{n_total} cycle(s), {len(cycles)} new")
    return 1 if cycles else 0


def _gate_knobs(root: str, check: bool, write: Optional[str]) -> int:
    from .lint import check_knob_docs, knob_table_markdown, write_knob_table

    if write:
        path = write if os.path.isabs(write) else os.path.join(root, write)
        write_knob_table(path)
        print(f"hvdt-knobs: wrote {path}")
        return 0
    if check:
        problems = check_knob_docs(root)
        for p in problems:
            print(f"hvdt-knobs: {p}")
        print(f"hvdt-knobs: {len(problems)} drift problem(s)")
        return 1 if problems else 0
    print(knob_table_markdown())
    return 0


def _selfcheck_step():
    """Build the reference program pair for the schedule self-check:
    the overlapped bucketed exchange on a two-tier (dcn, ici) mesh —
    once plain, once under the hierarchical transport policy.  Runs on
    however many devices exist (axis sizes degrade to 1; the jaxpr
    still carries every collective)."""
    import inspect

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh
    from jax.sharding import PartitionSpec as P

    try:
        from jax import shard_map
    except ImportError:                     # jax 0.4.x
        from jax.experimental.shard_map import shard_map

    devs = jax.devices()
    n = len(devs)
    inner = 4 if n % 4 == 0 else (2 if n % 2 == 0 else 1)
    mesh = Mesh(np.asarray(devs, dtype=object).reshape(n // inner, inner),
                ("dcn", "ici"))
    smap_kw = {}
    sig = inspect.signature(shard_map).parameters
    if "check_rep" in sig:
        smap_kw["check_rep"] = False
    elif "check_vma" in sig:
        smap_kw["check_vma"] = False

    rows = mesh.shape["dcn"] * mesh.shape["ici"]
    tree = {
        "w": jnp.zeros((rows, 96), jnp.float32),
        "b": jnp.zeros((rows, 17), jnp.float32),
        "i": jnp.zeros((rows, 8), jnp.int32),
    }
    leaves = list(tree.values())

    def traced(*ls):
        from ..common.types import ReduceOp
        from ..ops.overlap import OverlapScheduler

        out = OverlapScheduler().exchange(
            list(ls), axis=("dcn", "ici"), op=ReduceOp.AVERAGE,
            threshold_bytes=4096)
        return tuple(out)

    def step(*ls):
        return shard_map(traced, mesh=mesh,
                         in_specs=(P(("dcn", "ici")),) * len(ls),
                         out_specs=(P(),) * len(ls), **smap_kw)(*ls)

    return step, leaves, tree


def _gate_selfcheck(export: Optional[str], root: str) -> int:
    from . import schedule as sched

    problems: List[str] = []
    old_env = {k: os.environ.get(k)
               for k in ("HVDT_OVERLAP", "HVDT_TRANSPORT")}
    try:
        os.environ["HVDT_OVERLAP"] = "on"
        os.environ.pop("HVDT_TRANSPORT", None)
        from ..ops import overlap as ovl
        from ..transport import policy as tpolicy

        ovl.reset()
        tpolicy.reset()
        step, leaves, tree = _selfcheck_step()

        fp1 = sched.extract_schedule(step, *leaves, label="overlap-plain")
        fp2 = sched.extract_schedule(step, *leaves, label="overlap-plain")
        if fp1.digest != fp2.digest:
            problems.append("schedule fingerprint unstable across two "
                            "traces of the same program")
        if not fp1.events:
            problems.append("self-check step traced no collectives")
        problems.extend(
            f["message"]
            for f in sched.verify_no_data_dependent_collectives(fp1))
        problems.extend(
            f["message"]
            for f in sched.verify_bucket_plan_invariance(leaves, 4096))

        # Hierarchical leg: post-pin collectives must stay psum-family.
        os.environ["HVDT_TRANSPORT"] = \
            "ici:ring:f32:64M,dcn:ring:f32:64M"
        tpolicy.reset()
        step_h, leaves_h, _ = _selfcheck_step()
        fp_h = sched.extract_schedule(step_h, *leaves_h,
                                      label="overlap-hier")
        problems.extend(
            f["message"]
            for f in sched.verify_post_pin_psum_family(fp_h))
        problems.extend(
            f["message"]
            for f in sched.verify_no_data_dependent_collectives(fp_h))

        if export:
            path = export if os.path.isabs(export) \
                else os.path.join(root, export)
            fp1.save(path)
            print(f"hvdt-schedule: exported {fp1.summary()} -> {path}")
        print(f"hvdt-schedule: {fp1.summary()}")
        print(f"hvdt-schedule: {fp_h.summary()}")
    finally:
        for k, v in old_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        from ..ops import overlap as ovl
        from ..transport import policy as tpolicy

        ovl.reset()
        tpolicy.reset()
    for p in problems:
        print(f"hvdt-schedule: FAIL {p}")
    print(f"hvdt-schedule: {len(problems)} problem(s)")
    return 1 if problems else 0


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m horovod_tpu.analysis",
        description="Static distributed-correctness analysis "
                    "(collective-schedule verifier + hvdt-lint + "
                    "lock-order graph).")
    p.add_argument("--all", action="store_true",
                   help="lint + locks + knob-table drift check + "
                        "schedule self-check (the CI gate; default "
                        "when no mode flag is given)")
    p.add_argument("--lint", action="store_true")
    p.add_argument("--locks", action="store_true")
    p.add_argument("--knob-table", action="store_true",
                   help="print the generated knob table")
    p.add_argument("--check", action="store_true",
                   help="with --knob-table: fail on docs drift")
    p.add_argument("--write", default=None, metavar="PATH",
                   help="with --knob-table: write the generated doc")
    p.add_argument("--selfcheck", action="store_true",
                   help="trace the reference step and run the "
                        "schedule verifier passes")
    p.add_argument("--schedule", default=None, metavar="OUT.json",
                   help="export the self-check fingerprint (implies "
                        "--selfcheck)")
    p.add_argument("--baseline", default=None, metavar="PATH",
                   help="ratchet baseline file (default: "
                        "<root>/.hvdt-lint-baseline.json)")
    p.add_argument("--update-baseline", action="store_true")
    p.add_argument("--dump-locks", action="store_true")
    p.add_argument("--root", default=None,
                   help="repo root (default: the checkout containing "
                        "this package)")
    args = p.parse_args(argv)

    root = _repo_root(args.root)
    from .lint import BASELINE_NAME

    baseline = args.baseline or os.path.join(root, BASELINE_NAME)

    any_mode = (args.lint or args.locks or args.knob_table
                or args.selfcheck or args.schedule or args.dump_locks)
    if args.all or not any_mode:
        args.lint = args.locks = args.selfcheck = True
        args.knob_table, args.check = True, True

    rc = 0
    if args.update_baseline:
        # Re-key lint findings; carry lock-cycle suppressions through.
        from .lint import (default_paths, lint_paths, load_baseline,
                           save_baseline)

        old = load_baseline(baseline)
        keep = {k: v for k, v in old.items()
                if k.startswith("lock-cycle:")}
        all_findings = lint_paths(default_paths(root), root=root)
        save_baseline(baseline, all_findings, reasons=old, keep=keep)
        print(f"hvdt-lint: baseline rewritten with "
              f"{len(all_findings)} lint + {len(keep)} lock "
              f"suppression(s) -> {baseline}")
        return 0

    if args.lint:
        rc |= _gate_lint(root, baseline, update=False)
    if args.locks or args.dump_locks:
        rc |= _gate_locks(root, baseline, dump=args.dump_locks)
    if args.knob_table:
        rc |= _gate_knobs(root, check=args.check, write=args.write)
    if args.selfcheck or args.schedule:
        rc |= _gate_selfcheck(args.schedule, root)
    print(f"hvdt-analysis: {'CLEAN' if rc == 0 else 'VIOLATIONS'}")
    return rc


if __name__ == "__main__":
    sys.exit(main())
