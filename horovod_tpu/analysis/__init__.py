"""Static distributed-correctness analysis (``hvdt-lint``).

Three checkers over the codebase-as-artifact, wired as one CLI and one
CI gate (``python -m horovod_tpu.analysis --all`` / ``hvdtrun lint``):

* :mod:`~horovod_tpu.analysis.schedule` — trace a step function,
  extract its ordered collective schedule from the jaxpr into a
  canonical fingerprint, and statically verify the contracts runtime
  forensics can only diagnose after the fact: deterministic bucket
  plans, hot-swap-compatible autotune legs, psum-family post-pin
  collectives, no data-dependent collectives.  Exported fingerprints
  feed the flight recorder's static-expected-vs-runtime-observed
  desync reports (``HVDT_EXPECTED_SCHEDULE``).
* :mod:`~horovod_tpu.analysis.lint` — AST rule registry (knob drift,
  unguarded version-sensitive jax APIs, zero-overhead gates, set-order
  nondeterminism, bare sleep polls) with a ratcheting baseline, plus
  the generated knob table (``docs/knobs.md``) and its drift check.
* :mod:`~horovod_tpu.analysis.locks` — static lock-order graph over
  the threaded control plane; new acquisition-order cycles fail CI.
* :mod:`~horovod_tpu.analysis.costmodel` /
  :mod:`~horovod_tpu.analysis.topology` — the analytical alpha-beta
  topology cost model: constants fitted from measured
  ``bench_allreduce`` rows, evaluated over schedule fingerprints for
  declared topologies (256 chips on a 1-CPU container), ratcheted by
  the ``--perf`` static perf-regression gate against
  ``.hvdt-perf-baseline.json`` and consulted by autotune pre-seeding
  (``HVDT_AUTOTUNE_MODEL_SEED``).
"""

from __future__ import annotations

from typing import List, Optional

__all__ = ["main"]


def main(argv: Optional[List[str]] = None) -> int:
    """CLI entry point (``hvdtrun lint`` dispatches here)."""
    from .__main__ import main as _main

    return _main(argv)
