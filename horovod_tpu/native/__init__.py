"""Native core loader — builds (if needed) and binds libhvdt_core.so.

The reference loads its C++ core from Python via ctypes
(ref: horovod/common/basics.py:33-34 loading mpi_lib_v2); same pattern
here: a C API (native/include/hvdt.h) over the native runtime pieces that
remain host-side on TPU — the TCP host-collective backend (Gloo analog),
the async timeline writer, and Adasum host math.

The library is compiled on demand with the in-image g++ via native/Makefile
(no pip/pybind11 dependency — plain ctypes).  ``available()`` gates all
callers so pure-Python fallbacks keep working where a toolchain is absent.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

__all__ = ["available", "load", "NativeError", "TcpProcessGroup",
           "NativeTimeline", "adasum_combine"]

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           os.pardir, os.pardir, "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libhvdt_core.so")
# Installed-wheel location: setup.py ships the prebuilt library inside the
# package (no source tree / toolchain on the install host).
_PKG_LIB_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "_lib", "libhvdt_core.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_failed: Optional[str] = None


class NativeError(RuntimeError):
    """A native-core call returned nonzero; message from hvdt_last_error."""


def _build() -> bool:
    makefile = os.path.join(_NATIVE_DIR, "Makefile")
    if not os.path.exists(makefile):
        return False
    try:
        # Cross-process lock: multiple ranks on one host all call load()
        # on startup; without it concurrent `make` invocations write the
        # same .o/.so and a rank can dlopen a half-written library.
        import fcntl

        with open(os.path.join(_NATIVE_DIR, ".build.lock"), "w") as lockf:
            fcntl.flock(lockf, fcntl.LOCK_EX)
            try:
                subprocess.run(["make", "-C", _NATIVE_DIR],
                               capture_output=True, check=True, timeout=300)
            finally:
                fcntl.flock(lockf, fcntl.LOCK_UN)
    except (subprocess.SubprocessError, OSError):
        return False
    return os.path.exists(_LIB_PATH)


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    c_p, c_i, c_i64 = ctypes.c_void_p, ctypes.c_int, ctypes.c_int64
    c_pp = ctypes.POINTER(ctypes.c_void_p)
    c_i64p = ctypes.POINTER(c_i64)
    lib.hvdt_last_error.restype = ctypes.c_char_p
    lib.hvdt_dtype_size.restype = c_i64
    lib.hvdt_dtype_size.argtypes = [c_i]
    lib.hvdt_tcp_group_create.argtypes = [c_i, c_i, ctypes.c_char_p, c_i,
                                          c_pp]
    lib.hvdt_tcp_group_destroy.argtypes = [c_p]
    lib.hvdt_group_rank.argtypes = [c_p]
    lib.hvdt_group_size.argtypes = [c_p]
    lib.hvdt_allreduce.argtypes = [c_p, c_p, c_i64, c_i, c_i]
    lib.hvdt_allgatherv.argtypes = [c_p, c_p, c_i64, c_p, c_i64p, c_i]
    lib.hvdt_broadcast.argtypes = [c_p, c_p, c_i64, c_i]
    lib.hvdt_alltoallv.argtypes = [c_p, c_p, c_i64p, c_p, c_i64p, c_i]
    lib.hvdt_barrier.argtypes = [c_p]
    lib.hvdt_adasum_allreduce.argtypes = [c_p, c_p, c_i64, c_i]
    lib.hvdt_adasum_combine.argtypes = [c_p, c_p, c_i64, c_i]
    lib.hvdt_timeline_create.argtypes = [ctypes.c_char_p, c_pp]
    lib.hvdt_timeline_event.argtypes = [c_p, ctypes.c_char_p,
                                        ctypes.c_char_p, ctypes.c_char,
                                        c_i64, c_i64, ctypes.c_char_p]
    lib.hvdt_timeline_close.argtypes = [c_p]
    return lib


def load() -> ctypes.CDLL:
    """Load (building first if necessary) the native core; raises on
    failure — use available() to probe."""
    global _lib, _load_failed
    with _lock:
        if _lib is not None:
            return _lib
        if _load_failed is not None:
            raise NativeError(_load_failed)
        # Always run make in a source tree: the Makefile's dependency
        # tracking no-ops when the .so is current and rebuilds it when a
        # C++ source changed — a stale binary must never shadow the
        # sources.  The .so is a build artifact (gitignored), not a
        # vendored blob.  Installed wheels have no source tree; they use
        # the library setup.py packaged next to this module.
        if _build() or os.path.exists(_LIB_PATH):
            lib_path = _LIB_PATH
        elif os.path.exists(_PKG_LIB_PATH):
            lib_path = _PKG_LIB_PATH
        else:
            _load_failed = ("native core unavailable "
                            "(build failed and no existing .so)")
            raise NativeError(_load_failed)
        try:
            _lib = _bind(ctypes.CDLL(lib_path))
        except OSError as e:  # pragma: no cover - load error surface
            _load_failed = f"cannot load {lib_path}: {e}"
            raise NativeError(_load_failed)
        return _lib


def available() -> bool:
    try:
        load()
        return True
    except NativeError:
        return False


def _check(lib: ctypes.CDLL, rc: int) -> None:
    if rc != 0:
        raise NativeError(lib.hvdt_last_error().decode("utf-8", "replace"))


from .tcp import TcpProcessGroup, adasum_combine  # noqa: E402
from .timeline_native import NativeTimeline  # noqa: E402
