"""TcpProcessGroup — numpy front end of the native TCP collective backend.

Host-CPU fallback data plane (ref: ops/gloo_operations.cc + the gloo
context bootstrap gloo/gloo_context.cc), carried by the C++ core
(native/src/tcp_group.cc) over a full TCP socket mesh: ring allreduce,
ring allgatherv, broadcast, pairwise alltoallv, barrier, and Adasum VHDD.

Used where XLA collectives are not the right tool: eager host tensors in
multi-process runs without a TPU mesh, launcher/control traffic, and
CPU-only CI.  All calls release the GIL (blocking socket IO happens in
C++), so in-process multi-rank tests can drive N ranks from N threads.
"""

from __future__ import annotations

import ctypes
from typing import Optional, Sequence

import numpy as np

from . import NativeError, _check, load
from ..common.types import ReduceOp, data_type_of

__all__ = ["TcpProcessGroup", "adasum_combine"]

# ReduceOp (horovod_tpu.common.types) -> hvdt_reduce_op (native/include/hvdt.h)
_OP_MAP = {
    ReduceOp.SUM: 0,
    ReduceOp.AVERAGE: 0,  # sum on the wire; caller divides (prescale/postscale)
    ReduceOp.PRODUCT: 1,
    ReduceOp.MIN: 2,
    ReduceOp.MAX: 3,
}


def _dtype_code(arr: np.ndarray) -> int:
    return int(data_type_of(arr.dtype))


def _as_c(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.c_void_p)


def _counts_arr(counts: Sequence[int]):
    a = (ctypes.c_int64 * len(counts))(*counts)
    return a


class TcpProcessGroup:
    """One rank's handle on a full-mesh TCP group.

    ``addrs`` is the rank-ordered list of "host:port" endpoints; every rank
    passes the same list (the launcher provides it through the env
    contract, mirroring how the reference's gloo context reads
    HOROVOD_GLOO_RENDEZVOUS_ADDR — runner/gloo_run.py:65-76).
    """

    def __init__(self, rank: int, size: int, addrs: Sequence[str],
                 timeout_ms: int = 30000):
        self._lib = load()
        handle = ctypes.c_void_p()
        rc = self._lib.hvdt_tcp_group_create(
            rank, size, ",".join(addrs).encode(), timeout_ms,
            ctypes.byref(handle))
        _check(self._lib, rc)
        self._h = handle
        self.rank = rank
        self.size = size

    def close(self) -> None:
        if self._h:
            self._lib.hvdt_tcp_group_destroy(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):  # best effort
        try:
            self.close()
        except Exception:
            pass

    # -- collectives (all in element counts, numpy in/out) --

    def allreduce(self, tensor: np.ndarray,
                  op: ReduceOp = ReduceOp.SUM) -> np.ndarray:
        """Returns the reduced array (input is not mutated)."""
        if op == ReduceOp.ADASUM:
            return self.adasum_allreduce(tensor)
        out = np.ascontiguousarray(tensor).copy()
        _check(self._lib, self._lib.hvdt_allreduce(
            self._h, _as_c(out), out.size, _dtype_code(out),
            _OP_MAP[ReduceOp(op)]))
        if op == ReduceOp.AVERAGE:
            out = (out / self.size).astype(tensor.dtype)
        return out

    def allgather(self, tensor: np.ndarray) -> np.ndarray:
        """Variable-first-dimension allgather (ref semantics: concatenate
        along axis 0; other dims must match)."""
        t = np.ascontiguousarray(tensor)
        row = int(np.prod(t.shape[1:], dtype=np.int64)) if t.ndim else 1
        my_rows = t.shape[0] if t.ndim else 1
        rows = self._exchange_counts(my_rows)
        counts = [r * row for r in rows]
        out = np.empty((sum(rows),) + t.shape[1:], dtype=t.dtype)
        _check(self._lib, self._lib.hvdt_allgatherv(
            self._h, _as_c(t), t.size, _as_c(out), _counts_arr(counts),
            _dtype_code(t)))
        return out

    def broadcast(self, tensor: np.ndarray, root: int = 0) -> np.ndarray:
        out = np.ascontiguousarray(tensor).copy()
        _check(self._lib, self._lib.hvdt_broadcast(
            self._h, _as_c(out), out.nbytes, root))
        return out

    def alltoall(self, tensor: np.ndarray,
                 splits: Optional[Sequence[int]] = None) -> np.ndarray:
        """Scatter row-splits of ``tensor`` to each rank, gather theirs
        (ref: AlltoallOp::PrepareOutputAndParams recv-split exchange,
        ops/collective_operations.cc:209-273)."""
        t = np.ascontiguousarray(tensor)
        row = int(np.prod(t.shape[1:], dtype=np.int64)) if t.ndim > 1 else 1
        if splits is None:
            base, extra = divmod(t.shape[0], self.size)
            splits = [base + (1 if i < extra else 0)
                      for i in range(self.size)]
        if sum(splits) != t.shape[0]:
            raise ValueError("splits must sum to dim 0")
        # Exchange split tables so each rank knows its recv layout.
        split_mat = self._exchange_splits(splits)
        recv_rows = [split_mat[src][self.rank] for src in range(self.size)]
        send_counts = [s * row for s in splits]
        recv_counts = [r * row for r in recv_rows]
        out = np.empty((sum(recv_rows),) + t.shape[1:], dtype=t.dtype)
        _check(self._lib, self._lib.hvdt_alltoallv(
            self._h, _as_c(t), _counts_arr(send_counts), _as_c(out),
            _counts_arr(recv_counts), _dtype_code(t)))
        return out

    def barrier(self) -> None:
        _check(self._lib, self._lib.hvdt_barrier(self._h))

    def adasum_allreduce(self, tensor: np.ndarray) -> np.ndarray:
        t = np.ascontiguousarray(tensor)
        work = t.astype(np.float64 if t.dtype == np.float64 else np.float32)
        _check(self._lib, self._lib.hvdt_adasum_allreduce(
            self._h, _as_c(work), work.size, _dtype_code(work)))
        return work.astype(t.dtype)

    # -- helpers --

    def _exchange_counts(self, mine: int) -> list:
        buf = np.empty(self.size, dtype=np.int64)
        me = np.array([mine], dtype=np.int64)
        _check(self._lib, self._lib.hvdt_allgatherv(
            self._h, _as_c(me), 1, _as_c(buf),
            _counts_arr([1] * self.size), int(_dtype_code(me))))
        return [int(x) for x in buf]

    def _exchange_splits(self, splits: Sequence[int]) -> np.ndarray:
        mine = np.asarray(splits, dtype=np.int64)
        buf = np.empty(self.size * self.size, dtype=np.int64)
        _check(self._lib, self._lib.hvdt_allgatherv(
            self._h, _as_c(mine), self.size, _as_c(buf),
            _counts_arr([self.size] * self.size), int(_dtype_code(mine))))
        return buf.reshape(self.size, self.size)


def adasum_combine(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Local pairwise Adasum combine — the C++ reference math
    (native/src/adasum.cc), used to validate the JAX implementation."""
    lib = load()
    if a.shape != b.shape or a.dtype != b.dtype:
        raise ValueError("operands must match")
    out = np.ascontiguousarray(a).copy()
    bb = np.ascontiguousarray(b)
    _check(lib, lib.hvdt_adasum_combine(
        _as_c(out), _as_c(bb), out.size, _dtype_code(out)))
    return out
