"""NativeTimeline — Python handle on the C++ async timeline writer.

Off-loads Chrome-trace JSON formatting and file IO to the native writer
thread (native/src/timeline.cc; ref: common/timeline.h:48-102
TimelineWriter), so per-event cost on the training path is one queue push.
The pure-Python Timeline (horovod_tpu/timeline.py) remains the fallback
and the two emit the same event vocabulary.
"""

from __future__ import annotations

import json
import time
from typing import Optional

from . import _check, load

__all__ = ["NativeTimeline"]


class NativeTimeline:
    """Chrome-trace writer; one 'process' row per tensor name
    (ref: timeline.cc:244-266 'tensors as pids')."""

    def __init__(self, path: str):
        import ctypes

        self._lib = load()
        handle = ctypes.c_void_p()
        _check(self._lib,
               self._lib.hvdt_timeline_create(path.encode(),
                                              ctypes.byref(handle)))
        self._h = handle
        self._t0 = time.monotonic_ns()

    def _now_us(self) -> int:
        return (time.monotonic_ns() - self._t0) // 1000

    def _emit(self, pid_name: str, name: str, ph: str, ts_us: int,
              dur_us: int = 0, args: Optional[dict] = None) -> None:
        if self._h is None:
            return
        args_json = json.dumps(args) if args else None
        _check(self._lib, self._lib.hvdt_timeline_event(
            self._h, pid_name.encode(), name.encode(), ph.encode(),
            ts_us, dur_us,
            args_json.encode() if args_json else None))

    def begin(self, tensor: str, phase: str,
              args: Optional[dict] = None) -> None:
        self._emit(tensor, phase, "B", self._now_us(), 0, args)

    def end(self, tensor: str, phase: str,
            args: Optional[dict] = None) -> None:
        self._emit(tensor, phase, "E", self._now_us(), 0, args)

    def complete(self, tensor: str, phase: str, start_us: int, dur_us: int,
                 args: Optional[dict] = None) -> None:
        self._emit(tensor, phase, "X", start_us, dur_us, args)

    def instant(self, tensor: str, name: str,
                args: Optional[dict] = None) -> None:
        self._emit(tensor, name, "i", self._now_us(), 0, args)

    def close(self) -> None:
        if self._h is not None:
            self._lib.hvdt_timeline_close(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
