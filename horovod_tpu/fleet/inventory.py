"""The single pod inventory both workloads lease from.

Training and serving already share the rendezvous/KV machinery and the
exit taxonomy; what they did NOT share was the answer to "who owns pod
X right now".  :class:`FleetInventory` is that answer: an ordered pod
set (the discovery ``@pod`` columns / ``HVDT_POD_SIZE`` chunking that
:func:`runner.elastic.pods.group_pods` produces) with at most one
**lease** per pod, keyed by workload kind (``"train"`` / ``"serve"``).

Failure state is *shared, not duplicated*: the inventory rides the same
:class:`~..runner.elastic.pods.PodTracker` exit-window correlation and
:class:`~..runner.elastic.discovery.HostManager` blacklist-with-cooldown
the two drivers already use, so a crashed pod is unavailable to BOTH
workloads through ONE correlated removal event — N ranks of a dying
slice cost one blacklist entry and one lease release, never one per
workload per rank (the drain-under-failure test pins exactly this).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

from ..common.logging_util import get_logger
from ..runner.elastic import pods as pods_mod
from ..runner.elastic.discovery import HostManager

__all__ = ["Lease", "FleetInventory", "WORKLOAD_KINDS"]

log = get_logger(__name__)

WORKLOAD_KINDS = ("train", "serve")


@dataclasses.dataclass(frozen=True)
class Lease:
    """One pod leased to one workload."""

    pod: str
    kind: str            # one of WORKLOAD_KINDS
    acquired_at: float

    def to_dict(self) -> Dict[str, object]:
        return {"pod": self.pod, "kind": self.kind,
                "acquired_at": round(self.acquired_at, 3)}


class FleetInventory:
    """Leases over an ordered pod set, sharing the elastic failure state.

    ``host_manager`` / ``pod_tracker`` are the SAME objects the training
    and serving drivers hold (or fresh ones for standalone simulation):
    a pod blacklisted by either driver is excluded from
    :meth:`available` here, and :meth:`record_failure` folds correlated
    exits into one removal event via the tracker window before it
    blacklists + releases — so the scheduler's retry lands elsewhere and
    the lease is released exactly once per loss.
    """

    def __init__(self, pods: Sequence[str],
                 host_manager: Optional[HostManager] = None,
                 pod_tracker: Optional[pods_mod.PodTracker] = None,
                 clock: Callable[[], float] = time.monotonic):
        self._order: List[str] = list(dict.fromkeys(pods))
        self._hm = host_manager
        self._tracker = pod_tracker or pods_mod.PodTracker()
        self._clock = clock
        self._lock = threading.Lock()
        self._leases: Dict[str, Lease] = {}
        self.release_events = 0    # audit: every lease release, once each

    @property
    def tracker(self) -> pods_mod.PodTracker:
        return self._tracker

    @property
    def pods(self) -> List[str]:
        return list(self._order)

    # -- leases ------------------------------------------------------------

    def acquire(self, pod: str, kind: str) -> bool:
        """Lease ``pod`` to ``kind``.  Refused (False) when the pod is
        unknown, already leased, blacklisted, or draining."""
        if kind not in WORKLOAD_KINDS:
            raise ValueError(f"unknown workload kind {kind!r}; "
                             f"valid: {WORKLOAD_KINDS}")
        if pod not in self._order or not self._usable(pod):
            return False
        with self._lock:
            if pod in self._leases:
                return False
            self._leases[pod] = Lease(pod, kind, self._clock())
            return True

    def release(self, pod: str) -> bool:
        """Release ``pod``'s lease.  Exactly-once: True only when a
        lease was actually held — the double-release a crash landing
        mid-reclaim could cause is a no-op, not a second event."""
        with self._lock:
            if self._leases.pop(pod, None) is None:
                return False
            self.release_events += 1
            return True

    def lease_of(self, pod: str) -> Optional[Lease]:
        with self._lock:
            return self._leases.get(pod)

    def leased(self, kind: Optional[str] = None) -> List[str]:
        """Pods currently leased (inventory order), optionally filtered
        to one workload kind."""
        with self._lock:
            held = {p: ls for p, ls in self._leases.items()}
        return [p for p in self._order if p in held
                and (kind is None or held[p].kind == kind)]

    # -- availability (shared failure state) -------------------------------

    def _usable(self, pod: str) -> bool:
        if self._hm is not None and self._hm.is_pod_blacklisted(pod):
            return False
        return pod not in self._tracker.drained_pods()

    def available(self) -> List[str]:
        """Unleased pods placeable for EITHER workload: not leased, not
        blacklisted, not draining — one view, both drivers' state."""
        with self._lock:
            held = set(self._leases)
        return [p for p in self._order
                if p not in held and self._usable(p)]

    def record_failure(self, pod: str, now: Optional[float] = None) -> bool:
        """One rank's failure exit on ``pod``.  Returns True only when
        this OPENS the pod-removal event (the PodTracker window folds
        the slice's remaining exits into it) — and only then does the
        pod get blacklisted and its lease released, so a pod_crash
        landing DURING a reclaim still costs exactly one event, one
        blacklist entry, and one release."""
        if not self._tracker.record_failure(pod, now=now):
            return False
        if self._hm is not None:
            self._hm.blacklist_pod(pod)
        released = self.release(pod)
        log.warning("fleet: pod %s removed (correlated failure event; "
                    "lease %sreleased)", pod,
                    "" if released else "already ")
        return True

    def drain(self, pod: str, now: Optional[float] = None) -> bool:
        """Mark ``pod`` draining (preemption / platform reclaim) for
        both workloads and release its lease."""
        fresh = self._tracker.drain(pod, now=now)
        self.release(pod)
        return fresh

    # -- introspection -----------------------------------------------------

    def describe(self) -> Dict[str, object]:
        with self._lock:
            leases = [ls.to_dict() for _, ls in
                      sorted(self._leases.items())]
        return {"pods": list(self._order),
                "leases": leases,
                "available": self.available(),
                "removal_events": self._tracker.removal_events,
                "release_events": self.release_events}
