"""The bin-packing fleet reconciler: priced, guardrailed pod moves
between elastic training and elastic serving.

Sits ABOVE the two drivers' existing seams — ``ElasticDriver.resize()``
(training joins/leaves at pod granularity; exit-83 drains plus
emergency-commit + peer-RAM restore make a shrink cheap) and
``ServeDriver``'s replica-target KV key — and owns exactly two move
kinds over the shared :class:`~.inventory.FleetInventory`:

* ``reclaim`` — serving pressure (router queue depth per replica /
  p99-vs-SLO headroom) crossed the ENTER band: drain one training pod
  and hand it to serving.
* ``backfill`` — the diurnal trough: serving pressure is far below the
  band, so a serve pod goes back to training.

Every move is **priced before commit**, never probed live: the training
side by ``CostModel.allreduce_seconds`` at the candidate world size
plus the compute anchor (the goodput the chips would earn), the serving
side by predicted SLO headroom under queue-proportional p99 scaling.
Reclaim candidates are ranked slowest-pod-first: a synchronous step
runs at the straggler's pace, so reclaiming the pod with the worst
step-time median costs the least goodput — and the SAME ranking
function drives the CPU simulator (:mod:`.simulate`), which is how the
acceptance criterion "simulated reclaim ranking agrees with the live
decision on the same inputs" holds by construction.

The guardrail battery is the PR-18 controller's, verbatim in spirit:
per-move-kind cooldown (doubled after a rollback), hysteresis
enter/exit bands over the pressure series, a min-gain floor, a total
move budget, observe (dry-run) mode, and a never-worse rollback — a
reclaim that fails to bring pressure back under the exit band within
the recovery window is inverted (the pod backfills home).  Every
decision and outcome is an auditable ``fleet_decision`` /
``fleet_outcome`` record in the ``HVDT_EVENT_LOG`` JSONL, rendered by
``hvdtrun top`` and ``analysis --report``.

The scheduler also owns ``/serve/target_replicas``: it writes a
**seq-guarded JSON doc** (:func:`write_target`) carrying a last-writer
audit field, while a raw-int KV value or ``--target-file`` stays the
operator override that beats everyone.  The PR-18 controller's
``scale_replicas`` action is routed here as a *hint*
(:meth:`FleetScheduler.hint_scale`) whenever a scheduler is active,
which resolves the two-writers race on the key.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ..common import config
from ..common.logging_util import get_logger
from .inventory import FleetInventory

__all__ = ["MOVE_KINDS", "Move", "PricedMove", "FleetConfig",
           "FleetDecision", "FleetScheduler", "read_target",
           "write_target", "get_scheduler", "install", "reset"]

log = get_logger(__name__)

MOVE_KINDS = ("reclaim", "backfill")


# ---------------------------------------------------------------------------
# The seq-guarded replica-target doc (satellite: one key, many writers)
# ---------------------------------------------------------------------------


def read_target(raw: Optional[bytes]) -> Optional[Dict[str, Any]]:
    """Decode the ``/serve/target_replicas`` value into a uniform doc.

    Three on-wire forms, by precedence at the reader:

    * raw int (``b"3"``) — the operator's out-of-band override
      (``seq`` is None: it beats every doc writer);
    * JSON doc ``{"target": n, "seq": k, "writer": ...}`` — the
      fleet scheduler / routed controller hint, seq-guarded;
    * anything else — None (garbage never scales a fleet).
    """
    if raw is None:
        return None
    try:
        text = raw.decode()
    except UnicodeDecodeError:
        return None
    try:
        return {"target": int(text), "seq": None, "writer": "operator"}
    except ValueError:
        pass
    try:
        doc = json.loads(text)
    except ValueError:
        return None
    if not isinstance(doc, dict) or "target" not in doc:
        return None
    try:
        doc["target"] = int(doc["target"])
    except (TypeError, ValueError):
        return None
    return doc


def write_target(kv: Any, target: int, writer: str, reason: str = "",
                 expect_seq: Optional[int] = None
                 ) -> Optional[Dict[str, Any]]:
    """Seq-guarded write of the replica-target doc.

    Read-increment-write under the KV lock: each successful write bumps
    ``seq`` by one and stamps the last-writer audit field.  Refused
    (None) when a raw-int operator override currently owns the key, or
    when ``expect_seq`` is given and the key's seq moved underneath the
    caller — the compare-and-swap that makes two concurrent writers
    (fleet scheduler vs controller hint) serialize instead of racing.
    """
    from ..serve.autoscale import TARGET_KV_KEY

    with kv.lock:
        cur = read_target(kv.store.get(TARGET_KV_KEY))
        if cur is not None and cur.get("seq") is None:
            return None     # operator raw int owns the key
        seq = int(cur.get("seq") or 0) if cur else 0
        if expect_seq is not None and seq != expect_seq:
            return None
        doc = {"target": int(target), "seq": seq + 1,
               "writer": str(writer), "reason": str(reason),
               "ts": time.time()}
        kv.store[TARGET_KV_KEY] = json.dumps(doc).encode()
        return doc


# ---------------------------------------------------------------------------
# Moves + pricing
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Move:
    """One candidate pod move between the workloads."""

    kind: str            # reclaim | backfill
    pod: str
    reason: str = ""

    def __post_init__(self) -> None:
        if self.kind not in MOVE_KINDS:
            raise ValueError(f"unknown move kind {self.kind!r}; "
                             f"valid: {MOVE_KINDS}")

    def to_dict(self) -> Dict[str, Any]:
        return {"kind": self.kind, "pod": self.pod, "reason": self.reason}


@dataclasses.dataclass(frozen=True)
class PricedMove:
    """A move with its offline price tag (all terms dimensionless
    fractions of entitlement, so train and serve sides compare)."""

    move: Move
    predicted_gain: float        # serve relief minus train cost
    train_fraction_after: float  # predicted training throughput keep
    pressure_after: float        # predicted serving pressure after

    def to_dict(self) -> Dict[str, Any]:
        return {"move": self.move.to_dict(),
                "predicted_gain": round(self.predicted_gain, 6),
                "train_fraction_after":
                    round(self.train_fraction_after, 6),
                "pressure_after": round(self.pressure_after, 6)}


@dataclasses.dataclass
class FleetConfig:
    """Knob bundle (``HVDT_FLEET_*``; see docs/knobs.md)."""

    mode: str = "act"               # act | observe (dry-run)
    cooldown_s: float = 60.0
    enter_ratio: float = 1.2        # pressure at/above this -> reclaim
    exit_ratio: float = 1.05        # ...recovered/re-armed below this
    backfill_ratio: float = 0.5     # pressure below this -> trough
    recovery_window: int = 3        # verify ticks before rollback
    min_gain: float = 0.0           # predicted-gain floor (fraction)
    max_moves: int = 0              # 0 = unbounded
    min_train_pods: int = 1
    min_serve_units: int = 1
    queue_hi: float = 8.0           # pressure denominator (serve knob)

    @classmethod
    def from_env(cls) -> "FleetConfig":
        raw = (config.get_str("HVDT_FLEET") or "").strip().lower()
        mode = "observe" if raw in ("observe", "dry-run", "dryrun") \
            else "act"
        return cls(
            mode=mode,
            cooldown_s=config.get_float("HVDT_FLEET_COOLDOWN_S"),
            enter_ratio=config.get_float("HVDT_FLEET_ENTER_RATIO"),
            exit_ratio=config.get_float("HVDT_FLEET_EXIT_RATIO"),
            backfill_ratio=config.get_float("HVDT_FLEET_BACKFILL_RATIO"),
            recovery_window=config.get_int("HVDT_FLEET_RECOVERY_WINDOW"),
            min_gain=config.get_float("HVDT_FLEET_MIN_GAIN"),
            max_moves=config.get_int("HVDT_FLEET_MAX_MOVES"),
            min_train_pods=config.get_int("HVDT_FLEET_MIN_TRAIN_PODS"),
            queue_hi=config.get_float("HVDT_SERVE_QUEUE_HI"))


@dataclasses.dataclass
class FleetDecision:
    """One tick outcome — the in-memory twin of the JSONL record."""

    trigger: Dict[str, Any]
    candidates: List[PricedMove]
    chosen: Optional[PricedMove]
    outcome: str          # applied | observed | suppressed:<reason>
    step: Optional[int] = None
    train_pods: int = 0
    serve_units: int = 0

    def to_record(self) -> Dict[str, Any]:
        return {
            "kind": "fleet_decision",
            "trigger": self.trigger,
            "candidates": [p.to_dict() for p in self.candidates],
            "chosen": self.chosen.to_dict() if self.chosen else None,
            "outcome": self.outcome,
            "step": self.step,
            "train_pods": self.train_pods,
            "serve_units": self.serve_units,
        }


@dataclasses.dataclass
class _PendingVerify:
    decision: FleetDecision
    trigger_key: str
    pressure_at_decision: float
    ticks_left: int
    rollback: Optional[Move]


class FleetScheduler:
    """See module docstring.  Thread-safe; the launcher ticks it from a
    control thread while the simulator and tests tick it inline.

    The 1-pod-per-serve-unit model: a reclaimed pod adds exactly one
    replica-unit of serving capacity and a backfilled pod removes one —
    the bin the packing happens in IS the pod, matching the whole-pod
    join/leave invariant on the training side.
    """

    def __init__(self, inventory: FleetInventory,
                 cfg: Optional[FleetConfig] = None,
                 model=None, kv: Any = None,
                 event_log=None, registry=None,
                 clock: Callable[[], float] = time.monotonic,
                 grad_bytes: Optional[float] = None,
                 flops_per_step: Optional[float] = None,
                 chips_per_pod: int = 4,
                 peak_flops: Optional[float] = None):
        from ..analysis.topology import (REFERENCE_STEP_WORKLOAD,
                                         chip_peak_flops)

        self.inventory = inventory
        self.cfg = cfg or FleetConfig.from_env()
        if model is None:
            from ..analysis.costmodel import CostModel

            model = CostModel()
        self.model = model
        self.kv = kv
        self._explicit_log = event_log
        self._clock = clock
        self.grad_bytes = float(
            grad_bytes if grad_bytes is not None
            else REFERENCE_STEP_WORKLOAD["grad_bytes"])
        self.flops_per_step = float(
            flops_per_step if flops_per_step is not None
            else REFERENCE_STEP_WORKLOAD["flops_per_step"])
        self.chips_per_pod = max(1, int(chips_per_pod))
        # Same peak-rate source as the MFU gauge and the perf gate —
        # never a literal here (v5e is the fleet's reference chip).
        self.peak_flops = float(
            peak_flops if peak_flops is not None
            else chip_peak_flops("v5e") or 0.0)
        self._lock = threading.Lock()
        self._appliers: Dict[str, Callable[[Move], bool]] = {}
        self._cooldown_until: Dict[str, float] = {}
        self._cooldown_s: Dict[str, float] = {}
        self._disarmed: set = set()
        self._pending: List[_PendingVerify] = []
        self._applied_total = 0
        self._last_signals: Dict[str, Any] = {}
        self.moves_applied: Dict[str, int] = {k: 0 for k in MOVE_KINDS}
        self.rollbacks = 0      # audit: never-worse rollbacks fired
        reg = registry
        if reg is None:
            from ..telemetry.metrics import default_registry

            reg = default_registry()
        self._m_decisions = reg.counter(
            "hvdt_fleet_decisions_total",
            "Fleet scheduler decisions by move kind and outcome")
        self._m_suppressed = reg.counter(
            "hvdt_fleet_suppressed_total",
            "Fleet scheduler decisions suppressed by guardrail")
        self._m_rollbacks = reg.counter(
            "hvdt_fleet_rollbacks_total",
            "Never-worse fleet rollbacks (pressure failed to recover)")
        self._m_pending = reg.gauge(
            "hvdt_fleet_pending",
            "Applied fleet moves awaiting pressure verification")
        self._m_pressure = reg.gauge(
            "hvdt_fleet_pressure",
            "Serving pressure the fleet scheduler last acted on")
        self._m_train_pods = reg.gauge(
            "hvdt_fleet_train_pods",
            "Pods currently leased to training")
        self._m_serve_units = reg.gauge(
            "hvdt_fleet_serve_units",
            "Pods currently leased to serving")

    # -- wiring ------------------------------------------------------------

    def bind(self, kind: str, fn: Callable[[Move], bool]) -> None:
        """Attach the applier for one move kind (driver seams in the
        launcher, state mutators in the simulator/tests)."""
        if kind not in MOVE_KINDS:
            raise ValueError(f"unknown move kind {kind!r}")
        self._appliers[kind] = fn

    @property
    def pending(self) -> int:
        with self._lock:
            return len(self._pending)

    def _emit(self, doc: Dict[str, Any]) -> Dict[str, Any]:
        sink = self._explicit_log
        if sink is None:
            from ..telemetry import anomaly

            sink = anomaly.get_event_log()
        if sink is not None:
            return sink.emit(doc)
        return doc

    # -- pricing -----------------------------------------------------------

    def train_step_seconds(self, pods: int) -> float:
        """Predicted step seconds at ``pods`` training pods: the cost
        model's gradient exchange on that topology plus the compute
        anchor — the same closed form the PR-18 pricer uses, evaluated
        on CPU with no devices (TopologySpec is declarative)."""
        from ..analysis.topology import TopologySpec

        pods = max(1, int(pods))
        topo = TopologySpec(pods=pods, chips_per_pod=self.chips_per_pod)
        comm = self.model.allreduce_seconds(
            self.grad_bytes, topo, hierarchical=pods > 1)["seconds"]
        compute = self.flops_per_step / (
            self.peak_flops * topo.total_chips)
        return comm + compute

    def train_throughput(self, pods: int) -> float:
        """Relative training throughput (examples/sec shape):
        chips served per step second."""
        pods = max(1, int(pods))
        return (pods * self.chips_per_pod) / self.train_step_seconds(pods)

    def pressure(self, queue_per_replica: float = 0.0,
                 p99_ms: Optional[float] = None,
                 slo_p99_ms: float = 0.0) -> float:
        """The serving pressure ratio the hysteresis bands run over:
        max of queue depth per replica vs ``HVDT_SERVE_QUEUE_HI`` and
        p99 vs the SLO — 1.0 means exactly at threshold."""
        terms = [0.0]
        if self.cfg.queue_hi > 0:
            terms.append(float(queue_per_replica) / self.cfg.queue_hi)
        if slo_p99_ms and p99_ms is not None:
            terms.append(float(p99_ms) / float(slo_p99_ms))
        return max(terms)

    def price_move(self, move: Move, *, train_pods: int,
                   serve_units: int, pressure: float,
                   pod_step_medians: Optional[Dict[str, float]] = None
                   ) -> PricedMove:
        """Offline price of one move under the current signals.

        Reclaim: serve relief is queue-proportional (pressure scales
        with offered load per unit, so +1 unit divides it by
        (units+1)/units); train cost is the throughput fraction lost at
        the shrunken world — discounted by the candidate pod's
        straggler ratio, because a synchronous step already runs at the
        slowest pod's pace.  Backfill is the mirror image, charged the
        predicted pressure increase on the remaining units.
        """
        medians = pod_step_medians or {}
        if move.kind == "reclaim":
            after_units = serve_units + 1
            pressure_after = pressure * serve_units / after_units \
                if serve_units > 0 else 0.0
            ratio = 1.0
            if medians.get(move.pod):
                ordered = sorted(medians.values())
                base = ordered[(len(ordered) - 1) // 2]
                if base > 0:
                    ratio = max(1.0, medians[move.pod] / base)
            thr_now = self.train_throughput(train_pods) / ratio
            thr_after = self.train_throughput(train_pods - 1)
            frac_after = thr_after / thr_now if thr_now > 0 else 1.0
            train_cost = max(0.0, 1.0 - frac_after)
            relief = pressure - pressure_after
            return PricedMove(move, relief - train_cost,
                              min(1.0, frac_after), pressure_after)
        # backfill
        after_units = max(1, serve_units - 1)
        pressure_after = pressure * serve_units / after_units \
            if serve_units > 1 else float("inf")
        thr_now = self.train_throughput(train_pods)
        thr_after = self.train_throughput(train_pods + 1)
        train_gain = thr_after / thr_now - 1.0 if thr_now > 0 else 0.0
        risk = max(0.0, pressure_after - self.cfg.backfill_ratio)
        return PricedMove(move, train_gain - risk,
                          min(1.0, thr_after / max(thr_after, thr_now)),
                          pressure_after)

    def rank_reclaims(self, *, train_pods: Optional[List[str]] = None,
                      serve_units: int,
                      pressure: float,
                      pod_step_medians: Optional[Dict[str, float]] = None
                      ) -> List[PricedMove]:
        """All reclaim candidates priced, best first — slowest pod
        ranks highest because its straggler discount shrinks the train
        cost.  This single function is the ranking BOTH the live tick
        and the CPU simulator use (the sim-vs-live agreement
        acceptance pins it)."""
        pods = (train_pods if train_pods is not None
                else self.inventory.leased("train"))
        if len(pods) <= self.cfg.min_train_pods:
            return []
        priced = [self.price_move(
            Move("reclaim", p, reason="serve_pressure"),
            train_pods=len(pods), serve_units=serve_units,
            pressure=pressure, pod_step_medians=pod_step_medians)
            for p in pods]
        return sorted(priced, key=lambda pm: -pm.predicted_gain)

    # -- the loop ----------------------------------------------------------

    def tick(self, *, queue_per_replica: float = 0.0,
             p99_ms: Optional[float] = None, slo_p99_ms: float = 0.0,
             pod_step_medians: Optional[Dict[str, float]] = None,
             goodput_fraction: Optional[float] = None,
             step: Optional[int] = None) -> List[FleetDecision]:
        """One reconcile tick: verify pending moves against the fresh
        pressure, then decide.  Returns the decisions made."""
        pressure = self.pressure(queue_per_replica, p99_ms, slo_p99_ms)
        self._last_signals = {
            "queue_per_replica": queue_per_replica, "p99_ms": p99_ms,
            "slo_p99_ms": slo_p99_ms, "pressure": pressure,
            "pod_step_medians": dict(pod_step_medians or {}),
            "goodput_fraction": goodput_fraction, "step": step,
        }
        self._m_pressure.set(pressure)
        self._verify(pressure, step)
        # Leases are read AFTER verification: a rollback just relabeled.
        train = self.inventory.leased("train")
        serve = self.inventory.leased("serve")
        self._m_train_pods.set(len(train))
        self._m_serve_units.set(len(serve))
        out: List[FleetDecision] = []
        if pressure >= self.cfg.enter_ratio:
            d = self._decide(
                trigger={"kind": "serve_pressure", "ratio": pressure},
                candidates=self.rank_reclaims(
                    train_pods=train, serve_units=len(serve),
                    pressure=pressure,
                    pod_step_medians=pod_step_medians),
                pressure=pressure, step=step,
                train_pods=len(train), serve_units=len(serve))
            if d is not None:
                out.append(d)
        elif (pressure <= self.cfg.backfill_ratio
              and len(serve) > self.cfg.min_serve_units
              and self.inventory.leased("serve")):
            # Trough: give the *newest* serve pod back to training —
            # the oldest serve placements hold the warmest caches,
            # matching the ServeDriver's drain-newest-first policy.
            pod = serve[-1]
            cand = self.price_move(
                Move("backfill", pod, reason="serve_trough"),
                train_pods=len(train), serve_units=len(serve),
                pressure=pressure, pod_step_medians=pod_step_medians)
            d = self._decide(
                trigger={"kind": "serve_trough", "ratio": pressure},
                candidates=[cand], pressure=pressure, step=step,
                train_pods=len(train), serve_units=len(serve))
            if d is not None:
                out.append(d)
        with self._lock:
            self._m_pending.set(len(self._pending))
        return out

    def hint_scale(self, target: int, source: str = "controller",
                   reason: str = "") -> bool:
        """The PR-18 controller's ``scale_replicas`` action, routed
        through the fleet instead of racing it on the KV key.  A hint
        for MORE capacity becomes a reclaim decision under the full
        guardrail battery (so a hint can be suppressed — that is the
        point); a hint at/below current capacity is recorded and
        dropped (the trough path owns scale-down).  Returns True when
        the hint was accepted for audit, whatever the verdict."""
        sig = dict(self._last_signals)
        serve = self.inventory.leased("serve")
        train = self.inventory.leased("train")
        trigger = {"kind": "controller_hint", "source": source,
                   "target": int(target), "reason": reason,
                   "ratio": sig.get("pressure", 0.0)}
        if int(target) <= len(serve):
            self._emit(FleetDecision(
                trigger=trigger, candidates=[], chosen=None,
                outcome="suppressed:hint_not_growth",
                step=sig.get("step"), train_pods=len(train),
                serve_units=len(serve)).to_record())
            self._m_suppressed.inc(reason="hint_not_growth")
            return True
        pressure = max(float(sig.get("pressure") or 0.0),
                       self.cfg.enter_ratio)
        self._decide(
            trigger=trigger,
            candidates=self.rank_reclaims(
                train_pods=train, serve_units=len(serve),
                pressure=pressure,
                pod_step_medians=sig.get("pod_step_medians")),
            pressure=pressure, step=sig.get("step"),
            train_pods=len(train), serve_units=len(serve))
        return True

    def _trigger_key(self, trigger: Dict[str, Any]) -> str:
        return str(trigger.get("kind", ""))

    def _decide(self, *, trigger: Dict[str, Any],
                candidates: List[PricedMove], pressure: float,
                step: Optional[int], train_pods: int,
                serve_units: int) -> Optional[FleetDecision]:
        if not candidates:
            return None
        now = self._clock()
        key = self._trigger_key(trigger)
        decision = FleetDecision(
            trigger=trigger, candidates=candidates, chosen=None,
            outcome="", step=step, train_pods=train_pods,
            serve_units=serve_units)
        with self._lock:
            if (self.cfg.max_moves
                    and self._applied_total >= self.cfg.max_moves):
                return self._suppress(decision, "budget")
            if key in self._disarmed:
                return self._suppress(decision, "hysteresis")
            chosen: Optional[PricedMove] = None
            cooled = False
            for pm in candidates:
                if pm.predicted_gain < self.cfg.min_gain:
                    break   # ranked — nothing further clears the bar
                if now < self._cooldown_until.get(pm.move.kind, 0.0):
                    cooled = True
                    continue
                chosen = pm
                break
            if chosen is None:
                return self._suppress(
                    decision, "cooldown" if cooled else "no_gain")
            decision.chosen = chosen
            if self.cfg.mode == "observe":
                decision.outcome = "observed"
                self._m_decisions.inc(move=chosen.move.kind,
                                      outcome="observed")
                self._emit(decision.to_record())
                return decision
            applier = self._appliers.get(chosen.move.kind)

        ok = False
        if applier is not None:
            try:
                ok = bool(applier(chosen.move))
            except Exception as e:  # an actuator must never sink us
                log.warning("fleet applier %s failed: %s",
                            chosen.move.kind, e)
        with self._lock:
            if not ok:
                return self._suppress(decision, "apply_failed")
            decision.outcome = "applied"
            self._applied_total += 1
            self.moves_applied[chosen.move.kind] += 1
            cd = self._cooldown_s.get(chosen.move.kind,
                                      self.cfg.cooldown_s)
            self._cooldown_until[chosen.move.kind] = now + cd
            self._disarmed.add(key)
            inverse = Move(
                "backfill" if chosen.move.kind == "reclaim"
                else "reclaim",
                chosen.move.pod,
                reason=f"rollback:{chosen.move.reason}")
            self._pending.append(_PendingVerify(
                decision=decision, trigger_key=key,
                pressure_at_decision=pressure,
                ticks_left=max(1, self.cfg.recovery_window),
                rollback=inverse))
            self._m_decisions.inc(move=chosen.move.kind,
                                  outcome="applied")
        self._relabel(chosen.move)
        self._emit(decision.to_record())
        log.info("fleet applied %s of pod %s (predicted gain %.3g)",
                 chosen.move.kind, chosen.move.pod,
                 chosen.predicted_gain)
        return decision

    def _relabel(self, move: Move) -> None:
        """Flip the applied move's pod lease to the receiving workload
        (release + re-acquire; a pod the applier already lost to a
        concurrent failure simply stays unleased)."""
        self.inventory.release(move.pod)
        self.inventory.acquire(
            move.pod, "serve" if move.kind == "reclaim" else "train")

    def _suppress(self, decision: FleetDecision, reason: str
                  ) -> FleetDecision:
        """(lock held) Record a guardrail suppression."""
        decision.outcome = f"suppressed:{reason}"
        self._m_suppressed.inc(reason=reason)
        self._emit(decision.to_record())
        return decision

    # -- verification / rollback -------------------------------------------

    def _verify(self, pressure: float, step: Optional[int]) -> None:
        """Judge pending moves against the fresh pressure.

        A reclaim recovers EARLY when pressure drops under the exit
        band; at window expiry it recovers as long as pressure did not
        get WORSE than at decision time — a sustained flash crowd may
        need several reclaims, and never-worse means "roll back moves
        that hurt", not "roll back moves that weren't singly
        sufficient".  A backfill fails FAST when pressure crosses the
        enter band (it tipped serving over) and recovers by surviving
        its window.
        """
        rollbacks: List[_PendingVerify] = []
        recovered: List[_PendingVerify] = []
        with self._lock:
            still: List[_PendingVerify] = []
            for p in self._pending:
                kind = p.decision.chosen.move.kind
                if kind == "reclaim" and pressure <= self.cfg.exit_ratio:
                    recovered.append(p)
                    continue
                if kind == "backfill" \
                        and pressure >= self.cfg.enter_ratio:
                    rollbacks.append(p)
                    continue
                p.ticks_left -= 1
                if p.ticks_left > 0:
                    still.append(p)
                elif kind == "reclaim" \
                        and pressure > p.pressure_at_decision + 1e-9:
                    rollbacks.append(p)
                else:
                    recovered.append(p)
            self._pending = still
            for p in recovered:
                self._disarmed.discard(p.trigger_key)
                self._m_decisions.inc(
                    move=p.decision.chosen.move.kind,
                    outcome="recovered")
        for p in recovered:
            self._emit({
                "kind": "fleet_outcome",
                "outcome": "recovered",
                "move": p.decision.chosen.move.to_dict(),
                "predicted_gain": p.decision.chosen.predicted_gain,
                "pressure_before": p.pressure_at_decision,
                "pressure_after": pressure,
                "step": step,
            })
        for p in rollbacks:
            self._rollback(p, pressure, step)

    def _rollback(self, p: _PendingVerify, pressure: float,
                  step: Optional[int]) -> None:
        """Never-worse: the move did not help inside the window — apply
        the inverse move and double the kind's cooldown."""
        kind = p.decision.chosen.move.kind
        ok = None
        if p.rollback is not None:
            applier = self._appliers.get(p.rollback.kind)
            if applier is not None:
                try:
                    ok = bool(applier(p.rollback))
                except Exception as e:
                    log.warning("fleet rollback %s failed: %s",
                                p.rollback.kind, e)
                    ok = False
            if ok:
                self._relabel(p.rollback)
        with self._lock:
            now = self._clock()
            cd = 2 * self._cooldown_s.get(kind, self.cfg.cooldown_s)
            self._cooldown_s[kind] = cd
            self._cooldown_until[kind] = now + cd
            # The trigger stays disarmed until the pressure series
            # itself exits the band — rollback is not a license to flap.
            self.rollbacks += 1
            self._m_rollbacks.inc()
            self._m_decisions.inc(move=kind, outcome="rolled_back")
        self._emit({
            "kind": "fleet_outcome",
            "outcome": "rolled_back",
            "move": p.decision.chosen.move.to_dict(),
            "rollback": (p.rollback.to_dict()
                         if p.rollback is not None else None),
            "rollback_applied": ok,
            "predicted_gain": p.decision.chosen.predicted_gain,
            "pressure_before": p.pressure_at_decision,
            "pressure_after": pressure,
            "step": step,
        })
        log.warning("fleet rolled back %s of pod %s (pressure %.3g did "
                    "not recover)", kind, p.decision.chosen.move.pod,
                    pressure)


# ---------------------------------------------------------------------------
# Zero-overhead engagement (the faults/controller idiom)
# ---------------------------------------------------------------------------


_lock = threading.Lock()
_installed: Optional[FleetScheduler] = None


def install(scheduler: Optional[FleetScheduler]) -> None:
    """Install the process-wide scheduler instance (the launcher wires
    it; tests and the simulator install their own)."""
    global _installed
    with _lock:
        _installed = scheduler


def get_scheduler() -> Optional[FleetScheduler]:
    """The installed scheduler when ``HVDT_FLEET`` is active, else None
    — one env read on the unset path, zero objects, zero threads.  The
    controller's ``scale_replicas`` applier calls this to decide
    whether its action routes as a fleet hint."""
    raw = (os.environ.get("HVDT_FLEET") or "").strip().lower()
    if not raw or raw in ("0", "off", "false"):
        return None
    with _lock:
        return _installed


def reset() -> None:
    """Drop the installed scheduler (test isolation)."""
    install(None)
