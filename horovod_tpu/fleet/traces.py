"""Synthetic traffic traces for the fleet scenario harness.

A :class:`TrafficTrace` is a piecewise-linear offered-load curve —
``(t_seconds, requests_per_second)`` breakpoints plus the serving SLO —
small enough to check into the repo as JSON (``tools/traces/*.json``)
and deterministic enough that a simulation report is reproducible
byte-for-byte from the trace + fault plan + seed.

Three builders cover the shapes the utilization story is about:

* :func:`diurnal` — the day curve: a long overnight trough (training's
  backfill window), a morning ramp, a sustained daytime plateau, an
  evening fall-off.
* :func:`flash_crowd` — a step onto a multiple of baseline within
  seconds: the reclaim path's forcing function.
* :func:`step_function` — a square wave between low and high: the
  hysteresis/cooldown battery's worst case (a flappy scheduler fails
  this one).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Optional, Tuple

__all__ = ["TrafficTrace", "diurnal", "flash_crowd", "step_function",
           "BUILTIN_TRACES", "load_trace"]


@dataclasses.dataclass(frozen=True)
class TrafficTrace:
    """Piecewise-linear offered load over time."""

    name: str
    points: Tuple[Tuple[float, float], ...]   # (t_s, rps), t ascending
    slo_p99_ms: float = 250.0

    def __post_init__(self) -> None:
        if not self.points:
            raise ValueError("a trace needs at least one (t, rps) point")
        ts = [t for t, _ in self.points]
        if ts != sorted(ts):
            raise ValueError("trace points must be time-ascending")

    @property
    def duration_s(self) -> float:
        return self.points[-1][0]

    def rps_at(self, t: float) -> float:
        """Offered load at ``t`` (linear between breakpoints, clamped
        to the endpoints outside the trace)."""
        pts = self.points
        if t <= pts[0][0]:
            return pts[0][1]
        for (t0, r0), (t1, r1) in zip(pts, pts[1:]):
            if t <= t1:
                if t1 <= t0:
                    return r1
                frac = (t - t0) / (t1 - t0)
                return r0 + frac * (r1 - r0)
        return pts[-1][1]

    # -- JSON round-trip ---------------------------------------------------

    def to_dict(self) -> Dict[str, object]:
        return {"name": self.name,
                "slo_p99_ms": self.slo_p99_ms,
                "points": [[t, r] for t, r in self.points]}

    @classmethod
    def from_dict(cls, doc: Dict[str, object]) -> "TrafficTrace":
        pts = tuple((float(t), float(r))
                    for t, r in doc.get("points") or ())
        return cls(name=str(doc.get("name") or "trace"),
                   points=pts,
                   slo_p99_ms=float(doc.get("slo_p99_ms") or 250.0))

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.to_dict(), f, indent=2, sort_keys=True)
            f.write("\n")

    @classmethod
    def load(cls, path: str) -> "TrafficTrace":
        with open(path) as f:
            return cls.from_dict(json.load(f))


def diurnal(base_rps: float = 40.0, peak_rps: float = 400.0,
            day_s: float = 3600.0, slo_p99_ms: float = 250.0
            ) -> TrafficTrace:
    """One compressed "day": trough, morning ramp, plateau, fall-off.
    ``day_s`` scales the whole curve (default one simulated hour)."""
    d = day_s
    return TrafficTrace(
        name="diurnal", slo_p99_ms=slo_p99_ms,
        points=(
            (0.0, base_rps),            # overnight trough
            (0.25 * d, base_rps),
            (0.40 * d, peak_rps),       # morning ramp
            (0.70 * d, peak_rps),       # daytime plateau
            (0.85 * d, base_rps),       # evening fall-off
            (d, base_rps),
        ))


def flash_crowd(base_rps: float = 50.0, spike_rps: float = 600.0,
                onset_s: float = 300.0, hold_s: float = 600.0,
                total_s: float = 1800.0, slo_p99_ms: float = 250.0
                ) -> TrafficTrace:
    """Baseline, then a near-instant step to ``spike_rps`` at
    ``onset_s`` held for ``hold_s`` — the reclaim forcing function."""
    return TrafficTrace(
        name="flash_crowd", slo_p99_ms=slo_p99_ms,
        points=(
            (0.0, base_rps),
            (onset_s, base_rps),
            (onset_s + 10.0, spike_rps),
            (onset_s + hold_s, spike_rps),
            (onset_s + hold_s + 60.0, base_rps),
            (max(total_s, onset_s + hold_s + 120.0), base_rps),
        ))


def step_function(low_rps: float = 40.0, high_rps: float = 300.0,
                  period_s: float = 600.0, cycles: int = 3,
                  slo_p99_ms: float = 250.0) -> TrafficTrace:
    """A square wave between ``low_rps`` and ``high_rps`` — the
    anti-flap battery: hysteresis + cooldown must keep the scheduler
    from chasing every edge."""
    pts: List[Tuple[float, float]] = [(0.0, low_rps)]
    t = 0.0
    for _ in range(max(1, cycles)):
        half = period_s / 2.0
        pts.append((t + half, low_rps))
        pts.append((t + half + 5.0, high_rps))
        pts.append((t + period_s, high_rps))
        pts.append((t + period_s + 5.0, low_rps))
        t += period_s + 5.0
    return TrafficTrace(name="step_function", slo_p99_ms=slo_p99_ms,
                        points=tuple(pts))


BUILTIN_TRACES = {
    "diurnal": diurnal,
    "flash_crowd": flash_crowd,
    "step_function": step_function,
}


def load_trace(name_or_path: str,
               slo_p99_ms: Optional[float] = None) -> TrafficTrace:
    """A builtin trace by name, or a checked-in JSON trace by path."""
    builder = BUILTIN_TRACES.get(name_or_path)
    if builder is not None:
        return builder() if slo_p99_ms is None \
            else builder(slo_p99_ms=slo_p99_ms)
    trace = TrafficTrace.load(name_or_path)
    if slo_p99_ms is not None:
        trace = dataclasses.replace(trace, slo_p99_ms=slo_p99_ms)
    return trace
