"""Trace-driven chaos simulation of the fleet scheduler — on CPU, with
no devices.

The point of this harness is that it runs the REAL scheduler
(:class:`~.scheduler.FleetScheduler`: same pricing, same guardrails,
same event records) against a synthetic world cheap enough for CI: pod
capacity is priced by ``TopologySpec`` + the cost model (a 16-pod fleet
is a dataclass, not hardware), serving is a fluid queue (offered rps vs
per-unit capacity, queue-proportional p99), and faults come from the
same ``resilience.faults`` plans the live stack injects — ``pod_crash``
lands as a correlated inventory removal mid-reclaim, ``slow_replica``
inflates the simulated p99, ``traffic_spike`` adds synthetic offered
load through :meth:`FaultInjector.extra_rps`.

One run emits a goodput-vs-SLO-compliance report::

    {"goodput_fraction": 0.97, "slo_compliance": 0.93,
     "reclaims": 1, "drains": 2, "dropped_requests": 0, ...}

where goodput_fraction is productive training chip-time over allocated
training chip-time (restart charges per world change, the sub-30s
recovery budget) and slo_compliance is the fraction of ticks with
simulated p99 inside the trace's SLO.  ``hvdtrun fleet`` is this
module's CLI; ``bench.py --fleet`` wraps the same entry point, and
``--event-log`` threads every ``fleet_decision`` into the JSONL that
``analysis --report`` and ``hvdtrun top`` render.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional

from ..common.logging_util import get_logger
from ..resilience import faults
from ..runner.elastic.discovery import HostManager
from ..runner.hosts import HostInfo
from .inventory import FleetInventory
from .scheduler import FleetConfig, FleetScheduler, Move
from .traces import TrafficTrace, load_trace

__all__ = ["simulate_trace", "main"]

log = get_logger(__name__)


class _SimExit(Exception):
    """Raised by the injector's exit_fn inside the simulator — a pod
    crash is an event here, not a process death."""

    def __init__(self, code: int):
        super().__init__(f"sim exit {code}")
        self.code = code


def simulate_trace(trace: TrafficTrace, *,
                   pods: int = 5,
                   chips_per_pod: int = 4,
                   serve_units: int = 1,
                   tick_s: float = 10.0,
                   rps_per_unit: float = 100.0,
                   base_p99_ms: float = 60.0,
                   queue_limit_per_unit: float = 50.0,
                   restart_s: float = 20.0,
                   fault_plan: Optional[str] = None,
                   seed: int = 0,
                   cfg: Optional[FleetConfig] = None,
                   model=None,
                   event_log=None) -> Dict[str, Any]:
    """Replay ``trace`` (+ an optional fault plan) against a fresh
    scheduler over a simulated ``pods``-pod fleet.  Deterministic for a
    given (trace, plan, seed).  Returns the report dict."""
    if pods < 2:
        raise ValueError("the fleet needs at least 2 pods to move one")
    serve_units = max(1, min(int(serve_units), pods - 1))
    names = [f"pod{i}" for i in range(pods)]
    hm = HostManager(
        lambda: [HostInfo(n, chips_per_pod, pod=n) for n in names])
    sim_now = [0.0]
    inv = FleetInventory(names, host_manager=hm,
                         clock=lambda: sim_now[0])
    for n in names[:serve_units]:
        inv.acquire(n, "serve")
    for n in names[serve_units:]:
        inv.acquire(n, "train")
    entitled_train = len(inv.leased("train"))

    sched = FleetScheduler(inv, cfg=cfg, model=model,
                           event_log=event_log,
                           clock=lambda: sim_now[0],
                           chips_per_pod=chips_per_pod)

    slow_s: List[float] = []
    inj: Optional[faults.FaultInjector] = None
    if fault_plan:
        inj = faults.FaultInjector(
            faults.parse_plan(fault_plan), seed=seed,
            sleep_fn=slow_s.append,
            exit_fn=lambda code: (_ for _ in ()).throw(_SimExit(code)))

    # The world-change ledger: every resize (reclaim/backfill/crash)
    # charges ``restart_s`` of the new training world — the emergency
    # commit + peer-RAM restore budget the live stack holds under 30s.
    charges = {"restart_chip_s": 0.0}

    def _world_changed() -> None:
        charges["restart_chip_s"] += \
            min(restart_s, tick_s) * len(inv.leased("train")) \
            * chips_per_pod

    def _apply_reclaim(move: Move) -> bool:
        # Drain the training pod (exit-83 path) and hand it to serving.
        _world_changed()
        counts["reclaims"] += 1
        counts["drains"] += 1
        return True

    def _apply_backfill(move: Move) -> bool:
        _world_changed()
        counts["backfills"] += 1
        counts["drains"] += 1
        return True

    sched.bind("reclaim", _apply_reclaim)
    sched.bind("backfill", _apply_backfill)

    counts = {"reclaims": 0, "backfills": 0, "drains": 0}
    queue = 0.0
    dropped = 0.0
    offered_total = 0.0
    slo_ok = 0
    max_p99 = 0.0
    alloc_chip_s = 0.0
    decisions: List[Dict[str, Any]] = []
    n_ticks = max(1, int(trace.duration_s / tick_s))

    for i in range(n_ticks):
        t = i * tick_s
        sim_now[0] = t
        slow_s.clear()

        # -- faults first: the world the scheduler sees this tick ------
        if inj is not None:
            inj.fire("serve.traffic", step=i, rank=0, now=t)
            for u in range(len(inv.leased("serve"))):
                try:
                    inj.fire("serve.predict", step=i, rank=u)
                except _SimExit:
                    # A serve-unit crash: the pod's removal event hits
                    # both workloads through the shared inventory.
                    victims = inv.leased("serve")
                    if victims:
                        inv.record_failure(victims[-1], now=t)
            for pod in list(inv.leased("train")):
                try:
                    inj.fire("step", step=i, rank=0, pod=pod)
                except _SimExit:
                    if inv.record_failure(pod, now=t):
                        _world_changed()

        # -- serving: fluid queue over the current unit count ----------
        units = len(inv.leased("serve"))
        offered = trace.rps_at(t)
        if inj is not None:
            offered += inj.extra_rps(now=t)
        offered_total += offered * tick_s
        capacity = units * rps_per_unit
        queue = max(0.0, queue + (offered - capacity) * tick_s)
        queue_cap = queue_limit_per_unit * max(1, units)
        dropped_tick = 0.0
        if queue > queue_cap:
            dropped_tick = queue - queue_cap
            dropped += dropped_tick
            queue = queue_cap
        slow_ms = 1e3 * sum(slow_s) / max(1, units)
        p99 = base_p99_ms * (1.0 + queue / max(capacity, 1e-9)) + slow_ms
        max_p99 = max(max_p99, p99)
        # A tick that sheds load is not compliant, whatever its p99 —
        # a dropped request is an SLO violation by definition.
        if p99 <= trace.slo_p99_ms and dropped_tick == 0.0:
            slo_ok += 1

        # -- training goodput accounting --------------------------------
        alloc_chip_s += len(inv.leased("train")) * chips_per_pod * tick_s

        # -- the scheduler's tick (the same code the launcher runs) -----
        for d in sched.tick(
                queue_per_replica=queue / max(1, units),
                p99_ms=p99, slo_p99_ms=trace.slo_p99_ms,
                goodput_fraction=_goodput(alloc_chip_s, charges),
                step=i):
            decisions.append(d.to_record())

    return {
        "trace": trace.name,
        "pods": pods,
        "chips_per_pod": chips_per_pod,
        "ticks": n_ticks,
        "tick_s": tick_s,
        "slo_p99_ms": trace.slo_p99_ms,
        "goodput_fraction": round(_goodput(alloc_chip_s, charges), 6),
        "slo_compliance": round(slo_ok / n_ticks, 6),
        "reclaims": counts["reclaims"],
        "backfills": counts["backfills"],
        "drains": counts["drains"],
        "rollbacks": sched.rollbacks,
        "dropped_requests": int(round(dropped)),
        "requests_offered": int(round(offered_total)),
        "max_p99_ms": round(max_p99, 3),
        "recovery_s": restart_s,
        "entitled_train_pods": entitled_train,
        "final": {"train_pods": len(inv.leased("train")),
                  "serve_units": len(inv.leased("serve"))},
        "faults": dict(inj.counters) if inj is not None else {},
        "removal_events": inv.tracker.removal_events,
        "decisions": decisions,
    }


def _goodput(alloc_chip_s: float, charges: Dict[str, float]) -> float:
    if alloc_chip_s <= 0:
        return 1.0
    return max(0.0, 1.0 - charges["restart_chip_s"] / alloc_chip_s)


def main(argv: Optional[List[str]] = None) -> int:
    """``hvdtrun fleet <trace>`` — replay a traffic trace (builtin name
    or JSON path) through the fleet scheduler on CPU and print the
    goodput-vs-SLO report as one JSON doc."""
    p = argparse.ArgumentParser(
        prog="hvdtrun fleet",
        description="Trace-driven CPU simulation of the bin-packing "
                    "fleet scheduler (no devices; TopologySpec + cost "
                    "model price the pod-scale capacity).")
    p.add_argument("trace",
                   help="Builtin trace name (diurnal, flash_crowd, "
                        "step_function) or a trace JSON path "
                        "(tools/traces/*.json).")
    p.add_argument("--pods", type=int, default=5,
                   help="Total fleet pods (default 5).")
    p.add_argument("--chips-per-pod", type=int, default=4,
                   help="Chips per pod for the cost model (default 4).")
    p.add_argument("--serve-units", type=int, default=1,
                   help="Pods initially leased to serving (default 1).")
    p.add_argument("--tick-s", type=float, default=10.0,
                   help="Simulated seconds per scheduler tick.")
    p.add_argument("--slo-p99-ms", type=float, default=None,
                   help="Override the trace's serving SLO.")
    p.add_argument("--fault-plan", default=None,
                   help="resilience.faults plan to inject (e.g. "
                        "'pod_crash@step=12:pod=pod3,"
                        "traffic_spike@step=20:rps=300:secs=120').")
    p.add_argument("--seed", type=int, default=0,
                   help="Fault RNG seed (deterministic replay).")
    p.add_argument("--observe", action="store_true",
                   help="Dry-run: the scheduler decides + logs but "
                        "never moves a pod.")
    p.add_argument("--event-log", default=None,
                   help="Append fleet_decision/fleet_outcome JSONL "
                        "records here (renders in analysis --report "
                        "and hvdtrun top).")
    args = p.parse_args(argv)

    trace = load_trace(args.trace, slo_p99_ms=args.slo_p99_ms)
    cfg = FleetConfig.from_env()
    if args.observe:
        cfg.mode = "observe"
    event_log = None
    if args.event_log:
        from ..telemetry.anomaly import EventLog

        event_log = EventLog(args.event_log)
    report = simulate_trace(
        trace, pods=args.pods, chips_per_pod=args.chips_per_pod,
        serve_units=args.serve_units, tick_s=args.tick_s,
        fault_plan=args.fault_plan, seed=args.seed, cfg=cfg,
        event_log=event_log)
    # The decision stream is for the event log / --report; the stdout
    # contract is the summary the bench harness parses.
    summary = {k: v for k, v in report.items() if k != "decisions"}
    print(json.dumps(summary, sort_keys=True))
    return 0


if __name__ == "__main__":
    sys.exit(main())
