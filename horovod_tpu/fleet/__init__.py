"""One fleet, two workloads — the bin-packing scheduler that lets
elastic training and elastic serving share a single pod inventory.

ROADMAP item 5's utilization story ("Exploring the limits of
Concurrency in ML Training on Google TPUs", PAPERS.md): a diurnal
serving trough leaves chips idle and a flash crowd has no sanctioned
way to reclaim them as long as training and serving are launched as
two separate worlds.  This package closes the loop:

* :mod:`inventory` — the single pod inventory with per-workload
  leases, sharing ``PodTracker``/``HostManager`` blacklist+cooldown
  state so one crashed pod is unavailable to BOTH workloads with one
  correlated event.
* :mod:`scheduler` — the bin-packing reconciler above
  ``ElasticDriver.resize()`` and ``ServeDriver``'s replica-target KV
  key, every move priced before commit and wrapped in the PR-18
  guardrail battery (cooldown, hysteresis, min-gain, budget, observe
  mode, never-worse rollback).
* :mod:`traces` + :mod:`simulate` — synthetic traffic traces and the
  CPU chaos simulator that replays them (plus ``resilience.faults``
  plans) against the same scheduler code, pricing pod-scale capacity
  with ``TopologySpec`` + the cost model and no devices.

Engagement follows the faults/controller idiom: ``get_scheduler()``
returns ``None`` unless ``HVDT_FLEET`` is set — the unset path is one
env read, zero objects, zero threads.
"""

from .inventory import FleetInventory, Lease                   # noqa: F401
from .scheduler import (FleetConfig, FleetScheduler, Move,     # noqa: F401
                        PricedMove, get_scheduler, install, read_target,
                        reset, write_target)
from .traces import TrafficTrace, load_trace                   # noqa: F401

__all__ = ["FleetInventory", "Lease", "FleetConfig", "FleetScheduler",
           "Move", "PricedMove", "TrafficTrace", "load_trace",
           "get_scheduler", "install", "reset", "read_target",
           "write_target"]
