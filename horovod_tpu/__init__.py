"""horovod_tpu — a TPU-native distributed training framework.

A brand-new framework with the capabilities of Horovod (the reference at
/root/reference, v0.23.0 — see SURVEY.md), re-architected for TPU:

* data plane = XLA collectives over ICI/DCN (``jax.lax.psum`` et al.) instead
  of NCCL/MPI/Gloo transports;
* rendezvous = the JAX coordination service instead of MPI init / Gloo HTTP;
* jit-native fused gradient path (DistributedOptimizer over optax) plus an
  eager negotiated path for Horovod-style named async collectives;
* parallelism substrate beyond the reference: mesh axes for dp/tp/sp/ep,
  reduce-scatter, ring attention (SURVEY.md §2.7, §5.7).

Typical use::

    import horovod_tpu as hvd
    hvd.init()
    opt = hvd.DistributedOptimizer(optax.adam(1e-3))
"""

from __future__ import annotations

__version__ = "0.1.0"

from .common.basics import (  # noqa: F401
    init,
    shutdown,
    is_initialized,
    rank,
    size,
    local_rank,
    local_size,
    cross_rank,
    cross_size,
    num_devices,
    local_devices,
    global_devices,
    is_homogeneous,
    topology,
    mesh,
    set_mesh,
)
from .common.process_sets import (  # noqa: F401
    ProcessSet,
    add_process_set,
    remove_process_set,
    global_process_set,
    process_set_by_id,
)
from .common.types import ReduceOp, Status  # noqa: F401
from .common.exceptions import (  # noqa: F401
    HorovodInternalError,
    HostsUpdatedInterrupt,
)

# Reduce-op aliases matching the reference's module-level constants
# (ref: torch/mpi_ops.py Average/Sum/Adasum/Min/Max/Product).
Average = ReduceOp.AVERAGE
Sum = ReduceOp.SUM
Adasum = ReduceOp.ADASUM
Min = ReduceOp.MIN
Max = ReduceOp.MAX
Product = ReduceOp.PRODUCT

from . import ops  # noqa: F401,E402
from .ops import device  # noqa: F401,E402


def __getattr__(name):
    # Lazy imports for heavier subsystems so `import horovod_tpu` stays fast.
    try:
        if name in ("allreduce", "allreduce_async", "allgather",
                    "allgather_async", "broadcast", "broadcast_async",
                    "alltoall", "alltoall_async", "reducescatter",
                    "reducescatter_async", "grouped_allreduce",
                    "grouped_allreduce_async", "synchronize", "poll", "join",
                    "barrier"):
            from .ops import eager

            return getattr(eager, name)
        if name == "DistributedOptimizer":
            from .optimizer import DistributedOptimizer

            return DistributedOptimizer
        if name in ("broadcast_parameters", "broadcast_optimizer_state",
                    "broadcast_object", "allgather_object"):
            from . import functions

            return getattr(functions, name)
        if name == "Compression":
            from .ops.compression import Compression

            return Compression
        if name in ("sparse_allreduce", "sparse_allreduce_async"):
            # ref: torch/mpi_ops.py:556-578 sparse_allreduce_async
            from .ops import sparse

            return getattr(sparse, name)
        if name in ("mpi_built", "mpi_enabled", "mpi_threads_supported",
                    "gloo_built", "gloo_enabled", "nccl_built", "ddl_built",
                    "ccl_built", "cuda_built", "rocm_built", "xla_built",
                    "tpu_available", "native_built", "tcp_enabled"):
            from .common import util

            return getattr(util, name)
        if name in ("start_timeline", "stop_timeline"):
            # Dynamic timeline control at top level (ref: horovod C API
            # horovod_start_timeline, operations.cc:1032-1064).
            from . import timeline as _tl

            return getattr(_tl, name)
        if name == "run":
            # Programmatic launcher (ref: horovod/runner/__init__.py:210
            # hvd.run) — run a function on np workers, results by rank.
            from .runner import run

            return run
        if name in ("fused_adam", "fused_sgd"):
            # Fused Pallas optimizer kernels (single-HBM-pass updates;
            # compose with DistributedOptimizer unchanged).
            from .ops import optim_kernels

            return getattr(optim_kernels, name)
        if name in ("enable_compilation_cache", "donated_step",
                    "overlap_step"):
            from . import step_pipeline as _sp

            return getattr(_sp, name)
        if name == "overlap":
            # Overlap scheduling layer (dependency-ordered gradient
            # exchange, async collectives, pipelined updates).
            from .ops import overlap

            return overlap
        if name == "zero":
            # ZeRO-sharded gradient exchange / optimizer state
            # (reduce-scatter wire, shard-local fused updates,
            # allgather-on-demand parameters).
            from .ops import zero

            return zero
        if name in ("elastic", "timeline", "models", "parallel", "runner",
                    "callbacks", "sync_batch_norm", "optimizer", "autotune",
                    "data", "native", "orchestrate", "interop",
                    "step_pipeline", "serve", "quant", "resilience",
                    "telemetry", "control"):
            import importlib

            return importlib.import_module(f".{name}", __name__)
    except ImportError as e:
        raise AttributeError(
            f"horovod_tpu.{name} is unavailable: {e}") from e
    raise AttributeError(f"module 'horovod_tpu' has no attribute {name!r}")
