"""Framework exceptions (ref: horovod/common/exceptions.py:1-49)."""

from __future__ import annotations

__all__ = [
    "HorovodTpuError",
    "HorovodInternalError",
    "HostsUpdatedInterrupt",
    "NotInitializedError",
    "TensorMismatchError",
]


class HorovodTpuError(Exception):
    """Base class for all framework errors."""


class HorovodInternalError(HorovodTpuError):
    """Internal error raised when a collective operation fails mid-flight.

    In elastic mode this triggers restore-from-last-commit
    (ref: common/exceptions.py:23, common/elastic.py:151-175).
    """


class HostsUpdatedInterrupt(HorovodTpuError):
    """Raised in elastic mode when host membership changed; training should
    re-rendezvous without rolling back state (ref: common/exceptions.py:33).
    """

    def __init__(self, skip_sync: bool = False):
        super().__init__()
        self.skip_sync = skip_sync


class NotInitializedError(HorovodTpuError):
    def __init__(self, what: str = "Framework"):
        super().__init__(
            f"{what} has not been initialized; call horovod_tpu.init() first."
        )


class TensorMismatchError(HorovodTpuError):
    """Shape/dtype/op mismatch across ranks detected during negotiation
    (ref: controller.cc:495 ConstructResponse error branches)."""
