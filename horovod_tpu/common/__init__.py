from . import basics, config, exceptions, process_sets, types  # noqa: F401
