"""Env-var knob registry — single source of truth for runtime configuration.

TPU-native analog of the reference's env registry (ref: common/common.h:107-141,
parsed in operations.cc:436-607 and utils/env_parser.cc).  Precedence follows
the reference (runner/common/util/config_parser.py): CLI > env > config file >
built-in default; the launcher translates CLI flags into these env vars.

All knobs use the ``HVDT_`` prefix (Horovod-TPU).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Callable, Dict, Optional

from ..analysis.topology import NOMINAL_SIM_PEAK_FLOPS

__all__ = ["Knob", "KNOBS", "CONTRACT_VARS", "get", "get_bool", "get_int",
           "get_float", "get_str", "registry_doc"]


def _parse_bool(v: str) -> bool:
    return v.strip().lower() in ("1", "true", "yes", "on")


@dataclasses.dataclass(frozen=True)
class Knob:
    name: str
    default: Any
    parser: Callable[[str], Any]
    doc: str

    def read(self) -> Any:
        raw = os.environ.get(self.name)
        if raw is None:
            return self.default
        try:
            return self.parser(raw)
        except (ValueError, TypeError):
            return self.default


def _k(name: str, default: Any, parser: Callable[[str], Any], doc: str) -> Knob:
    return Knob(name, default, parser, doc)


# Registry.  Reference analogs noted per knob (common.h line refs).
KNOBS: Dict[str, Knob] = {
    k.name: k
    for k in [
        # --- fusion / cycle (ref: HOROVOD_FUSION_THRESHOLD common.h:112,
        #     HOROVOD_CYCLE_TIME :113) ---
        _k("HVDT_FUSION_THRESHOLD", 64 * 1024 * 1024, int,
           "Tensor-fusion bucket size in bytes for fused collectives. "
           "64 MiB default (TPU HBM-friendly; ref default 128 MiB)."),
        _k("HVDT_CYCLE_TIME", 0.0, float,
           "Background-loop cycle time in ms for the eager path. 0 = run "
           "as fast as possible (XLA mode forces 0 in the reference, "
           "operations.cc:500-506)."),
        _k("HVDT_BATCH_COLLECTIVES", True, _parse_bool,
           "Pack multiple same-dtype tensors into one fused collective."),
        # --- overlap scheduling (ops/overlap.py: dependency-ordered
        #     bucket schedule, async collectives, pipelined int8 wire,
        #     fused-update latency hiding) ---
        _k("HVDT_OVERLAP", "", str,
           "Overlapped gradient exchange: 'on' routes bucketed gradient "
           "collectives through the reverse-topological, barrier-pinned "
           "overlap schedule (ops/overlap.py) so each bucket's allreduce "
           "is issued as soon as its grads exist; unset/'off' (default) "
           "keeps the monolithic fused_allreduce path — the EXACT "
           "pre-existing code objects (overlap.get_scheduler() is None, "
           "zero wrappers)."),
        _k("HVDT_XLA_LATENCY_HIDING", "auto", str,
           "XLA latency-hiding scheduler / async collective fusion "
           "flags (ridden via LIBTPU_INIT_ARGS, read once at TPU "
           "backend init; inert off-TPU): auto (skip when JAX_PLATFORMS "
           "pins a non-TPU backend), on, off.  Engaged by hvd.init() "
           "and bench.py --overlap — this is what turns the overlap "
           "schedule's dependency freedom into overlapped execution on "
           "hardware."),
        _k("HVDT_AUTOTUNE_OVERLAP", False, _parse_bool,
           "Add an overlap-schedule on/off dimension to the autotune "
           "search space; the step builder is rebuilt with overlap=... "
           "at each knob change (autotune.AutotunedStep), hot-swappable "
           "because both legs keep one optimizer state tree (the "
           "schedule changes lowering, never state).  Starting point "
           "comes from HVDT_OVERLAP."),
        # --- transport policies (horovod_tpu/transport: per-mesh-axis
        #     algorithm / wire dtype / fusion threshold + the two-level
        #     hierarchical allreduce) ---
        _k("HVDT_TRANSPORT", "", str,
           "Per-mesh-axis transport policy: comma entries "
           "axis:algorithm:wire[:threshold] with axis in "
           "{ici,dcn,dp,pp,fsdp,ep,sp,tp}, algorithm in "
           "{ring,tree,2d_ring}, wire in {f32,bf16,fp16,int8}, "
           "threshold like 64M — e.g. 'ici:ring:f32:64M,dcn:tree:int8:"
           "8M'; 'auto' derives the topology default (innermost axis = "
           "ICI ring f32, outer = DCN tree f32 8M).  Multi-axis reduce "
           "groups then run the hierarchical allreduce (fast-axis "
           "reduce-scatter -> slow-axis shard exchange -> allgather).  "
           "Unset (default) keeps the flat path as the identical code "
           "objects (transport.get_policy() is None, zero wrappers); "
           "unknown vocabulary fails hvd.init() with the valid lists."),
        _k("HVDT_AUTOTUNE_TRANSPORT", False, _parse_bool,
           "Add a flat-vs-hierarchical transport dimension (0/1) to the "
           "autotune search space; the step builder is rebuilt with "
           "transport=... at each knob change (autotune.AutotunedStep), "
           "hot-swappable because both legs keep one optimizer state "
           "tree (the policy changes lowering, never state).  Starting "
           "point: HVDT_TRANSPORT set, or the measured "
           "HVDT_AUTOTUNE_TRANSPORT_SEED verdict."),
        _k("HVDT_AUTOTUNE_TRANSPORT_SEED", "", str,
           "Path to a bench_allreduce.py --json-out file; when its "
           "measured hierarchical_speedup_vs_flat_at_peak exceeds 1.0 "
           "the autotuner's transport dimension STARTS on the "
           "hierarchical leg — policies are seeded from measurements, "
           "not guesses."),
        # --- ZeRO-sharded gradient exchange / optimizer state
        #     (ops/zero.py: reduce-scatter wire, shard-local fused
        #     updates, allgather-on-demand parameters) ---
        _k("HVDT_ZERO", "", str,
           "ZeRO-style state-sharding stage: 'grads' swaps the fused "
           "allreduce for an explicit reduce-scatter + invariant-"
           "allgather split (same wire bytes, deferrable allgather; any "
           "optax optimizer); 'states' reduce-scatters gradients and "
           "runs the single-HBM-pass optimizer update on each rank's "
           "1/n shard of the moments, allgathering only the parameter "
           "deltas (optimizer HBM shrinks ~n x; requires fused_adam/"
           "fused_sgd); 'params' additionally keeps the parameters "
           "sharded between steps (allgather-on-demand via the fsdp "
           "sharding rules).  Unset/'off' (default) keeps the "
           "replicated path as the identical code objects "
           "(zero.get_zero() is None, zero wrappers); unknown stages "
           "fail hvd.init() with the valid list."),
        _k("HVDT_AUTOTUNE_ZERO", False, _parse_bool,
           "Add a replicated-vs-ZeRO-sharded dimension (0/1) to the "
           "autotune search space; the step builder is rebuilt with "
           "zero=... at each knob change (autotune.AutotunedStep), "
           "hot-swappable because both legs keep ONE sharded state "
           "tree (the replicated leg exchanges via allreduce and "
           "slices its shard — same layout, different wire).  Starting "
           "point: HVDT_ZERO set, or the measured "
           "HVDT_AUTOTUNE_ZERO_SEED verdict."),
        _k("HVDT_AUTOTUNE_ZERO_SEED", "", str,
           "Path to a bench_allreduce.py --reduce-scatter --json-out "
           "file; when its measured rs_ag_speedup_vs_allreduce_at_peak "
           "exceeds 1.0 the autotuner's zero dimension STARTS on the "
           "sharded leg — seeded from measurements, not guesses "
           "(mirrors HVDT_AUTOTUNE_TRANSPORT_SEED)."),
        # --- 4D parallelism (horovod_tpu/parallel: expert axis +
        #     1F1B pipeline as first-class mesh axes) ---
        _k("HVDT_PP", 1, int,
           "Pipeline-parallel extent of the pod mesh "
           "(parallel.mesh.pod_mesh_spec): carves whole pod groups "
           "into 1F1B stages — the pp axis rides the DCN tier, its "
           "ppermute ticks cross pods.  Must divide the pod count; 1 "
           "(default) keeps the classic (dcn, ici) 2-axis mesh."),
        _k("HVDT_EP", 1, int,
           "Expert-parallel extent of the pod mesh "
           "(parallel.mesh.pod_mesh_spec): carves chips inside each "
           "pod into expert ranks — the ep axis rides the ICI tier, "
           "the MoE dispatch/combine a2a stays on-pod.  Must divide "
           "the pod size; 1 (default) keeps the classic 2-axis mesh."),
        _k("HVDT_MOE_CAPACITY_FACTOR", 1.25, float,
           "Default expert capacity factor for "
           "parallel.moe.moe_dispatch_combine: per-expert slots = "
           "ceil(tokens * top_k / experts * factor).  Tokens over "
           "capacity are dropped (residual passthrough); "
           "hvdt_moe_dropped_fraction reports the realized drop rate."),
        _k("HVDT_MOE_TOPK", 1, int,
           "Default experts-per-token for "
           "parallel.moe.moe_dispatch_combine (gates renormalized "
           "over the chosen k; 1 = switch routing).  Primary choices "
           "claim capacity before secondary ones."),
        _k("HVDT_PEAK_FLOPS", NOMINAL_SIM_PEAK_FLOPS, float,
           "Nominal peak FLOP/s for parallel.pipeline."
           "report_pipeline_mfu (per-chip peak x chips).  On the CPU "
           "sim any consistent value works — MFU is a ratio; the "
           "hvdt_pipeline_mfu gauge carries the result."),
        _k("HVDT_PIPELINE_MICROBATCHES", 8, int,
           "Default 1F1B microbatch count (the pipeline autotune "
           "dimension's starting point; bench.py --pipeline default). "
           "More microbatches shrink the bubble fraction "
           "(p-1)/(m+p-1) at the cost of smaller per-tick payloads."),
        _k("HVDT_AUTOTUNE_MOE", False, _parse_bool,
           "Add an expert capacity-factor dimension to the autotune "
           "search space; the step builder is rebuilt with "
           "capacity_factor=... at each knob change "
           "(autotune.AutotunedStep), hot-swappable because capacity "
           "changes the dispatch layout, never optimizer state.  "
           "Starting point: HVDT_MOE_CAPACITY_FACTOR set explicitly, "
           "the measured HVDT_AUTOTUNE_MOE_SEED verdict, or the cost "
           "model's a2a-wire ordering (HVDT_AUTOTUNE_MODEL_SEED)."),
        _k("HVDT_AUTOTUNE_MOE_SEED", "", str,
           "Path to a bench.py --moe --json-out file; its measured "
           "capacity_factor_at_peak becomes the autotuner's MoE "
           "dimension starting point — policies are seeded from "
           "measurements, not guesses (mirrors "
           "HVDT_AUTOTUNE_TRANSPORT_SEED)."),
        _k("HVDT_AUTOTUNE_PIPELINE", False, _parse_bool,
           "Add a 1F1B microbatch-count dimension to the autotune "
           "search space; the step builder is rebuilt with "
           "microbatches=... at each knob change "
           "(autotune.AutotunedStep), hot-swappable because the "
           "microbatch clock changes lowering, never state.  Starting "
           "point: HVDT_PIPELINE_MICROBATCHES set explicitly, the "
           "measured HVDT_AUTOTUNE_PIPELINE_SEED verdict, or the "
           "cost model's ppermute ordering (HVDT_AUTOTUNE_MODEL_SEED)."),
        _k("HVDT_AUTOTUNE_PIPELINE_SEED", "", str,
           "Path to a bench.py --pipeline --json-out file; its "
           "measured microbatches_at_peak becomes the autotuner's "
           "pipeline dimension starting point (mirrors "
           "HVDT_AUTOTUNE_MOE_SEED)."),
        # --- activation rematerialization (models/: jax.checkpoint
        #     policy on the transformer block — the second half of the
        #     memory-for-MFU trade next to HVDT_ZERO) ---
        _k("HVDT_REMAT", "", str,
           "Activation rematerialization for the transformer block: "
           "'none'/'' (default) saves all activations; 'full' saves "
           "only block inputs (min HBM, +1/3 FLOPs); 'dots' uses "
           "jax.checkpoint_policies.dots_with_no_batch_dims_saveable "
           "(save matmul outputs, recompute elementwise+attention — "
           "falls back to 'full' with a warning on jax builds without "
           "the policy).  Consumed by models.remat_from_env / bench.py "
           "--remat; unknown values raise with the valid list."),
        # --- cache (ref: HOROVOD_CACHE_CAPACITY common.h:114) ---
        _k("HVDT_CACHE_CAPACITY", 1024, int,
           "Response-cache capacity (negotiated-collective descriptors)."),
        # --- autotune (ref: HOROVOD_AUTOTUNE* common.h:132-137) ---
        _k("HVDT_AUTOTUNE", False, _parse_bool,
           "Enable Bayesian autotuning of fusion threshold / cycle time."),
        _k("HVDT_AUTOTUNE_LOG", "", str, "CSV log file for autotune samples."),
        _k("HVDT_AUTOTUNE_WARMUP_SAMPLES", 3, int, "Autotune warmup discard count."),
        _k("HVDT_AUTOTUNE_STEPS_PER_SAMPLE", 10, int, "Steps per autotune sample."),
        _k("HVDT_AUTOTUNE_BAYES_OPT_MAX_SAMPLES", 20, int, "Max BO samples."),
        _k("HVDT_AUTOTUNE_GAUSSIAN_PROCESS_NOISE", 0.8, float, "GP noise alpha."),
        _k("HVDT_AUTOTUNE_FUSED_OPTIMIZER", False, _parse_bool,
           "Add a fused-vs-unfused optimizer dimension (0/1) to the "
           "autotune search space; the step builder is then rebuilt "
           "with fused=... at each knob change (autotune.AutotunedStep). "
           "Starting point comes from HVDT_FUSED_OPTIMIZER."),
        # --- telemetry (horovod_tpu/telemetry: metrics registry,
        #     per-collective instrumentation, straggler detection,
        #     per-worker /metrics exporter — no reference analog beyond
        #     the Timeline; the observability subsystem) ---
        _k("HVDT_TELEMETRY", False, _parse_bool,
           "Enable the unified telemetry subsystem: per-collective "
           "bytes/latency metrics, step stats (examples/s, MFU, goodput),"
           " straggler detection, and the per-worker /metrics HTTP "
           "exporter (started by hvd.init()).  Off (default) installs "
           "ZERO wrapper objects on the hot paths "
           "(telemetry.instrument.get_recorder() is None)."),
        _k("HVDT_METRICS_PORT", 9090, int,
           "Base port for the per-worker /metrics + /healthz exporter; "
           "each worker binds base + local_rank (0 = ephemeral port).  "
           "A taken slot falls back to ephemeral with a logged warning."),
        _k("HVDT_STRAGGLER_WINDOW", 64, int,
           "Steps between cross-rank step-duration allgathers for "
           "straggler detection (telemetry/straggler.py).  0 disables "
           "the cross-rank check."),
        _k("HVDT_STRAGGLER_THRESHOLD", 2.0, float,
           "A rank is flagged as a straggler when its mean step time "
           "over the last window exceeds this multiple of the median."),
        _k("HVDT_TELEMETRY_PUBLISH_S", 30.0, float,
           "Seconds between worker snapshot publishes to the rendezvous "
           "KV (/telemetry/<rank>) for driver-side aggregation; only "
           "active under the elastic launcher.  0 disables publishing."),
        # --- live perf attribution (telemetry/history.py +
        #     telemetry/anomaly.py: per-metric time series, windowed
        #     anomaly detectors, predicted-vs-observed pricing) ---
        _k("HVDT_HISTORY", False, _parse_bool,
           "Keep bounded per-metric time series (ring buffers of "
           "(wall_ts, step, value) samples: step time, examples/s, MFU, "
           "goodput fraction, per-axis wire bytes, perf-deviation "
           "ratio), served as /timeseries on the per-worker exporter, "
           "published in the KV telemetry snapshot for driver-side "
           "step-aligned roll-ups, and fed to the windowed anomaly "
           "detectors.  Requires HVDT_TELEMETRY.  Off (default) = zero "
           "overhead (telemetry.history.get_history() is None)."),
        _k("HVDT_HISTORY_WINDOW", 512, int,
           "Max samples retained per time series (ring buffer; the "
           "recent window is what detectors and `hvdtrun top` read, "
           "memory stays flat)."),
        _k("HVDT_HISTORY_SAMPLE_S", 1.0, float,
           "Minimum seconds between time-series samples (the recording "
           "cadence; steps arriving faster are coalesced into one "
           "sample carrying their mean step time).  0 = sample every "
           "observed step (tests, short runs)."),
        _k("HVDT_EVENT_LOG", "", str,
           "Path of the structured JSONL anomaly event log: each "
           "detector firing (step_time_shift, goodput_drop, "
           "mfu_regression, wire_drift, straggler_onset, "
           "perf_deviation) appends one JSON line with kind / step / "
           "rank / pod / value / baseline / ratio / message; the "
           "elastic driver writes cluster-scoped events (a pod-wide "
           "shift is ONE event) to the same format.  Empty (default) = "
           "off (telemetry.anomaly.get_event_log() is None); "
           "hvdt_anomaly_total{kind} counters ride the registry either "
           "way when detectors run."),
        _k("HVDT_EVENT_LOG_MAX_BYTES", 0, int,
           "Size bound for the HVDT_EVENT_LOG JSONL file: when an "
           "append would push it past this many bytes the file rotates "
           "to <path>.1 (keep-1 — the previous .1 is replaced) and a "
           "fresh file starts, so a long run with a chatty online "
           "controller cannot grow the log unboundedly.  0 (default) = "
           "unbounded (the pre-rotation behavior)."),
        # --- online policy controller (horovod_tpu/control: the
        #     driver-side loop that prices anomaly events with the cost
        #     model and acts at step boundaries) ---
        _k("HVDT_CONTROLLER", "", str,
           "Engage the online policy controller on the elastic driver: "
           "anomaly events from the HVDT_EVENT_LOG sensor plane are "
           "mapped to candidate actions (flip a transport leg, retune "
           "the bucket threshold, toggle the overlap/ZeRO legs, evict "
           "a straggler pod, resize the world, scale serve replicas), "
           "priced OFFLINE with the analytical cost model, and the "
           "best candidate clearing the guardrails is applied at a "
           "step boundary through the no-recompile autotune leg "
           "machinery, then verified against "
           "hvdt_perf_deviation_ratio with a never-worse rollback.  "
           "Values: empty/0 (default) = off "
           "(control.get_controller() is None, zero overhead); 1/on = "
           "act; observe = decide + log but never apply (dry run).  "
           "Decisions append controller_decision / controller_outcome "
           "records to the event JSONL — auditable and replayable."),
        _k("HVDT_CONTROLLER_COOLDOWN_S", 60.0, float,
           "Per-action-kind cooldown: after the controller applies an "
           "action, the same kind is ineligible for this many seconds "
           "(doubled after each never-worse rollback of that kind) so "
           "one bad actuator cannot thrash the run."),
        _k("HVDT_CONTROLLER_ENTER_RATIO", 1.2, float,
           "Hysteresis ENTER band: a triggering event's slowdown ratio "
           "must be at least this factor before the controller acts "
           "(events below it are recorded as suppressed:hysteresis)."),
        _k("HVDT_CONTROLLER_EXIT_RATIO", 1.05, float,
           "Hysteresis EXIT band: hvdt_perf_deviation_ratio must fall "
           "back under this factor for an applied action to count as "
           "recovered and for its trigger to re-arm — the enter/exit "
           "split is what prevents flapping on an oscillating series."),
        _k("HVDT_CONTROLLER_RECOVERY_WINDOW", 3, int,
           "Controller ticks an applied action gets to bring the "
           "deviation ratio under the exit band before the never-worse "
           "rollback re-applies the inverse leg (one-way actions — "
           "evict/resize/replica-scale — just expire)."),
        _k("HVDT_CONTROLLER_MIN_GAIN_S", 0.0, float,
           "Minimum predicted step-seconds improvement a candidate "
           "must clear (from the offline cost-model pricing) to be "
           "applied; candidates below it are suppressed:no_gain."),
        _k("HVDT_CONTROLLER_MAX_ACTIONS", 0, int,
           "Total actions the controller may apply over one run (0 = "
           "unbounded) — the blast-radius bound for unattended runs."),
        # --- fleet scheduler (horovod_tpu/fleet: one pod inventory,
        #     two workloads — training backfills serving's trough and
        #     drains when router pressure crosses the band) ---
        _k("HVDT_FLEET", "", str,
           "Engage the bin-packing fleet scheduler over the shared pod "
           "inventory: serving pressure (router queue depth per "
           "replica vs HVDT_SERVE_QUEUE_HI, p99 vs the SLO) above the "
           "ENTER band reclaims a training pod for serving (exit-83 "
           "drain; emergency commit + peer-RAM restore make it cheap), "
           "and a deep trough backfills a serve pod to training — "
           "every move priced offline (cost model at the candidate "
           "world size vs predicted SLO headroom) and wrapped in the "
           "controller guardrail battery.  Values: empty/0 (default) "
           "= off (fleet.get_scheduler() is None, zero overhead); "
           "1/on = act; observe = decide + log but never move a pod.  "
           "Decisions append fleet_decision / fleet_outcome records "
           "to the event JSONL.  When active it owns the "
           "/serve/target_replicas key via a seq-guarded doc; the "
           "controller's scale_replicas action becomes a hint routed "
           "through it; raw-int KV / --target-file overrides still "
           "win."),
        _k("HVDT_FLEET_COOLDOWN_S", 60.0, float,
           "Per-move-kind cooldown: after the fleet scheduler applies "
           "a reclaim or backfill, the same kind is ineligible for "
           "this many seconds (doubled after each never-worse "
           "rollback) so one workload cannot thrash the other."),
        _k("HVDT_FLEET_ENTER_RATIO", 1.2, float,
           "Hysteresis ENTER band on the serving pressure ratio "
           "(queue/HVDT_SERVE_QUEUE_HI or p99/SLO, whichever is "
           "worse): pressure must reach this factor before a reclaim "
           "fires (below it -> suppressed:hysteresis)."),
        _k("HVDT_FLEET_EXIT_RATIO", 1.05, float,
           "Hysteresis EXIT band: pressure must fall back under this "
           "factor for an applied reclaim to count as recovered and "
           "for the pressure trigger to re-arm — the enter/exit split "
           "that keeps a flappy traffic series from ping-ponging "
           "pods."),
        _k("HVDT_FLEET_BACKFILL_RATIO", 0.5, float,
           "Trough band: serving pressure at/below this fraction of "
           "threshold marks a trough, releasing one serve pod back to "
           "training (never below the serve floor, and charged the "
           "predicted pressure increase before commit)."),
        _k("HVDT_FLEET_RECOVERY_WINDOW", 3, int,
           "Scheduler ticks an applied move gets to prove itself: a "
           "reclaim must bring pressure under the exit band before "
           "the window expires or the never-worse rollback backfills "
           "the pod home; a backfill that pushes pressure over the "
           "ENTER band inside the window is reclaimed back."),
        _k("HVDT_FLEET_MIN_GAIN", 0.0, float,
           "Minimum predicted gain (dimensionless: serving relief "
           "minus training throughput cost) a candidate move must "
           "clear; candidates below it are suppressed:no_gain."),
        _k("HVDT_FLEET_MAX_MOVES", 0, int,
           "Total moves the fleet scheduler may apply over one run "
           "(0 = unbounded) — the blast-radius bound."),
        _k("HVDT_FLEET_MIN_TRAIN_PODS", 1, int,
           "Floor on pods leased to training: reclaims never shrink "
           "the training world below this many pods (the elastic "
           "min_np analog at fleet granularity)."),
        _k("HVDT_PERF_DEVIATION_RATIO", 2.0, float,
           "Fire a perf_deviation anomaly event when "
           "hvdt_perf_deviation_ratio (observed EWMA step seconds vs "
           "the cost-model-predicted step seconds: predicted exposed "
           "comm + compute anchor) exceeds this factor — the runtime "
           "mirror of the CI --perf ratchet.  Needs "
           "HVDT_EXPECTED_SCHEDULE (or an in-process traced "
           "fingerprint) so hvd.init() can price the schedule."),
        # --- distributed tracing + flight recorder (telemetry/trace.py,
        #     telemetry/flight_recorder.py — cross-rank forensics) ---
        _k("HVDT_TRACE_DIR", "", str,
           "Enable distributed span tracing and write per-rank Chrome-"
           "trace dumps (trace_rank<N>.json) plus desync reports into "
           "this directory; under the elastic launcher the driver also "
           "merges per-rank dumps from the rendezvous KV into "
           "trace_merged.json (rank as pid).  Empty (default) = off, "
           "zero overhead (telemetry.trace.get_tracer() is None)."),
        _k("HVDT_TRACE_BUFFER", 65536, int,
           "Max spans retained per rank by the trace buffer (ring; "
           "forensics wants the recent window, memory stays flat)."),
        _k("HVDT_FLIGHT_RECORDER", False, _parse_bool,
           "Enable the collective flight recorder: an always-cheap ring "
           "buffer of the last N collective events per rank (seq, "
           "op/name/dtype/bytes/wire, in-flight vs done), dumped on "
           "stall-abort (with a cross-rank desync report), on "
           "preemption, and on demand via the exporter's /flightrecorder"
           " endpoint.  Off (default) = zero overhead "
           "(telemetry.flight_recorder.get_flight_recorder() is None)."),
        _k("HVDT_FLIGHT_RECORDER_EVENTS", 256, int,
           "Ring capacity (events) of the collective flight recorder."),
        _k("HVDT_EXPECTED_SCHEDULE", "", str,
           "Path to a static collective-schedule fingerprint JSON "
           "(exported by `python -m horovod_tpu.analysis --schedule "
           "OUT.json` or analysis.schedule.ScheduleFingerprint.save). "
           "When set, desync reports gain an `expected_schedule` "
           "section comparing the STATIC expected issue order against "
           "every rank's runtime-observed events and naming the first "
           "deviation — static-expected vs observed forensics instead "
           "of observed-vs-observed.  Empty (default) = off."),
        _k("HVDT_COSTMODEL_CALIBRATION", "", str,
           "Path to the analytical cost model's fitted calibration "
           "JSON (per-(tier, algorithm, wire) alpha-beta constants, "
           "regenerated by tools/fit_costmodel.py from bench_allreduce "
           "--json-out rows).  Empty (default) = the checked-in "
           ".hvdt-costmodel-calibration.json at the repo root; a "
           "missing file degrades to the analysis/topology.py "
           "order-of-magnitude defaults."),
        _k("HVDT_PERF_BASELINE", "", str,
           "Path to the static perf-regression baseline JSON the "
           "`python -m horovod_tpu.analysis --perf` gate ratchets "
           "against (predicted exposed-comm seconds, per-axis wire "
           "bytes, overlap fraction for the reference fingerprints; "
           "regenerated by --update-perf-baseline).  Empty (default) "
           "= the checked-in .hvdt-perf-baseline.json at the repo "
           "root."),
        _k("HVDT_AUTOTUNE_MODEL_SEED", "", str,
           "Let autotune consult the static cost model "
           "(analysis/costmodel.predict_leg_order) to order its "
           "flat-vs-hierarchical / wire-dtype / overlap starting legs "
           "when no measured HVDT_AUTOTUNE_*_SEED sweep is available: "
           "'1' uses the default calibration, a path names a "
           "calibration file.  Unset (default) = off — measured seeds "
           "and explicit env policies always win over the model."),
        # --- timeline (ref: HOROVOD_TIMELINE common.h:110) ---
        _k("HVDT_TIMELINE", "", str,
           "Write per-tensor Chrome-tracing timeline JSON to this path."),
        _k("HVDT_TIMELINE_MARK_CYCLES", False, _parse_bool,
           "Mark background-loop cycles in the timeline."),
        # --- stall detection (ref: HOROVOD_STALL_CHECK_* common.h:116-118) ---
        _k("HVDT_STALL_CHECK_DISABLE", False, _parse_bool, "Disable stall inspector."),
        _k("HVDT_STALL_CHECK_TIME_SECONDS", 60, int,
           "Warn when a tensor is ready on some-but-not-all ranks this long."),
        _k("HVDT_STALL_SHUTDOWN_TIME_SECONDS", 0, int,
           "Abort after this long stalled (0 = never)."),
        _k("HVDT_STALL_ABORT_TIME_SECONDS", 0, int,
           "Stall-escalation abort rung (resilience/escalation.py): past "
           "this age the coordinator aborts the stalled negotiation with "
           "an error response, so waiters raise HorovodInternalError and "
           "the elastic retry loop recovers instead of hanging forever. "
           "0 = disabled (warn-only, the seed behavior)."),
        _k("HVDT_STALL_RESET_TIME_SECONDS", 0, int,
           "Stall-escalation reset rung: past this age a worker "
           "additionally publishes READY to the elastic driver's "
           "registry, requesting a full re-rendezvous.  0 = disabled."),
        # --- resilience: fault injection + failure detection ---
        _k("HVDT_FAULT_PLAN", "", str,
           "Declarative chaos-testing fault plan (resilience/faults.py), "
           "e.g. 'crash@step=12:rank=1,hang@step=30:secs=20,"
           "corrupt_ckpt@step=40,kv_drop@p=0.1'.  Empty (default) "
           "compiles every injection point to a no-op."),
        _k("HVDT_FAULT_SEED", 0, int,
           "RNG seed for probabilistic fault-plan entries (kv_drop@p=...) "
           "so chaos runs are reproducible."),
        _k("HVDT_FAULT_JOURNAL", "", str,
           "Path prefix for the fired-fault journal (per rank: "
           "<path>.rank<N>).  Elastic recovery respawns processes; the "
           "journal carries each fault's fired count across restarts so "
           "'times' bounds fires per JOB, not per process life.  Empty "
           "= per-process counting."),
        _k("HVDT_CONTROL_PLANE_TIMEOUT_S", 300.0, float,
           "Coordination-service gather/broadcast timeout — the failure-"
           "detection latency bound: a dead peer surfaces as this timeout "
           "firing, converted to HorovodInternalError for the elastic "
           "retry loop.  Chaos tests shrink it to recover in seconds."),
        _k("HVDT_ELASTIC_BLACKLIST_COOLDOWN_S", 0.0, float,
           "Blacklist cooldown for failed hosts in elastic discovery: 0 "
           "(default) = permanent blacklist; >0 = the host re-enters "
           "discovery after the cooldown, doubling per repeated failure "
           "(capped 8x).  Set on preemptible fleets where a crash rarely "
           "means a bad machine — and for single-host chaos runs, where "
           "a permanent blacklist would strand the job."),
        _k("HVDT_TCP_CONNECT_RETRIES", 3, int,
           "Socket-mesh bootstrap attempts for the native TCP data plane "
           "(shared exponential backoff between tries): peers of a "
           "restarted rank come up at different times."),
        # --- pod-granular elastic control plane (runner/elastic/pods.py) ---
        _k("HVDT_POD", "", str,
           "Pod (TPU slice) id this worker belongs to.  Set per slot by "
           "the elastic launcher from the discovery script's "
           "'host[:slots][@pod]' column; read by pod-scoped fault-plan "
           "entries (pod_crash/pod_partition) and published in the "
           "telemetry KV snapshot so the driver can aggregate per pod."),
        _k("HVDT_POD_SIZE", 0, int,
           "Slots per pod.  Driver side: chunk undeclared discovery "
           "hosts (in order) into pods of this many slots — the "
           "alternative to the @pod discovery column.  Worker side: the "
           "ici extent of the two-level (dcn, ici) mesh contract "
           "(parallel.mesh.pod_mesh_spec).  0 = per-host pods (the flat "
           "PR-4 semantics)."),
        _k("HVDT_POD_EXIT_WINDOW_S", 10.0, float,
           "Pod exit-correlation window: failure exits of one pod's "
           "ranks within this many seconds collapse into ONE pod-"
           "removal event — one blacklist entry, one cooldown clock — "
           "instead of N independent recovery decisions for what is a "
           "single correlated slice loss."),
        _k("HVDT_POD_DRAIN_GRACE_S", 60.0, float,
           "How long a preemption-drained pod stays excluded from pod "
           "assignment while waiting for the platform to reclaim its "
           "hosts; after the grace it becomes placeable again rather "
           "than stranded (a drain is advisory, not a blacklist)."),
        _k("HVDT_POD_STRAGGLER_EVICT", 0, int,
           "Pod-straggler eviction rung: a pod whose median step time "
           "exceeds HVDT_STRAGGLER_THRESHOLD x the cross-pod median for "
           "this many consecutive telemetry windows is evicted "
           "(cooldown blacklist + pod-granular resize down) instead of "
           "dragging every synchronous step.  0 = disabled.  Needs "
           "HVDT_TELEMETRY on the workers (the driver aggregates their "
           "KV snapshots)."),
        # --- continuous goodput (checkpoint.py / resilience/peer_store.py) ---
        _k("HVDT_ASYNC_CKPT", False, _parse_bool,
           "Asynchronous non-blocking checkpointing: "
           "CheckpointManager.save_async takes a device->host snapshot "
           "at the commit point and hands it to a background writer "
           "thread (queue depth 1, a newer snapshot supersedes a queued "
           "older one); the LAST_GOOD pointer advances only after the "
           "manifest write + fsync completes.  Unset (default): "
           "save_async IS the synchronous save (identity contract)."),
        _k("HVDT_CKPT_SNAPSHOT_BUDGET_S", 1.0, float,
           "Stall budget for the commit-point device->host checkpoint "
           "snapshot (the only part of an async save the step loop "
           "pays).  Snapshots are timed into the "
           "hvdt_ckpt_snapshot_seconds summary; one exceeding the "
           "budget logs a warning and increments "
           "hvdt_ckpt_snapshot_over_budget_total."),
        _k("HVDT_PEER_STORE", False, _parse_bool,
           "In-memory peer-replicated snapshot tier: at every commit "
           "point each rank publishes its committed snapshot over the "
           "rendezvous KV and mirrors peer (rank+1) %% n's newest "
           "snapshot in host RAM, so a single-rank or single-pod loss "
           "restores surviving state over the KV/TCP path without "
           "touching the filesystem (manifest-verified disk remains "
           "the fallback tier).  Needs the elastic rendezvous env "
           "(HVDT_RENDEZVOUS_ADDR) to be active."),
        # --- logging (ref: HOROVOD_LOG_LEVEL) ---
        _k("HVDT_LOG_LEVEL", "warning", str,
           "trace|debug|info|warning|error|fatal"),
        _k("HVDT_LOG_HIDE_TIME", False, _parse_bool, "Hide timestamps in log lines."),
        # --- profiler (ref: HOROVOD_DISABLE_NVTX_RANGES) ---
        _k("HVDT_DISABLE_PROFILER_RANGES", False, _parse_bool,
           "Disable jax.profiler TraceAnnotation ranges around eager ops."),
        # --- kernels ---
        _k("HVDT_FLASH_ATTENTION", "auto", str,
           "Pallas flash-attention kernel: auto (TPU only), on, off."),
        _k("HVDT_FLASH_SMALLSEQ", "auto", str,
           "Head-batched single-block attention kernel "
           "(flash_attention_smallseq) for short sequences (seq <= "
           "1024): auto (currently DISENGAGED pending the TPU A/B — an "
           "unmeasured kernel is not a default), on, off.  "
           "HVDT_FLASH_ATTENTION=off overrides to off; "
           "HVDT_FLASH_ATTENTION=on forces the streaming kernel "
           "instead (A/B semantics)."),
        _k("HVDT_FLASH_SMALLSEQ_HB", 8, int,
           "heads_per_block for the smallseq attention kernel (clamped "
           "to divide the head count; tuning knob for the grid-overhead "
           "vs VMEM trade)."),
        _k("HVDT_FUSED_CONV1X1", False, _parse_bool,
           "Route eligible ResNet 1x1 conv+BN(+ReLU) blocks through the "
           "fused Pallas kernels (ops/conv_fused.py): train mode emits "
           "conv output + batch-stat partials in one pass, eval mode "
           "fuses the folded affine into the matmul epilogue.  Default "
           "OFF pending the TPU A/B (tools/tpu_ab.py resnet_bench_fused "
           "leg) — an unmeasured kernel is not a default.  Eligibility: "
           "1x1, stride 1, Cin % 128 == 0 AND Cout % 128 == 0 (SyncBN "
           "via psum'd stat partials when bn_axis is set)."),
        _k("HVDT_FLASH_BWD", "xla", str,
           "flash_attention backward: xla (blockwise XLA recompute) or "
           "kernel (Pallas flash_grad_block passes). Read at TRACE time "
           "inside the custom_vjp: a grad function jitted before the env "
           "changed keeps its old backward until re-traced."),
        _k("HVDT_RING_PALLAS", False, _parse_bool,
           "Run ring attention's per-step block update and backward "
           "through the Pallas kernels (when shapes tile)."),
        _k("HVDT_FUSED_OPTIMIZER", False, _parse_bool,
           "Route optimizer updates through the fused Pallas kernels "
           "(ops/optim_kernels.fused_adam/fused_sgd) where leaves are "
           "tile-eligible; ineligible leaves fall back to the identical "
           "XLA math.  Default OFF pending the TPU A/B (bench.py "
           "--fused-optimizer exports this; the autotuner's fused "
           "dimension reads it as the starting point)."),
        # --- step pipeline ---
        _k("HVDT_COMPILATION_CACHE", "", str,
           "Directory for JAX's persistent XLA compilation cache "
           "(step_pipeline.enable_compilation_cache; engaged inside "
           "hvd.init() and by bench.py).  Empty/off = disabled."),
        _k("HVDT_COMPILATION_CACHE_MIN_COMPILE_SECS", 1.0, float,
           "Only persist compilations at least this expensive — keeps "
           "the multi-second train steps, skips trivial helper jits."),
        # --- serving (horovod_tpu/serve: engine, batcher, HTTP front end,
        #     hot reload — no reference analog; the inference workload) ---
        _k("HVDT_SERVE_HOST", "127.0.0.1", str,
           "Bind address for the serving HTTP front end."),
        _k("HVDT_SERVE_PORT", 8000, int,
           "Bind port for the serving HTTP front end (0 = ephemeral)."),
        _k("HVDT_SERVE_BUCKETS", "1,8,32", str,
           "Comma ladder of batch-size shape buckets the engine jits; "
           "requests are padded up to the smallest admitting bucket so "
           "steady-state traffic never recompiles."),
        _k("HVDT_SERVE_MAX_BATCH_SIZE", 32, int,
           "Max rows the dynamic batcher coalesces into one dispatch."),
        _k("HVDT_SERVE_MAX_DELAY_MS", 5.0, float,
           "Max linger (ms) the batcher waits for a fuller batch after "
           "the first request arrives — the batching latency budget."),
        _k("HVDT_SERVE_MAX_QUEUE_DEPTH", 256, int,
           "Admission-control bound (rows queued but not dispatched); "
           "past it /predict sheds load with HTTP 503 instead of "
           "growing the queue into an OOM."),
        _k("HVDT_SERVE_REQUEST_TIMEOUT_S", 30.0, float,
           "Per-request deadline inside the server (504 past it)."),
        _k("HVDT_SERVE_RELOAD_INTERVAL_S", 10.0, float,
           "Seconds between checkpoint-directory polls for hot weight "
           "reload (serve/reload.py CheckpointWatcher)."),
        # --- elastic serving control plane (serve/router.py +
        #     serve/autoscale.py on the pod-aware elastic machinery) ---
        _k("HVDT_SERVE_HEARTBEAT_S", 2.0, float,
           "Replica heartbeat period to the rendezvous KV "
           "(/serve/replicas/<id>); the router treats a replica whose "
           "heartbeat is older than 2x this as dead and routes around "
           "it — the serving analog of the elastic dead-peer bound."),
        _k("HVDT_SERVE_SLO_P99_MS", 0.0, float,
           "p99 latency SLO (ms) for routing and autoscaling: the "
           "router ejects a replica whose reported p99 breaches it, "
           "and the autoscaler scales up while the fleet p99 sits "
           "above it.  0 = no SLO enforcement."),
        _k("HVDT_SERVE_REPLICAS", 1, int,
           "Initial/target replica count for `hvdtrun serve "
           "--replicas` (the elastic serving control plane; 1 = the "
           "single-replica PR-2 path unless --autoscale raises it)."),
        _k("HVDT_SERVE_MAX_REPLICAS", 4, int,
           "Autoscaler ceiling on replica count (and the localhost "
           "slot count of the default serve host discovery)."),
        _k("HVDT_SERVE_AUTOSCALE", False, _parse_bool,
           "Enable the replica autoscaler loop: scale up on queue "
           "depth per replica / p99-over-SLO, scale down on idle "
           "queues, within [1, HVDT_SERVE_MAX_REPLICAS]."),
        _k("HVDT_SERVE_SCALE_COOLDOWN_S", 10.0, float,
           "Minimum seconds between autoscaler scale events — resize "
           "decisions must not flap faster than replicas boot/drain."),
        _k("HVDT_SERVE_QUEUE_HI", 16.0, float,
           "Scale-UP watermark: mean queued rows per live replica "
           "above this adds a replica (queue depth is the leading "
           "indicator; p99 breaches confirm it)."),
        _k("HVDT_SERVE_QUEUE_LO", 2.0, float,
           "Scale-DOWN watermark: mean queued rows per replica below "
           "this (with p99 inside the SLO) drains the newest replica."),
        _k("HVDT_SERVE_ROUTER_PORT", 0, int,
           "Bind port for the serving router front tier (0 = "
           "ephemeral; the router logs the bound port on start)."),
        _k("HVDT_SERVE_EJECT_COOLDOWN_S", 3.0, float,
           "Seconds an ejected replica (failed probe / SLO breach / "
           "dispatch failures) sits out of routing before re-admission "
           "— doubles per repeated ejection like the elastic host "
           "blacklist cooldown."),
        _k("HVDT_SERVE_HEDGE_MS", 0.0, float,
           "Hedge-request threshold (ms): a /predict still unanswered "
           "past it is duplicated to a second replica and the first "
           "response wins.  0 = adaptive (hedge past ~2x the router's "
           "observed p99, floored at 50 ms); negative = hedging off."),
        # --- continuous-batching LLM decode engine (serve/llm: paged KV
        #     cache, per-iteration scheduler, jitted decode loop) ---
        _k("HVDT_SERVE_ENGINE", "static", str,
           "Serving engine: 'static' (the shape-bucket InferenceEngine) "
           "or 'continuous' (the serve/llm continuous-batching decode "
           "engine with a paged KV cache; --model transformer only).  "
           "The router and autoscaler are engine-agnostic."),
        _k("HVDT_KV_BLOCK_SIZE", 16, int,
           "Tokens per paged-KV-cache block.  Smaller blocks waste less "
           "tail capacity per sequence but grow the block tables; the "
           "decode step's gather shape is [slots, blocks_per_seq * "
           "block_size], so block_size * HVDT_KV_SEQ_BLOCKS bounds "
           "context length."),
        _k("HVDT_KV_BLOCKS", 128, int,
           "Total paged-KV-cache block budget per engine (physical "
           "block 0 is the write sink for inactive decode slots and is "
           "never allocated).  The scheduler admits/evicts against this "
           "budget; HBM cost is 2 * layers * blocks * block_size * "
           "kv_heads * head_dim * dtype bytes."),
        _k("HVDT_KV_SEQ_BLOCKS", 8, int,
           "Block-table length per sequence (max context = this * "
           "HVDT_KV_BLOCK_SIZE tokens).  Fixed so the decode step's "
           "gather never changes shape — the zero-recompile contract."),
        _k("HVDT_SERVE_DECODE_SLOTS", 8, int,
           "Decode-slot count of the continuous engine: sequences "
           "decoded per iteration.  Fixed shape — admission/eviction "
           "swaps sequences in and out of slots without recompiling."),
        _k("HVDT_SERVE_PREFILL_CHUNK", 64, int,
           "Prefill chunk length (tokens) of the continuous engine.  "
           "Long prompts stream through in chunks of this size, one "
           "chunk per iteration, so a long prefill never stalls decode "
           "for more than one chunk's worth of compute (decode-p99 "
           "disaggregation)."),
        _k("HVDT_SERVE_MAX_NEW_TOKENS", 32, int,
           "Default generation budget per request for the continuous "
           "engine (a request's max_new_tokens field overrides, capped "
           "by the context bound)."),
        _k("HVDT_SERVE_INT8", False, _parse_bool,
           "Serve transformer weights block-scaled int8 (quant/kernels "
           "quantize_flat) in the continuous engine: eligible matmul "
           "weights are stored int8+scales in HBM and dequantized "
           "inside the jitted step — ~4x weight-HBM density per "
           "replica, unchanged request API."),
        _k("HVDT_SERVE_BATCH_QUOTA", 0.5, float,
           "Ceiling fraction of decode slots the 'batch' tenant class "
           "may hold.  The live quota adapts below this off the "
           "interactive-tenant queue-wait time series (telemetry/"
           "history.Series): sustained interactive waiting shrinks the "
           "batch share, an idle interactive queue restores it."),
        _k("HVDT_SERVE_RING_PREFILL", 0, int,
           "Sequence-parallel degree for long-context prefill in the "
           "continuous engine: prompts spanning at least half the "
           "context ride a shard_map ring_attention island over this "
           "many devices (0/1 = chunked single-device prefill only)."),
        # --- host data plane (ref: HOROVOD_CPU_OPERATIONS common.h:127-128,
        #     LibType selection env_parser.cc) ---
        _k("HVDT_CPU_OPERATIONS", "xla", str,
           "Host-collective data plane: 'xla' (host tensors ride the device "
           "mesh) or 'tcp' (native C++ socket-mesh backend, the Gloo analog)."),
        _k("HVDT_TCP_ADDRS", "", str,
           "Rank-ordered host:port list for the native TCP backend (set by "
           "the launcher when HVDT_CPU_OPERATIONS=tcp; process set k "
           "listens on port + k*HVDT_TCP_SET_PORT_STRIDE)."),
        _k("HVDT_TCP_TIMEOUT_MS", 30000, int,
           "Connect timeout for the native TCP backend mesh bootstrap."),
        _k("HVDT_TCP_SET_PORT_STRIDE", 128, int,
           "Port stride between process sets' socket meshes. All base "
           "ports on one host must live in a contiguous block smaller "
           "than this stride, so per-set listener ports (base + "
           "set_id*stride) never collide with another rank's ports."),
        # --- elastic (ref: HOROVOD_ELASTIC common.h:139) ---
        _k("HVDT_ELASTIC", False, _parse_bool, "Elastic (fault-tolerant) mode."),
        # --- topology / rendezvous (set by the launcher; ref env contract
        #     runner/gloo_run.py:65-76) ---
        _k("HVDT_RANK", -1, int, "Global process rank (set by launcher)."),
        _k("HVDT_SIZE", -1, int, "Global process count (set by launcher)."),
        _k("HVDT_LOCAL_RANK", -1, int, "Rank within the host (set by launcher)."),
        _k("HVDT_LOCAL_SIZE", -1, int, "Processes on this host (set by launcher)."),
        _k("HVDT_CROSS_RANK", -1, int, "Host index (set by launcher)."),
        _k("HVDT_CROSS_SIZE", -1, int, "Number of hosts (set by launcher)."),
        _k("HVDT_HOSTNAME", "", str, "Logical hostname assigned by launcher."),
        _k("HVDT_COORDINATOR_ADDR", "", str,
           "host:port of the JAX coordination service / rendezvous KV."),
        _k("HVDT_RENDEZVOUS_ADDR", "", str, "Rendezvous HTTP KV server address."),
        _k("HVDT_RENDEZVOUS_PORT", 0, int, "Rendezvous HTTP KV server port."),
        _k("HVDT_SECRET_KEY", "", str, "HMAC key for launcher RPC authentication."),
        # --- mesh defaults ---
        _k("HVDT_MESH_AXES", "", str,
           "Comma list of axis=size pairs for the default mesh, e.g. "
           "'dp=4,tp=2'. Empty = 1-D data-parallel mesh over all devices."),
        # --- orchestrators (horovod_tpu/orchestrate: Spark barrier
        #     execution + estimator dataframe sharding) ---
        _k("HVDT_SPARK_START_TIMEOUT", 600.0, float,
           "Seconds the Spark barrier job waits for every executor "
           "slot to check in before aborting the launch (the "
           "--start-timeout analog for orchestrate/spark.run)."),
        _k("HVDT_SPARK_RUN_TIMEOUT", 86400.0, float,
           "Wall-clock bound (seconds) on one orchestrate/spark.run "
           "barrier job; past it the job group is cancelled and the "
           "run raises instead of holding executors forever."),
        _k("HVDT_SPARK_COORD_TIMEOUT", 120.0, float,
           "Seconds a Spark barrier task waits for rank 0's "
           "coordinator address broadcast before giving up."),
        _k("HVDT_DFSHARD_TIMEOUT", 120.0, float,
           "Seconds the estimator's dataframe-shard fetch waits for "
           "each worker's partition to materialize."),
        # --- bench / example harness A/B switches (read by bench.py and
        #     examples/, documented in docs/performance.md) ---
        _k("HVDT_BENCH_NO_CACHE", False, _parse_bool,
           "bench.py: bypass the persistent compilation cache for this "
           "run — keeps an experimental config's compilations out of "
           "the shared cache during A/B sweeps (tools/tpu_ab.py sets "
           "it on the experiment leg)."),
        _k("HVDT_LM_SINGLE", True, _parse_bool,
           "examples/jax_transformer_lm.py: run the single-island step "
           "layout (default); 0/false re-runs the per-stage island leg "
           "as the A/B comparison documented in docs/performance.md."),
        # --- persistence safety ---
        _k("HVDT_MLPARAMS_ALLOW_PREFIXES", "horovod_tpu.", str,
           "Comma list of module prefixes orchestrate/ml_params.load() "
           "may import classes from (metadata.json 'class' field); a "
           "non-allowlisted class is rejected BEFORE any unpickling. "
           "Extend when persisting your own MLParams subclasses, e.g. "
           "'horovod_tpu.,myproject.models.'."),
        # --- numerics ---
        _k("HVDT_ALLREDUCE_DTYPE", "", str,
           "Force wire dtype for allreduce ('bfloat16' for compression-"
           "on-the-wire; empty = tensor dtype)."),
        # --- quantized wire (horovod_tpu/quant: block-scaled int8
        #     collectives with error feedback) ---
        _k("HVDT_COMPRESSION", "", str,
           "Gradient wire compressor by name: none|bf16|fp16|int8|int4 "
           "(empty = none).  Consumed by hvd.init() and by "
           "DistributedOptimizer wrappers when compression= is unset; "
           "unknown names raise with the valid list.  The launcher "
           "forwards --compression."),
        _k("HVDT_QUANT", False, _parse_bool,
           "Shorthand for HVDT_COMPRESSION=int8 (wins over it): route "
           "gradient collectives over the block-scaled int8 wire "
           "(quant/collectives two-stage quantized allreduce).  Pair "
           "with quant.with_error_feedback for f32-parity convergence."),
        _k("HVDT_QUANT_BLOCK", 256, int,
           "Block size (elements) for int8/int4 wire quantization: one "
           "f32 absmax scale per block.  256 default = 1.6% scale "
           "overhead; must be a multiple of 128 for the int8 Pallas "
           "lowering (256 for the packed-int4 one; other values fall "
           "back to identical-math XLA)."),
        _k("HVDT_QUANT_KERNELS", "auto", str,
           "Quantize/dequantize lowering: auto (Pallas on TPU, XLA "
           "elsewhere), on (force Pallas — interpret mode off-TPU, the "
           "kernel-equivalence test path), off (XLA everywhere).  Both "
           "lowerings share the same block math."),
        _k("HVDT_AUTOTUNE_QUANT", False, _parse_bool,
           "Add a quantized-wire leg dimension (f32/int8/int4) to the "
           "autotune search space; the step builder is rebuilt with "
           "quant=.../quant_leg=... at each knob change "
           "(autotune.AutotunedStep), hot-swappable because all legs "
           "keep one optimizer state tree (see "
           "quant.with_error_feedback(enabled=...), whose residual is "
           "leg-independent f32).  Starting point comes from "
           "HVDT_QUANT / HVDT_COMPRESSION."),
        _k("HVDT_FP8", "off", str,
           "fp8 (e4m3) compute path: off (default) or matmul — route "
           "the transformer MLP/attention-projection matmuls through "
           "quant.fp8.fp8_matmul (per-tensor delayed-max scaling, f32 "
           "accumulation).  A capability probe falls back to the plain "
           "matmul when the installed jax/backend lacks working fp8 "
           "dtypes, so 'matmul' is always safe to set; unknown values "
           "raise with the valid list."),
    ]
}


# Internal env-contract variables: set by the launcher / elastic driver /
# serve control plane for their own child processes — wiring, not
# operator-facing knobs, so they carry no Knob entry (no default, no
# CLI flag).  Declared here so the static analyzer (horovod_tpu/analysis
# lint rule `knob-drift`) can tell wiring from a typo'd or undeclared
# knob; every HVDT_* read anywhere in the tree must appear either in
# KNOBS or here.
CONTRACT_VARS: Dict[str, str] = {
    "HVDT_SECRET": "HMAC secret for the rendezvous KV (launcher -> "
                   "workers; hex).",
    "HVDT_GENERATION": "Elastic cluster generation counter (driver -> "
                       "workers on each re-rendezvous).",
    "HVDT_NICS": "--network-interface allowlist the launcher exports "
                 "to workers.",
    "HVDT_POD_INDEX": "Pod index of this host (launcher topology "
                      "contract).",
    "HVDT_POD_RANK": "Rank within the pod (launcher topology contract).",
    "HVDT_NUM_PODS": "Pod count of the current mesh (elastic driver "
                     "contract).",
    "HVDT_EXEC_ADDR": "Executor-pool KV address (orchestrate/executor "
                      "driver -> workers).",
    "HVDT_EXEC_PORT": "Executor-pool KV port.",
    "HVDT_EXEC_SECRET": "Executor-pool KV HMAC secret (hex).",
    "HVDT_RUNFUNC_ADDR": "runner.run() function-shipping KV address.",
    "HVDT_RUNFUNC_PORT": "runner.run() function-shipping KV port.",
    "HVDT_RUNFUNC_SECRET": "runner.run() function-shipping KV secret "
                           "(hex).",
    "HVDT_SERVE_REPLICA_ID": "Replica id the serve autoscaler assigns "
                             "to each spawned serving process.",
}


def get(name: str) -> Any:
    return KNOBS[name].read()


def get_bool(name: str) -> bool:
    return bool(get(name))


def get_int(name: str) -> int:
    return int(get(name))


def get_float(name: str) -> float:
    return float(get(name))


def get_str(name: str) -> str:
    return str(get(name))


def registry_doc() -> str:
    """Render the knob registry as help text (used by the CLI)."""
    lines = []
    for k in KNOBS.values():
        lines.append(f"{k.name} (default: {k.default!r})\n    {k.doc}")
    return "\n".join(lines)
