"""Leveled, rank-tagged logging (ref: common/logging.{h,cc} LOG(level, rank))."""

from __future__ import annotations

import logging
import os
import sys

_LEVELS = {
    "trace": 5,
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "fatal": logging.CRITICAL,
}

logging.addLevelName(5, "TRACE")

_configured = False


def get_logger(name: str = "horovod_tpu") -> logging.Logger:
    global _configured
    logger = logging.getLogger(name)
    if not _configured:
        from . import config

        level = _LEVELS.get(config.get_str("HVDT_LOG_LEVEL").lower(), logging.WARNING)
        handler = logging.StreamHandler(sys.stderr)
        rank = os.environ.get("HVDT_RANK", "-")
        if config.get_bool("HVDT_LOG_HIDE_TIME"):
            fmt = f"[%(levelname)s | rank {rank}] %(message)s"
        else:
            fmt = f"%(asctime)s [%(levelname)s | rank {rank}] %(message)s"
        handler.setFormatter(logging.Formatter(fmt))
        root = logging.getLogger("horovod_tpu")
        root.addHandler(handler)
        root.setLevel(level)
        root.propagate = False
        _configured = True
    return logger
