"""Process sets — sub-groups of ranks doing independent collectives.

TPU-native re-conception of the reference's process sets
(ref: common/process_set.{h,cc} ProcessSet/ProcessSetTable;
Python API common/process_sets.py:1-163; dynamic add/remove coordination
operations.cc:1211-1277).

Translation: in the reference each ProcessSet owns its own controller,
TensorQueue and ResponseCache because collectives are negotiated at runtime.
On TPU a process set maps to a **sub-mesh**: the jax devices belonging to the
member processes.  Collectives inside jit are compiled against that sub-mesh;
the eager path keys its queues/caches by process-set id.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from .exceptions import HorovodTpuError

__all__ = ["ProcessSet", "ProcessSetTable", "global_process_set", "add_process_set", "remove_process_set", "process_set_by_id"]


class ProcessSet:
    """A set of process ranks + the sub-mesh over their devices."""

    def __init__(self, ranks: Sequence[int], set_id: int, topo, parent_mesh):
        self.ranks: List[int] = sorted(set(int(r) for r in ranks))
        self.id = set_id
        self._topo = topo
        self._mesh = None
        self._parent_mesh = parent_mesh

    # -- membership ---------------------------------------------------------
    def included(self, global_rank: Optional[int] = None) -> bool:
        r = self._topo.rank if global_rank is None else global_rank
        return r in self.ranks

    def size(self) -> int:
        return len(self.ranks)

    def rank(self) -> int:
        """This process's rank within the set (ref: process_sets.py rank())."""
        if not self.included():
            raise HorovodTpuError(
                f"Process {self._topo.rank} is not part of process set {self.id}")
        return self.ranks.index(self._topo.rank)

    # -- mesh ---------------------------------------------------------------
    @property
    def mesh(self):
        """Sub-mesh over the devices owned by member processes (1-D 'dp')."""
        if self._mesh is None:
            import jax
            from jax.sharding import Mesh

            if self.ranks == list(range(self._topo.size)):
                self._mesh = self._parent_mesh
            else:
                devs = [d for d in jax.devices()
                        if d.process_index in set(self.ranks)]
                self._mesh = Mesh(np.asarray(devs, dtype=object), ("dp",))
        return self._mesh

    def __repr__(self) -> str:
        return f"ProcessSet(id={self.id}, ranks={self.ranks})"


class ProcessSetTable:
    """id → ProcessSet registry (ref: common/process_set.{h,cc}
    ProcessSetTable; lock-guarded like operations.cc:336)."""

    GLOBAL_ID = 0

    def __init__(self, topo, global_mesh):
        self._lock = threading.RLock()
        self._topo = topo
        self._global_mesh = global_mesh
        self._next_id = 1
        self._sets: Dict[int, ProcessSet] = {
            self.GLOBAL_ID: ProcessSet(range(topo.size), self.GLOBAL_ID, topo,
                                       global_mesh)
        }

    def get(self, set_id: int) -> ProcessSet:
        with self._lock:
            try:
                return self._sets[set_id]
            except KeyError:
                raise HorovodTpuError(f"Unknown process set id {set_id}")

    def global_set(self) -> ProcessSet:
        return self.get(self.GLOBAL_ID)

    def add(self, ranks: Sequence[int]) -> ProcessSet:
        """Register a new process set.

        All member ranks must call with identical rank lists — deterministic
        ids replace the reference's cross-rank id negotiation
        (operations.cc:1211-1277): under SPMD every process executes the
        same registration sequence, so ids agree by construction.
        """
        ranks = sorted(set(int(r) for r in ranks))
        bad = [r for r in ranks if r < 0 or r >= self._topo.size]
        if bad:
            raise HorovodTpuError(f"Invalid ranks for process set: {bad}")
        with self._lock:
            for ps in self._sets.values():
                if ps.ranks == ranks:
                    return ps
            ps = ProcessSet(ranks, self._next_id, self._topo, self._global_mesh)
            self._sets[self._next_id] = ps
            self._next_id += 1
            return ps

    def remove(self, set_id: int) -> None:
        if set_id == self.GLOBAL_ID:
            raise HorovodTpuError("Cannot remove the global process set")
        with self._lock:
            self._sets.pop(set_id, None)

    def ids(self) -> List[int]:
        with self._lock:
            return sorted(self._sets)


# -- module-level convenience API (ref: common/process_sets.py) -------------

def _table() -> ProcessSetTable:
    from . import basics

    tbl = basics._global_state().process_set_table
    if tbl is None:
        from .exceptions import NotInitializedError

        raise NotInitializedError()
    return tbl


def global_process_set() -> ProcessSet:
    return _table().global_set()


def add_process_set(ranks: Sequence[int]) -> ProcessSet:
    return _table().add(ranks)


def remove_process_set(set_id: int) -> None:
    _table().remove(set_id)


def process_set_by_id(set_id: int) -> ProcessSet:
    return _table().get(set_id)
