"""Core framework-neutral types.

TPU-native re-conception of the reference's core type layer
(ref: horovod/common/common.h:197-382 — Status, TensorShape, DataType,
TensorTableEntry).  On TPU the tensor abstraction is a jax.Array, so the
adapter interfaces (Tensor/OpContext/PersistentBuffer/ReadyEvent,
common.h:259-339) collapse into plain functions over pytrees; what remains
load-bearing here is the Status machinery used by the async eager path and
the dtype registry shared by the wire protocol and the collective layer.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Any, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "StatusType",
    "Status",
    "DataType",
    "TensorShape",
    "ReduceOp",
    "DATA_TYPE_TO_NUMPY",
    "NUMPY_TO_DATA_TYPE",
    "data_type_of",
]


class StatusType(enum.IntEnum):
    """Mirrors the reference status taxonomy (common.h:190-195)."""

    OK = 0
    UNKNOWN_ERROR = 1
    PRECONDITION_ERROR = 2
    ABORTED = 3
    INVALID_ARGUMENT = 4
    IN_PROGRESS = 5


@dataclasses.dataclass(frozen=True)
class Status:
    """Async operation status (ref: common.h:197-232)."""

    type: StatusType = StatusType.OK
    reason: str = ""

    @staticmethod
    def ok() -> "Status":
        return _OK

    @staticmethod
    def unknown(msg: str) -> "Status":
        return Status(StatusType.UNKNOWN_ERROR, msg)

    @staticmethod
    def precondition(msg: str) -> "Status":
        return Status(StatusType.PRECONDITION_ERROR, msg)

    @staticmethod
    def aborted(msg: str) -> "Status":
        return Status(StatusType.ABORTED, msg)

    @staticmethod
    def invalid_argument(msg: str) -> "Status":
        return Status(StatusType.INVALID_ARGUMENT, msg)

    @staticmethod
    def in_progress() -> "Status":
        return Status(StatusType.IN_PROGRESS, "")

    def ok_p(self) -> bool:
        return self.type == StatusType.OK

    def in_progress_p(self) -> bool:
        return self.type == StatusType.IN_PROGRESS


_OK = Status()

# Error message used when two in-flight tensors share a name
# (ref: common.h:229 DUPLICATE_NAME_ERROR).
DUPLICATE_NAME_ERROR = (
    "Requested to collective-op a tensor with the same name as another tensor "
    "that is currently being processed.  If you want to request another tensor, "
    "use a different tensor name."
)


class DataType(enum.IntEnum):
    """Wire dtype enum (ref: message.h:30-41).

    Values kept stable — they appear in the serialized wire protocol.
    """

    UINT8 = 0
    INT8 = 1
    UINT16 = 2
    INT16 = 3
    INT32 = 4
    INT64 = 5
    FLOAT16 = 6
    FLOAT32 = 7
    FLOAT64 = 8
    BOOL = 9
    # TPU-native extension: bf16 is the native matmul dtype on TPU.
    BFLOAT16 = 10


def _bfloat16_np():
    import ml_dtypes  # ships with jax

    return np.dtype(ml_dtypes.bfloat16)


DATA_TYPE_TO_NUMPY = {
    DataType.UINT8: np.dtype(np.uint8),
    DataType.INT8: np.dtype(np.int8),
    DataType.UINT16: np.dtype(np.uint16),
    DataType.INT16: np.dtype(np.int16),
    DataType.INT32: np.dtype(np.int32),
    DataType.INT64: np.dtype(np.int64),
    DataType.FLOAT16: np.dtype(np.float16),
    DataType.FLOAT32: np.dtype(np.float32),
    DataType.FLOAT64: np.dtype(np.float64),
    DataType.BOOL: np.dtype(np.bool_),
}

NUMPY_TO_DATA_TYPE = {v: k for k, v in DATA_TYPE_TO_NUMPY.items()}


def data_type_of(array: Any) -> DataType:
    """Map a numpy/jax array (or dtype) to the wire DataType."""
    dtype = np.dtype(getattr(array, "dtype", array))
    if dtype.name == "bfloat16":
        return DataType.BFLOAT16
    try:
        return NUMPY_TO_DATA_TYPE[dtype]
    except KeyError as e:
        raise ValueError(f"Unsupported dtype for collective ops: {dtype}") from e


def numpy_dtype_of(dt: DataType) -> np.dtype:
    if dt == DataType.BFLOAT16:
        return _bfloat16_np()
    return DATA_TYPE_TO_NUMPY[dt]


class ReduceOp(enum.IntEnum):
    """Reduction selector (ref: message carries ReduceOp for allreduce;
    Average/Sum split into prescale/postscale in the bindings —
    torch/mpi_ops.py and tensorflow/__init__.py:55)."""

    AVERAGE = 0
    SUM = 1
    ADASUM = 2
    MIN = 3
    MAX = 4
    PRODUCT = 5


@dataclasses.dataclass(frozen=True)
class TensorShape:
    """Shape value object (ref: common.h:234-257)."""

    dims: Tuple[int, ...] = ()

    @staticmethod
    def of(array: Any) -> "TensorShape":
        return TensorShape(tuple(int(d) for d in array.shape))

    def num_elements(self) -> int:
        n = 1
        for d in self.dims:
            n *= d
        return n

    def __str__(self) -> str:
        return "[" + ", ".join(str(d) for d in self.dims) + "]"
