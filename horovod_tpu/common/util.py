"""Capability predicates (ref: horovod/common/util.py:137-200).

Reference scripts gate behavior and tests on these
(``hvd.nccl_built()``, ``hvd.mpi_enabled()`` — e.g.
test/parallel/test_torch.py capability skips).  Keeping the exact names
lets those scripts port unchanged: the GPU/MPI-transport predicates are
honestly False here (the XLA data plane replaced them — SURVEY.md §5.8),
and the TPU build's real capabilities get predicates of their own.
"""

from __future__ import annotations

__all__ = [
    "mpi_built", "mpi_enabled", "mpi_threads_supported",
    "gloo_built", "gloo_enabled", "nccl_built", "ddl_built", "ccl_built",
    "cuda_built", "rocm_built",
    "xla_built", "tpu_available", "native_built", "tcp_enabled",
]


def mpi_built(verbose: bool = False) -> bool:
    """False: no MPI transport exists in this build (XLA collectives
    replace it)."""
    return False


def mpi_enabled() -> bool:
    return False


def mpi_threads_supported() -> bool:
    return False


def gloo_built(verbose: bool = False) -> bool:
    """False — the host-CPU fallback here is the native TCP backend; use
    :func:`native_built` / :func:`tcp_enabled` for that capability."""
    return False


def gloo_enabled() -> bool:
    return False


def nccl_built(verbose: bool = False) -> bool:
    return False


def ddl_built(verbose: bool = False) -> bool:
    return False


def ccl_built(verbose: bool = False) -> bool:
    return False


def cuda_built(verbose: bool = False) -> bool:
    return False


def rocm_built(verbose: bool = False) -> bool:
    return False


def xla_built(verbose: bool = False) -> bool:
    """True: the XLA data plane is this build's collective backend."""
    return True


def tpu_available(verbose: bool = False) -> bool:
    """Whether an initialized-or-initializable TPU backend is present.

    Honest probe of the CURRENT process's JAX platform list; unlike the
    reference's link-time ``*_built`` checks this can differ per process
    (CPU-pinned test children return False).
    """
    try:
        import jax

        return any(d.platform == "tpu" for d in jax.devices())
    except Exception:
        return False


def native_built(verbose: bool = False) -> bool:
    """Whether the C++ native core (TCP collectives, Adasum VHDD,
    timeline writer) compiled and loads — the analog of the reference's
    transport ``*_built`` probes."""
    from ..native import available

    return available()


def tcp_enabled() -> bool:
    """Whether the native TCP data plane is selected for host collectives
    (HVDT_CPU_OPERATIONS=tcp with a rank-address contract present)."""
    from ..ops import tcp_backend

    return tcp_backend.enabled()
