"""Process model, topology, and lifecycle — the ``hvd.init()`` layer.

TPU-native re-conception of the reference's init path
(ref: common/basics.py:33-489 HorovodBasics; operations.cc:811-863
InitializeHorovodOnce; operations.cc:887-1353 C API).

Key design translation (SURVEY.md §7 step 1):

* rank / local_rank / cross_rank map onto JAX's process topology:
  ``rank`` = ``jax.process_index()``, ``cross_rank`` = host index,
  ``local_rank`` = position within the host.  The launcher provides these
  via the ``HVDT_*`` env contract (the analog of runner/gloo_run.py:65-76);
  without a launcher they are derived from JAX itself.
* Rendezvous = the JAX coordination service (``jax.distributed.initialize``),
  replacing the reference's MPI init / Gloo HTTP rendezvous
  (gloo/gloo_context.cc).
* There is no background C++ thread to spawn at init: under jit, collective
  scheduling is XLA's job.  The eager negotiated path (ops/eager.py) starts
  its controller thread lazily on first use.

Unlike the reference (one process per accelerator), JAX runs one process per
*host* controlling several local devices; chip-level parallelism is expressed
through sharded arrays over the mesh.  ``size()``/``rank()`` therefore count
processes (matching the reference's process semantics) while
``num_devices()``/``device_rank`` count chips.
"""

from __future__ import annotations

import atexit
import dataclasses
import os
import threading
from typing import Any, List, Optional, Sequence

from . import config
from .exceptions import NotInitializedError
from .logging_util import get_logger

__all__ = [
    "init",
    "shutdown",
    "is_initialized",
    "rank",
    "size",
    "local_rank",
    "local_size",
    "cross_rank",
    "cross_size",
    "num_devices",
    "local_devices",
    "global_devices",
    "is_homogeneous",
    "Topology",
    "topology",
]

log = get_logger(__name__)


@dataclasses.dataclass(frozen=True)
class Topology:
    """Static process/device topology, fixed at init.

    (ref: the rank/local_rank/cross_rank triple of SlotInfo,
    runner/common/util/hosts.py:155, consumed by controller
    DoInitialization mpi_controller.cc:28.)
    """

    rank: int
    size: int
    local_rank: int
    local_size: int
    cross_rank: int
    cross_size: int
    num_devices: int          # global device (chip) count
    num_local_devices: int

    @property
    def is_homogeneous(self) -> bool:
        return self.num_devices == self.num_local_devices * self.cross_size * (
            self.local_size if self.local_size else 1
        ) or self.size == 1


class _GlobalState:
    """Process-wide framework state (ref: global_state.h:39-126
    HorovodGlobalState — minus the background thread, which on TPU only
    exists for the eager path and lives in ops/eager.py)."""

    def __init__(self) -> None:
        self.lock = threading.RLock()
        self.initialized = False
        self.topology: Optional[Topology] = None
        self.mesh = None  # jax.sharding.Mesh over all participating devices
        self.process_set_table = None  # built at init (process_sets.py)
        self.eager_controller = None   # lazy (ops/eager.py)

    def reset(self) -> None:
        self.initialized = False
        self.topology = None
        self.mesh = None
        self.process_set_table = None
        self.eager_controller = None


_state = _GlobalState()


def _global_state() -> _GlobalState:
    return _state


def _jax_distributed_initialized() -> bool:
    """True if the JAX distributed runtime is already connected.

    Must not initialize the XLA backend as a side effect (unlike
    jax.process_count()), since jax.distributed.initialize() has to run
    before backend init."""
    import jax

    is_init = getattr(jax.distributed, "is_initialized", None)
    if is_init is not None:
        return bool(is_init())
    from jax._src import distributed as _dist  # fallback for older jax

    return getattr(_dist.global_state, "client", None) is not None


def _build_default_mesh(devices: Sequence[Any]):
    """Build the default mesh: 1-D data-parallel over all devices, or the
    axes requested via HVDT_MESH_AXES (e.g. 'dp=4,tp=2')."""
    import numpy as np
    from jax.sharding import Mesh

    spec = config.get_str("HVDT_MESH_AXES")
    devs = np.asarray(devices, dtype=object)
    if not spec:
        return Mesh(devs, ("dp",))
    axes, sizes = [], []
    for part in spec.split(","):
        name, _, sz = part.strip().partition("=")
        axes.append(name)
        sizes.append(int(sz))
    total = 1
    for s in sizes:
        total *= s
    if total != len(devices):
        raise ValueError(
            f"HVDT_MESH_AXES product {total} != device count {len(devices)}")
    return Mesh(devs.reshape(sizes), tuple(axes))


def init(
    *,
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    mesh=None,
    process_sets: Optional[Sequence[Sequence[int]]] = None,
) -> None:
    """Initialize the framework (ref: basics.py init → operations.cc:889
    horovod_init).

    Reads the launcher env contract (HVDT_RANK/SIZE/LOCAL_RANK/...) when
    present; connects the JAX distributed runtime for multi-process runs;
    builds the global device mesh and process-set table.

    Args:
      coordinator_address: host:port of the JAX coordination service.
        Defaults to HVDT_COORDINATOR_ADDR from the launcher.
      num_processes / process_id: override process topology (defaults from
        the env contract).
      mesh: optional pre-built jax.sharding.Mesh to adopt instead of the
        default 1-D data-parallel mesh.
      process_sets: optional list of rank lists to register as process sets
        at init (ref: horovod_init's ranks argument + init(comm=[...])).
    """
    import jax

    with _state.lock:
        if _state.initialized:
            log.debug("init() called twice; ignoring")
            return

        # Persistent XLA compilation cache (HVDT_COMPILATION_CACHE):
        # engage before anything compiles, so launcher-forwarded env
        # (hvdtrun --compilation-cache-dir) takes effect in every worker.
        from ..step_pipeline import enable_compilation_cache

        enable_compilation_cache()

        # XLA latency-hiding / async-collective-fusion flags
        # (HVDT_XLA_LATENCY_HIDING, ops/overlap.py): engage BEFORE the
        # first jax computation below initializes the backend — libtpu
        # reads LIBTPU_INIT_ARGS once at TPU init.  auto (default) keeps
        # non-TPU environments untouched; never raises.
        try:
            from ..ops.overlap import enable_latency_hiding

            enable_latency_hiding()
        except Exception as e:  # flags must never sink init
            log.warning("latency-hiding flags not engaged: %r", e)

        # Wire-compression env selection (HVDT_COMPRESSION / HVDT_QUANT):
        # resolve NOW so an unknown name fails at init with the valid
        # list, not at the first optimizer step on some worker.
        from ..ops.compression import Compression

        _env_comp = Compression.from_env()
        if _env_comp is not Compression.none:
            log.info("gradient wire compression from env: %s",
                     _env_comp.__name__)

        # Transport-policy env selection (HVDT_TRANSPORT): parse NOW so
        # unknown axis/algorithm/wire vocabulary or garbage thresholds
        # fail at init with the valid lists, not at the first traced
        # step on some worker (same idiom as HVDT_COMPRESSION above).
        from ..transport import validate_env as _transport_validate

        _env_transport = _transport_validate()
        if _env_transport is not None:
            log.info("transport policy from env: %s",
                     _env_transport.describe())

        # ZeRO stage env selection (HVDT_ZERO): validate NOW so an
        # unknown stage fails at init with the valid list, not at the
        # first optimizer build on some worker (same idiom as above).
        from ..ops import zero as _zero

        _env_zero_stage = _zero.validate_env()
        if _env_zero_stage is not None:
            log.info("ZeRO state sharding from env: stage=%s",
                     _env_zero_stage)

        env_size = config.get_int("HVDT_SIZE")
        env_rank = config.get_int("HVDT_RANK")
        coord = coordinator_address or config.get_str("HVDT_COORDINATOR_ADDR")
        n_proc = num_processes if num_processes is not None else (
            env_size if env_size > 0 else None)
        proc_id = process_id if process_id is not None else (
            env_rank if env_rank >= 0 else None)

        # jax.distributed.initialize must run before anything initializes the
        # XLA backend (jax.process_count() would), so the "already connected"
        # check must not touch the backend.
        if coord and (n_proc or 0) > 1 and not _jax_distributed_initialized():
            log.info("connecting JAX distributed runtime at %s (%s/%s)",
                     coord, proc_id, n_proc)
            jax.distributed.initialize(
                coordinator_address=coord,
                num_processes=n_proc,
                process_id=proc_id,
            )

        p_rank = jax.process_index()
        p_size = jax.process_count()

        local_rank_ = config.get_int("HVDT_LOCAL_RANK")
        local_size_ = config.get_int("HVDT_LOCAL_SIZE")
        cross_rank_ = config.get_int("HVDT_CROSS_RANK")
        cross_size_ = config.get_int("HVDT_CROSS_SIZE")
        if local_rank_ < 0:
            local_rank_, local_size_ = 0, 1
            cross_rank_, cross_size_ = p_rank, p_size

        devices = jax.devices()
        topo = Topology(
            rank=p_rank,
            size=p_size,
            local_rank=local_rank_,
            local_size=local_size_,
            cross_rank=cross_rank_,
            cross_size=cross_size_,
            num_devices=len(devices),
            num_local_devices=len(jax.local_devices()),
        )

        _state.topology = topo
        _state.mesh = mesh if mesh is not None else _build_default_mesh(devices)

        from . import process_sets as ps

        _state.process_set_table = ps.ProcessSetTable(topo, _state.mesh)
        if process_sets:
            for ranks in process_sets:
                _state.process_set_table.add(list(ranks))

        _state.initialized = True
        log.info("initialized: %s", topo)

        # Telemetry exporter (HVDT_TELEMETRY=1): per-worker /metrics +
        # /healthz on HVDT_METRICS_PORT + local_rank.  No-op when the
        # subsystem is off; never raises (observability must not sink
        # init).
        from ..telemetry.exporter import maybe_start_exporter

        maybe_start_exporter(topology=topo)

        # Predicted-vs-observed perf attribution: when an expected
        # schedule fingerprint is configured (HVDT_EXPECTED_SCHEDULE),
        # price it with the fitted cost model on the ambient topology
        # and publish hvdt_expected_step_comm_seconds /
        # hvdt_expected_wire_bytes{axis}; the StepTimer stream then
        # keeps hvdt_perf_deviation_ratio live.  No-op when telemetry
        # is off; never raises.
        from ..telemetry.step_stats import maybe_publish_expected_cost

        maybe_publish_expected_cost()


def shutdown() -> None:
    """Tear down (ref: operations.cc horovod_shutdown)."""
    from ..telemetry import trace as _trace
    from ..telemetry.exporter import stop_exporter
    from ..timeline import stop_timeline

    from ..ops import tcp_backend

    try:
        # Final span flush: per-rank Chrome-trace file into
        # HVDT_TRACE_DIR + KV publish for the driver-side merge (no-op
        # when tracing is off; never sinks shutdown).
        _trace.flush()
    except Exception:   # pragma: no cover - defensive
        pass
    stop_exporter()
    with _state.lock:
        if not _state.initialized:
            stop_timeline()  # a timeline may exist without init
            return
        multi = _state.topology is not None and _state.topology.size > 1
        if _state.eager_controller is not None:
            _state.eager_controller.shutdown()
        _state.reset()
    tcp_backend.shutdown_groups()
    stop_timeline()
    if multi:
        _sync_distributed_teardown()


def _sync_distributed_teardown() -> None:
    """Barrier the processes before the coordination service dies.

    Rank 0's process hosts the JAX coordination service; if it exits
    while a slower rank's client still holds connections/heartbeats, the
    orphaned client's C++ threads abort the process ("terminate called
    after throwing an instance of ...", observed on a loaded 1-core box
    where rank skew at exit is seconds).  A bounded coordination-service
    barrier lines everyone up, then ``jax.distributed.shutdown``
    disconnects clients cleanly before interpreter exit.  Best-effort:
    a crashed peer must not turn OUR exit into a hang."""
    import jax

    try:
        from jax._src import distributed as _jd

        client = getattr(_jd.global_state, "client", None)
        if client is None:
            return
    except Exception as e:
        # The private-API lookup itself failed (a jax upgrade moved
        # jax._src.distributed.global_state): the orderly teardown is
        # silently gone, which is exactly the racy-exit regression this
        # barrier fixed — make that loudly visible.
        # tests/test_basics.py::test_private_distributed_api_resolves pins
        # the attribute against the installed jax.
        log.warning("shutdown barrier unavailable (private jax API "
                    "moved?): %s — exits may race", e)
        return
    try:
        client.wait_at_barrier("hvdt_shutdown", 10_000)  # ms
    except Exception as e:  # pragma: no cover - peer-crash path
        log.debug("shutdown barrier skipped (peer gone?): %s", e)
        return
    try:
        # Tear the local PJRT client (and its cross-process collective
        # threads) down NOW, while every peer is provably alive and idle
        # (post-barrier) — leaving it to interpreter finalization lets a
        # faster peer's exit reset sockets under blocked collective
        # threads, which aborts the process from a C++ destructor.
        import jax.extend as jex

        jex.backend.clear_backends()
    except Exception as e:  # pragma: no cover
        log.debug("clear_backends failed: %s", e)
    try:
        jax.distributed.shutdown()
    except Exception as e:  # pragma: no cover
        log.debug("jax.distributed.shutdown failed: %s", e)


atexit.register(shutdown)


def _topo() -> Topology:
    t = _state.topology
    if t is None:
        raise NotInitializedError()
    return t


def is_initialized() -> bool:
    return _state.initialized


def topology() -> Topology:
    return _topo()


def rank() -> int:
    return _topo().rank


def size() -> int:
    return _topo().size


def local_rank() -> int:
    return _topo().local_rank


def local_size() -> int:
    return _topo().local_size


def cross_rank() -> int:
    return _topo().cross_rank


def cross_size() -> int:
    return _topo().cross_size


def num_devices() -> int:
    return _topo().num_devices


def is_homogeneous() -> bool:
    return _topo().is_homogeneous


def local_devices() -> List[Any]:
    import jax

    _topo()
    return list(jax.local_devices())


def global_devices() -> List[Any]:
    import jax

    _topo()
    return list(jax.devices())


def mesh():
    """The global device mesh adopted at init."""
    m = _state.mesh
    if m is None:
        raise NotInitializedError()
    return m


def set_mesh(new_mesh) -> None:
    """Adopt a caller-provided mesh as the global mesh (axes for dp/tp/...)."""
    with _state.lock:
        _topo()
        _state.mesh = new_mesh
