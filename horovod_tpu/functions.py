"""State-consistency helpers: broadcast parameters / optimizer state / objects.

TPU-native analog of the reference's broadcast functions
(ref: torch/functions.py:30-235 broadcast_parameters /
broadcast_optimizer_state / broadcast_object; tensorflow/functions.py
broadcast_variables).  Used at training start (and after elastic resets) to
make rank 0's state authoritative.
"""

from __future__ import annotations

import io
import pickle
from typing import Any, Optional

import numpy as np

from .common.process_sets import ProcessSet, global_process_set

__all__ = ["broadcast_parameters", "broadcast_optimizer_state",
           "broadcast_object", "allgather_object"]


def broadcast_parameters(params, root_rank: int = 0,
                         process_set: Optional[ProcessSet] = None):
    """Broadcast a pytree of arrays from ``root_rank`` to all ranks
    (ref: torch/functions.py:30 broadcast_parameters).

    Eager-path operation (host collectives); returns a new pytree.  Inside
    jit, use ops.device.broadcast instead.
    """
    import jax

    from .ops import eager

    ps = process_set or global_process_set()
    leaves, treedef = jax.tree.flatten(params)
    handles = [
        eager.broadcast_async(leaf, root_rank,
                              name=f"broadcast_parameters.{i}",
                              process_set=ps)
        for i, leaf in enumerate(leaves)
    ]
    out = [eager.synchronize(h) for h in handles]
    return jax.tree.unflatten(treedef, out)


def broadcast_optimizer_state(opt_state, root_rank: int = 0,
                              process_set: Optional[ProcessSet] = None):
    """Broadcast an optax optimizer-state pytree (ref: torch/functions.py
    broadcast_optimizer_state — there a state-dict walk; here optimizer
    state is already a pytree, so it reduces to broadcast_parameters with
    non-array leaves carried via object broadcast)."""
    import jax

    ps = process_set or global_process_set()
    leaves, treedef = jax.tree.flatten(opt_state)
    array_idx = [i for i, l in enumerate(leaves)
                 if hasattr(l, "shape") and hasattr(l, "dtype")]
    array_set = set(array_idx)
    arrays = [leaves[i] for i in array_idx]
    new_arrays = broadcast_parameters(arrays, root_rank, ps) if arrays else []
    others = [l for i, l in enumerate(leaves) if i not in array_set]
    new_others = broadcast_object(others, root_rank, ps) if others else []
    out = list(leaves)
    for i, v in zip(array_idx, new_arrays):
        out[i] = v
    oi = 0
    for i in range(len(out)):
        if i not in array_set:
            out[i] = new_others[oi]
            oi += 1
    return jax.tree.unflatten(treedef, out)


def broadcast_object(obj: Any, root_rank: int = 0,
                     process_set: Optional[ProcessSet] = None,
                     name: Optional[str] = None) -> Any:
    """Broadcast an arbitrary picklable object
    (ref: torch/functions.py:146 broadcast_object: serialize → bcast size →
    bcast payload)."""
    from .ops import eager

    ps = process_set or global_process_set()
    name = name or "broadcast_object"
    if ps.rank() == root_rank:
        payload = np.frombuffer(pickle.dumps(obj), dtype=np.uint8).copy()
        size = np.array([payload.shape[0]], dtype=np.int64)
    else:
        payload = None
        size = np.zeros(1, dtype=np.int64)
    size = eager.broadcast(size, root_rank, name=f"{name}.size",
                           process_set=ps)
    n = int(size[0])
    if payload is None:
        payload = np.zeros(n, dtype=np.uint8)
    payload = eager.broadcast(payload, root_rank, name=f"{name}.data",
                              process_set=ps)
    return pickle.loads(np.asarray(payload).tobytes())


def allgather_object(obj: Any, process_set: Optional[ProcessSet] = None,
                     name: Optional[str] = None) -> list:
    """Gather a picklable object from every rank (ref: torch/mpi_ops.py
    allgather_object)."""
    from .ops import eager

    ps = process_set or global_process_set()
    name = name or "allgather_object"
    payload = np.frombuffer(pickle.dumps(obj), dtype=np.uint8).copy()
    gathered = eager.allgather(payload.reshape(-1, 1),
                               name=f"{name}.data", process_set=ps)
    # ragged gather of (n_i, 1) blocks; recover per-rank lengths
    sizes = eager.allgather(np.array([[payload.shape[0]]], dtype=np.int64),
                            name=f"{name}.sizes", process_set=ps)
    out = []
    offset = 0
    flat = np.asarray(gathered).reshape(-1)
    for n in np.asarray(sizes).reshape(-1):
        out.append(pickle.loads(flat[offset:offset + int(n)]
                                .astype(np.uint8).tobytes()))
        offset += int(n)
    return out
