// TimelineWriter — async Chrome-trace writer (see timeline.cc).
#pragma once

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>

namespace hvdt {

class TimelineWriter {
 public:
  struct Event {
    std::string pid_name;
    std::string name;
    char ph;
    int64_t ts_us;
    int64_t dur_us;
    std::string args_json;
  };

  explicit TimelineWriter(const std::string& path);
  ~TimelineWriter();

  int Start();
  void Enqueue(Event ev);
  int Close();

 private:
  void Loop();
  void WriteEvent(const Event& ev);

  std::string path_;
  std::FILE* file_ = nullptr;
  std::thread thread_;
  std::mutex mu_;
  std::condition_variable cv_;
  std::deque<Event> queue_;
  std::atomic<bool> running_{false};
  std::unordered_map<std::string, int> pids_;  // writer thread only
};

}  // namespace hvdt
