// TcpGroup — full-mesh TCP process group with ring collectives.
// See tcp_group.cc for design notes (Gloo analog of the native core).
#pragma once

#include <cstdint>
#include <string>
#include <vector>

namespace hvdt {

class TcpGroup {
 public:
  TcpGroup() = default;
  ~TcpGroup();
  TcpGroup(const TcpGroup&) = delete;
  TcpGroup& operator=(const TcpGroup&) = delete;

  int Connect(int rank, int size, const std::string& addrs_csv,
              int timeout_ms);

  int rank() const { return rank_; }
  int size() const { return size_; }

  int Allreduce(void* buf, int64_t count, int dtype, int op);
  int Allgatherv(const void* in, int64_t in_count, void* out,
                 const int64_t* counts, int dtype);
  int Broadcast(void* buf, int64_t nbytes, int root);
  int Alltoallv(const void* in, const int64_t* send_counts, void* out,
                const int64_t* recv_counts, int dtype);
  int Barrier();

  // Pairwise primitives (used by collectives and Adasum VHDD).
  int SendRecv(int send_peer, const void* send_buf, int64_t send_n,
               int recv_peer, void* recv_buf, int64_t recv_n);
  int Send(int peer, const void* buf, int64_t n);
  int Recv(int peer, void* buf, int64_t n);

 private:
  void Segment(int64_t count, int k, int64_t* off, int64_t* len) const;

  int rank_ = 0;
  int size_ = 1;
  std::vector<int> fds_;  // fds_[peer] — full mesh, -1 for self
};

}  // namespace hvdt
