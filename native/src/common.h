// Internal shared helpers for the native core: per-thread error state,
// dtype size/dispatch, fp16/bf16 <-> fp32 conversion, elementwise reduce.
// (ref concepts: horovod/common/common.h DataType; horovod/common/half.cc
// CPU fp16 math — here bf16/fp16 segments are widened to fp32, reduced,
// and narrowed, which is also what the TPU VPU does for bf16 accumulate.)
#pragma once

#include <cstdint>
#include <cstring>
#include <string>

#include "../include/hvdt.h"

namespace hvdt {

// Per-thread error message, surfaced through hvdt_last_error().
std::string& last_error();

inline int fail(const std::string& msg) {
  last_error() = msg;
  return 1;
}

inline int64_t dtype_size(int dtype) {
  switch (dtype) {
    case HVDT_UINT8:
    case HVDT_INT8:
    case HVDT_BOOL:
      return 1;
    case HVDT_UINT16:
    case HVDT_INT16:
    case HVDT_FLOAT16:
    case HVDT_BFLOAT16:
      return 2;
    case HVDT_INT32:
    case HVDT_FLOAT32:
      return 4;
    case HVDT_INT64:
    case HVDT_FLOAT64:
      return 8;
    default:
      return -1;
  }
}

// ---- half-precision conversions (round-to-nearest-even for narrowing) ----

inline float bf16_to_f32(uint16_t h) {
  uint32_t bits = static_cast<uint32_t>(h) << 16;
  float f;
  std::memcpy(&f, &bits, 4);
  return f;
}

inline uint16_t f32_to_bf16(float f) {
  uint32_t bits;
  std::memcpy(&bits, &f, 4);
  if ((bits & 0x7fffffffu) > 0x7f800000u) return (bits >> 16) | 0x0040;  // NaN
  uint32_t lsb = (bits >> 16) & 1u;
  bits += 0x7fffu + lsb;
  return static_cast<uint16_t>(bits >> 16);
}

inline float fp16_to_f32(uint16_t h) {
  uint32_t sign = (h & 0x8000u) << 16;
  uint32_t exp = (h >> 10) & 0x1f;
  uint32_t man = h & 0x3ffu;
  uint32_t bits;
  if (exp == 0) {
    if (man == 0) {
      bits = sign;
    } else {  // subnormal: normalize
      int shift = 0;
      while (!(man & 0x400u)) {
        man <<= 1;
        ++shift;
      }
      man &= 0x3ffu;
      bits = sign | ((127 - 15 - shift) << 23) | (man << 13);
    }
  } else if (exp == 31) {
    bits = sign | 0x7f800000u | (man << 13);
  } else {
    bits = sign | ((exp + 127 - 15) << 23) | (man << 13);
  }
  float f;
  std::memcpy(&f, &bits, 4);
  return f;
}

inline uint16_t f32_to_fp16(float f) {
  uint32_t bits;
  std::memcpy(&bits, &f, 4);
  uint32_t sign = (bits >> 16) & 0x8000u;
  int32_t exp = static_cast<int32_t>((bits >> 23) & 0xff) - 127 + 15;
  uint32_t man = bits & 0x7fffffu;
  if (((bits >> 23) & 0xff) == 0xff)  // inf/NaN
    return static_cast<uint16_t>(sign | 0x7c00u | (man ? 0x200u : 0));
  if (exp >= 31) return static_cast<uint16_t>(sign | 0x7c00u);  // overflow
  if (exp <= 0) {  // subnormal or zero
    if (exp < -10) return static_cast<uint16_t>(sign);
    man |= 0x800000u;
    int shift = 14 - exp;
    uint32_t half = man >> shift;
    uint32_t rem = man & ((1u << shift) - 1);
    uint32_t mid = 1u << (shift - 1);
    if (rem > mid || (rem == mid && (half & 1))) ++half;
    return static_cast<uint16_t>(sign | half);
  }
  uint32_t half = sign | (exp << 10) | (man >> 13);
  uint32_t rem = man & 0x1fffu;
  if (rem > 0x1000u || (rem == 0x1000u && (half & 1))) ++half;
  return static_cast<uint16_t>(half);
}

// ---- elementwise reduce: acc[i] = acc[i] OP in[i] ----

template <typename T>
void reduce_typed(T* acc, const T* in, int64_t n, int op) {
  switch (op) {
    case HVDT_OP_SUM:
      for (int64_t i = 0; i < n; ++i) acc[i] = acc[i] + in[i];
      break;
    case HVDT_OP_PRODUCT:
      for (int64_t i = 0; i < n; ++i) acc[i] = acc[i] * in[i];
      break;
    case HVDT_OP_MIN:
      for (int64_t i = 0; i < n; ++i) acc[i] = in[i] < acc[i] ? in[i] : acc[i];
      break;
    case HVDT_OP_MAX:
      for (int64_t i = 0; i < n; ++i) acc[i] = in[i] > acc[i] ? in[i] : acc[i];
      break;
  }
}

template <uint16_t (*Narrow)(float), float (*Widen)(uint16_t)>
void reduce_half(uint16_t* acc, const uint16_t* in, int64_t n, int op) {
  for (int64_t i = 0; i < n; ++i) {
    float a = Widen(acc[i]), b = Widen(in[i]);
    float r;
    switch (op) {
      case HVDT_OP_SUM: r = a + b; break;
      case HVDT_OP_PRODUCT: r = a * b; break;
      case HVDT_OP_MIN: r = b < a ? b : a; break;
      default: r = b > a ? b : a; break;
    }
    acc[i] = Narrow(r);
  }
}

// Reduce `in` into `acc`, both holding n elements of `dtype`.
inline int reduce_buffers(void* acc, const void* in, int64_t n, int dtype,
                          int op) {
  switch (dtype) {
    case HVDT_UINT8:
    case HVDT_BOOL:
      reduce_typed(static_cast<uint8_t*>(acc),
                   static_cast<const uint8_t*>(in), n, op);
      return 0;
    case HVDT_INT8:
      reduce_typed(static_cast<int8_t*>(acc), static_cast<const int8_t*>(in),
                   n, op);
      return 0;
    case HVDT_UINT16:
      reduce_typed(static_cast<uint16_t*>(acc),
                   static_cast<const uint16_t*>(in), n, op);
      return 0;
    case HVDT_INT16:
      reduce_typed(static_cast<int16_t*>(acc),
                   static_cast<const int16_t*>(in), n, op);
      return 0;
    case HVDT_INT32:
      reduce_typed(static_cast<int32_t*>(acc),
                   static_cast<const int32_t*>(in), n, op);
      return 0;
    case HVDT_INT64:
      reduce_typed(static_cast<int64_t*>(acc),
                   static_cast<const int64_t*>(in), n, op);
      return 0;
    case HVDT_FLOAT32:
      reduce_typed(static_cast<float*>(acc), static_cast<const float*>(in),
                   n, op);
      return 0;
    case HVDT_FLOAT64:
      reduce_typed(static_cast<double*>(acc), static_cast<const double*>(in),
                   n, op);
      return 0;
    case HVDT_FLOAT16:
      reduce_half<f32_to_fp16, fp16_to_f32>(
          static_cast<uint16_t*>(acc), static_cast<const uint16_t*>(in), n,
          op);
      return 0;
    case HVDT_BFLOAT16:
      reduce_half<f32_to_bf16, bf16_to_f32>(
          static_cast<uint16_t*>(acc), static_cast<const uint16_t*>(in), n,
          op);
      return 0;
    default:
      return fail("unsupported dtype for reduce: " + std::to_string(dtype));
  }
}

}  // namespace hvdt
