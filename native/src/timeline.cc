// Async Chrome-trace timeline writer.
//
// Re-conception of the reference's Timeline
// (ref: horovod/common/timeline.{h,cc} — TimelineWriter with a dedicated
// writer thread timeline.h:48-102, "tensors as pids" JSON emit
// timeline.cc:217-294).  Events are queued under a mutex and flushed by a
// background thread so instrumentation never blocks the training path;
// pid metadata records are emitted lazily per tensor name, matching the
// reference's per-tensor process rows in chrome://tracing.

#include <atomic>
#include <condition_variable>
#include <cstdio>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>

#include "common.h"
#include "timeline.h"

namespace hvdt {

namespace {

// Minimal JSON string escaping for event/tensor names.
std::string json_escape(const std::string& s) {
  std::string out;
  out.reserve(s.size() + 2);
  for (char c : s) {
    switch (c) {
      case '"': out += "\\\""; break;
      case '\\': out += "\\\\"; break;
      case '\n': out += "\\n"; break;
      case '\t': out += "\\t"; break;
      default:
        if (static_cast<unsigned char>(c) < 0x20) {
          char buf[8];
          std::snprintf(buf, sizeof(buf), "\\u%04x", c);
          out += buf;
        } else {
          out += c;
        }
    }
  }
  return out;
}

}  // namespace

TimelineWriter::TimelineWriter(const std::string& path) : path_(path) {}

int TimelineWriter::Start() {
  file_ = std::fopen(path_.c_str(), "w");
  if (!file_) return fail("cannot open timeline file " + path_);
  // Unterminated JSON array — the chrome trace format explicitly allows a
  // missing ']' so writers can append forever (same as the reference).
  std::fputs("[\n", file_);
  running_.store(true);
  thread_ = std::thread([this] { Loop(); });
  return 0;
}

void TimelineWriter::Enqueue(Event ev) {
  {
    std::lock_guard<std::mutex> lk(mu_);
    queue_.push_back(std::move(ev));
  }
  cv_.notify_one();
}

void TimelineWriter::Loop() {
  std::deque<Event> batch;
  while (true) {
    {
      std::unique_lock<std::mutex> lk(mu_);
      cv_.wait(lk, [this] { return !queue_.empty() || !running_.load(); });
      batch.swap(queue_);
    }
    for (const Event& ev : batch) WriteEvent(ev);
    batch.clear();
    if (!running_.load()) {
      std::lock_guard<std::mutex> lk(mu_);
      if (queue_.empty()) break;
    }
    std::fflush(file_);
  }
}

void TimelineWriter::WriteEvent(const Event& ev) {
  // One "process" per tensor/pid-name (ref timeline.cc:244-266): emit the
  // process_name metadata record on first sight.
  auto it = pids_.find(ev.pid_name);
  int pid;
  if (it == pids_.end()) {
    pid = static_cast<int>(pids_.size());
    pids_.emplace(ev.pid_name, pid);
    std::fprintf(file_,
                 "{\"name\":\"process_name\",\"ph\":\"M\",\"pid\":%d,"
                 "\"args\":{\"name\":\"%s\"}},\n",
                 pid, json_escape(ev.pid_name).c_str());
  } else {
    pid = it->second;
  }
  std::fprintf(file_, "{\"name\":\"%s\",\"ph\":\"%c\",\"pid\":%d,\"ts\":%lld",
               json_escape(ev.name).c_str(), ev.ph, pid,
               static_cast<long long>(ev.ts_us));
  if (ev.ph == 'X')
    std::fprintf(file_, ",\"dur\":%lld", static_cast<long long>(ev.dur_us));
  if (ev.ph == 'i') std::fputs(",\"s\":\"p\"", file_);
  if (!ev.args_json.empty())
    std::fprintf(file_, ",\"args\":%s", ev.args_json.c_str());
  std::fputs("},\n", file_);
}

int TimelineWriter::Close() {
  if (!running_.exchange(false)) return 0;
  cv_.notify_one();
  if (thread_.joinable()) thread_.join();
  // Drain anything enqueued after the final loop pass.
  for (const Event& ev : queue_) WriteEvent(ev);
  queue_.clear();
  std::fclose(file_);
  file_ = nullptr;
  return 0;
}

TimelineWriter::~TimelineWriter() {
  if (running_.load()) Close();
}

}  // namespace hvdt
