// TCP host-collective backend — the Gloo analog of the native core.
//
// Re-conception of the reference's host-CPU data plane
// (ref: horovod/common/ops/gloo_operations.cc ring allreduce/allgatherv/
// broadcast/alltoallv; horovod/common/gloo/gloo_context.cc full-mesh
// bootstrap from a rendezvous).  On TPU the accelerator collectives are
// XLA programs over ICI; this backend carries *host* tensors (eager
// fallback, control traffic, CPU-only tests) over plain TCP with no MPI,
// NCCL, or Gloo dependency.
//
// Topology: one listening socket per rank; for each pair (i, j) with
// i < j, rank j connects to rank i and identifies itself with a 4-byte
// rank handshake — a full socket mesh.  Sockets are full-duplex; a
// poll()-based sendrecv makes pairwise exchanges deadlock-free for
// arbitrary message sizes.
//
// Algorithms:
//   allreduce  — ring reduce-scatter + ring allgather (bandwidth-optimal,
//                2*(p-1)/p * bytes on the wire per rank).
//   allgatherv — ring passing of rank blocks, p-1 steps.
//   broadcast  — direct sends over the mesh (root fan-out).
//   alltoallv  — p-1 pairwise sendrecv rounds, peer = (rank ± step) % p.
//   barrier    — 1-byte ring allreduce.

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <chrono>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

#include "common.h"
#include "tcp_group.h"

namespace hvdt {

std::string& last_error() {
  static thread_local std::string err;
  return err;
}

namespace {

using Clock = std::chrono::steady_clock;

int set_nodelay(int fd) {
  int one = 1;
  return setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
}

// Read/write exactly n bytes on a blocking socket.
int read_full(int fd, void* buf, int64_t n) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = ::recv(fd, p, static_cast<size_t>(n), 0);
    if (r <= 0) {
      if (r < 0 && (errno == EINTR)) continue;
      return fail("recv failed: " + std::string(r == 0 ? "peer closed"
                                                       : strerror(errno)));
    }
    p += r;
    n -= r;
  }
  return 0;
}

int write_full(int fd, const void* buf, int64_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t r = ::send(fd, p, static_cast<size_t>(n), MSG_NOSIGNAL);
    if (r < 0) {
      if (errno == EINTR) continue;
      return fail("send failed: " + std::string(strerror(errno)));
    }
    p += r;
    n -= r;
  }
  return 0;
}

struct Addr {
  std::string host;
  int port = 0;
};

bool parse_addrs(const std::string& csv, std::vector<Addr>* out) {
  size_t pos = 0;
  while (pos <= csv.size()) {
    size_t comma = csv.find(',', pos);
    std::string item = csv.substr(
        pos, comma == std::string::npos ? std::string::npos : comma - pos);
    if (!item.empty()) {
      size_t colon = item.rfind(':');
      if (colon == std::string::npos) return false;
      Addr a;
      a.host = item.substr(0, colon);
      a.port = std::atoi(item.c_str() + colon + 1);
      out->push_back(a);
    }
    if (comma == std::string::npos) break;
    pos = comma + 1;
  }
  return true;
}

}  // namespace

TcpGroup::~TcpGroup() {
  for (int fd : fds_)
    if (fd >= 0) ::close(fd);
}

int TcpGroup::Connect(int rank, int size, const std::string& addrs_csv,
                      int timeout_ms) {
  rank_ = rank;
  size_ = size;
  fds_.assign(size, -1);
  if (size == 1) return 0;

  std::vector<Addr> addrs;
  if (!parse_addrs(addrs_csv, &addrs) || static_cast<int>(addrs.size()) != size)
    return fail("bad addrs list (need " + std::to_string(size) +
                " host:port entries): " + addrs_csv);

  auto deadline = Clock::now() + std::chrono::milliseconds(timeout_ms);

  // Listen on our own port; ranks below us will be accepted here.
  int listen_fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (listen_fd < 0) return fail("socket: " + std::string(strerror(errno)));
  int one = 1;
  setsockopt(listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in self{};
  self.sin_family = AF_INET;
  self.sin_addr.s_addr = INADDR_ANY;
  self.sin_port = htons(static_cast<uint16_t>(addrs[rank].port));
  if (::bind(listen_fd, reinterpret_cast<sockaddr*>(&self), sizeof(self)) < 0 ||
      ::listen(listen_fd, size) < 0) {
    ::close(listen_fd);
    return fail("bind/listen on port " + std::to_string(addrs[rank].port) +
                ": " + strerror(errno));
  }

  // Higher ranks dial lower ranks: we accept size-1-rank peers and dial
  // `rank` peers; interleave so no ordering constraint exists.
  int need_accept = size - 1 - rank;
  int accepted = 0;
  for (int peer = 0; peer < rank; ++peer) {
    // Dial peer (it has a lower rank, so it accepts).
    // getaddrinfo, not gethostbyname: the Python layer drives N rank
    // threads through concurrent bootstraps in one process, and
    // gethostbyname returns a pointer into static storage (a data race
    // that can memcpy a torn peer address).
    sockaddr_in sa{};
    sa.sin_family = AF_INET;
    sa.sin_port = htons(static_cast<uint16_t>(addrs[peer].port));
    addrinfo hints{};
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    addrinfo* res = nullptr;
    if (::getaddrinfo(addrs[peer].host.c_str(), nullptr, &hints, &res) != 0 ||
        res == nullptr) {
      if (res) ::freeaddrinfo(res);
      ::close(listen_fd);
      return fail("cannot resolve host " + addrs[peer].host);
    }
    sa.sin_addr = reinterpret_cast<sockaddr_in*>(res->ai_addr)->sin_addr;
    ::freeaddrinfo(res);
    int fd = -1;
    while (true) {
      fd = ::socket(AF_INET, SOCK_STREAM, 0);
      if (fd < 0) break;
      if (::connect(fd, reinterpret_cast<sockaddr*>(&sa), sizeof(sa)) == 0)
        break;
      ::close(fd);
      fd = -1;
      if (Clock::now() > deadline) {
        ::close(listen_fd);
        return fail("timeout connecting to rank " + std::to_string(peer));
      }
      ::usleep(20 * 1000);  // peer may not be listening yet
    }
    if (fd < 0) {
      ::close(listen_fd);
      return fail("connect: " + std::string(strerror(errno)));
    }
    set_nodelay(fd);
    int32_t my_rank = rank;
    if (write_full(fd, &my_rank, 4)) {
      ::close(fd);
      ::close(listen_fd);
      return 1;
    }
    fds_[peer] = fd;
  }
  while (accepted < need_accept) {
    pollfd pfd{listen_fd, POLLIN, 0};
    auto left = std::chrono::duration_cast<std::chrono::milliseconds>(
                    deadline - Clock::now())
                    .count();
    if (left <= 0 || ::poll(&pfd, 1, static_cast<int>(left)) <= 0) {
      ::close(listen_fd);
      return fail("timeout accepting peers (" + std::to_string(accepted) +
                  "/" + std::to_string(need_accept) + ")");
    }
    int fd = ::accept(listen_fd, nullptr, nullptr);
    if (fd < 0) continue;
    set_nodelay(fd);
    int32_t peer_rank = -1;
    if (read_full(fd, &peer_rank, 4)) {
      ::close(fd);
      continue;
    }
    if (peer_rank <= rank || peer_rank >= size || fds_[peer_rank] != -1) {
      ::close(fd);
      ::close(listen_fd);
      return fail("bad handshake rank " + std::to_string(peer_rank));
    }
    fds_[peer_rank] = fd;
    ++accepted;
  }
  ::close(listen_fd);
  return 0;
}

// Full-duplex pairwise exchange on one socket: interleave send and recv
// with poll so large messages can't deadlock (both sides sending first
// would fill kernel buffers).
int TcpGroup::SendRecv(int send_peer, const void* send_buf, int64_t send_n,
                       int recv_peer, void* recv_buf, int64_t recv_n) {
  if (send_peer == rank_ && recv_peer == rank_) {
    if (send_buf != recv_buf && recv_n > 0)
      std::memcpy(recv_buf, send_buf, static_cast<size_t>(recv_n));
    return 0;
  }
  if (send_peer == rank_ || recv_peer == rank_)
    return fail("sendrecv: one-sided self exchange is not defined");
  const char* sp = static_cast<const char*>(send_buf);
  char* rp = static_cast<char*>(recv_buf);
  int64_t to_send = send_n, to_recv = recv_n;
  int sfd = fds_[send_peer];
  int rfd = fds_[recv_peer];
  while (to_send > 0 || to_recv > 0) {
    pollfd pfds[2];
    int n = 0;
    int si = -1, ri = -1;
    if (to_send > 0 && sfd >= 0) {
      pfds[n] = {sfd, POLLOUT, 0};
      si = n++;
    }
    if (to_recv > 0 && rfd >= 0) {
      pfds[n] = {rfd, POLLIN, 0};
      ri = n++;
    }
    if (n == 0) return fail("sendrecv: no progress possible");
    if (::poll(pfds, n, -1) < 0) {
      if (errno == EINTR) continue;
      return fail("poll: " + std::string(strerror(errno)));
    }
    if (si >= 0 && (pfds[si].revents & (POLLOUT | POLLERR | POLLHUP))) {
      ssize_t r = ::send(sfd, sp, static_cast<size_t>(to_send), MSG_NOSIGNAL);
      if (r < 0 && errno != EINTR && errno != EAGAIN)
        return fail("send: " + std::string(strerror(errno)));
      if (r > 0) {
        sp += r;
        to_send -= r;
      }
    }
    if (ri >= 0 && (pfds[ri].revents & (POLLIN | POLLERR | POLLHUP))) {
      ssize_t r = ::recv(rfd, rp, static_cast<size_t>(to_recv), 0);
      if (r == 0) return fail("sendrecv: peer closed");
      if (r < 0 && errno != EINTR && errno != EAGAIN)
        return fail("recv: " + std::string(strerror(errno)));
      if (r > 0) {
        rp += r;
        to_recv -= r;
      }
    }
  }
  return 0;
}

int TcpGroup::Send(int peer, const void* buf, int64_t n) {
  return write_full(fds_[peer], buf, n);
}

int TcpGroup::Recv(int peer, void* buf, int64_t n) {
  return read_full(fds_[peer], buf, n);
}

// Segment k of a count-element buffer split into size_ near-equal parts.
void TcpGroup::Segment(int64_t count, int k, int64_t* off, int64_t* len) const {
  *off = count * k / size_;
  *len = count * (k + 1) / size_ - *off;
}

int TcpGroup::Allreduce(void* buf, int64_t count, int dtype, int op) {
  if (size_ == 1) return 0;
  int64_t esize = dtype_size(dtype);
  if (esize < 0) return fail("bad dtype " + std::to_string(dtype));
  char* data = static_cast<char*>(buf);
  int left = (rank_ - 1 + size_) % size_;
  int right = (rank_ + 1) % size_;
  int64_t max_seg = count / size_ + 1;
  std::vector<char> tmp(static_cast<size_t>(max_seg * esize));

  // Phase 1: ring reduce-scatter.  After p-1 steps rank r owns the fully
  // reduced segment (r+1) % p.
  for (int step = 0; step < size_ - 1; ++step) {
    int send_seg = (rank_ - step + size_) % size_;
    int recv_seg = (rank_ - step - 1 + 2 * size_) % size_;
    int64_t soff, slen, roff, rlen;
    Segment(count, send_seg, &soff, &slen);
    Segment(count, recv_seg, &roff, &rlen);
    if (SendRecv(right, data + soff * esize, slen * esize, left, tmp.data(),
                 rlen * esize))
      return 1;
    if (rlen > 0 &&
        reduce_buffers(data + roff * esize, tmp.data(), rlen, dtype, op))
      return 1;
  }
  // Phase 2: ring allgather of the reduced segments.
  for (int step = 0; step < size_ - 1; ++step) {
    int send_seg = (rank_ + 1 - step + 2 * size_) % size_;
    int recv_seg = (rank_ - step + 2 * size_) % size_;
    int64_t soff, slen, roff, rlen;
    Segment(count, send_seg, &soff, &slen);
    Segment(count, recv_seg, &roff, &rlen);
    if (SendRecv(right, data + soff * esize, slen * esize, left,
                 data + roff * esize, rlen * esize))
      return 1;
  }
  return 0;
}

int TcpGroup::Allgatherv(const void* in, int64_t in_count, void* out,
                         const int64_t* counts, int dtype) {
  int64_t esize = dtype_size(dtype);
  if (esize < 0) return fail("bad dtype " + std::to_string(dtype));
  if (counts[rank_] != in_count)
    return fail("allgatherv: counts[rank] != in_count");
  std::vector<int64_t> offs(size_, 0);
  for (int i = 1; i < size_; ++i) offs[i] = offs[i - 1] + counts[i - 1];
  char* o = static_cast<char*>(out);
  std::memcpy(o + offs[rank_] * esize, in,
              static_cast<size_t>(in_count * esize));
  if (size_ == 1) return 0;
  int left = (rank_ - 1 + size_) % size_;
  int right = (rank_ + 1) % size_;
  // Ring: at step s we forward the block originally from (rank - s).
  for (int step = 0; step < size_ - 1; ++step) {
    int send_blk = (rank_ - step + size_) % size_;
    int recv_blk = (rank_ - step - 1 + 2 * size_) % size_;
    if (SendRecv(right, o + offs[send_blk] * esize, counts[send_blk] * esize,
                 left, o + offs[recv_blk] * esize, counts[recv_blk] * esize))
      return 1;
  }
  return 0;
}

int TcpGroup::Broadcast(void* buf, int64_t nbytes, int root) {
  if (size_ == 1) return 0;
  if (rank_ == root) {
    for (int peer = 0; peer < size_; ++peer)
      if (peer != rank_ && Send(peer, buf, nbytes)) return 1;
    return 0;
  }
  return Recv(root, buf, nbytes);
}

int TcpGroup::Alltoallv(const void* in, const int64_t* send_counts, void* out,
                        const int64_t* recv_counts, int dtype) {
  int64_t esize = dtype_size(dtype);
  if (esize < 0) return fail("bad dtype " + std::to_string(dtype));
  std::vector<int64_t> soffs(size_, 0), roffs(size_, 0);
  for (int i = 1; i < size_; ++i) {
    soffs[i] = soffs[i - 1] + send_counts[i - 1];
    roffs[i] = roffs[i - 1] + recv_counts[i - 1];
  }
  const char* ip = static_cast<const char*>(in);
  char* op_ = static_cast<char*>(out);
  std::memcpy(op_ + roffs[rank_] * esize, ip + soffs[rank_] * esize,
              static_cast<size_t>(send_counts[rank_] * esize));
  // p-1 pairwise rounds: send to (rank+s), recv from (rank-s) — a
  // deadlock-free schedule for any p given full-duplex sendrecv.
  for (int step = 1; step < size_; ++step) {
    int to = (rank_ + step) % size_;
    int from = (rank_ - step + size_) % size_;
    if (SendRecv(to, ip + soffs[to] * esize, send_counts[to] * esize, from,
                 op_ + roffs[from] * esize, recv_counts[from] * esize))
      return 1;
  }
  return 0;
}

int TcpGroup::Barrier() {
  uint8_t b = 1;
  return Allreduce(&b, 1, HVDT_UINT8, HVDT_OP_MAX);
}

}  // namespace hvdt
