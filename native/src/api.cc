// C API surface — the ctypes boundary (ref: the reference exposes its core
// through a C API in horovod/common/operations.cc:887-1353, loaded from
// Python via ctypes in horovod/common/basics.py:33-34; same pattern here).

#include <cstring>
#include <string>

#include "../include/hvdt.h"
#include "common.h"
#include "tcp_group.h"
#include "timeline.h"

namespace hvdt {
int AdasumAllreduce(TcpGroup* g, void* buf, int64_t count, int dtype);
int AdasumCombine(void* a, const void* b, int64_t count, int dtype);
}  // namespace hvdt

using hvdt::TcpGroup;
using hvdt::TimelineWriter;

extern "C" {

const char* hvdt_last_error(void) { return hvdt::last_error().c_str(); }

int64_t hvdt_dtype_size(int dtype) { return hvdt::dtype_size(dtype); }

int hvdt_tcp_group_create(int rank, int size, const char* addrs_csv,
                          int timeout_ms, hvdt_group_t* out) {
  if (rank < 0 || size <= 0 || rank >= size || !out)
    return hvdt::fail("invalid rank/size");
  auto* g = new TcpGroup();
  int rc = g->Connect(rank, size, addrs_csv ? addrs_csv : "", timeout_ms);
  if (rc) {
    delete g;
    return rc;
  }
  *out = g;
  return 0;
}

int hvdt_tcp_group_destroy(hvdt_group_t g) {
  delete static_cast<TcpGroup*>(g);
  return 0;
}

int hvdt_group_rank(hvdt_group_t g) { return static_cast<TcpGroup*>(g)->rank(); }
int hvdt_group_size(hvdt_group_t g) { return static_cast<TcpGroup*>(g)->size(); }

int hvdt_allreduce(hvdt_group_t g, void* buf, int64_t count, int dtype,
                   int op) {
  return static_cast<TcpGroup*>(g)->Allreduce(buf, count, dtype, op);
}

int hvdt_allgatherv(hvdt_group_t g, const void* in, int64_t in_count,
                    void* out, const int64_t* counts, int dtype) {
  return static_cast<TcpGroup*>(g)->Allgatherv(in, in_count, out, counts,
                                               dtype);
}

int hvdt_broadcast(hvdt_group_t g, void* buf, int64_t nbytes, int root) {
  return static_cast<TcpGroup*>(g)->Broadcast(buf, nbytes, root);
}

int hvdt_alltoallv(hvdt_group_t g, const void* in, const int64_t* send_counts,
                   void* out, const int64_t* recv_counts, int dtype) {
  return static_cast<TcpGroup*>(g)->Alltoallv(in, send_counts, out,
                                              recv_counts, dtype);
}

int hvdt_barrier(hvdt_group_t g) { return static_cast<TcpGroup*>(g)->Barrier(); }

int hvdt_adasum_allreduce(hvdt_group_t g, void* buf, int64_t count,
                          int dtype) {
  return hvdt::AdasumAllreduce(static_cast<TcpGroup*>(g), buf, count, dtype);
}

int hvdt_adasum_combine(void* a, const void* b, int64_t count, int dtype) {
  return hvdt::AdasumCombine(a, b, count, dtype);
}

int hvdt_timeline_create(const char* path, hvdt_timeline_t* out) {
  if (!path || !out) return hvdt::fail("timeline: null path/out");
  auto* t = new TimelineWriter(path);
  int rc = t->Start();
  if (rc) {
    delete t;
    return rc;
  }
  *out = t;
  return 0;
}

int hvdt_timeline_event(hvdt_timeline_t t, const char* pid_name,
                        const char* name, char ph, int64_t ts_us,
                        int64_t dur_us, const char* args_json) {
  if (!t) return hvdt::fail("timeline: null handle");
  TimelineWriter::Event ev;
  ev.pid_name = pid_name ? pid_name : "";
  ev.name = name ? name : "";
  ev.ph = ph;
  ev.ts_us = ts_us;
  ev.dur_us = dur_us;
  ev.args_json = args_json ? args_json : "";
  static_cast<TimelineWriter*>(t)->Enqueue(std::move(ev));
  return 0;
}

int hvdt_timeline_close(hvdt_timeline_t t) {
  if (!t) return 0;
  auto* w = static_cast<TimelineWriter*>(t);
  int rc = w->Close();
  delete w;
  return rc;
}

}  // extern "C"
